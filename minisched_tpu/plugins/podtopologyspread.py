"""PodTopologySpread: even spreading across topology domains.

Re-creates the in-tree ``podtopologyspread`` plugin from the reference's
default roster (scheduler/scheduler_test.go:307-332; default score weight
2) — the second pod↔pod×node coupling plugin (BASELINE config 4).
Semantics follow upstream v1.22:

* Filter (DoNotSchedule constraints): domains are counted over nodes that
  pass the pod's nodeSelector/required node affinity (eligible nodes);
  placing on node n must keep ``count(domain(n)) + 1 − min_domain_count ≤
  max_skew``.  Nodes lacking the topology key are rejected; if no eligible
  node carries the key, the constraint is unsatisfiable everywhere.
* Score (ScheduleAnyway constraints): raw = Σ matching-pod count of the
  node's domain per constraint (keyless nodes take the constraint's worst
  domain count), then reversed min-max normalization to [0, 100] — fewer
  co-located matches → higher score.  (Upstream's normalization formula
  differs in detail; this integer re-derivation keeps the same ordering
  and is implemented identically by the scalar oracle and the kernel.)

Batch form: gathers of ``combo_dsum`` rows (models/constraints.py) with a
mask-aware min over the eligible-node axis, reusing the NodeAffinity
eligibility kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import (
    CycleState,
    MAX_NODE_SCORE,
    NodeScoreList,
    Status,
)
from minisched_tpu.models.constraints import TS_DO_NOT_SCHEDULE, _matches
from minisched_tpu.plugins.normalize import (
    minmax_normalize_batch,
    minmax_normalize_scalar,
)
from minisched_tpu.plugins.nodeaffinity import (
    node_affinity_eligible,
    required_node_affinity_mask,
)

NAME = "PodTopologySpread"
PRE_FILTER_KEY = "PreFilter" + NAME
PRE_SCORE_KEY = "PreScore" + NAME

REASON_SKEW = "node(s) didn't match pod topology spread constraints"
REASON_KEY = (
    "node(s) didn't match pod topology spread constraints (missing required label)"
)

_INF = 1 << 30


def _constraint_counts(constraint, pod, node_infos: List[NodeInfo],
                       eligible: Optional[Dict[str, bool]] = None):
    """Count assigned pods matching the constraint's selector (same
    namespace) per topology-domain value.

    ``eligible`` (node name → bool, precomputed once per pod) restricts
    counting to nodes passing the pod's nodeSelector/required node
    affinity — upstream's PreFilter skips ineligible nodes entirely (its
    Score pass does not).
    """
    nss = (pod.metadata.namespace,)
    counts: Dict[str, int] = {}
    for ni in node_infos:
        val = ni.node.metadata.labels.get(constraint.topology_key)
        if val is None:
            continue
        if eligible is not None and not eligible.get(ni.name, False):
            continue
        n = sum(1 for p in ni.pods if _matches(constraint.label_selector, nss, p))
        if n:
            counts[val] = counts.get(val, 0) + n
    return counts


class _Normalize:
    """Reversed min-max: fewer co-located matching pods → higher score;
    all equal → MAX_NODE_SCORE."""

    def normalize_score(self, state: CycleState, pod: Any, scores: NodeScoreList) -> Status:
        minmax_normalize_scalar(scores, reverse=True, fill=MAX_NODE_SCORE)
        return Status.success()


class PodTopologySpread(Plugin, BatchEvaluable):
    needs_extra = True
    #: the sequential scan carries the combo aggregates for this plugin
    scan_carried_planes = ("combos",)

    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def pre_filter(
        self, state: CycleState, pod: Any, node_infos: List[NodeInfo]
    ) -> Status:
        hard = []  # (constraint, counts, min_count or None)
        eligible = None
        if any(
            c.when_unsatisfiable == "DoNotSchedule"
            for c in pod.spec.topology_spread_constraints
        ):
            # one eligibility evaluation per node, shared by all constraints
            eligible = {
                ni.name: node_affinity_eligible(pod, ni.node)[0]
                for ni in node_infos
            }
        for c in pod.spec.topology_spread_constraints:
            if c.when_unsatisfiable != "DoNotSchedule":
                continue
            counts = _constraint_counts(c, pod, node_infos, eligible=eligible)
            # min over domains represented among ELIGIBLE nodes with the key
            min_count = None
            for ni in node_infos:
                if not eligible.get(ni.name, False):
                    continue
                val = ni.node.metadata.labels.get(c.topology_key)
                if val is None:
                    continue
                cnt = counts.get(val, 0)
                if min_count is None or cnt < min_count:
                    min_count = cnt
            hard.append((c, counts, min_count))
        state.write(PRE_FILTER_KEY, hard)
        return Status.success()

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        hard = state.read(PRE_FILTER_KEY)
        labels = node_info.node.metadata.labels
        for c, counts, min_count in hard:
            val = labels.get(c.topology_key)
            if val is None:
                return Status.unresolvable(REASON_KEY).with_plugin(NAME)
            if min_count is None:  # no eligible domain anywhere
                return Status.unschedulable(REASON_SKEW).with_plugin(NAME)
            if counts.get(val, 0) + 1 - min_count > c.max_skew:
                return Status.unschedulable(REASON_SKEW).with_plugin(NAME)
        return Status.success()

    def pre_score(self, state: CycleState, pod: Any, nodes: List[Any]) -> Status:
        node_infos = state.read("nodeinfos")
        soft = []  # (topology_key, counts, worst)
        for c in pod.spec.topology_spread_constraints:
            if c.when_unsatisfiable != "ScheduleAnyway":
                continue
            counts = _constraint_counts(c, pod, node_infos)
            worst = max(counts.values(), default=0)
            soft.append((c.topology_key, counts, worst))
        state.write(PRE_SCORE_KEY, soft)
        return Status.success()

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        soft = state.read(PRE_SCORE_KEY)
        ni: NodeInfo = state.read("nodeinfo/" + node_name)
        labels = ni.node.metadata.labels
        total = 0
        for topo_key, counts, worst in soft:
            val = labels.get(topo_key)
            total += counts.get(val, 0) if val is not None else worst
        return total, Status.success()

    def score_extensions(self):
        return _Normalize()

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.POD, ActionType.ALL),
            ClusterEvent(GVK.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any, extra: Any):
        """MXU-shaped spread filter.

        Never materializes a (P, D, N) tensor: per-domain sums contract
        over the node axis as one (P, N) × (N, K·D) matmul (exact in f32
        while every count stays < 2²⁴ — domain counts are bounded by the
        cluster's assigned-pod total), then per-pod rows are selected by
        topology-key index.  Waves with no DoNotSchedule constraint in a
        slot skip that slot's work entirely via ``lax.cond`` — the common
        plain-pod wave costs O(P) here instead of the dense kernels.
        """
        if extra is None:
            raise ValueError(
                "PodTopologySpread batch kernels need the wave's "
                "ConstraintTables (models/constraints.py) — pass `extra`"
            )
        import jax

        C = extra.ts_combo.shape[1]
        P = extra.ts_combo.shape[0]
        N = nodes.valid.shape[0]
        active = (
            (jnp.arange(C)[None, :] < extra.ts_n[:, None])
            & (extra.ts_mode == TS_DO_NOT_SCHEDULE)
        )  # (P, C)

        def all_ok(_):
            return jnp.ones((P, N), bool)

        def compute_all(_):
            elig = (
                required_node_affinity_mask(pods, nodes)
                & nodes.valid[None, :]
            )
            K, D, _ = extra.topo_onehot.shape
            # (N, K·D) one-hot, shared by every slot's contractions.
            # Precision.HIGHEST keeps the f32 dots exact (counts < 2²⁴) —
            # TPU default precision feeds bf16 into the MXU and would
            # silently round counts above 256
            dot = partial(
                jnp.matmul, precision=jax.lax.Precision.HIGHEST
            )
            onehot_t = jnp.reshape(
                extra.topo_onehot, (K * D, N)
            ).T.astype(jnp.float32)
            # exists[p, k, d]: some ELIGIBLE node sits in domain d of key k
            e_all = dot(
                elig.astype(jnp.float32), onehot_t
            ).reshape(P, K, D) > 0

            def slot(c, _):
                combo = extra.ts_combo[:, c]  # (P,)
                haskey = extra.combo_haskey[combo]  # (P, N)
                # domain sums restricted to the pod's ELIGIBLE nodes
                # (upstream PreFilter skips ineligible nodes entirely)
                x = jnp.where(elig, extra.combo_here[combo], 0)  # (P, N)
                key = extra.combo_key[combo]  # (P,)
                unique = extra.topo_unique[key]  # (P,)
                # zone-like path: per-domain sums via the MXU, then select
                # each pod's key row and EXPAND per-node domain sums back
                # through the same one-hot — a (P, N) take_along_axis here
                # lowered to a per-element scalar-core gather that was 67%
                # of the blocked scan's step wall; the matmul form stays
                # on the MXU and is exact (counts < 2²⁴ in f32).  Keyless
                # nodes get 0 instead of an arbitrary row — masked out by
                # ``haskey`` either way.
                a_all = dot(x.astype(jnp.float32), onehot_t).reshape(P, K, D)
                key_oh = (
                    key[:, None] == jnp.arange(K)[None, :]
                ).astype(jnp.float32)  # (P, K)
                A = jnp.einsum(
                    "pkd,pk->pd", a_all, key_oh,
                    precision=jax.lax.Precision.HIGHEST,
                ).astype(jnp.int32)  # (P, D)
                exists = jnp.einsum(
                    "pkd,pk->pd", e_all.astype(jnp.float32), key_oh,
                    precision=jax.lax.Precision.HIGHEST,
                ) > 0  # (P, D)
                a_key = (a_all * key_oh[:, :, None]).reshape(P, K * D)
                dsum_z = dot(a_key, onehot_t.T).astype(jnp.int32)  # (P, N)
                m_z = jnp.min(jnp.where(exists, A, _INF), axis=1)  # (P,)
                # hostname-like path: every domain is one node
                dsum_u = x
                m_u = jnp.min(jnp.where(elig & haskey, x, _INF), axis=1)
                dsum = jnp.where(unique[:, None], dsum_u, dsum_z)
                m = jnp.where(unique, m_u, m_z)
                ok = (
                    haskey
                    & (m < _INF)[:, None]
                    & (dsum + 1 - m[:, None] <= extra.ts_skew[:, c, None])
                )
                return ok | ~active[:, c, None]

            out = jnp.ones((P, N), bool)
            for c in range(C):  # static, MAX_TSC slots
                out = out & jax.lax.cond(
                    jnp.any(active[:, c]), partial(slot, c), all_ok, None
                )
            return out

        return jax.lax.cond(jnp.any(active), compute_all, all_ok, None)

    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any],
                    extra: Any):
        if extra is None:
            raise ValueError(
                "PodTopologySpread batch kernels need the wave's "
                "ConstraintTables (models/constraints.py) — pass `extra`"
            )
        import jax

        C = extra.ts_combo.shape[1]
        P = extra.ts_combo.shape[0]
        N = nodes.valid.shape[0]
        active = (
            (jnp.arange(C)[None, :] < extra.ts_n[:, None])
            & (extra.ts_mode != TS_DO_NOT_SCHEDULE)
        )  # (P, C)

        def zero(_):
            # no soft constraint in the wave: every node scores 0, and the
            # reversed min-max normalization fills MAX everywhere — same as
            # the scalar path's empty-constraint total
            return jnp.zeros((P, N), jnp.int32)

        def compute(_):
            # slot loop instead of a (P, C, N) gather: waves rarely carry
            # more than one soft constraint, and each inactive slot skips
            # its (P, N) planes via lax.cond
            total = jnp.zeros((P, N), jnp.int32)
            for c in range(C):
                def slot(_c=c):
                    combo = extra.ts_combo[:, _c]
                    dsum = extra.combo_dsum[combo]  # (P, N)
                    haskey = extra.combo_haskey[combo]
                    worst = jnp.max(
                        jnp.where(haskey, dsum, 0), axis=1, keepdims=True
                    )
                    contrib = jnp.where(haskey, dsum, worst)
                    return jnp.where(active[:, _c, None], contrib, 0)

                total = total + jax.lax.cond(
                    jnp.any(active[:, c]),
                    lambda _, _c=c: slot(_c),
                    zero,
                    None,
                )
            return total

        return jax.lax.cond(jnp.any(active), compute, zero, None)

    def batch_normalize(self, ctx: Any, scores, mask):
        return minmax_normalize_batch(
            scores, mask, reverse=True, fill=MAX_NODE_SCORE
        )
