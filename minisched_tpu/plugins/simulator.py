"""Simulator plugin wrapper: instrument every Filter/Score call.

Re-creates ``scheduler/plugin/plugins.go`` — the layer that wraps each
default filter/score plugin so every ``Filter`` / ``Score`` /
``NormalizeScore`` call also records its outcome into the resultstore
(plugins.go:229-325), the ``<name>ForSimulator`` naming (:242-244), the
registry of wrapped factories (NewRegistry, :24-70), and the config
conversion that swaps default plugins for wrapped ones
(ConvertForSimulator, :146-202; convertConfigurationForSimulator,
scheduler/scheduler.go:97-142 — only plugin enablement/args are accepted
from the custom config).

Wrappers are composed per capability (filter-only / score-only / both) so
capability probing stays truthful; every other extension point (pre-score,
pre-filter, permit, events, batch kernels) delegates untouched through
``__getattr__``.

Scalar-path instrumentation only: the batch path records the equivalent
artifact via ``Store.record_batch_result`` from the fused kernel's
diagnostics (one write per wave, not a host callback per pair — hooks
inside a jitted kernel would be the wrong TPU design).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from minisched_tpu.framework.types import CycleState, NodeScoreList, Status
from minisched_tpu.observability.resultstore import (
    PASSED_FILTER_MESSAGE,
    Store,
)
from minisched_tpu.service.config import PluginEnabled, PluginSet, SchedulerConfig

SUFFIX = "ForSimulator"  # plugins.go:242-244


def plugin_name(name: str) -> str:
    return name + SUFFIX


class _Base:
    """Shared wrapper plumbing: naming + transparent delegation."""

    def __init__(self, inner: Any, store: Store, weight: int = 1):
        self._inner = inner
        self._store = store
        self._weight = weight

    def name(self) -> str:
        return plugin_name(self._inner.name())

    @property
    def original_name(self) -> str:
        return self._inner.name()

    def __getattr__(self, item):
        # pre_score/pre_filter/permit/events/batch kernels — and anything
        # else — delegate iff the wrapped plugin has them, keeping
        # capability probes (framework/plugin.py) truthful
        return getattr(self._inner, item)


class _FilterRecorder(_Base):
    """plugins.go:311-325: record pass/reason for every Filter call."""

    def filter(self, state: CycleState, pod: Any, node_info: Any) -> Status:
        status = self._inner.filter(state, pod, node_info)
        msg = (
            PASSED_FILTER_MESSAGE
            if (status is None or status.is_success())
            else ("; ".join(status.reasons) or "failed")
        )
        self._store.add_filter_result(
            pod.metadata.key, node_info.name, self._inner.name(), msg
        )
        return status


class _ScoreRecorder(_Base):
    """plugins.go:294-309 + :275-292: record raw and final scores."""

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        score, status = self._inner.score(state, pod, node_name)
        self._store.add_score_result(
            pod.metadata.key, node_name, self._inner.name(), score
        )
        # plugins without NormalizeScore never get a normalize call, so the
        # raw score (× weight) IS the final score
        if self._inner_extensions() is None:
            self._store.add_normalized_score_result(
                pod.metadata.key, node_name, self._inner.name(), score, self._weight
            )
        return score, status

    def _inner_extensions(self):
        ext = getattr(self._inner, "score_extensions", None)
        return ext() if callable(ext) else None

    def score_extensions(self):
        if self._inner_extensions() is None:
            return None
        return _RecordingScoreExtensions(self)


class _RecordingScoreExtensions:
    def __init__(self, wrapper: "_ScoreRecorder"):
        self._w = wrapper

    def normalize_score(
        self, state: CycleState, pod: Any, scores: NodeScoreList
    ) -> Status:
        status = self._w._inner_extensions().normalize_score(state, pod, scores)
        if status is None or status.is_success():
            for ns in scores:
                self._w._store.add_normalized_score_result(
                    pod.metadata.key,
                    ns.name,
                    self._w.original_name,
                    ns.score,
                    self._w._weight,
                )
        return status


class _FilterScoreRecorder(_FilterRecorder, _ScoreRecorder):
    pass


def _filter_capable(p: Any) -> bool:
    return callable(getattr(p, "filter", None))


def _score_capable(p: Any) -> bool:
    return callable(getattr(p, "score", None))


def make_simulator_plugin(inner: Any, store: Store, weight: int = 1) -> Any:
    """Wrap one plugin with the recorders matching its capabilities
    (the reference composes fake/real plugins the same way,
    plugins_test.go:981-1042)."""
    f, s = _filter_capable(inner), _score_capable(inner)
    cls = (
        _FilterScoreRecorder
        if f and s
        else _FilterRecorder if f else _ScoreRecorder if s else _Base
    )
    return cls(inner, store, weight)


def wrap_chains(
    filter_plugins: List[Any],
    score_plugins: List[Any],
    store: Store,
    weights: Optional[dict] = None,
) -> Tuple[List[Any], List[Any]]:
    """Wrap instantiated plugin chains (shared instances stay shared —
    a plugin serving filter+score gets ONE wrapper, like the reference's
    singleton factories, plugins.go:24-70)."""
    weights = weights or {}
    cache: dict = {}

    def wrap(p: Any) -> Any:
        if id(p) not in cache:
            cache[id(p)] = make_simulator_plugin(p, store, weights.get(p.name(), 1))
        return cache[id(p)]

    return [wrap(p) for p in filter_plugins], [wrap(p) for p in score_plugins]


def register_simulator_plugins(store: Store, weights: Optional[dict] = None) -> None:
    """NewRegistry (plugins.go:24-70): register a ``<name>ForSimulator``
    factory for every known plugin, wrapping the original factory."""
    from minisched_tpu.plugins import registry

    weights = weights or {}
    registry._ensure_builtins()
    for name in registry.registered_names():
        if name.endswith(SUFFIX):
            continue
        original = registry._REGISTRY[name]

        def factory(args, ts, _orig=original, _name=name):
            return make_simulator_plugin(
                _orig(args, ts), store, weights.get(_name, 1)
            )

        registry.register(plugin_name(name), factory)


def convert_for_simulator(plugin_set: PluginSet) -> PluginSet:
    """ConvertForSimulator (plugins.go:146-202): every enabled plugin is
    replaced by its ``<name>ForSimulator`` wrapped version and all default
    plugins are disabled (wildcard)."""
    return PluginSet(
        enabled=[
            PluginEnabled(plugin_name(e.name), e.weight) for e in plugin_set.enabled
        ],
        disabled=["*"],
    )


def convert_configuration_for_simulator(cfg: SchedulerConfig) -> SchedulerConfig:
    """convertConfigurationForSimulator (scheduler/scheduler.go:97-142):
    accepts only plugin enablement + args from the given config and swaps
    filter/score plugin sets for simulator-wrapped ones."""
    out = cfg.clone()
    out.filter = convert_for_simulator(cfg.filter)
    out.score = convert_for_simulator(cfg.score)
    return out
