"""NodeNumber — the pedagogical multi-extension-point plugin.

Re-creates ``minisched/plugins/score/nodenumber/nodenumber.go:22-124``:
favors nodes whose trailing digit equals the pod name's trailing digit
(score 10 vs 0, :73-95), and delays binding of the chosen pod by
{node suffix} seconds through the Permit "Wait" protocol with a 10s timeout
(:102-119).  Single-digit suffixes only (:21).

Faithful behavior notes:
* ``PreScore`` succeeds without writing state when the pod has no digit
  suffix (:50-56); ``Score`` then errors on the missing state (:74-77) —
  the reference's real (if surprising) semantics, kept for parity.
* ``time_scale`` compresses the permit delays for tests (1.0 = reference
  timing); it scales both the per-node allow delay and the 10s timeout.

Batch form: the pre-score state becomes a per-pod suffix column; the score
matrix is one vectorized compare.  The permit delay stays host-side — wall
clock delays are control-plane behavior, not device math.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp

from minisched_tpu.engine.waitingpod import Handle
from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

NAME = "NodeNumber"
PRE_SCORE_STATE_KEY = "PreScore" + NAME
MATCH_SCORE = 10
PERMIT_TIMEOUT_S = 10.0


def _suffix_number(name: str) -> Optional[int]:
    if name and name[-1].isdigit():
        return int(name[-1])
    return None


class NodeNumber(Plugin, BatchEvaluable):
    def __init__(self, handle: Optional[Handle] = None, time_scale: float = 1.0):
        self.h = handle
        self.time_scale = time_scale

    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def pre_score(self, state: CycleState, pod: Any, nodes: List[Any]) -> Status:
        num = _suffix_number(pod.metadata.name)
        if num is None:
            return Status.success()  # success even without a digit suffix
        state.write(PRE_SCORE_STATE_KEY, num)
        return Status.success()

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        try:
            podnum = state.read(PRE_SCORE_STATE_KEY)
        except KeyError as e:
            # reference errors when PreScore wrote nothing (:74-77)
            return 0, Status.from_error(e).with_plugin(NAME)
        nodenum = _suffix_number(node_name)
        if nodenum is None:
            return 0, Status.success()
        if podnum == nodenum:
            return MATCH_SCORE, Status.success()
        return 0, Status.success()

    def score_extensions(self):
        return None

    def permit(self, state: CycleState, pod: Any, node_name: str) -> Tuple[Status, float]:
        nodenum = _suffix_number(node_name)
        if nodenum is None:
            return Status.success(), 0.0
        handle = self.h

        def _allow() -> None:
            wp = handle.get_waiting_pod(pod.metadata.uid) if handle else None
            if wp is not None:
                wp.allow(NAME)

        t = threading.Timer(nodenum * self.time_scale, _allow)
        t.daemon = True
        t.start()
        return Status.wait(), PERMIT_TIMEOUT_S * self.time_scale

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(GVK.NODE, ActionType.ADD)]

    # -- batch -------------------------------------------------------------
    def batch_pre_score(self, ctx: Any, pods: Any, nodes: Any) -> Dict[str, Any]:
        return {"pod_suffix": pods.suffix}

    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        pod_suffix = aux["pod_suffix"]  # (P,)
        match = (pod_suffix[:, None] == nodes.suffix[None, :]) & (
            pod_suffix[:, None] >= 0
        ) & (nodes.suffix[None, :] >= 0)
        return jnp.where(match, MATCH_SCORE, 0).astype(jnp.int32)

    def batch_permit_delays(self, node_suffix):
        """Per-node allow delay in seconds (host applies after placement)."""
        return jnp.where(node_suffix >= 0, node_suffix * self.time_scale, 0.0)
