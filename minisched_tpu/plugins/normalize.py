"""Shared min-max score normalization — scalar and batch forms.

Both cross-pod plugins rescale raw scores to [0, MAX_NODE_SCORE] with a
min-max over the feasible nodes; they differ only in direction (InterPod-
Affinity: higher raw is better; PodTopologySpread: fewer co-located
matches is better) and the all-equal fill value.  One implementation per
form keeps the two plugins' rounding identical.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from minisched_tpu.framework.types import MAX_NODE_SCORE, NodeScoreList


def minmax_normalize_scalar(
    scores: NodeScoreList, reverse: bool, fill: int
) -> None:
    """In-place min-max rescale of a NodeScoreList; all-equal → ``fill``."""
    if not scores:
        return
    lo = min(ns.score for ns in scores)
    hi = max(ns.score for ns in scores)
    for ns in scores:
        if hi == lo:
            ns.score = fill
        elif reverse:
            ns.score = MAX_NODE_SCORE * (hi - ns.score) // (hi - lo)
        else:
            ns.score = MAX_NODE_SCORE * (ns.score - lo) // (hi - lo)


def minmax_normalize_batch(scores: Any, mask: Any, reverse: bool, fill: int):
    """Mask-aware batch form: min/max taken over feasible nodes only;
    identical floor-division rounding to the scalar form."""
    big = jnp.iinfo(jnp.int32).max
    lo = jnp.min(jnp.where(mask, scores, big), axis=1, keepdims=True)
    hi = jnp.max(jnp.where(mask, scores, -big), axis=1, keepdims=True)
    spread = hi - lo
    if reverse:
        out = MAX_NODE_SCORE * (hi - scores) // jnp.maximum(spread, 1)
    else:
        out = MAX_NODE_SCORE * (scores - lo) // jnp.maximum(spread, 1)
    return jnp.where(spread > 0, out, fill).astype(jnp.int32)
