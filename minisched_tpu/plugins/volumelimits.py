"""Per-cloud volume attach limits: EBSLimits / GCEPDLimits / AzureDiskLimits.

The reference's default filter roster enumerates four volume-limit plugins
(scheduler/scheduler_test.go:315-318): EBSLimits, GCEPDLimits,
NodeVolumeLimits and AzureDiskLimits — upstream, each counts only volumes
of its own driver family against that family's per-node attach limit.
This module provides the shared counting core and the three per-cloud
plugins; the generic counter (``NodeVolumeLimits``, covering every volume
not claimed by a named cloud family — upstream's CSI path) lives in
plugins/volumebinding.py for import compatibility and subclasses the same
core.

A volume's family is the ``driver`` of the PV its claim is bound to
(api/objects.PVSpec.driver); unbound or unresolvable claims count as
generic.  Scalar forms resolve claims through the injected
``store_client`` (like VolumeBinding); with no client injected every
volume is generic — the pre-split behavior, kept so directly-constructed
``NodeVolumeLimits`` works without a control plane.  Batch forms read the
``pod_vols_fam`` / ``node_vols_fam`` planes of the wave's
ConstraintTables (models/constraints.py), where the same family
resolution ran host-side.

Default limits follow upstream v1.22's non-CSI defaults: EBS 39 (AWS
attach limit), GCE PD 16, Azure Disk 16, generic 16.
"""

from __future__ import annotations

from typing import Any, List, Optional

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

#: family axis of the pod_vols_fam/node_vols_fam constraint planes;
#: index 0 is the generic (non-cloud / CSI / unbound) family
FAMILIES = ("", "ebs", "gcepd", "azuredisk")
FAM_GENERIC, FAM_EBS, FAM_GCEPD, FAM_AZURE = range(len(FAMILIES))

REASON_LIMIT = "node(s) exceed max volume count"

DEFAULT_MAX_VOLUMES = 16  # generic / GCE PD / Azure Disk
DEFAULT_MAX_EBS = 39  # AWS attach limit


def volume_family(pvc: Optional[Any], pv_by_name: Any) -> int:
    """Family index of one claim: its bound PV's driver, else generic."""
    if pvc is None or not pvc.spec.volume_name:
        return FAM_GENERIC
    pv = pv_by_name.get(pvc.spec.volume_name)
    if pv is None:
        return FAM_GENERIC
    try:
        return FAMILIES.index(pv.spec.driver)
    except ValueError:
        return FAM_GENERIC


class VolumeLimitsCore(Plugin, BatchEvaluable):
    """Shared counting core: pod's family-f volumes + node's mounted
    family-f volumes must stay within ``max_volumes``."""

    reads_committed_state = True  # intra-wave commits change the verdict

    needs_extra = True
    #: class-level family index; also the repair loop's marker for
    #: volume-limit plugins (ops/repair.py reads it with max_volumes)
    volume_family_index = FAM_GENERIC
    #: the sequential scan carries the volume planes for this plugin
    scan_carried_planes = ("volumes",)

    def __init__(self, max_volumes: Optional[int] = None):
        self.max_volumes = (
            max_volumes if max_volumes is not None else self.default_max()
        )
        self.store_client = None  # injected by the service

    @classmethod
    def default_max(cls) -> int:
        return DEFAULT_MAX_VOLUMES

    # -- scalar ------------------------------------------------------------
    def _family_keys(self, pod: Any, store: Any, pv_by_name: Any):
        """(set of counting keys of this family the pod mounts, number of
        unresolvable mounts).  A counting key identifies a VOLUME — the
        bound PV, or the claim itself when unbound — so mounts sharing one
        volume count once (upstream counts unique volumes, not mounts);
        unresolvable mounts have no identity and count one each (generic
        family)."""
        f = self.volume_family_index
        if store is None:
            # no control plane: every volume is generic, keyed by claim name
            if f != FAM_GENERIC:
                return set(), 0
            return {(pod.metadata.namespace, v) for v in pod.spec.volumes}, 0
        keys = set()
        missing = 0
        for vol in pod.spec.volumes:
            try:
                pvc = store.get(
                    "PersistentVolumeClaim", pod.metadata.namespace, vol
                )
            except KeyError:
                missing += 1
                continue
            if volume_family(pvc, pv_by_name) != f:
                continue
            keys.add(
                ("pv", pvc.spec.volume_name)
                if pvc.spec.volume_name
                else ("pvc", f"{pod.metadata.namespace}/{vol}")
            )
        return keys, (missing if f == FAM_GENERIC else 0)

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        if not pod.spec.volumes:
            return Status.success()
        store = self.store_client.store if self.store_client is not None else None
        # one PV map per filter call, shared across the pod + node's pods
        pv_by_name = (
            {pv.metadata.name: pv for pv in store.list("PersistentVolume")}
            if store is not None
            else {}
        )
        pod_keys, pod_missing = self._family_keys(pod, store, pv_by_name)
        node_keys: set = set()
        node_missing = 0
        for p in node_info.pods:
            if not p.spec.volumes:
                continue
            k, m = self._family_keys(p, store, pv_by_name)
            node_keys |= k
            node_missing += m
        # only volumes NOT already attached to the node are new attachments
        new = len(pod_keys - node_keys) + pod_missing
        if new == 0:
            return Status.success()
        if len(node_keys) + node_missing + new > self.max_volumes:
            return Status.unschedulable(REASON_LIMIT).with_plugin(self.name())
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(GVK.POD, ActionType.DELETE)]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any, extra: Any):
        if extra is None:
            raise ValueError(
                f"{self.name()} batch kernel needs the wave's "
                "ConstraintTables — pass `extra`"
            )
        import jax.numpy as jnp

        f = self.volume_family_index
        V = extra.pod_claims.shape[1]
        in_range = jnp.arange(V)[None, :] < extra.pod_n_vols[:, None]
        valid = in_range & extra.pod_claim_valid  # (P, V)
        cnt = extra.claim_cnt[extra.pod_claims]  # (P, V) counting rows
        fam = extra.claim_family[extra.pod_claims]  # (P, V)
        use = valid & (fam == f)
        # mounts sharing one volume within the pod count once
        dup = jnp.any(
            (cnt[:, :, None] == cnt[:, None, :])
            & use[:, None, :]
            & (jnp.arange(V)[None, None, :] < jnp.arange(V)[None, :, None]),
            axis=2,
        )
        use = use & ~dup
        # a volume already attached to the node is not a NEW attachment
        attached = extra.vol_any[cnt]  # (P, V, N)
        new = jnp.sum(
            use[:, :, None] & ~attached, axis=1, dtype=jnp.int32
        )  # (P, N)
        if f == FAM_GENERIC:
            new = new + extra.pod_missing[:, None]
        fits = extra.node_vols_fam[f][None, :] + new <= self.max_volumes
        return (new == 0) | fits


class EBSLimits(VolumeLimitsCore):
    volume_family_index = FAM_EBS

    @classmethod
    def default_max(cls) -> int:
        return DEFAULT_MAX_EBS

    def name(self) -> str:
        return "EBSLimits"


class GCEPDLimits(VolumeLimitsCore):
    volume_family_index = FAM_GCEPD

    def name(self) -> str:
        return "GCEPDLimits"


class AzureDiskLimits(VolumeLimitsCore):
    volume_family_index = FAM_AZURE

    def name(self) -> str:
        return "AzureDiskLimits"
