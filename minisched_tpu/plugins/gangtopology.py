"""GangTopology — torus-locality scoring for gang members.

The device half of the gang subsystem (ISSUE 6 tentpole part 3): a score
plugin in the fused chain that pulls each gang member toward its
already-placed peers at zero marginal device cost — the gang aggregates
ride as six PodTable columns (models/tables.py), the node side is five
static columns, and the kernel is a handful of vector ops folded into
the one jitted evaluation.

Scoring rule (identical in the scalar and batch forms, pure ints):

* singleton pods (``gang_id == 0``) and sliceless nodes score 0 — with
  no gang specs present the plugin contributes an all-zero matrix, so
  placements are BIT-IDENTICAL to the chain without it (the parity rule
  the acceptance criteria pin).
* warm gang (placed members exist, ``gang_n > 0``):
  ``SLICE_BONUS`` for nodes on the gang's majority slice, plus a torus
  proximity term ``clamp(TORUS_MAX - dist, 0, TORUS_MAX)`` where
  ``dist`` is the RING distance to the placed centroid, computed
  scaled-by-n so the math stays integral: per axis, with the node's
  slice dimension ``D`` (NodeTable ``slice_dx/dy/dz`` — ISSUE 7
  satellite closing the ISSUE 6 wraparound follow-up),
  ``a = |x·n − Σx|``; ``ring = min(a mod n·D, n·D − a mod n·D)`` when
  ``D > 0``, else ``a`` (identity: dim-less nodes keep the exact
  non-wrapping Manhattan term, so placements without dims are
  bit-identical to the pre-wraparound scorer);
  ``dist = (ring_x + ring_y + ring_z) // n``.
* cold gang (no member placed yet): a deterministic hash preference
  ``mix32(gang_id, slice_hash) >> 27`` (0..31) — every member of one
  gang ranks slices identically, so even the first wave packs the gang
  toward one slice instead of scattering it.

Max raw score is SLICE_BONUS + TORUS_MAX = 96 < MAX_NODE_SCORE; no
normalization needed (identity extensions).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax.numpy as jnp

from minisched_tpu.api.objects import gang_key
from minisched_tpu.engine.tiebreak import mix32 as mix32_py
from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status
from minisched_tpu.models.tables import fnv1a32

NAME = "GangTopology"
PRE_SCORE_STATE_KEY = "PreScore" + NAME

#: same-slice bonus — dominates the proximity term so members pack onto
#: one slice before optimizing intra-slice distance
SLICE_BONUS = 64
#: proximity band: nodes further than this many torus hops from the
#: placed centroid score 0 on the proximity term
TORUS_MAX = 32
_M32 = 0xFFFFFFFF


def _ring_scaled(delta: int, n: int, dim: int) -> int:
    """Scaled-by-n ring distance along one torus axis: ``delta`` is
    ``x·n − Σ``, ``dim`` the axis's ring size (0 = unknown → the
    non-wrapping |delta| — the identity the parity rule pins).  Pure
    ints; min(r, m−r) is symmetric, so |delta| mod m and delta mod m
    give the same answer."""
    a = abs(delta)
    if dim <= 0:
        return a
    m = n * dim
    r = a % m
    return min(r, m - r)


def _score_one(
    gang_id: int, agg, slice_hash: int, x: int, y: int, z: int,
    dims: tuple = (0, 0, 0),
) -> int:
    """The shared scalar rule (see module docstring); ``agg`` is the
    gang aggregate tuple or None (cold), ``dims`` the node's slice
    torus dimensions (engine/gang.node_dims)."""
    if gang_id == 0 or slice_hash == 0:
        return 0
    if agg is None or agg[4] <= 0:
        return mix32_py(gang_id & _M32, slice_hash & _M32) >> 27
    maj, sx, sy, sz, n = agg
    score = SLICE_BONUS if (maj and slice_hash == maj) else 0
    dist = (
        _ring_scaled(x * n - sx, n, dims[0])
        + _ring_scaled(y * n - sy, n, dims[1])
        + _ring_scaled(z * n - sz, n, dims[2])
    ) // n
    prox = TORUS_MAX - dist
    if prox < 0:
        prox = 0
    elif prox > TORUS_MAX:
        prox = TORUS_MAX
    return score + prox


class GangTopology(Plugin, BatchEvaluable):
    """Score plugin (scalar + batch) — no filter half: locality is a
    preference, never a feasibility constraint (a gang that cannot fit
    on one slice must still place)."""

    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def pre_score(self, state: CycleState, pod: Any, nodes: List[Any]) -> Status:
        key = gang_key(pod)
        if key is None:
            return Status.success()
        from minisched_tpu.engine.gang import gang_view_from_infos

        try:
            node_infos = state.read("nodeinfos")
        except KeyError:
            return Status.success()  # snapshotless caller: cold-start rule
        view = gang_view_from_infos(node_infos, keys={key})
        state.write(PRE_SCORE_STATE_KEY, view.get(key))
        return Status.success()

    def score(
        self, state: CycleState, pod: Any, node_name: str
    ) -> Tuple[int, Status]:
        key = gang_key(pod)
        if key is None:
            return 0, Status.success()
        try:
            agg = state.read(PRE_SCORE_STATE_KEY)
        except KeyError:
            agg = None
        from minisched_tpu.engine.gang import node_dims, node_topo

        node = state.read("nodeinfo/" + node_name).node
        sh, x, y, z = node_topo(node)
        return (
            _score_one(fnv1a32(key), agg, sh, x, y, z, node_dims(node)),
            Status.success(),
        )

    def score_extensions(self):
        return None

    def events_to_register(self) -> List[ClusterEvent]:
        # a peer's bind (Pod UPDATE) changes the locality landscape; a
        # node join can open a slice
        return [
            ClusterEvent(GVK.POD, ActionType.UPDATE),
            ClusterEvent(GVK.NODE, ActionType.ADD),
        ]

    # -- batch -------------------------------------------------------------
    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        from minisched_tpu.ops.fused import mix32

        sh = nodes.slice_hash[None, :]  # i32[1, N]
        gid = pods.gang_id[:, None]  # i32[P, 1]
        n = pods.gang_n[:, None]
        nz = jnp.maximum(n, 1)
        # warm branch: slice bonus + torus proximity to the centroid
        match = (sh == pods.gang_slice[:, None]) & (
            pods.gang_slice[:, None] != 0
        )

        def ring(coord, ssum, dim):
            # scaled-by-n ring distance (== _ring_scaled): a mod m folded
            # to the shorter way around; dim 0 (unknown) keeps the
            # non-wrapping |a| — bit-identical to the pre-wraparound term
            a = jnp.abs(coord[None, :] * n - ssum[:, None])  # (P, N)
            m = jnp.maximum(nz * dim[None, :], 1)
            r = a % m
            return jnp.where(dim[None, :] > 0, jnp.minimum(r, m - r), a)

        dist = (
            ring(nodes.torus_x, pods.gang_sx, nodes.slice_dx)
            + ring(nodes.torus_y, pods.gang_sy, nodes.slice_dy)
            + ring(nodes.torus_z, pods.gang_sz, nodes.slice_dz)
        ) // nz
        prox = jnp.clip(TORUS_MAX - dist, 0, TORUS_MAX)
        warm = jnp.where(match, SLICE_BONUS, 0) + prox
        # cold branch: deterministic per-(gang, slice) hash preference —
        # int32 → uint32 wraps two's-complement, matching the scalar
        # ``& 0xFFFFFFFF``
        cold = (
            mix32(gid.astype(jnp.uint32), sh.astype(jnp.uint32))
            >> jnp.uint32(27)
        ).astype(jnp.int32)
        raw = jnp.where(n > 0, warm, cold)
        live = (gid != 0) & (sh != 0)
        return jnp.where(live, raw, 0).astype(jnp.int32)
