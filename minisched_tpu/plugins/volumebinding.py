"""Volume scheduling plugins: VolumeBinding filter + NodeVolumeLimits.

Re-creates the volume members of the reference's default filter roster
(scheduler/scheduler_test.go:307-332 enumerates VolumeBinding,
NodeVolumeLimits and friends; BASELINE's config 3 notes the volume-limit
plugins), against this framework's PV/PVC model:

* ``VolumeBinding`` — every PVC the pod mounts must exist (missing →
  unresolvable, upstream's "unbound immediate PersistentVolumeClaims");
  a BOUND claim restricts the pod to nodes carrying its PV's required
  node labels (volume node affinity); an UNBOUND claim needs some free
  PV of sufficient capacity whose labels the node satisfies (bindable).
* ``NodeVolumeLimits`` — the node's mounted-volume count (assigned pods'
  volumes) plus the pod's own must stay within ``max_volumes``
  (upstream's CSI attach limits, collapsed to one count).

Scalar forms read the PV/PVC store through an injected ``store_client``
(the service wires it, like the permit Handle).  Batch forms read the
volume planes of the wave's ConstraintTables: the per-claim node masks
are precomputed host-side (control-plane coupling), and the kernels are
gathers + comparisons.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status
from minisched_tpu.plugins.volumelimits import FAM_GENERIC, VolumeLimitsCore

BINDING_NAME = "VolumeBinding"
LIMITS_NAME = "NodeVolumeLimits"

REASON_UNBOUND = "pod has unbound immediate PersistentVolumeClaims"
REASON_CONFLICT = "node(s) had volume node affinity conflict"
REASON_NO_PV = "node(s) didn't find available persistent volumes to bind"


def _labels_ok(required: Dict[str, str], node: Any) -> bool:
    labels = node.metadata.labels
    return all(labels.get(k) == v for k, v in required.items())


def claim_node_mask(pvc: Any, pvs: Any, nodes: Any):
    """Which nodes can host a pod mounting ``pvc`` — the ONE definition of
    volume feasibility, shared by the scalar filter and the host-side
    constraint-table build (models/constraints.py) so the two paths cannot
    drift.  A claim bound to a missing PV yields all-False (the scalar
    filter reports it unresolvable; both paths leave the pod unschedulable).
    """
    if pvc.spec.volume_name:
        pv_by_name = {pv.metadata.name: pv for pv in pvs}
        pv = pv_by_name.get(pvc.spec.volume_name)
        if pv is None:
            return [False] * len(nodes)
        return [_labels_ok(pv.spec.required_node_labels, n) for n in nodes]
    free = [
        pv
        for pv in pvs
        if not pv.spec.claim_ref and pv.spec.capacity >= pvc.spec.request
    ]
    return [
        any(_labels_ok(pv.spec.required_node_labels, n) for pv in free)
        for n in nodes
    ]


class VolumeBinding(Plugin, BatchEvaluable):
    needs_extra = True
    #: reads only bind-static planes (claim_mask/vol_ok) — the sequential
    #: scan carries nothing for it
    scan_carried_planes = ()

    def __init__(self):
        self.store_client = None  # injected by the service (like permit's h)

    def name(self) -> str:
        return BINDING_NAME

    # -- scalar ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        if not pod.spec.volumes:
            return Status.success()
        if self.store_client is None:
            return Status.error(f"{BINDING_NAME}: no store client injected")
        store = self.store_client.store
        node = node_info.node
        pvs = None  # fetched lazily: bound-only pods never list the PV store
        for vol in pod.spec.volumes:
            try:
                pvc = store.get(
                    "PersistentVolumeClaim", pod.metadata.namespace, vol
                )
            except KeyError:
                return Status.unresolvable(REASON_UNBOUND).with_plugin(BINDING_NAME)
            if pvc.spec.volume_name:
                try:
                    pv = store.get("PersistentVolume", "", pvc.spec.volume_name)
                except KeyError:
                    return Status.unresolvable(REASON_UNBOUND).with_plugin(
                        BINDING_NAME
                    )
                if not _labels_ok(pv.spec.required_node_labels, node):
                    return Status.unschedulable(REASON_CONFLICT).with_plugin(
                        BINDING_NAME
                    )
            else:
                if pvs is None:
                    pvs = store.list("PersistentVolume")
                bindable = any(
                    not pv.spec.claim_ref
                    and pv.spec.capacity >= pvc.spec.request
                    and _labels_ok(pv.spec.required_node_labels, node)
                    for pv in pvs
                )
                if not bindable:
                    return Status.unschedulable(REASON_NO_PV).with_plugin(
                        BINDING_NAME
                    )
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.PERSISTENT_VOLUME, ActionType.ADD | ActionType.UPDATE),
            ClusterEvent(
                GVK.PERSISTENT_VOLUME_CLAIM, ActionType.ADD | ActionType.UPDATE
            ),
            ClusterEvent(GVK.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any, extra: Any):
        if extra is None:
            raise ValueError(
                "VolumeBinding batch kernel needs the wave's ConstraintTables "
                "(built with pvcs/pvs) — pass `extra`"
            )
        in_range = (
            jnp.arange(extra.pod_claims.shape[1])[None, :]
            < extra.pod_n_vols[:, None]
        )  # (P, V)
        per_claim = extra.claim_mask[extra.pod_claims]  # (P, V, N)
        claims_ok = jnp.all(per_claim | ~in_range[:, :, None], axis=1)  # (P, N)
        return extra.vol_ok[:, None] & claims_ok


class NodeVolumeLimits(VolumeLimitsCore):
    """The generic volume counter (upstream's CSI limits path): counts
    every volume NOT bound to a named cloud family (EBS/GCEPD/AzureDisk
    have their own roster entries — plugins/volumelimits.py).  With no
    store client injected every volume is generic, which is the pre-split
    behavior."""

    volume_family_index = FAM_GENERIC

    def name(self) -> str:
        return LIMITS_NAME
