"""ImageLocality score: favor nodes that already cache the pod's images.

Re-creates the in-tree ``imagelocality`` plugin from the reference's
default roster (scheduler/scheduler_test.go:307-332; default weight 1).
Upstream formula, re-derived in integer MiB so oracle and kernel agree to
the bit:

    scaled(image)  = size_mb * nodes_with_image // total_nodes
    sum_scores(n)  = Σ_containers scaled(image)  where node n has the image
    score(n)       = clamp01((sum - 23*C) / (1000*C - 23*C)) * 100
                     (C = container count; thresholds 23Mi/1000Mi per
                      upstream's min/maxThreshold)

The spread factor (``nodes_with_image / total_nodes``) needs cross-node
aggregation: the scalar path computes it in PreScore over the node list;
the batch path reduces the has-image matrix over the node axis inside the
same fused kernel.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax.numpy as jnp

from minisched_tpu.framework.nodeinfo import MIB, NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, MAX_NODE_SCORE, Status

NAME = "ImageLocality"
STATE_KEY = "PreScore" + NAME

MIN_THRESHOLD_MB = 23
MAX_THRESHOLD_MB = 1000


def _priority(sum_scores: int, num_containers: int) -> int:
    lo = MIN_THRESHOLD_MB * num_containers
    hi = MAX_THRESHOLD_MB * num_containers
    if sum_scores < lo:
        return 0
    if sum_scores > hi:
        return MAX_NODE_SCORE
    return (sum_scores - lo) * MAX_NODE_SCORE // (hi - lo)


class ImageLocality(Plugin, BatchEvaluable):
    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def pre_score(self, state: CycleState, pod: Any, nodes: List[Any]) -> Status:
        """Aggregate image spread over the FULL node snapshot (upstream uses
        the shared lister, not the feasible list): image → (node count, size
        in MiB).  Size is the max advertised across nodes so both paths
        agree on one canonical size per image."""
        try:
            all_nodes = [ni.node for ni in state.read("nodeinfos")]
        except KeyError:
            all_nodes = nodes  # standalone use without the engine snapshot
        spread: Dict[str, Tuple[int, int]] = {}
        for node in all_nodes:
            for img, size in node.status.images.items():
                count, max_size = spread.get(img, (0, 0))
                spread[img] = (count + 1, max(max_size, size // MIB))
        state.write(STATE_KEY, (spread, len(all_nodes)))
        return Status.success()

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        try:
            spread, total_nodes = state.read(STATE_KEY)
        except KeyError as e:
            return 0, Status.from_error(e).with_plugin(NAME)
        ni: NodeInfo = state.read("nodeinfo/" + node_name)
        node_images = ni.node.status.images
        total = 0
        containers = pod.spec.containers
        for c in containers:
            if c.image and c.image in node_images:
                count, size_mb = spread[c.image]
                total += size_mb * count // max(total_nodes, 1)
        return _priority(total, len(containers)), Status.success()

    def score_extensions(self):
        return None

    # -- batch -------------------------------------------------------------
    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        img_in_range = (
            jnp.arange(nodes.image_key.shape[1])[None, :] < nodes.num_images[:, None]
        )  # (N, I)
        c_in_range = (
            jnp.arange(pods.image_key.shape[1])[None, :]
            < pods.num_containers[:, None]
        ) & (pods.image_key != 0)  # (P, C)
        # (P, C, N, I): node n's image slot i == pod p's container c's image
        eq = (
            pods.image_key[:, :, None, None] == nodes.image_key[None, None, :, :]
        ) & img_in_range[None, None, :, :]
        has = jnp.any(eq, axis=3) & c_in_range[:, :, None] & nodes.valid[None, None, :]
        # (P, C): canonical size (max across nodes) and node spread count
        size_at = jnp.max(
            jnp.sum(jnp.where(eq, nodes.image_size_mb[None, None, :, :], 0), axis=3),
            axis=2,
        )
        n_with = jnp.sum(has, axis=2)  # (P, C)
        total_nodes = jnp.maximum(jnp.sum(nodes.valid), 1)
        scaled = size_at * n_with // total_nodes  # (P, C)
        sums = jnp.sum(jnp.where(has, scaled[:, :, None], 0), axis=1)  # (P, N)
        lo = MIN_THRESHOLD_MB * pods.num_containers[:, None]
        hi = MAX_THRESHOLD_MB * pods.num_containers[:, None]
        score = (sums - lo) * MAX_NODE_SCORE // jnp.maximum(hi - lo, 1)
        score = jnp.where(sums < lo, 0, score)
        score = jnp.where(sums > hi, MAX_NODE_SCORE, score)
        return score.astype(jnp.int32)
