"""NodeAffinity plugin: nodeSelector + required/preferred node affinity.

Re-creates the in-tree ``nodeaffinity`` plugin from the reference's default
roster (scheduler/scheduler_test.go:307-332; default score weight 1):
Filter enforces ``spec.nodeSelector`` (AND over labels) and
``requiredDuringSchedulingIgnoredDuringExecution`` (OR over terms, AND over
match expressions); Score sums the weights of matching
``preferredDuringScheduling`` terms.

Batch form: expressions are encoded host-side into fixed-capacity operator/
operand arrays (models/tables.py: MAX_AFF_TERMS × MAX_AFF_REQS ×
MAX_AFF_VALS) and evaluated as pure broadcast-reduces against the node
LABEL PROFILES — all six selector operators (In/NotIn/Exists/DoesNotExist/
Gt/Lt) in one fused kernel, no per-object work at schedule time.  Nodes
dedupe to Dp distinct label signatures (node pools), so the unrolled
(P, terms, reqs, ·, L) expression machinery runs over Dp rows instead of
N nodes — ~N/Dp less VPU work and HBM traffic — and the verdict expands
to (P, N) with one gather through ``nodes.profile_id``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Tuple

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status
from minisched_tpu.models import tables

NAME = "NodeAffinity"


def node_affinity_eligible(pod: Any, node: Any) -> Tuple[bool, str]:
    """Does ``node`` pass the pod's spec.nodeSelector + required affinity?
    Returns (eligible, reason) — also used by PodTopologySpread's
    eligible-node gating (upstream requiredSchedulingTerm.Match)."""
    labels = node.metadata.labels
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False, "node(s) didn't match Pod's node selector"
    aff = pod.spec.affinity
    na = aff.node_affinity if aff is not None else None
    if na is not None and na.required_terms is not None:
        if not any(term.matches(labels) for term in na.required_terms):
            return False, "node(s) didn't match Pod's node affinity"
    return True, ""


class NodeAffinity(Plugin, BatchEvaluable):
    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status.unresolvable("node not found")
        ok, reason = node_affinity_eligible(pod, node)
        if not ok:
            return Status.unresolvable(reason).with_plugin(NAME)
        return Status.success()

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        ni: NodeInfo = state.read("nodeinfo/" + node_name)
        labels = ni.node.metadata.labels
        aff = pod.spec.affinity
        na = aff.node_affinity if aff is not None else None
        if na is None:
            return 0, Status.success()
        total = sum(
            p.weight for p in na.preferred if p.preference.matches(labels)
        )
        return total, Status.success()

    def score_extensions(self):
        return None

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL)
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any):
        return required_node_affinity_mask(pods, nodes)

    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        import jax

        P = pods.pref_key.shape[0]
        N = nodes.profile_id.shape[0]

        def compute(_):
            term_match = terms_match(
                (
                    pods.pref_key,
                    pods.pref_op,
                    pods.pref_vals,
                    pods.pref_nvals,
                    pods.pref_numval,
                    pods.pref_nreqs,
                ),
                nodes,
            )  # (P,T,Dp)
            T = pods.pref_key.shape[1]
            term_in_range = jnp.arange(T)[None, :] < pods.pref_nterms[:, None]
            weights = jnp.where(
                term_match & term_in_range[:, :, None],
                pods.pref_weight[:, :, None],
                0,
            )
            per_profile = jnp.sum(weights, axis=1).astype(jnp.int32)  # (P,Dp)
            return jnp.take(per_profile, nodes.profile_id, axis=1)

        # a wave with no preferred terms scores 0 everywhere — skip the
        # whole (P, T, R, Dp, L) term machinery
        return jax.lax.cond(
            jnp.any(pods.pref_nterms > 0),
            compute,
            lambda _: jnp.zeros((P, N), jnp.int32),
            None,
        )


# ---------------------------------------------------------------------------
# Module-level batch helpers — also used by PodTopologySpread's node
# eligibility (upstream computes spread domains only over nodes passing the
# pod's nodeSelector/required affinity)
# ---------------------------------------------------------------------------


def terms_match(prefix_arrays, nodes: Any):
    """Evaluate encoded NodeSelectorTerms against the node label PROFILES.

    prefix_arrays: (key, op, vals, nvals, numval, nreqs) with shapes
    (P,T,R), (P,T,R), (P,T,R,V), (P,T,R), (P,T,R), (P,T).
    Returns bool[P, T, Dp]: term t of pod p matches label profile d —
    expand to nodes with ``jnp.take(·, nodes.profile_id, axis=-1)``.
    """
    key, op, vals, nvals, numval, nreqs = prefix_arrays
    P, T, R = key.shape
    D, L = nodes.prof_label_key.shape
    # label lookup over (P,T,R,Dp,L), reduced immediately over L.  Label
    # keys are unique within a profile, so a masked sum *selects* the
    # value of the (at most one) slot matching the requirement's key —
    # keeping every intermediate at rank ≤ 5 with the smallest axes
    # innermost.
    lab_in_range = (
        jnp.arange(L)[None, :] < nodes.prof_num_labels[:, None]
    )  # (Dp,L)
    key_eq = key[:, :, :, None, None] == nodes.prof_label_key[None, None, None, :, :]
    present = key_eq & lab_in_range[None, None, None, :, :]  # (P,T,R,Dp,L)
    has_key = jnp.any(present, axis=4)  # (P,T,R,Dp)
    node_val = jnp.sum(
        jnp.where(present, nodes.prof_label_value[None, None, None, :, :], 0),
        axis=4,
    )  # (P,T,R,Dp) — the profile's value-hash for this key (0 if absent)
    num_ok = present & nodes.prof_label_num_ok[None, None, None, :, :]
    has_num = jnp.any(num_ok, axis=4)  # (P,T,R,Dp)
    node_num = jnp.sum(
        jnp.where(num_ok, nodes.prof_label_numval[None, None, None, :, :], 0),
        axis=4,
    )
    # value-set membership: profile's value ∈ operand set (V is tiny)
    v_in_range = jnp.arange(vals.shape[3])[None, None, None, :] < nvals[:, :, :, None]
    in_set = has_key & jnp.any(
        (node_val[:, :, :, :, None] == vals[:, :, :, None, :])
        & v_in_range[:, :, :, None, :],
        axis=4,
    )  # (P,T,R,Dp)
    num_gt = has_num & (node_num > numval[:, :, :, None])
    num_lt = has_num & (node_num < numval[:, :, :, None])
    op_b = op[:, :, :, None]
    req_ok = (
        ((op_b == tables.OP_IN) & in_set)
        | ((op_b == tables.OP_NOT_IN) & ~in_set)
        | ((op_b == tables.OP_EXISTS) & has_key)
        | ((op_b == tables.OP_DOES_NOT_EXIST) & ~has_key)
        | ((op_b == tables.OP_GT) & num_gt)
        | ((op_b == tables.OP_LT) & num_lt)
    )  # (P,T,R,Dp)
    req_in_range = (jnp.arange(R)[None, None, :] < nreqs[:, :, None])  # (P,T,R)
    term_match = jnp.all(req_ok | ~req_in_range[:, :, :, None], axis=2)  # (P,T,Dp)
    return term_match


def required_node_affinity_mask(pods: Any, nodes: Any):
    """bool[P, N]: node passes the pod's spec.nodeSelector AND required
    node affinity (the NodeAffinity filter predicate).

    Cost scales with what the wave actually carries: each nodeSelector
    slot and the whole required-affinity term machinery are behind
    ``lax.cond``, and everything runs per label PROFILE (Dp rows) with
    one (P, N) gather at the end — a wave of plain pods reduces to O(P)
    predicates.
    """
    import jax

    P = pods.sel_key.shape[0]
    D = nodes.prof_label_key.shape[0]
    S = pods.sel_key.shape[1]
    lab_in_range = (
        jnp.arange(nodes.prof_label_key.shape[1])[None, :]
        < nodes.prof_num_labels[:, None]
    )  # (Dp,L)

    def all_true(_):
        return jnp.ones((P, D), bool)

    def sel_slot(s, _):
        # spec.nodeSelector slot s: profile must carry the exact label pair
        ok = jnp.any(
            (pods.sel_key[:, s][:, None, None] == nodes.prof_label_key[None, :, :])
            & (
                pods.sel_value[:, s][:, None, None]
                == nodes.prof_label_value[None, :, :]
            )
            & lab_in_range[None, :, :],
            axis=2,
        )  # (P, Dp)
        return ok | (pods.num_sel <= s)[:, None]

    sel_ok = jnp.ones((P, D), bool)
    for s in range(S):
        sel_ok = sel_ok & jax.lax.cond(
            jnp.any(pods.num_sel > s), partial(sel_slot, s), all_true, None
        )

    def aff(_):
        # required affinity: OR over terms (no terms → pass)
        term_match = terms_match(
            (
                pods.aff_key,
                pods.aff_op,
                pods.aff_vals,
                pods.aff_nvals,
                pods.aff_numval,
                pods.aff_nreqs,
            ),
            nodes,
        )  # (P,T,Dp)
        T = pods.aff_key.shape[1]
        term_in_range = (
            jnp.arange(T)[None, :] < pods.aff_nterms[:, None]
        )  # (P,T)
        any_term = jnp.any(
            term_match & term_in_range[:, :, None], axis=1
        )  # (P,Dp)
        # a required affinity with an empty term list matches nothing —
        # any_term over zero in-range terms is already False, so gate only
        # on the requirement's *presence* (upstream MatchNodeSelectorTerms)
        return jnp.where(pods.aff_required[:, None], any_term, True)

    aff_ok = jax.lax.cond(jnp.any(pods.aff_required), aff, all_true, None)
    return jnp.take(sel_ok & aff_ok, nodes.profile_id, axis=1)  # (P, N)
