"""VolumeRestrictions filter: single-attach volumes can't share a node.

Member of the reference's default filter roster
(scheduler/scheduler_test.go:314).  Upstream semantics (v1.22
``volumerestrictions``): a pod conflicts with a node when another pod
already on that node mounts the same underlying disk, unless every mount
involved is read-only (the GCE-PD rule; EBS/AzureDisk forbid any
sharing — this framework applies the one permissive rule uniformly and
documents it so the scalar oracle and the kernel agree on ONE semantic).

In this framework's volume model the "same underlying disk" is two claims
bound to the same PersistentVolume, and the mount's access intent is the
claim's ``read_only`` flag (api/objects.PVCSpec.read_only).

Scalar form resolves claims through the injected ``store_client``; the
batch form derives per-claim conflicts from the ``vol_any``/``vol_rw``
per-volume mount planes of the wave's ConstraintTables: claim c conflicts
on node n iff some mount of its volume there is writable, or any mount
exists and c itself is writable.  The repair loop (ops/repair.py) carries
those planes across rounds, so conflicts with pods committed EARLIER IN
THE SAME WAVE are enforced too, not just assigned-pod ones.
"""

from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

NAME = "VolumeRestrictions"

REASON_CONFLICT = "node(s) had volume restrictions conflict"
REASON_UNBOUND = "pod has unbound immediate PersistentVolumeClaims"


def mounts_conflict(pvc: Any, other_pvc: Any) -> bool:
    """Two bound claims conflict iff they share a PV and either mount is
    writable — the ONE conflict rule, shared by the scalar filter and the
    host-side constraint-table build."""
    return (
        bool(pvc.spec.volume_name)
        and pvc.spec.volume_name == other_pvc.spec.volume_name
        and not (pvc.spec.read_only and other_pvc.spec.read_only)
    )


class VolumeRestrictions(Plugin, BatchEvaluable):

    reads_committed_state = True  # intra-wave commits change the verdict
    needs_extra = True
    #: the repair loop's marker (ops/repair.py): carry per-volume mount
    #: state across rounds and dedup same-round mounts
    enforces_volume_restrictions = True
    #: the sequential scan carries the volume planes for this plugin
    scan_carried_planes = ("volumes",)

    def __init__(self):
        self.store_client = None  # injected by the service

    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        if not pod.spec.volumes:
            return Status.success()
        if self.store_client is None:
            return Status.error(f"{NAME}: no store client injected")
        store = self.store_client.store

        def resolve(ns: str, vol: str):
            return store.get("PersistentVolumeClaim", ns, vol)

        for vol in pod.spec.volumes:
            try:
                pvc = resolve(pod.metadata.namespace, vol)
            except KeyError:
                return Status.unresolvable(REASON_UNBOUND).with_plugin(NAME)
            if not pvc.spec.volume_name:
                continue  # unbound: no disk identity yet
            for other in node_info.pods:
                for ovol in other.spec.volumes:
                    try:
                        opvc = resolve(other.metadata.namespace, ovol)
                    except KeyError:
                        continue
                    if mounts_conflict(pvc, opvc):
                        return Status.unschedulable(REASON_CONFLICT).with_plugin(
                            NAME
                        )
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.POD, ActionType.DELETE),
            ClusterEvent(
                GVK.PERSISTENT_VOLUME_CLAIM, ActionType.ADD | ActionType.UPDATE
            ),
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any, extra: Any):
        if extra is None:
            raise ValueError(
                "VolumeRestrictions batch kernel needs the wave's "
                "ConstraintTables — pass `extra`"
            )
        in_range = (
            jnp.arange(extra.pod_claims.shape[1])[None, :]
            < extra.pod_n_vols[:, None]
        )  # (P, V)
        # conflict of each referenced claim per node, from the volume planes
        cv = jnp.maximum(extra.claim_vol, 0)
        bound = extra.claim_vol >= 0
        conflict = bound[:, None] & (
            extra.vol_rw[cv]
            | (extra.vol_any[cv] & ~extra.claim_ro[:, None])
        )  # (C2, N)
        per_claim = conflict[extra.pod_claims]  # (P, V, N)
        ok = jnp.all(~per_claim | ~in_range[:, :, None], axis=1)  # (P, N)
        return extra.vol_ok[:, None] & ok
