"""InterPodAffinity: required/preferred pod (anti-)affinity, both directions.

Re-creates the in-tree ``interpodaffinity`` plugin from the reference's
default roster (scheduler/scheduler_test.go:307-332; default score weight
1) — the pod↔pod×node coupling plugin (BASELINE config 4).  Semantics
follow upstream v1.22:

* Filter rejects a node when (1) one of the pod's required anti-affinity
  terms has a matching assigned pod in the node's topology domain, (2) an
  *assigned* pod's required anti-affinity term matches the incoming pod
  and the node shares that pod's topology domain (the reverse direction),
  or (3) a required affinity term is unsatisfied — no matching pod in the
  domain, except the bootstrap case: the pod matches its own term selector
  and NO pod matches cluster-wide, in which case any node carrying the
  topology key qualifies.
* Score sums weight × (matching pods in the node's domain) over the pod's
  preferred terms (anti-affinity terms contribute negative weight), PLUS
  the symmetric direction: every *assigned* pod's preferred affinity
  (+w) / anti-affinity (−w) terms and required affinity terms (at
  ``HARD_POD_AFFINITY_WEIGHT``) score toward an incoming pod matching
  them, over the assigned pod's topology domain.  The total then min-max
  normalizes to [0, 100].

Batch form (models/constraints.py): gathers of ``combo_dsum`` rows, one
bool matmul for the reverse required-anti direction, and one int matmul
(``pod_matches_combo @ rev_weight``) for the symmetric scoring — all
MXU-shaped at scale.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import (
    CycleState,
    MAX_NODE_SCORE,
    NodeScoreList,
    Status,
)
from minisched_tpu.models.constraints import _matches, _term_namespaces
from minisched_tpu.plugins.normalize import (
    minmax_normalize_batch,
    minmax_normalize_scalar,
)

NAME = "InterPodAffinity"
PRE_FILTER_KEY = "PreFilter" + NAME
PRE_SCORE_KEY = "PreScore" + NAME

REASON_AFFINITY = "node(s) didn't match pod affinity rules"
REASON_ANTI = "node(s) didn't satisfy existing pods anti-affinity rules"


def _assigned_pods(node_infos: List[NodeInfo]) -> List[Any]:
    out = []
    for ni in node_infos:
        out.extend(ni.pods)
    return out


def _domain_counts(term, pod_ns: str, node_infos: List[NodeInfo]):
    """(counts per topo value, global count) of assigned pods matching the
    term's selector in the term's namespaces."""
    nss = _term_namespaces(term, pod_ns)
    counts: Dict[str, int] = {}
    total = 0
    for ni in node_infos:
        val = ni.node.metadata.labels.get(term.topology_key)
        for p in ni.pods:
            if _matches(term.label_selector, nss, p):
                total += 1
                if val is not None:
                    counts[val] = counts.get(val, 0) + 1
    return counts, total


class _Normalize:
    """Upstream interpodaffinity NormalizeScore: min-max to [0, 100]; all
    equal → 0."""

    def normalize_score(self, state: CycleState, pod: Any, scores: NodeScoreList) -> Status:
        minmax_normalize_scalar(scores, reverse=False, fill=0)
        return Status.success()


class InterPodAffinity(Plugin, BatchEvaluable):
    needs_extra = True
    #: which coupling planes the sequential scan must carry for this
    #: plugin (ops/sequential.py): the combo aggregates
    scan_carried_planes = ("combos",)

    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def pre_filter(
        self, state: CycleState, pod: Any, node_infos: List[NodeInfo]
    ) -> Status:
        ns = pod.metadata.namespace
        aff = pod.spec.affinity
        pa = aff.pod_affinity if aff is not None else None
        pan = aff.pod_anti_affinity if aff is not None else None

        aff_terms = []  # (term, counts, global, self_match)
        for term in pa.required if pa is not None else ():
            counts, total = _domain_counts(term, ns, node_infos)
            nss = _term_namespaces(term, ns)
            aff_terms.append(
                (term, counts, total, _matches(term.label_selector, nss, pod))
            )
        anti_terms = []  # (term, counts)
        for term in pan.required if pan is not None else ():
            counts, _ = _domain_counts(term, ns, node_infos)
            anti_terms.append((term, counts))

        # reverse direction: assigned pods' required anti-affinity terms
        # that match the incoming pod → forbidden (topo_key, value) pairs
        forbidden: set = set()
        for ni in node_infos:
            for q in ni.pods:
                qaff = q.spec.affinity
                qpan = qaff.pod_anti_affinity if qaff is not None else None
                for term in qpan.required if qpan is not None else ():
                    nss = _term_namespaces(term, q.metadata.namespace)
                    if not _matches(term.label_selector, nss, pod):
                        continue
                    val = ni.node.metadata.labels.get(term.topology_key)
                    if val is not None:
                        forbidden.add((term.topology_key, val))

        state.write(PRE_FILTER_KEY, (aff_terms, anti_terms, forbidden))
        return Status.success()

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        aff_terms, anti_terms, forbidden = state.read(PRE_FILTER_KEY)
        labels = node_info.node.metadata.labels
        for key, val in forbidden:
            if labels.get(key) == val:
                return Status.unresolvable(REASON_ANTI).with_plugin(NAME)
        for term, counts in anti_terms:
            val = labels.get(term.topology_key)
            if val is not None and counts.get(val, 0) > 0:
                return Status.unresolvable(REASON_ANTI).with_plugin(NAME)
        for term, counts, total, self_match in aff_terms:
            val = labels.get(term.topology_key)
            satisfied = val is not None and (
                counts.get(val, 0) > 0 or (total == 0 and self_match)
            )
            if not satisfied:
                return Status.unschedulable(REASON_AFFINITY).with_plugin(NAME)
        return Status.success()

    def pre_score(self, state: CycleState, pod: Any, nodes: List[Any]) -> Status:
        from minisched_tpu.models.constraints import rev_pref_terms_of

        ns = pod.metadata.namespace
        node_infos = state.read("nodeinfos")
        aff = pod.spec.affinity
        weighted = []  # (topo_key, counts, signed weight)
        if aff is not None and aff.pod_affinity is not None:
            for wt in aff.pod_affinity.preferred:
                counts, _ = _domain_counts(wt.term, ns, node_infos)
                weighted.append((wt.term.topology_key, counts, wt.weight))
        if aff is not None and aff.pod_anti_affinity is not None:
            for wt in aff.pod_anti_affinity.preferred:
                counts, _ = _domain_counts(wt.term, ns, node_infos)
                weighted.append((wt.term.topology_key, counts, -wt.weight))
        # symmetric direction: assigned pods' preferred/hard-affinity terms
        # that match THIS pod score over the assigned pod's topology domain
        sym: Dict[Tuple[str, str], int] = {}  # (topo_key, value) → Σ w
        for ni in node_infos:
            labels = ni.node.metadata.labels
            for q in ni.pods:
                for nss, sel, topo, w in rev_pref_terms_of(q):
                    if not _matches(sel, nss, pod):
                        continue
                    val = labels.get(topo)
                    if val is not None:
                        sym[(topo, val)] = sym.get((topo, val), 0) + w
        state.write(PRE_SCORE_KEY, (weighted, sym))
        return Status.success()

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        weighted, sym = state.read(PRE_SCORE_KEY)
        ni: NodeInfo = state.read("nodeinfo/" + node_name)
        labels = ni.node.metadata.labels
        total = 0
        for topo_key, counts, w in weighted:
            val = labels.get(topo_key)
            if val is not None:
                total += w * counts.get(val, 0)
        for (topo_key, val), w in sym.items():
            if labels.get(topo_key) == val:
                total += w
        return total, Status.success()

    def score_extensions(self):
        return _Normalize()

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.POD, ActionType.ALL),
            ClusterEvent(GVK.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any, extra: Any):
        if extra is None:
            raise ValueError(
                "InterPodAffinity batch kernels need the wave's "
                "ConstraintTables (models/constraints.py) — pass `extra`"
            )
        # reverse direction: one bool matmul over the existing-term axis
        rev = (
            jnp.einsum(
                "pt,tn->pn",
                extra.pod_matches_ex.astype(jnp.int32),
                extra.ex_domain.astype(jnp.int32),
            )
            > 0
        )  # (P, N)
        # same check against pods committed EARLIER IN THIS SCAN: the
        # sequential engine accumulates their anti-affinity domains into
        # combo_excl.  Statically all-False outside the scan — the matmul
        # only compiles when the scan context sets in_scan
        if getattr(ctx, "in_scan", False):
            rev = rev | (
                jnp.einsum(
                    "pc,cn->pn",
                    extra.pod_matches_combo.astype(jnp.int32),
                    extra.combo_excl.astype(jnp.int32),
                )
                > 0
            )

        # incoming required anti-affinity
        pan_in = (
            jnp.arange(extra.pan_combo.shape[1])[None, :] < extra.pan_n[:, None]
        )  # (P, A)
        pan_dsum = extra.combo_dsum[extra.pan_combo]  # (P, A, N)
        anti_viol = jnp.any((pan_dsum > 0) & pan_in[:, :, None], axis=1)

        # incoming required affinity (+ bootstrap special case)
        pa_in = (
            jnp.arange(extra.pa_combo.shape[1])[None, :] < extra.pa_n[:, None]
        )
        pa_dsum = extra.combo_dsum[extra.pa_combo]  # (P, A, N)
        pa_haskey = extra.combo_haskey[extra.pa_combo]
        pa_glob = extra.combo_global[extra.pa_combo]  # (P, A)
        bootstrap = (pa_glob == 0) & extra.pa_self  # (P, A)
        sat = (pa_dsum > 0) | (bootstrap[:, :, None] & pa_haskey)
        aff_ok = jnp.all(sat | ~pa_in[:, :, None], axis=1)

        return ~rev & ~anti_viol & aff_ok

    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any],
                    extra: Any):
        if extra is None:
            raise ValueError(
                "InterPodAffinity batch kernels need the wave's "
                "ConstraintTables (models/constraints.py) — pass `extra`"
            )
        in_range = (
            jnp.arange(extra.ppa_combo.shape[1])[None, :] < extra.ppa_n[:, None]
        )  # (P, W)
        dsum = extra.combo_dsum[extra.ppa_combo]  # (P, W, N)
        haskey = extra.combo_haskey[extra.ppa_combo]
        contrib = extra.ppa_w[:, :, None] * jnp.where(haskey, dsum, 0)
        incoming = jnp.sum(
            jnp.where(in_range[:, :, None], contrib, 0), axis=1
        )
        # symmetric direction: assigned (and scan-committed) pods' terms
        # scoring toward matching incoming pods — one int matmul over the
        # combo axis (rev_weight rows are zero for combos with no such
        # terms, so plain clusters add nothing)
        sym = jnp.einsum(
            "pc,cn->pn",
            extra.pod_matches_combo.astype(jnp.int32),
            extra.rev_weight,
        )
        return (incoming + sym).astype(jnp.int32)

    def batch_normalize(self, ctx: Any, scores, mask):
        return minmax_normalize_batch(scores, mask, reverse=False, fill=0)
