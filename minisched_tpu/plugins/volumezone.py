"""VolumeZone filter: a bound PV's zone/region labels must match the node.

Member of the reference's default filter roster
(scheduler/scheduler_test.go:320).  Upstream semantics (v1.22
``volumezone``): for every claim the pod mounts that is BOUND to a PV, any
zone/region topology label carried by the PV (set by the cloud provider)
must be matched exactly by the candidate node's labels; unbound claims are
skipped (VolumeBinding owns them), and a missing claim is unresolvable.

Scalar form resolves claims through the injected ``store_client``; the
batch form gathers the host-precomputed ``claim_zone_ok[C2, N]`` plane of
the wave's ConstraintTables (models/constraints.py) — the per-claim check
runs once per claim host-side, and the kernel is a gather + all-reduce
like VolumeBinding's.
"""

from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

NAME = "VolumeZone"

REASON_ZONE = "node(s) had no available volume zone"
REASON_UNBOUND = "pod has unbound immediate PersistentVolumeClaims"

#: the topology labels upstream treats as zonal (volume_zone.go's
#: topologyLabels): both the GA and the deprecated beta spellings
ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


def pv_zone_ok(pv: Any, node: Any) -> bool:
    """The ONE definition of PV↔node zone compatibility, shared by the
    scalar filter and the host-side constraint-table build."""
    labels = node.metadata.labels
    for key in ZONE_LABELS:
        want = pv.metadata.labels.get(key)
        if want is not None and labels.get(key) != want:
            return False
    return True


class VolumeZone(Plugin, BatchEvaluable):
    needs_extra = True
    #: reads only bind-static planes (claim_zone_ok) — the sequential scan
    #: carries nothing for it
    scan_carried_planes = ()

    def __init__(self):
        self.store_client = None  # injected by the service

    def name(self) -> str:
        return NAME

    # -- scalar ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        if not pod.spec.volumes:
            return Status.success()
        if self.store_client is None:
            return Status.error(f"{NAME}: no store client injected")
        store = self.store_client.store
        node = node_info.node
        for vol in pod.spec.volumes:
            try:
                pvc = store.get(
                    "PersistentVolumeClaim", pod.metadata.namespace, vol
                )
            except KeyError:
                return Status.unresolvable(REASON_UNBOUND).with_plugin(NAME)
            if not pvc.spec.volume_name:
                continue  # unbound: VolumeBinding's problem
            try:
                pv = store.get("PersistentVolume", "", pvc.spec.volume_name)
            except KeyError:
                return Status.unresolvable(REASON_UNBOUND).with_plugin(NAME)
            if not pv_zone_ok(pv, node):
                return Status.unschedulable(REASON_ZONE).with_plugin(NAME)
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.PERSISTENT_VOLUME, ActionType.ADD | ActionType.UPDATE),
            ClusterEvent(
                GVK.PERSISTENT_VOLUME_CLAIM, ActionType.ADD | ActionType.UPDATE
            ),
            ClusterEvent(GVK.NODE, ActionType.ADD | ActionType.UPDATE_NODE_LABEL),
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any, extra: Any):
        if extra is None:
            raise ValueError(
                "VolumeZone batch kernel needs the wave's ConstraintTables "
                "— pass `extra`"
            )
        in_range = (
            jnp.arange(extra.pod_claims.shape[1])[None, :]
            < extra.pod_n_vols[:, None]
        )  # (P, V)
        per_claim = extra.claim_zone_ok[extra.pod_claims]  # (P, V, N)
        ok = jnp.all(per_claim | ~in_range[:, :, None], axis=1)  # (P, N)
        return extra.vol_ok[:, None] & ok
