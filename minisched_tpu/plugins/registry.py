"""Plugin registry: name → factory, and config → plugin chains.

The role of the reference's registry + plugin wiring
(scheduler/plugin/plugins.go:24-70's NewRegistry and
minisched/initialize.go:80-138's create*Plugins): one factory per plugin
name, instantiated once per scheduler even when the plugin serves several
extension points (the reference shares its NodeNumber singleton the same
way, initialize.go:188-213).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

from minisched_tpu.framework.plugin import (
    implements_filter,
    implements_permit,
    implements_post_filter,
    implements_pre_score,
    implements_reserve,
    implements_score,
)
from minisched_tpu.service.config import SchedulerConfig

# factory signature: (args: dict, time_scale: float) -> plugin instance
Factory = Callable[[Dict[str, Any], float], Any]

_REGISTRY: Dict[str, Factory] = {}


def register(name: str, factory: Factory) -> None:
    _REGISTRY[name] = factory


def registered_names() -> List[str]:
    return sorted(_REGISTRY)


def _ensure_builtins() -> None:
    if "NodeUnschedulable" in _REGISTRY:
        return
    from minisched_tpu.plugins.imagelocality import ImageLocality
    from minisched_tpu.plugins.interpodaffinity import InterPodAffinity
    from minisched_tpu.plugins.nodeaffinity import NodeAffinity
    from minisched_tpu.plugins.podtopologyspread import PodTopologySpread
    from minisched_tpu.plugins.nodename import NodeName
    from minisched_tpu.plugins.nodenumber import NodeNumber
    from minisched_tpu.plugins.nodeports import NodePorts
    from minisched_tpu.plugins.noderesources import (
        NodeResourcesBalancedAllocation,
        NodeResourcesFit,
        NodeResourcesLeastAllocated,
    )
    from minisched_tpu.plugins.nodeunschedulable import NodeUnschedulable
    from minisched_tpu.plugins.tainttoleration import TaintToleration
    from minisched_tpu.plugins.volumebinding import NodeVolumeLimits, VolumeBinding

    register("NodeUnschedulable", lambda args, ts: NodeUnschedulable())
    register("NodeNumber", lambda args, ts: NodeNumber(time_scale=ts))
    register(
        "NodeResourcesFit",
        lambda args, ts: NodeResourcesFit(
            scoring_strategy=args.get("scoring_strategy", "LeastAllocated")
        ),
    )
    register(
        "NodeResourcesLeastAllocated",
        lambda args, ts: NodeResourcesLeastAllocated(),
    )
    register(
        "NodeResourcesBalancedAllocation",
        lambda args, ts: NodeResourcesBalancedAllocation(),
    )
    register("TaintToleration", lambda args, ts: TaintToleration())
    register("NodeAffinity", lambda args, ts: NodeAffinity())
    register("NodeName", lambda args, ts: NodeName())
    register("NodePorts", lambda args, ts: NodePorts())
    register("ImageLocality", lambda args, ts: ImageLocality())
    register("InterPodAffinity", lambda args, ts: InterPodAffinity())
    register("PodTopologySpread", lambda args, ts: PodTopologySpread())
    from minisched_tpu.plugins.volumelimits import (
        AzureDiskLimits,
        EBSLimits,
        GCEPDLimits,
    )
    from minisched_tpu.plugins.volumerestrictions import VolumeRestrictions
    from minisched_tpu.plugins.volumezone import VolumeZone

    from minisched_tpu.plugins.defaultpreemption import (
        DEFAULT_MIN_CANDIDATE_NODES_ABSOLUTE,
        DEFAULT_MIN_CANDIDATE_NODES_PERCENTAGE,
        DefaultPreemption,
    )

    register(
        "DefaultPreemption",
        lambda args, ts: DefaultPreemption(
            min_candidate_nodes_percentage=args.get(
                "min_candidate_nodes_percentage",
                DEFAULT_MIN_CANDIDATE_NODES_PERCENTAGE,
            ),
            min_candidate_nodes_absolute=args.get(
                "min_candidate_nodes_absolute",
                DEFAULT_MIN_CANDIDATE_NODES_ABSOLUTE,
            ),
        ),
    )
    from minisched_tpu.plugins.coscheduling import Coscheduling
    from minisched_tpu.plugins.gangtopology import GangTopology

    register("Coscheduling", lambda args, ts: Coscheduling(time_scale=ts))
    register("GangTopology", lambda args, ts: GangTopology())
    register("VolumeBinding", lambda args, ts: VolumeBinding())
    register("VolumeRestrictions", lambda args, ts: VolumeRestrictions())
    register("VolumeZone", lambda args, ts: VolumeZone())
    for _name, _cls in (
        ("NodeVolumeLimits", NodeVolumeLimits),
        ("EBSLimits", EBSLimits),
        ("GCEPDLimits", GCEPDLimits),
        ("AzureDiskLimits", AzureDiskLimits),
    ):
        register(
            _name,
            lambda args, ts, _cls=_cls: _cls(
                max_volumes=args.get("max_volumes")
            ),
        )


@dataclass
class PluginChains:
    filter: List[Any] = field(default_factory=list)
    post_filter: List[Any] = field(default_factory=list)
    pre_score: List[Any] = field(default_factory=list)
    score: List[Any] = field(default_factory=list)
    reserve: List[Any] = field(default_factory=list)
    permit: List[Any] = field(default_factory=list)
    #: instances that need the waitingpod Handle injected (attribute ``h``)
    needs_handle: List[Any] = field(default_factory=list)
    #: instances that need the control-plane client injected (attribute
    #: ``store_client`` — volume plugins read the PV/PVC store)
    needs_client: List[Any] = field(default_factory=list)

    def all_instances(self) -> List[Any]:
        seen: Dict[int, Any] = {}
        for chain in (self.filter, self.post_filter, self.pre_score,
                      self.score, self.reserve, self.permit):
            for p in chain:
                seen[id(p)] = p
        return list(seen.values())


_CAPABILITY_CHECKS = {
    "filter": implements_filter,
    "post_filter": implements_post_filter,
    "pre_score": implements_pre_score,
    "score": implements_score,
    "reserve": implements_reserve,
    "permit": implements_permit,
}


def build_plugins(cfg: SchedulerConfig) -> PluginChains:
    _ensure_builtins()
    chains = PluginChains()
    instances: Dict[str, Any] = {}
    for point, plugin_set in cfg.extension_points().items():
        for entry in plugin_set.enabled:
            if entry.name not in _REGISTRY:
                raise KeyError(
                    f"unknown plugin {entry.name!r}; registered: {registered_names()}"
                )
            if entry.name not in instances:
                args = cfg.plugin_args.get(entry.name, {})
                instances[entry.name] = _REGISTRY[entry.name](args, cfg.time_scale)
            inst = instances[entry.name]
            if not _CAPABILITY_CHECKS[point](inst):
                hint = (
                    " (reserve plugins must define both reserve() and "
                    "unreserve())"
                    if point == "reserve"
                    else ""
                )
                raise TypeError(
                    f"plugin {entry.name!r} does not implement {point}{hint}"
                )
            getattr(chains, point).append(inst)
    for inst in instances.values():
        if hasattr(inst, "h"):
            chains.needs_handle.append(inst)
        if hasattr(inst, "store_client"):
            chains.needs_client.append(inst)
    return chains


def canonical_filter_reasons() -> dict:
    """Plugin name → the canonical rejection message its scalar filter
    emits — the ``reasons`` mapping for batch result ingestion
    (observability.resultstore.record_batch_result), so wave-path
    annotations carry the same human-readable strings scalar cycles do.
    Imports the plugins' own REASON constants where one exists; plugins
    whose scalar messages are per-case (resources, ports) get their
    upstream-flavored summary string."""
    from minisched_tpu.plugins.interpodaffinity import REASON_AFFINITY
    from minisched_tpu.plugins.nodeunschedulable import REASON as REASON_UNSCHED
    from minisched_tpu.plugins.podtopologyspread import REASON_SKEW
    from minisched_tpu.plugins.volumebinding import REASON_NO_PV
    from minisched_tpu.plugins.volumelimits import REASON_LIMIT
    from minisched_tpu.plugins.volumerestrictions import REASON_CONFLICT
    from minisched_tpu.plugins.volumezone import REASON_ZONE

    return {
        "NodeUnschedulable": REASON_UNSCHED,
        "NodeName": "node(s) didn't match the requested node name",
        "TaintToleration": "node(s) had taints that the pod didn't tolerate",
        "NodeAffinity": "node(s) didn't match Pod's node affinity/selector",
        "NodePorts": "node(s) didn't have free ports for the requested pod ports",
        "NodeResourcesFit": "node(s) didn't have enough resources",
        "VolumeRestrictions": REASON_CONFLICT,
        "EBSLimits": REASON_LIMIT,
        "GCEPDLimits": REASON_LIMIT,
        "NodeVolumeLimits": REASON_LIMIT,
        "AzureDiskLimits": REASON_LIMIT,
        "VolumeBinding": REASON_NO_PV,
        "VolumeZone": REASON_ZONE,
        "PodTopologySpread": REASON_SKEW,
        "InterPodAffinity": REASON_AFFINITY,
        "NodeNumber": "node(s) rejected by nodenumber",
    }
