"""Node-resources plugins: Fit filter + LeastAllocated / BalancedAllocation
scorers (BASELINE config 3).

Re-creates the in-tree ``noderesources`` plugins the reference's default
config enables (scheduler/defaultconfig/defaultconfig.go:10-33; rosters
enumerated in scheduler/scheduler_test.go:307-332): ``NodeResourcesFit``
(filter), ``NodeResourcesLeastAllocated`` and
``NodeResourcesBalancedAllocation`` (score), with upstream's
GetNonzeroRequests defaults (100m CPU / 200Mi memory) applied by the
scorers only.

Unit discipline (bit-exact oracle/kernel parity): all resource math is
int32 in (milli-CPU, MiB) — scalar and batch paths quantize identically.
BalancedAllocation's upstream float64 ``(1 - |cpuFrac - memFrac|) * 100``
is re-derived in scaled integers (fractions quantized to 1e-4) so CPU
oracle and TPU kernel agree to the bit; same floor-division rounding as
upstream's int64 math everywhere else.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import MIB, NodeInfo, non_zero_requests
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, MAX_NODE_SCORE, Status
from minisched_tpu.models import tables

FIT_NAME = "NodeResourcesFit"
LEAST_ALLOCATED_NAME = "NodeResourcesLeastAllocated"
BALANCED_ALLOCATION_NAME = "NodeResourcesBalancedAllocation"

# BalancedAllocation fraction quantum (1e-3).  Chosen so the int32 device
# math ``min(requested, 2*alloc) * FRAC_SCALE`` cannot overflow for any
# node up to ~1 TiB memory / ~1000 cores (2**31 / 1000 / 2 ≈ 1.07e6 MiB).
FRAC_SCALE = 1_000


def _nz_cpu(milli: int) -> int:
    return milli or tables.DEFAULT_NONZERO_CPU


def _nz_mem_mib(mib: int) -> int:
    return mib or tables.DEFAULT_NONZERO_MEM_MIB


class NodeResourcesFit(Plugin, BatchEvaluable):
    """Filter: pod's requests fit the node's remaining allocatable.
    Also a scorer: the reference's default score roster enables
    ``NodeResourcesFit`` at weight 1 with a ``ScoringStrategy`` of
    ``LeastAllocated`` (scheduler/plugin/plugins_test.go:352,839-848), so
    the Fit plugin delegates scoring to the strategy's scorer.

    Filter semantics (upstream): pod-count headroom always checked;
    per-resource checks only for resources the pod actually requests (a
    zero request fits even an overcommitted node).
    """

    reads_committed_state = True  # intra-wave commits change the verdict

    def __init__(self, scoring_strategy: str = "LeastAllocated"):
        if scoring_strategy != "LeastAllocated":
            raise ValueError(
                f"unsupported ScoringStrategy {scoring_strategy!r} "
                "(LeastAllocated only)"
            )
        self._scorer = NodeResourcesLeastAllocated()

    def name(self) -> str:
        return FIT_NAME

    # -- score (strategy delegation) ---------------------------------------
    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        return self._scorer.score(state, pod, node_name)

    def score_extensions(self):
        return self._scorer.score_extensions()

    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        return self._scorer.batch_score(ctx, pods, nodes, aux)

    # -- scalar ------------------------------------------------------------
    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status.unresolvable("node not found")
        alloc = node.status.allocatable
        reasons: List[str] = []
        if len(node_info.pods) + 1 > alloc.pods:
            reasons.append("Too many pods")
        req = pod.resource_requests()
        if req.milli_cpu > 0 and req.milli_cpu > alloc.milli_cpu - node_info.requested.milli_cpu:
            reasons.append("Insufficient cpu")
        req_mem = req.memory // MIB
        if req_mem > 0 and req_mem > alloc.memory // MIB - node_info.req_mem_mib:
            reasons.append("Insufficient memory")
        req_eph = req.ephemeral_storage // MIB
        if req_eph > 0 and req_eph > alloc.ephemeral_storage // MIB - node_info.req_eph_mib:
            reasons.append("Insufficient ephemeral-storage")
        if reasons:
            return Status.unschedulable(*reasons).with_plugin(FIT_NAME)
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [
            ClusterEvent(GVK.POD, ActionType.DELETE),
            ClusterEvent(
                GVK.NODE, ActionType.ADD | ActionType.UPDATE_NODE_ALLOCATABLE
            ),
        ]

    # -- batch -------------------------------------------------------------
    def batch_filter(self, ctx: Any, pods: Any, nodes: Any):
        pods_ok = (nodes.req_pods + 1)[None, :] <= nodes.alloc_pods[None, :]

        def fits(pod_req, node_req, node_alloc):
            remaining = (node_alloc - node_req)[None, :]
            r = pod_req[:, None]
            return (r == 0) | (r <= remaining)

        return (
            pods_ok
            & fits(pods.req_cpu, nodes.req_cpu, nodes.alloc_cpu)
            & fits(pods.req_mem, nodes.req_mem, nodes.alloc_mem)
            & fits(pods.req_eph, nodes.req_eph, nodes.alloc_eph)
        )


class NodeResourcesLeastAllocated(Plugin, BatchEvaluable):
    """Score: favor nodes with the most free cpu+memory after placement.

    Upstream formula per resource (equal weights cpu=1, mem=1):
    ``(allocatable - requested) * 100 / allocatable`` (0 if over-allocated),
    averaged — all in integer floor division.
    """

    reads_committed_state = True  # intra-wave commits change the verdict

    def name(self) -> str:
        return LEAST_ALLOCATED_NAME

    # -- scalar ------------------------------------------------------------
    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        ni: NodeInfo = state.read("nodeinfo/" + node_name)
        alloc = ni.node.status.allocatable
        nz = non_zero_requests(pod)
        cpu = self._least(
            ni.non_zero_requested.milli_cpu + _nz_cpu(nz.milli_cpu), alloc.milli_cpu
        )
        mem = self._least(
            ni.nzreq_mem_mib + _nz_mem_mib(nz.memory // MIB), alloc.memory // MIB
        )
        return (cpu + mem) // 2, Status.success()

    @staticmethod
    def _least(requested: int, allocatable: int) -> int:
        if allocatable <= 0 or requested > allocatable:
            return 0
        return (allocatable - requested) * MAX_NODE_SCORE // allocatable

    def score_extensions(self):
        return None

    # -- batch -------------------------------------------------------------
    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        def least(pod_nz, node_nz, alloc):
            requested = pod_nz[:, None] + node_nz[None, :]
            a = alloc[None, :]
            score = jnp.where(a > 0, (a - requested) * MAX_NODE_SCORE // jnp.maximum(a, 1), 0)
            return jnp.where((a <= 0) | (requested > a), 0, score)

        pod_cpu = jnp.where(pods.req_cpu == 0, tables.DEFAULT_NONZERO_CPU, pods.req_cpu)
        pod_mem = jnp.where(pods.req_mem == 0, tables.DEFAULT_NONZERO_MEM_MIB, pods.req_mem)
        cpu = least(pod_cpu, nodes.nzreq_cpu, nodes.alloc_cpu)
        mem = least(pod_mem, nodes.nzreq_mem, nodes.alloc_mem)
        return ((cpu + mem) // 2).astype(jnp.int32)


class NodeResourcesBalancedAllocation(Plugin, BatchEvaluable):
    """Score: favor nodes where cpu and memory utilization stay balanced.

    Upstream: ``(1 - |cpuFraction - memFraction|) * 100`` with fractions of
    allocatable after placement, 0 if either fraction >= 1.  Fractions are
    quantized to 1e-4 (FRAC_SCALE) so the formula is pure int math.
    """

    reads_committed_state = True  # intra-wave commits change the verdict

    def name(self) -> str:
        return BALANCED_ALLOCATION_NAME

    # -- scalar ------------------------------------------------------------
    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        ni: NodeInfo = state.read("nodeinfo/" + node_name)
        alloc = ni.node.status.allocatable
        nz = non_zero_requests(pod)
        cpu_frac = self._frac(
            ni.non_zero_requested.milli_cpu + _nz_cpu(nz.milli_cpu), alloc.milli_cpu
        )
        mem_frac = self._frac(
            ni.nzreq_mem_mib + _nz_mem_mib(nz.memory // MIB), alloc.memory // MIB
        )
        if cpu_frac >= FRAC_SCALE or mem_frac >= FRAC_SCALE:
            return 0, Status.success()
        diff = abs(cpu_frac - mem_frac)
        return (FRAC_SCALE - diff) * MAX_NODE_SCORE // FRAC_SCALE, Status.success()

    @staticmethod
    def _frac(requested: int, allocatable: int) -> int:
        if allocatable <= 0:
            return FRAC_SCALE  # treat as saturated
        # clamp before scaling: any requested >= allocatable saturates the
        # score to 0 anyway, and the clamp keeps the device-side int32
        # multiply in range — scalar mirrors it exactly for parity
        return min(requested, 2 * allocatable) * FRAC_SCALE // allocatable

    def score_extensions(self):
        return None

    # -- batch -------------------------------------------------------------
    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        def frac(pod_nz, node_nz, alloc):
            requested = pod_nz[:, None] + node_nz[None, :]
            a = alloc[None, :]
            requested = jnp.minimum(requested, 2 * a)  # see scalar _frac
            return jnp.where(
                a > 0, requested * FRAC_SCALE // jnp.maximum(a, 1), FRAC_SCALE
            )

        pod_cpu = jnp.where(pods.req_cpu == 0, tables.DEFAULT_NONZERO_CPU, pods.req_cpu)
        pod_mem = jnp.where(pods.req_mem == 0, tables.DEFAULT_NONZERO_MEM_MIB, pods.req_mem)
        cpu_frac = frac(pod_cpu, nodes.nzreq_cpu, nodes.alloc_cpu)
        mem_frac = frac(pod_mem, nodes.nzreq_mem, nodes.alloc_mem)
        diff = jnp.abs(cpu_frac - mem_frac)
        score = (FRAC_SCALE - diff) * MAX_NODE_SCORE // FRAC_SCALE
        saturated = (cpu_frac >= FRAC_SCALE) | (mem_frac >= FRAC_SCALE)
        return jnp.where(saturated, 0, score).astype(jnp.int32)
