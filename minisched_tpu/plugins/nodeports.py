"""NodePorts filter: reject nodes where a requested host port is taken.

Re-creates the in-tree ``nodeports`` plugin from the reference's default
roster (scheduler/scheduler_test.go:307-332): a pod asking for host ports
only fits nodes where none of those ports are claimed by assigned pods.

Batch form: the NodeTable carries the ports claimed by assigned pods
(models/tables.py ``used_port``); the check is a (P, N, ports, ports)
broadcast-reduce.
"""

from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

NAME = "NodePorts"


def _pod_ports(pod: Any) -> List[int]:
    out: List[int] = []
    for c in pod.spec.containers:
        out.extend(c.ports)
    return out


class NodePorts(Plugin, BatchEvaluable):
    reads_committed_state = True  # intra-wave commits change the verdict

    def name(self) -> str:
        return NAME

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        wanted = _pod_ports(pod)
        if not wanted:
            return Status.success()
        in_use = set()
        for p in node_info.pods:
            in_use.update(_pod_ports(p))
        if any(port in in_use for port in wanted):
            return Status.unschedulable(
                "node(s) didn't have free ports for the requested pod ports"
            ).with_plugin(NAME)
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(GVK.POD, ActionType.DELETE)]

    def batch_filter(self, ctx: Any, pods: Any, nodes: Any):
        # slot-unrolled over the packed pod-port axis (ISSUE 7 satellite):
        # the old single expression broadcast a 4-D (P, N, Wp, Wn)
        # predicate before its reduce — with the port columns riding as
        # compile-time constants (the zero-elided packed schemas), XLA's
        # constant folder evaluated that whole broadcast at COMPILE time
        # and tripped the >2s slow-constant-folding alarm at bench scale.
        # Reducing per pod-port slot keeps every intermediate at
        # (P, N, Wn) — same boolean algebra (OR over slots ≡ any over the
        # slot axis), bit-identical masks, and the folded constants stay
        # small.  Wp is a static 8, so the unroll is fixed-size.
        want_in_range = (
            jnp.arange(pods.port.shape[1])[None, :] < pods.num_ports[:, None]
        )  # (P, Wp)
        used_in_range = (
            jnp.arange(nodes.used_port.shape[1])[None, :]
            < nodes.num_used_ports[:, None]
        )  # (N, Wn)
        P = pods.port.shape[0]
        N = nodes.used_port.shape[0]
        clash = jnp.zeros((P, N), bool)
        for j in range(pods.port.shape[1]):
            hit = jnp.any(
                (pods.port[:, j][:, None, None] == nodes.used_port[None, :, :])
                & used_in_range[None, :, :],
                axis=2,
            )  # (P, N)
            clash = clash | (want_in_range[:, j][:, None] & hit)
        return ~clash
