"""NodePorts filter: reject nodes where a requested host port is taken.

Re-creates the in-tree ``nodeports`` plugin from the reference's default
roster (scheduler/scheduler_test.go:307-332): a pod asking for host ports
only fits nodes where none of those ports are claimed by assigned pods.

Batch form: the NodeTable carries the ports claimed by assigned pods
(models/tables.py ``used_port``); the check is a (P, N, ports, ports)
broadcast-reduce.
"""

from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

NAME = "NodePorts"


def _pod_ports(pod: Any) -> List[int]:
    out: List[int] = []
    for c in pod.spec.containers:
        out.extend(c.ports)
    return out


class NodePorts(Plugin, BatchEvaluable):
    reads_committed_state = True  # intra-wave commits change the verdict

    def name(self) -> str:
        return NAME

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        wanted = _pod_ports(pod)
        if not wanted:
            return Status.success()
        in_use = set()
        for p in node_info.pods:
            in_use.update(_pod_ports(p))
        if any(port in in_use for port in wanted):
            return Status.unschedulable(
                "node(s) didn't have free ports for the requested pod ports"
            ).with_plugin(NAME)
        return Status.success()

    def events_to_register(self) -> List[ClusterEvent]:
        return [ClusterEvent(GVK.POD, ActionType.DELETE)]

    def batch_filter(self, ctx: Any, pods: Any, nodes: Any):
        want_in_range = (
            jnp.arange(pods.port.shape[1])[None, :] < pods.num_ports[:, None]
        )  # (P, Wp)
        used_in_range = (
            jnp.arange(nodes.used_port.shape[1])[None, :]
            < nodes.num_used_ports[:, None]
        )  # (N, Wn)
        clash = (
            (pods.port[:, None, :, None] == nodes.used_port[None, :, None, :])
            & want_in_range[:, None, :, None]
            & used_in_range[None, :, None, :]
        )  # (P, N, Wp, Wn)
        return ~jnp.any(clash, axis=(2, 3))
