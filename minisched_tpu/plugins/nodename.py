"""NodeName filter: pods pinned via ``spec.nodeName`` only fit that node.

Re-creates the in-tree ``nodename`` plugin from the reference's default
roster (scheduler/scheduler_test.go:307-332).  Batch form: one hash
comparison against the node-name column.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import BatchEvaluable, Plugin
from minisched_tpu.framework.types import CycleState, Status

NAME = "NodeName"


class NodeName(Plugin, BatchEvaluable):
    def name(self) -> str:
        return NAME

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status.unresolvable("node not found")
        if pod.spec.node_name and pod.spec.node_name != node.metadata.name:
            return Status.unresolvable(
                "node(s) didn't match the requested node name"
            ).with_plugin(NAME)
        return Status.success()

    def batch_filter(self, ctx: Any, pods: Any, nodes: Any):
        pinned = pods.spec_node_name != 0
        match = pods.spec_node_name[:, None] == nodes.name_hash[None, :]
        return jnp.where(pinned[:, None], match, True)
