"""Wire one scheduler engine into the HA plane.

``start_ha_engine`` composes the pieces: join the membership (lease CAS),
start a SchedulerService whose engine carries the membership's shard
filter (threaded through the event handlers' queue admission — see
engine/eventhandlers.py), attach the membership to the factory's Lease
informer, and register the **resync** callback that runs on every epoch
bump:

* adopt — every pending pod the new shard map gives us is (re)queued
  from the informer cache (``queue.add`` dedupes, so pods already queued
  cost a set lookup);
* shed — pending pods the map took away are dropped from our queue (the
  new owner admits them from its own cache);
* re-arbitrate — on a LOST member, a device engine's assume ledger is
  marked due immediately (the PR-1/PR-2 machinery: every assumption gets
  re-checked against the authoritative store), because the rebalance
  window is exactly when two engines can race a bind and the loser must
  release its assumed capacity promptly instead of waiting out the TTL.

Multiple HA engines run against ONE control plane either in-process
(N ``start_ha_engine`` calls over Clients sharing a store — the bench
``ha`` role) or over the wire (each engine a RemoteClient against the
REST façade; ha/proc.py runs them as killable child processes).
"""

from __future__ import annotations

import traceback
from typing import Any, Optional, Set, Tuple

from minisched_tpu.ha.membership import DEFAULT_TTL_S, Membership
from minisched_tpu.observability import counters
from minisched_tpu.service.service import SchedulerService


class HAEngine:
    """One engine + its membership, joined to the plane."""

    def __init__(
        self,
        service: SchedulerService,
        scheduler: Any,
        membership: Membership,
    ):
        self.service = service
        self.scheduler = scheduler
        self.membership = membership

    def stop(self) -> None:
        """Graceful departure: stop scheduling, then RELEASE the lease so
        peers adopt our shard immediately instead of waiting out the TTL."""
        self.membership.stop(release=True)
        self.service.close()

    def kill(self) -> None:
        """In-process crash simulation: the engine stops but the lease is
        ABANDONED — peers must detect the death by TTL expiry, exactly as
        with a SIGKILL'd process (which ha/proc.py provides for real)."""
        self.membership.stop(release=False)
        self.service.close()


def start_ha_engine(
    client: Any,
    engine_id: str,
    cfg: Any = None,
    ttl_s: float = DEFAULT_TTL_S,
    device_mode: bool = False,
    max_wave: int = 1024,
    device_mesh: Any = None,
    **start_kwargs: Any,
) -> HAEngine:
    """Join the plane and start one sharded engine over ``client``.

    Order matters: the lease is acquired BEFORE the engine starts (so the
    initial shard map includes us — an engine scheduling before joining
    would admit everything), and the shard filter is installed before the
    informers start (so the initial snapshot replay is already filtered;
    see SchedulerService.start_scheduler).

    ``device_mesh`` (device_mode only): the engine's wave evaluation then
    shards over the (pods × nodes) device mesh.  The two shardings are
    ORTHOGONAL axes (ISSUE 7): HA splits the POD POPULATION across
    engines by rendezvous hash (which pods an engine pops at all), the
    mesh splits each popped WAVE's compute across that engine's devices
    — composing them changes neither the shard map nor placement parity.
    None defers to the MINISCHED_MESH startup policy, like any engine.
    """
    membership = Membership(client, engine_id, ttl_s=ttl_s)
    membership.join()
    service = SchedulerService(client)
    sched = service.start_scheduler(
        cfg,
        device_mode=device_mode,
        max_wave=max_wave,
        device_mesh=device_mesh,
        shard_filter=membership.owns_pod,
        **start_kwargs,
    )
    membership.attach(service.informer_factory)

    pod_informer = service.informer_factory.informer_for("Pod")

    def resync(
        epoch: int,
        members: Tuple[str, ...],
        joined: Set[str],
        lost: Set[str],
    ) -> None:
        """Apply a new shard map to the queue (runs on the membership's
        heartbeat thread or the Lease informer's dispatch thread)."""
        adopted = 0
        shed = []
        for pod in pod_informer.lister():
            if pod.spec.node_name:
                continue  # bound: not schedulable work for anyone
            if membership.owns_pod(pod):
                # dedup: queued pods are a no-op.  requeue: an adopted
                # pod was already admitted on the dead peer — failover
                # must not re-gate it behind its tenant's quota hold
                sched.queue.add(pod, requeue=True)
                adopted += 1
            else:
                shed.append(pod)
        if shed:
            sched.queue.delete_many(shed)
        if lost:
            counters.inc("ha.shard_adopt")
            counters.inc("ha.shard_adopt_pods", adopted)
            # a lost member may have died with binds in flight; a device
            # engine re-arbitrates every assumption against the
            # authoritative store NOW (the same revalidation a reconnect
            # triggers) instead of waiting out the assume TTL
            revalidate = getattr(sched, "_revalidate_assume_ledger", None)
            if revalidate is not None:
                try:
                    revalidate()
                except Exception:
                    traceback.print_exc()

    membership.on_change.append(resync)
    # the engine may have started mid-churn (peers joining while our
    # informers synced): apply the current map once, unconditionally
    resync(membership.epoch, membership.members(), set(), set())
    membership.start()
    return HAEngine(service, sched, membership)
