"""Lease acquisition and renewal as 409-arbitrated compare-and-swap.

The Lease kind (api.objects.Lease) is just an object; what makes it a
LOCK is the protocol here: every write goes through the store's
``expected_rv`` precondition, so two contenders racing for the same lease
resolve exactly one winner — the loser's PUT lands a Conflict (the
apiserver's 409) and it backs off.  Works identically over the in-process
``ObjectStore`` and the REST-backed ``RemoteStore``: both raise
``store.Conflict`` on a stale ``expected_rv`` and ``KeyError`` on a
create of an existing name, which is the whole surface this module needs.

Expiry is reader-evaluated wall clock (``renew_time + ttl_s < now``) —
the store never reaps leases, matching the apiserver.  A takeover of an
expired lease is the same CAS: read the stale object, rewrite the holder,
PUT with the read's resource_version; if another survivor got there
first, Conflict, and the membership view converges through the watch
stream either way.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple

from minisched_tpu.api.objects import Lease, LeaseSpec, ObjectMeta
from minisched_tpu.controlplane.store import Conflict
from minisched_tpu.observability import counters

KIND_LEASE = "Lease"

#: the namespace HA coordination objects live in (kube parks coordination
#: leases in kube-system; ours get their own so scenario namespaces never
#: collide with the control plane's bookkeeping)
HA_NAMESPACE = "minisched-ha"


class LeaseLost(Exception):
    """A renewal found the lease held by someone else (our TTL ran out
    and a peer took over, or the object vanished).  The holder must stop
    trusting its membership and re-acquire."""


class LeaseManager:
    """Acquire/renew/release TTL'd leases against one store facade."""

    def __init__(
        self,
        client: Any,
        namespace: str = HA_NAMESPACE,
        clock=time.time,
    ):
        self._store = client.store
        self._ns = namespace
        self._clock = clock

    # -- reads --------------------------------------------------------------
    def get(self, name: str) -> Optional[Lease]:
        try:
            return self._store.get(KIND_LEASE, self._ns, name)
        except KeyError:
            return None

    def list(self) -> Tuple[List[Lease], int]:
        """All leases in the coordination namespace + the store rv the
        snapshot reflects (epoch-consistent — see store.list_with_rv)."""
        lw = getattr(self._store, "list_with_rv", None)
        if lw is not None:
            leases, rv = lw(KIND_LEASE)
        else:
            leases, rv = self._store.list(KIND_LEASE), 0
        return [l for l in leases if l.metadata.namespace == self._ns], rv

    # -- CAS writes ---------------------------------------------------------
    def acquire(self, name: str, holder: str, ttl_s: float) -> Optional[Lease]:
        """One acquisition attempt: create the lease, or take over an
        expired (or already-ours) one via ``expected_rv`` CAS.  Returns
        the stored Lease on success, None when a LIVE peer holds it or a
        racing contender won the CAS — the caller retries on its own
        cadence; this method never sleeps."""
        now = self._clock()
        fresh = Lease(
            metadata=ObjectMeta(name=name, namespace=self._ns),
            spec=LeaseSpec(
                holder=holder, ttl_s=float(ttl_s),
                acquire_time=now, renew_time=now,
            ),
        )
        try:
            out = self._store.create(KIND_LEASE, fresh)
            counters.inc("ha.lease_acquire")
            return out
        except KeyError:
            pass  # exists: maybe expired, maybe ours from a past life
        cur = self.get(name)
        if cur is None:
            return None  # deleted between create and get: retry later
        takeover = cur.spec.holder != holder
        if takeover and not cur.expired(now):
            return None  # live peer: no steal
        rv = cur.metadata.resource_version
        cur.spec.holder = holder
        cur.spec.ttl_s = float(ttl_s)
        cur.spec.acquire_time = now
        cur.spec.renew_time = now
        if takeover:
            cur.spec.transitions += 1
        try:
            out = self._store.update(KIND_LEASE, cur, expected_rv=rv)
        except (Conflict, KeyError):
            return None  # 409-arbitrated: another contender won (or gone)
        counters.inc("ha.lease_acquire")
        if takeover:
            counters.inc("ha.lease_takeover")
        return out

    def renew(self, lease: Lease, epoch: Optional[int] = None) -> Lease:
        """Heartbeat: bump ``renew_time`` (and the published epoch) via
        CAS on the lease we last wrote.  A Conflict means someone else
        wrote the object since — almost always a takeover after our TTL
        lapsed; re-read to distinguish:

        * holder is still us (our own earlier PUT whose response was
          lost — the remote client replays transport failures blindly):
          adopt the re-read object and retry the renewal once;
        * holder is a peer (or the lease vanished): raise LeaseLost.
        """
        holder = lease.spec.holder
        for attempt in range(2):
            now = self._clock()
            work = lease.clone()
            work.spec.renew_time = now
            if epoch is not None:
                work.spec.epoch = int(epoch)
            try:
                out = self._store.update(
                    KIND_LEASE, work,
                    expected_rv=lease.metadata.resource_version,
                )
                counters.inc("ha.lease_renew")
                return out
            except (Conflict, KeyError):
                cur = self.get(lease.metadata.name)
                if cur is None or cur.spec.holder != holder:
                    counters.inc("ha.lease_lost")
                    raise LeaseLost(
                        f"lease {lease.metadata.name!r} now held by "
                        f"{cur.spec.holder!r}" if cur is not None
                        else f"lease {lease.metadata.name!r} deleted"
                    )
                lease = cur  # our write, newer rv: retry the CAS once
        counters.inc("ha.lease_lost")
        raise LeaseLost(
            f"lease {lease.metadata.name!r}: renewal kept conflicting"
        )

    def release(self, name: str, holder: str) -> bool:
        """Graceful departure: delete the lease IF we still hold it (a
        racing takeover keeps its steal).  Peers see the DELETED event and
        rebalance immediately instead of waiting out the TTL."""
        cur = self.get(name)
        if cur is None or cur.spec.holder != holder:
            return False
        try:
            self._store.delete(KIND_LEASE, self._ns, name)
        except KeyError:
            return False
        counters.inc("ha.lease_release")
        return True

    def gc_expired(self, grace_factor: float = 10.0) -> int:
        """Delete leases dead for ``grace_factor × ttl`` — long-gone
        members' leases otherwise accrete forever.  Racing survivors both
        trying the delete is fine (the loser's KeyError is ignored); a
        comeback member just re-creates.  Returns how many were reaped."""
        leases, _rv = self.list()
        now = self._clock()
        reaped = 0
        for l in leases:
            if l.spec.renew_time + grace_factor * l.spec.ttl_s < now:
                try:
                    self._store.delete(
                        KIND_LEASE, self._ns, l.metadata.name
                    )
                    reaped += 1
                    counters.inc("ha.lease_gc")
                except KeyError:
                    pass
        return reaped
