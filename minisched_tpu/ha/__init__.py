"""HA scheduling plane: lease-based membership + sharded active-active engines.

One scheduler engine owning the whole cluster is the last single point of
failure left after the fault fabric (PR 1) and crash–restart recovery
(PR 2) hardened the control plane.  This package removes it: N engines
register TTL'd member **Leases** (api.objects.Lease — renewed via
``expected_rv`` CAS, so acquisition and takeover are 409-arbitrated), a
**Membership** layer derives a deterministic shard map (rendezvous hash of
pod uid over the live member set, versioned by a membership epoch), and a
shard filter threads through the engine's event handlers so each engine
only admits its shard's pods.  When a member's lease expires, survivors
observe it through the existing watch path, bump the epoch, and adopt the
orphaned shard — double-scheduling around the rebalance window is arbitrated
by the PR-2 primitives the engines already have (the bind subresource's
unset-node_name guard + per-entry ``expected_rv``), so no pod is ever bound
twice no matter how the shards flap.

    lease.py       CAS acquire / renew / release over any store facade
    membership.py  member registry, heartbeat, epochs, rendezvous shard map
    plane.py       wire an engine + membership into one HA participant
    proc.py        run an engine as a killable child process (chaos soaks)
"""

from minisched_tpu.ha.lease import LeaseLost, LeaseManager
from minisched_tpu.ha.membership import Membership, shard_owner
from minisched_tpu.ha.plane import HAEngine, start_ha_engine

__all__ = [
    "LeaseLost",
    "LeaseManager",
    "Membership",
    "shard_owner",
    "HAEngine",
    "start_ha_engine",
]
