"""Engines as killable child processes: real SIGKILL failover.

faults/proc.py kills the CONTROL PLANE; this module kills a SCHEDULER —
the other half of the HA story.  An :class:`EngineSupervisor` runs one HA
engine (ha/plane.start_ha_engine over a RemoteClient) in a fresh
``python -c`` child, SIGKILLs it on demand (no lease release, no queue
drain — the member just stops renewing), and the survivors must observe
the expiry through the watch path, bump their epochs, and adopt the
orphaned shard within the lease TTL.

Same process hygiene as the server supervisor: a fresh interpreter (the
parent's JAX runtime and threads never leak in), ``JAX_PLATFORMS=cpu``
by default (N scalar engines must not fight over one accelerator), a
parent-death watchdog so an aborted soak strands no children, and
readiness gated on OBSERVABLE state — the child's member lease appearing
live in the store, the engine-side analog of polling /healthz.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Optional


def _engine_child_main(
    base_url: str,
    engine_id: str,
    ttl_s: float = 2.0,
    device_mode: bool = False,
    max_wave: int = 64,
    parent_pid: Optional[int] = None,
    metrics_port: Optional[int] = None,
) -> None:
    """The child's whole life: join the plane over the wire, schedule,
    park until SIGKILL.  Runs in a fresh interpreter — import inside.

    ``metrics_port`` arms the sidecar telemetry listener
    (observability.metricsd): THIS engine process's histograms, counters
    and trace ring become scrapeable at ``/metrics`` / ``/debug/trace``
    — the engine has no façade of its own, so without the sidecar its
    telemetry dies with it."""
    from hashlib import blake2s

    from minisched_tpu.controlplane.remote import RemoteClient
    from minisched_tpu.ha.plane import start_ha_engine
    from minisched_tpu.service.config import default_full_roster_config

    if metrics_port is not None:
        from minisched_tpu.observability.metricsd import start_metrics_server

        start_metrics_server(port=metrics_port)

    # per-engine deterministic retry jitter (hash() is salted per process)
    seed = int.from_bytes(
        blake2s(engine_id.encode(), digest_size=4).digest(), "big"
    )
    client = RemoteClient(
        base_url, retries=10, backoff_initial_s=0.05, retry_seed=seed
    )
    start_ha_engine(
        client,
        engine_id,
        cfg=default_full_roster_config(),
        ttl_s=ttl_s,
        device_mode=device_mode,
        max_wave=max_wave,
    )
    if parent_pid:
        # orphan watchdog (see faults/proc.py: polling beats
        # PR_SET_PDEATHSIG-via-preexec_fn, which forces unsafe fork)
        def watchdog() -> None:
            while os.getppid() == parent_pid:
                time.sleep(0.5)
            os.kill(os.getpid(), signal.SIGKILL)

        threading.Thread(target=watchdog, daemon=True).start()
    threading.Event().wait()  # until SIGKILL — crashes don't say goodbye


_CHILD_CMD = (
    "import json, sys; "
    "from minisched_tpu.ha.proc import _engine_child_main; "
    "_engine_child_main(**json.loads(sys.argv[1]))"
)


class EngineSupervisor:
    """Run one HA scheduler engine as a killable child process."""

    def __init__(
        self,
        base_url: str,
        engine_id: str,
        ttl_s: float = 2.0,
        device_mode: bool = False,
        max_wave: int = 64,
        boot_timeout_s: float = 90.0,
        jax_platforms: str = "cpu",
        metrics_port: Optional[int] = None,
    ):
        self._base = base_url
        self.engine_id = engine_id
        self._ttl_s = ttl_s
        self._device_mode = device_mode
        self._max_wave = max_wave
        self._boot_timeout_s = boot_timeout_s
        self._jax_platforms = jax_platforms
        # metrics_port=0 asks for an ephemeral one picked NOW (the
        # parent must know the port to build metrics_url; the same port
        # is reused across restarts, like the server supervisor's)
        if metrics_port == 0:
            from minisched_tpu.faults.proc import _free_port

            metrics_port = _free_port()
        self._metrics_port = metrics_port
        self._proc: Any = None
        self.kills = 0

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    @property
    def metrics_url(self) -> Optional[str]:
        """Scrape URL of the child's telemetry sidecar, or None when the
        supervisor was built without ``metrics_port``."""
        if self._metrics_port is None:
            return None
        return f"http://127.0.0.1:{self._metrics_port}/metrics"

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def _lease_live(self) -> bool:
        """Is the child's member lease present and unexpired? — the
        readiness (and liveness) probe, read straight off the plane."""
        from minisched_tpu.controlplane.remote import RemoteStore
        from minisched_tpu.ha.lease import HA_NAMESPACE
        from minisched_tpu.ha.membership import MEMBER_PREFIX

        store = RemoteStore(self._base, retries=1, timeout_s=5.0)
        try:
            lease = store.get(
                "Lease", HA_NAMESPACE, MEMBER_PREFIX + self.engine_id
            )
        except Exception:
            return False
        return not lease.expired(time.time())

    def start(self) -> None:
        """Spawn the child and block until its member lease is live —
        the engine is then joined, synced, and scheduling its shard."""
        if self.alive():
            raise RuntimeError(f"engine {self.engine_id!r} already running")
        cfg = {
            "base_url": self._base,
            "engine_id": self.engine_id,
            "ttl_s": self._ttl_s,
            "device_mode": self._device_mode,
            "max_wave": self._max_wave,
            "parent_pid": os.getpid(),
            "metrics_port": self._metrics_port,
        }
        env = dict(os.environ)
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        if self._jax_platforms:
            env["JAX_PLATFORMS"] = self._jax_platforms
        self._proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD_CMD, json.dumps(cfg)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + self._boot_timeout_s
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise RuntimeError(
                    f"engine child {self.engine_id!r} died at boot "
                    f"(exitcode {self._proc.returncode})"
                )
            if self._lease_live():
                return
            time.sleep(0.1)
        raise RuntimeError(
            f"engine child {self.engine_id!r} never joined the plane "
            f"within {self._boot_timeout_s}s"
        )

    def kill(self) -> None:
        """SIGKILL — the lease stays behind, un-renewed; survivors must
        time it out and adopt the shard."""
        if self._proc is None:
            return
        if self._proc.poll() is None:
            self._proc.kill()
            self.kills += 1
        try:
            self._proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            pass
        self._proc = None

    def stop(self) -> None:
        self.kill()
