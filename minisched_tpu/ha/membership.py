"""Membership: who is scheduling, and which pods are whose.

Each engine joins the plane by acquiring a member lease
(``member-<id>``), then heartbeats it at ttl/3.  The live member set is
derived by READING the lease namespace — informer cache when attached
(the existing watch path, so renewals/joins/releases propagate as
events), a consistent ``list_with_rv`` otherwise — and filtering out
expired leases by wall clock.  Any change to the derived set bumps this
member's local **epoch** and fires the registered callbacks (the engine
wiring in plane.py adopts/sheds queue contents there).

The shard map is a **rendezvous (highest-random-weight) hash** of pod uid
over the sorted member ids: deterministic from the member set alone — two
engines that agree on WHO is alive agree on every pod's owner without any
coordination round — and minimal-churn by construction: removing one
member reassigns exactly that member's pods (each surviving member's
per-pod score is unchanged), so a failover moves only the orphaned shard.

Epoch semantics: the epoch is a LOCAL monotonic version of this member's
view (bumped once per observed membership change), published through the
lease on every renewal so external observers (tests, the bench ``ha``
role) can watch all survivors converge past a kill.  Correctness never
depends on epochs agreeing across members — placement conflicts during
the rebalance window are arbitrated by the store's bind preconditions —
the epoch only versions the map and gates "did everyone notice yet".
"""

from __future__ import annotations

import threading
import time
import traceback
from hashlib import blake2s
from typing import Any, Callable, List, Optional, Sequence, Set, Tuple

from minisched_tpu.ha.lease import HA_NAMESPACE, LeaseLost, LeaseManager
from minisched_tpu.observability import counters

#: default member-lease TTL: expiry (and thus worst-case orphaned-shard
#: detection) is bounded by this; renewal runs every ttl/3 so two missed
#: heartbeats still keep the lease alive
DEFAULT_TTL_S = 5.0

MEMBER_PREFIX = "member-"


def shard_owner(uid: str, members: Sequence[str]) -> Optional[str]:
    """Rendezvous hash: the member with the highest blake2s score for
    this uid owns it.  Pure function of (uid, member set) — identical
    across processes, minimal churn on membership change."""
    best: Optional[str] = None
    best_score = -1
    for m in members:
        score = int.from_bytes(
            blake2s(f"{m}|{uid}".encode(), digest_size=8).digest(), "big"
        )
        # deterministic tie-break on the smaller id (ties are a 2^-64
        # curiosity, but the map must still be a pure function)
        if score > best_score or (score == best_score and (best is None or m < best)):
            best, best_score = m, score
    return best


#: callback signature: (epoch, members, joined ids, lost ids)
ChangeCallback = Callable[[int, Tuple[str, ...], Set[str], Set[str]], None]


class Membership:
    """One engine's membership in the HA plane."""

    def __init__(
        self,
        client: Any,
        member_id: str,
        ttl_s: float = DEFAULT_TTL_S,
        namespace: str = HA_NAMESPACE,
        clock=time.time,
        heartbeat_interval_s: Optional[float] = None,
    ):
        self.member_id = member_id
        self.ttl_s = float(ttl_s)
        self._leases = LeaseManager(client, namespace=namespace, clock=clock)
        self._clock = clock
        self._interval = (
            heartbeat_interval_s
            if heartbeat_interval_s is not None
            else self.ttl_s / 3.0
        )
        self._mu = threading.Lock()
        self._members: Tuple[str, ...] = ()
        self._epoch = 0
        self._lease = None  # our member Lease (latest stored copy)
        self._informer: Any = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: fired (NOT under the membership lock) on every epoch bump;
        #: exceptions are contained — a consumer bug must not stop the
        #: heartbeat
        self.on_change: List[ChangeCallback] = []

    # -- introspection ------------------------------------------------------
    @property
    def lease_name(self) -> str:
        return MEMBER_PREFIX + self.member_id

    @property
    def epoch(self) -> int:
        with self._mu:
            return self._epoch

    def members(self) -> Tuple[str, ...]:
        with self._mu:
            return self._members

    def owns(self, uid: str) -> bool:
        """Does this member's shard contain ``uid``?  While our own lease
        write hasn't round-tripped through the view yet (join races the
        first recompute) we at least own our own shard — a plane of one."""
        with self._mu:
            members = self._members
        if self.member_id not in members:
            members = tuple(sorted((*members, self.member_id)))
        return shard_owner(uid, members) == self.member_id

    def owns_pod(self, pod: Any) -> bool:
        """The shard filter the engine wires (engine.Scheduler.shard_filter)."""
        return self.owns(pod.metadata.uid or pod.metadata.key)

    # -- lifecycle ----------------------------------------------------------
    def join(self, timeout_s: float = 30.0) -> None:
        """Acquire our member lease (CAS-arbitrated; a stale lease from a
        previous incarnation of this id is taken over once expired), then
        derive the initial member view."""
        deadline = time.monotonic() + timeout_s
        while True:
            got = self._leases.acquire(
                self.lease_name, self.member_id, self.ttl_s
            )
            if got is not None:
                self._lease = got
                break
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"member {self.member_id!r}: lease "
                    f"{self.lease_name!r} held by a live peer"
                )
            # a live holder under OUR name means a previous incarnation's
            # lease hasn't expired yet — wait out the TTL, not a spin
            time.sleep(min(0.2, self.ttl_s / 4.0))
        counters.inc("ha.member_join")
        self.recompute()

    def attach(self, informer_factory: Any) -> None:
        """Ride the existing watch path: lease events (renewals, joins,
        releases) trigger a recompute through the factory's Lease
        informer — so a peer's graceful release rebalances immediately,
        not at the next heartbeat tick."""
        from minisched_tpu.controlplane.informer import ResourceEventHandlers

        inf = informer_factory.informer_for("Lease")
        inf.add_event_handlers(
            ResourceEventHandlers(on_batch=lambda _events: self.recompute())
        )
        self._informer = inf

    def start(self) -> None:
        """Start the heartbeat thread: renew our lease, re-derive the
        member view (expiry is a CLOCK event — no watch event fires when
        a peer merely stops renewing, so the tick is what detects death),
        and GC long-dead leases."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run,
            name=f"ha-heartbeat-{self.member_id}",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.heartbeat_once()
            except Exception:  # the plane being unreachable is survivable
                traceback.print_exc()

    def heartbeat_once(self) -> None:
        lease = self._lease
        try:
            if lease is not None:
                self._lease = self._leases.renew(lease, epoch=self.epoch)
            else:
                self._lease = self._leases.acquire(
                    self.lease_name, self.member_id, self.ttl_s
                )
        except LeaseLost:
            # our TTL lapsed and a peer observed it; re-acquire (our own
            # expired lease is takeover-able) and let the epoch churn
            # settle through recompute
            self._lease = self._leases.acquire(
                self.lease_name, self.member_id, self.ttl_s
            )
        except Exception:
            # store unreachable: keep the old lease handle — the next
            # tick retries, and renew()'s re-read path absorbs the case
            # where this attempt actually landed server-side
            pass
        self.recompute()
        try:
            self._leases.gc_expired()
        except Exception:
            pass  # GC is housekeeping, never load-bearing

    def recompute(self) -> None:
        """Re-derive the live member set; on change, bump the epoch and
        fire callbacks.  Reads the informer cache when attached (the
        watch path), a consistent list otherwise."""
        try:
            # the informer is authoritative only once SYNCED: an
            # unsynced/relisting cache reads as empty, and an empty
            # member set would collapse owns() to a plane of one — this
            # engine would transiently adopt EVERY pod.  Until sync, the
            # epoch-consistent list is the view.
            if self._informer is not None and self._informer.wait_for_cache_sync(
                timeout=0
            ):
                leases = [
                    l
                    for l in self._informer.lister()
                    if l.metadata.namespace == self._leases._ns
                ]
            else:
                leases, _rv = self._leases.list()
        except Exception:
            return  # plane unreachable: keep the last view
        now = self._clock()
        live: Set[str] = set()
        expired_holders: Set[str] = set()
        for l in leases:
            if not l.metadata.name.startswith(MEMBER_PREFIX):
                continue  # non-member coordination lease
            holder = l.spec.holder or l.metadata.name[len(MEMBER_PREFIX):]
            if l.expired(now):
                expired_holders.add(holder)
            else:
                live.add(holder)
        new = tuple(sorted(live))
        with self._mu:
            if new == self._members:
                return
            old = self._members
            self._members = new
            self._epoch += 1
            epoch = self._epoch
        joined = set(new) - set(old)
        lost = set(old) - set(new)
        counters.inc("ha.epoch_bump")
        if lost:
            counters.inc("ha.member_lost", len(lost))
            # lost-with-a-stale-lease = died (TTL ran out); lost without
            # one = released gracefully — only the former is an "expiry"
            died = lost & expired_holders
            if died:
                counters.inc("ha.lease_expired", len(died))
        for cb in list(self.on_change):
            try:
                cb(epoch, new, joined, lost)
            except Exception:  # a consumer bug must not stop the heartbeat
                traceback.print_exc()

    def stop(self, release: bool = True) -> None:
        """Leave the plane.  ``release=True`` deletes our lease so peers
        rebalance immediately (graceful departure); ``release=False``
        abandons it — from every peer's perspective indistinguishable
        from a crash (the in-process kill switch for tests/bench)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self._interval))
            self._thread = None
        if release:
            try:
                self._leases.release(self.lease_name, self.member_id)
            except Exception:
                pass  # teardown with the plane down: peers time us out
