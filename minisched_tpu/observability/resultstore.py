"""Result store: per-decision scheduling results flushed to pod annotations.

Re-creates ``scheduler/plugin/resultstore/store.go`` — the reference's one
genuinely novel observability mechanism (SURVEY.md §5.5): a thread-safe
map of pod → node → plugin → {filter reason, raw score, final (normalized ×
weight) score}; on every pod Update event the pod's accumulated results are
JSON-serialized onto its own annotations (annotation.py keys) with an
exponential-backoff-retried update, then dropped from the store
(store.go:90-135) — "the scheduling framework doesn't have any phase to
hook scheduling finished" (store.go:60-61), so the pod's own update event
is the flush trigger.

TPU twist: ``record_batch_result`` ingests a fused-kernel
``PlacementResult`` produced with diagnostics (ops/fused.py), so the batch
path emits the SAME per-decision artifact as the scalar path — it doubles
as the parity-checking record (SURVEY.md §5.5).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence

from minisched_tpu.observability import annotation
from minisched_tpu.utils.retry import (
    RetryTimeoutError,
    retry_with_exponential_backoff,
)

PASSED_FILTER_MESSAGE = "passed"  # store.go's success marker
SUCCESS_MESSAGE = "success"


class Store:
    """store.go:24-69.  All three result kinds keyed [pod key][node][plugin]."""

    def __init__(self, client: Optional[Any] = None):
        self._mu = threading.Lock()
        self._filter: Dict[str, Dict[str, Dict[str, str]]] = {}
        self._score: Dict[str, Dict[str, Dict[str, int]]] = {}
        self._final: Dict[str, Dict[str, Dict[str, int]]] = {}
        self._client = client

    # ------------------------------------------------------------------
    # recording (store.go:171-229)
    # ------------------------------------------------------------------
    def add_filter_result(
        self, pod_key: str, node: str, plugin: str, reason: str
    ) -> None:
        with self._mu:
            self._filter.setdefault(pod_key, {}).setdefault(node, {})[plugin] = reason

    def add_score_result(
        self, pod_key: str, node: str, plugin: str, score: int
    ) -> None:
        with self._mu:
            self._score.setdefault(pod_key, {}).setdefault(node, {})[plugin] = int(
                score
            )

    def add_normalized_score_result(
        self, pod_key: str, node: str, plugin: str, score: int, weight: int = 1
    ) -> None:
        """Final score = normalized score × plugin weight (store.go:208-234)."""
        with self._mu:
            self._final.setdefault(pod_key, {}).setdefault(node, {})[plugin] = (
                int(score) * weight
            )

    # ------------------------------------------------------------------
    # reading / lifecycle
    # ------------------------------------------------------------------
    def get_data(self, pod_key: str):
        with self._mu:
            return (
                {n: dict(v) for n, v in self._filter.get(pod_key, {}).items()},
                {n: dict(v) for n, v in self._score.get(pod_key, {}).items()},
                {n: dict(v) for n, v in self._final.get(pod_key, {}).items()},
            )

    def has_data(self, pod_key: str) -> bool:
        with self._mu:
            return (
                pod_key in self._filter
                or pod_key in self._score
                or pod_key in self._final
            )

    def delete_data(self, pod_key: str) -> None:
        """store.go:134's DeleteData."""
        with self._mu:
            self._filter.pop(pod_key, None)
            self._score.pop(pod_key, None)
            self._final.pop(pod_key, None)

    def take_data(self, pod_key: str):
        """Atomically pop the pod's results (one lock hold) — the flush
        takes its snapshot out of the store FIRST so results recorded
        concurrently (a re-scheduling attempt racing the flush) are never
        silently discarded: they stay for the next flush trigger."""
        with self._mu:
            return (
                self._filter.pop(pod_key, {}),
                self._score.pop(pod_key, {}),
                self._final.pop(pod_key, {}),
            )

    # ------------------------------------------------------------------
    # annotation flush (store.go:90-168)
    # ------------------------------------------------------------------
    def add_scheduling_result_to_pod(self, old: Any, new: Any) -> None:
        """Pod-update handler: write the pod's accumulated results onto its
        annotations with retried updates, then drop them (store.go:90-135).
        Wire via ``informer_for("Pod").add_event_handlers(on_update=...)``.
        """
        if self._client is None:
            return
        pod_key = new.metadata.key
        if not self.has_data(pod_key):
            return
        # pop-then-flush: on retry exhaustion the snapshot is dropped (and
        # logged) rather than left behind — a persistently-failing pod must
        # not re-stall the informer dispatch thread on every later event
        filter_r, score_r, final_r = self.take_data(pod_key)

        def apply(pod: Any) -> Any:
            pod.metadata.annotations[annotation.FILTER_RESULT] = json.dumps(
                filter_r, sort_keys=True
            )
            pod.metadata.annotations[annotation.SCORE_RESULT] = json.dumps(
                score_r, sort_keys=True
            )
            pod.metadata.annotations[annotation.FINAL_SCORE_RESULT] = json.dumps(
                final_r, sort_keys=True
            )
            return pod

        def try_update() -> bool:
            # atomic read-modify-write: a plain get→clone→update here would
            # silently clobber a concurrent bind (last-writer-wins store)
            try:
                self._client.pods().mutate(
                    new.metadata.name, apply, new.metadata.namespace
                )
                return True
            except KeyError:
                return True  # pod gone; nothing to annotate
            except Exception:
                return False  # transient store error: retry (util/retry.go)

        try:
            retry_with_exponential_backoff(try_update)
        except RetryTimeoutError:
            import logging

            logging.getLogger(__name__).warning(
                "dropping scheduling results for %s: annotation flush "
                "retries exhausted",
                pod_key,
            )

    # ------------------------------------------------------------------
    # batch (TPU) ingestion
    # ------------------------------------------------------------------
    def record_batch_result(
        self,
        result: Any,
        pod_keys: Sequence[str],
        node_names: Sequence[str],
        filter_plugin_names: Sequence[str],
        score_plugin_names: Sequence[str],
        reasons: Optional[Dict[str, str]] = None,
    ) -> None:
        """Ingest a diagnostics-enabled fused evaluation (ops/fused.py
        ``PlacementResult`` with ``filter_masks``/``score_matrices``) so a
        wave's decisions carry the same per-plugin record as scalar cycles.

        ``reasons``: plugin name → rejection reason string (defaults to the
        plugin name itself).

        Cost note: the record is O(pods × nodes × plugins) of Python dict
        entries by design — the reference's artifact has the same shape
        (a full node map per pod, store.go:90-135).  Dicts are built
        outside the lock and installed with ONE lock hold per pod; at
        headline wave sizes (8k × 10k) record selectively, not every wave.
        """
        import numpy as np

        reasons = reasons or {}
        masks = (
            None
            if result.filter_masks is None
            else np.asarray(result.filter_masks)
        )
        finals = (
            None
            if result.score_matrices is None
            else np.asarray(result.score_matrices)
        )
        raws = (
            None
            if result.raw_score_matrices is None
            else np.asarray(result.raw_score_matrices)
        )
        for pi, pod_key in enumerate(pod_keys):
            filt: Dict[str, Dict[str, str]] = {}
            score: Dict[str, Dict[str, int]] = {}
            final: Dict[str, Dict[str, int]] = {}
            for ni, node in enumerate(node_names):
                if masks is not None:
                    filt[node] = {
                        plugin: (
                            PASSED_FILTER_MESSAGE
                            if masks[ki, pi, ni]
                            else reasons.get(plugin, plugin)
                        )
                        for ki, plugin in enumerate(filter_plugin_names)
                    }
                if raws is not None:
                    score[node] = {
                        plugin: int(raws[ki, pi, ni])
                        for ki, plugin in enumerate(score_plugin_names)
                    }
                if finals is not None:
                    final[node] = {
                        plugin: int(finals[ki, pi, ni])
                        for ki, plugin in enumerate(score_plugin_names)
                    }
            with self._mu:
                # merge per plugin — a wholesale node-map replace would drop
                # results another chain recorded for the same pod/node
                for target, data in (
                    (self._filter, filt),
                    (self._score, score),
                    (self._final, final),
                ):
                    if data:
                        pod_map = target.setdefault(pod_key, {})
                        for node, plugins in data.items():
                            pod_map.setdefault(node, {}).update(plugins)
