"""Flight-recorder scheduling traces: a bounded ring of per-pod spans.

The bench roles can tell you p99 time-to-bind; they cannot tell you what
ONE pod's scheduling life looked like — which wave popped it, whether the
build was skipped by the idle gate, whether commit-time re-arbitration
bounced it, what finally bound it.  This module records that story as
structured spans written through the queue and the engine:

    enqueue → pop → build/skip → evaluate → permit/gang-wait → re-arb
            → bind → ack

Each span is one flat dict: ``ts`` (wall clock), ``stage``, and —
when pod-scoped — ``pod`` (namespace/name key) + ``uid``; wave-scoped
spans carry ``wave`` (a per-engine monotonic wave id also stamped on the
pod spans of that wave) plus whatever the seam knows (mesh shards,
fallback/retry causes, node, status).  The ring is bounded (default 8192
spans, ``MINISCHED_TRACE_CAP``), so it is a flight recorder, not a log:
always on, O(1) per span, the last N things the scheduler did.

Consumers:

* ``/debug/trace`` on the REST façade (and the supervisors' child
  metrics listeners) dumps the ring as JSONL — the offline training feed
  the ROADMAP's learned-scoring item needs.
* ``flight_dump(reason)`` writes the ring to
  ``$MINISCHED_TRACE_DIR/trace-<reason>-<pid>-<n>.jsonl`` when that env
  var is set — called at wave park/error so a chaos soak's post-mortem
  artifact survives the process.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional


def _default_cap() -> int:
    try:
        return max(64, int(os.environ.get("MINISCHED_TRACE_CAP", "8192")))
    except ValueError:
        return 8192


def pod_key(pod: Any) -> str:
    """namespace/name — the join key across a pod's spans (uid rides
    alongside for identity across delete/re-create)."""
    try:
        return pod.metadata.key
    except AttributeError:
        return str(pod)


class TraceRing:
    """Bounded ring of span dicts.  One lock, append-only; the deque's
    maxlen does the eviction."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._mu = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = deque(
            maxlen=capacity or _default_cap()
        )
        self._dump_seq = 0

    def span(self, stage: str, **fields: Any) -> None:
        rec = {"ts": time.time(), "stage": stage}
        rec.update({k: v for k, v in fields.items() if v is not None})
        with self._mu:
            self._ring.append(rec)

    def span_pod(self, stage: str, pod: Any, **fields: Any) -> None:
        uid = None
        try:
            uid = pod.metadata.uid
        except AttributeError:
            pass
        self.span(stage, pod=pod_key(pod), uid=uid, **fields)

    def spans(
        self, pod: Optional[str] = None, stage: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        with self._mu:
            out = list(self._ring)
        if pod is not None:
            out = [s for s in out if s.get("pod") == pod]
        if stage is not None:
            out = [s for s in out if s.get("stage") == stage]
        return out

    def dump_jsonl(self) -> str:
        with self._mu:
            out = list(self._ring)
        return "".join(json.dumps(s, default=str) + "\n" for s in out)

    def flight_dump(self, reason: str) -> Optional[str]:
        """Write the ring to $MINISCHED_TRACE_DIR (no-op when unset —
        the ring stays scrapeable via /debug/trace either way).  Never
        raises: the flight recorder must not add a failure mode to the
        error path that triggered it."""
        d = os.environ.get("MINISCHED_TRACE_DIR")
        if not d:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            with self._mu:
                self._dump_seq += 1
                seq = self._dump_seq
            safe = "".join(
                ch if ch.isalnum() or ch in "-_" else "_" for ch in reason
            )
            path = os.path.join(
                d, f"trace-{safe}-{os.getpid()}-{seq}.jsonl"
            )
            with open(path, "w") as f:
                f.write(self.dump_jsonl())
            return path
        except OSError:
            return None

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()


GLOBAL = TraceRing()


def span(stage: str, **fields: Any) -> None:
    GLOBAL.span(stage, **fields)


def span_pod(stage: str, pod: Any, **fields: Any) -> None:
    GLOBAL.span_pod(stage, pod, **fields)


def spans(pod: Optional[str] = None, stage: Optional[str] = None):
    return GLOBAL.spans(pod=pod, stage=stage)


def dump_jsonl() -> str:
    return GLOBAL.dump_jsonl()


def flight_dump(reason: str) -> Optional[str]:
    return GLOBAL.flight_dump(reason)


def reset() -> None:
    GLOBAL.reset()
