"""Named monotonic counters: the recovery-side ledger of the fault story.

The fault fabric (minisched_tpu.faults) counts what was INJECTED; these
counters record what the system DID about it — remote retries, informer
reconnects, assume-lease expiries, failed bind batches.  A chaos soak
asserts both sides: faults fired, and every recovery path that should
have answered them actually ran.

One process-global registry (``GLOBAL``) keeps call sites one-liners —
``counters.inc("remote.retry")`` — without threading a handle through
every constructor; tests snapshot/reset around their scenario.
"""

from __future__ import annotations

import threading
from typing import Dict


class Counters:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._mu:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._mu:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._counts)

    def reset(self) -> None:
        with self._mu:
            self._counts.clear()


GLOBAL = Counters()


def inc(name: str, n: int = 1) -> None:
    GLOBAL.inc(name, n)


def get(name: str) -> int:
    return GLOBAL.get(name)


def snapshot() -> Dict[str, int]:
    return GLOBAL.snapshot()


def reset() -> None:
    GLOBAL.reset()
