"""Named monotonic counters: the recovery-side ledger of the fault story.

The fault fabric (minisched_tpu.faults) counts what was INJECTED; these
counters record what the system DID about it — remote retries, informer
reconnects, assume-lease expiries, failed bind batches.  A chaos soak
asserts both sides: faults fired, and every recovery path that should
have answered them actually ran.

One process-global registry (``GLOBAL``) keeps call sites one-liners —
``counters.inc("remote.retry")`` — without threading a handle through
every constructor; tests snapshot/reset around their scenario.

The HA plane (minisched_tpu.ha) records its lifecycle here under the
``ha.`` prefix — surfaced in the bench ``ha`` role's record:

    ha.lease_acquire / ha.lease_takeover / ha.lease_renew
        — member-lease CAS outcomes (takeover = an expired lease stolen)
    ha.lease_lost / ha.lease_expired / ha.lease_release / ha.lease_gc
        — a renewal losing its CAS; a peer observed dead by TTL; a
          graceful departure; long-dead lease reaping
    ha.member_join / ha.member_lost / ha.epoch_bump
        — membership-view changes (each member counts its OWN view, so N
          survivors observing one death add N to member_lost)
    ha.shard_adopt / ha.shard_adopt_pods
        — failover rebalances and how many orphaned pending pods the
          adopting engine re-admitted

The pipelined wave engine (engine/pipeline.py) records under
``wave_pipeline.``; its TIMERS (stall, build) live in the engine's
CycleMetrics, not here — counters are integers:

    wave_pipeline.waves
        — waves evaluated through the pipelined (overlapped) path
    wave_pipeline.build_fallback
        — batches the build worker handed back to the serial wave path
          (encode overflow, empty roster, priority bypass, build fault)
    wave_pipeline.rearb_requeued
        — pipelined winners rejected by commit-time re-arbitration
          (capacity taken by the overlapped previous wave) and requeued
    wave_pipeline.dirty_rows
        — node aggregate rows re-encoded incrementally (vs a full
          O(all nodes) fill per wave); the bench divides by waves
    wave_pipeline.zero_build_waves
        — pipelined waves whose node-table build was skipped WHOLESALE
          by the idle-wave gate (below); the churn bench's
          zero-build-wave ratio divides this by wave_pipeline.waves

The sustained-churn layer (ISSUE 8, DESIGN.md §22) records the
cheap-when-quiet story — surfaced in the bench ``churn`` role's record:

    wave_build.skipped
        — CachedNodeTableBuilder builds answered from the idle-wave
          reuse cache: empty dirty-set, unchanged cache epoch (or
          (name, rv) signature), same capacities, byte-equal
          assume-delta fingerprint → the previous tables returned
          wholesale, zero encode/fold/pack/transfer.  Counted at the
          builder, so serial and pipelined waves both land here.
    watch.fanout.encoded / watch.fanout.shared
        — HTTP watch streams serializing an event: first encode of the
          framed wire chunk (memoized on the WatchEvent the store fans
          out) vs. reuses by every other stream.  encoded staying
          O(events) while shared grows O(events × watchers) IS the
          shared-payload claim; the churn fanout microbench gates on it.
    watch.fanout.evicted_slow
        — watchers evicted because their queue exceeded the per-watch
          bound (DEFAULT_WATCH_QUEUE_EVENTS): the stream dies like a
          drop and the consumer recovers via resume/410→relist —
          degrade-the-laggard, never block-the-store-lock.
    watch.disconnects
        — watch streams whose client hung up mid-chunk (previously a
          silent exit); the handler prunes the registration immediately.
    queue.quota_held / queue.quota_admitted / queue.quota_gang_bypass
        — namespace-quota admission at the scheduling queue: arrivals
          parked in the per-namespace hold FIFO, holds promoted into
          freed slots (FIFO, deferred past a pop_batch so a tenant's
          share of one wave stays at its cap), and gang members
          admitted past the cap (an all-or-nothing gang is never split
          across the quota boundary).
    queue.quota_violation
        — tripwire, not a code path: a non-gang NEW arrival admitted
          past its namespace cap (requeues and gang bypass may exceed
          by contract; this may not).  Any nonzero value is an
          accounting bug; the churn bench fails on it.

The wire layer (ISSUE 9: the selector stream fanout in
controlplane/streamloop and the pooled keep-alive client in
controlplane/httppool) records under ``wire.`` — surfaced in the wire
bench records (``scheduler_over_http`` + ``wire_fanout``) alongside the
``watch.fanout.*`` family above:

    wire.streams_adopted
        — watch streams DETACHED from their handler thread into the
          selector loop after handshake + snapshot/resume replay (the
          thread returns to the pool: N watchers cost N sockets + ONE
          thread; MINISCHED_STREAMLOOP=0 keeps this at zero)
    wire.streams_active
        — gauge: streams the loop currently owns
    wire.evicted_outbuf
        — streams evicted because their per-socket out-buffer exceeded
          its byte bound: the SOCKET-level laggard (the kernel refused
          the bytes), distinct from the store-queue eviction counted by
          watch.fanout.evicted_slow.  Both die like a dropped stream
          and the consumer recovers via resume/410→relist; the wire
          bench requires the recovery to be exactly-once.
    wire.partial_writes
        — non-blocking sends the kernel truncated (backpressure
          evidence: the loop parked the remainder in the out-buffer)
    wire.keepalives
        — idle keepalive chunks written by the loop (same 0.5s cadence
          and bytes as the thread path)
    wire.pool_open / wire.pool_reuse
        — keep-alive client connections freshly opened vs checked out
          warm (reuse ≫ open is the pooled-transport claim; every
          RemoteStore/HTTPClient request rides one of these)
    wire.pool_stale_retry
        — requests replayed ONCE on a fresh connection after a REUSED
          socket turned out dead (the server closed it while idle —
          keep-alive timeout, injected http.500, restart); internal to
          the pool, never consumes the caller's backoff budget
    wire.relist_requests / wire.relist_bytes_shared
        — LIST verbs served by the REST façade, and the payload bytes
          answered from the COW read plane's memoized list cache
          (shared bytes streamed chunked, not re-encoded; ISSUE 14):
          bytes_shared / requests ≈ mean list size once the cache is
          warm, and the relist bench gates encodes ≪ requests
    store.list_cache.encodes / store.list_cache.hits
        — memoized list-payload cache outcomes keyed
          (kind, namespace, rv)-via-snapshot: a relist storm of N
          informers at one rv costs ONE encode (plus benign
          double-encode races) and N−1 hits; every snapshot swap
          invalidates wholesale by replacing the cache's owner

The multi-chip live wave engine (ISSUE 7: DeviceScheduler over a
jax.sharding.Mesh, parallel/sharding.MeshPackedCaller) records under
``wave_mesh.`` — surfaced in the bench ``mesh`` child and the c5
``wave_breakdown`` block:

    wave_mesh.pod_shards / wave_mesh.node_shards
        — the mesh factoring the engine acquired at startup (set once
          per engine construction; 2×4 on an 8-device host)
    wave_mesh.waves
        — repair waves evaluated SHARDED over the mesh (the tentpole
          path; a mesh engine whose count stays 0 is running degraded)
    wave_mesh.fallbacks
        — waves re-dispatched on ONE device after a sharded-evaluate
          failure (the per-wave fallback ladder; later waves retry the
          mesh — repeated fallbacks mean the mesh is effectively dead)
    wave_mesh.pad_pod_rows / wave_mesh.pad_node_rows
        — table rows shipped beyond the live wave/roster (mesh-axis
          capacity alignment waste); the bench divides by waves

The durable layer (controlplane/durable + walio + fsck) records the
storage-integrity story under ``storage.`` — surfaced in the bench
``disk`` role's record:

    storage.degraded_enter / storage.degraded_recovered
        — ENOSPC/EIO latched the store read-only; a recovery probe
          re-armed writes (dwell time lives in
          DurableObjectStore.storage_stats, not here)
    storage.append_error / storage.recovery_probe
        — WAL appends that failed at the OS; probe attempts while
          degraded (each consults the disk.enospc schedule, so an
          injected episode has real dwell)
    storage.degraded_parks
        — engine waves/binds parked on a typed StorageDegraded instead
          of crashing (capacity released with the requeue)
    storage.remote_degraded_retry
        — HTTP 507 answers the remote client retried with backoff
    storage.event_dropped_degraded
        — volatile Events shed while the disk was full (best-effort)
    storage.wal_corrupt_detected / storage.wal_salvaged
        — replay found a bad frame (bit-flip / torn mid-file write);
          salvage truncated at it because the checkpoint covered the
          loss (refusals re-raise the typed WalCorrupt instead)
    storage.ckpt_digest_mismatch / storage.ckpt_unverified
        — sha256 sidecar convicted a checkpoint; a pre-integrity
          checkpoint restored without a sidecar
    storage.ckpt_fallback_prev / storage.ckpt_fallback_replay
        — the restore chain fell back to the previous generation / to
          full WAL+archive replay
    storage.scrub_runs / storage.scrub_findings
        — background integrity passes and what they found
    storage.bitflip_injected / storage.torn_injected /
    storage.ckpt_corrupt_injected
        — the fault fabric's lying-disk evidence (what was WRITTEN
          corrupt; the detection counters above are the other half)
    storage.group_commit.groups / storage.group_commit.records
        — commit-barrier turns the group-commit pipeline ran, and the
          mutations they carried; records/groups is the live
          coalescing ratio (1.0 = no concurrency, nothing batched)
    storage.group_commit.fsyncs_saved
        — fsyncs the barrier avoided versus the per-mutation path
          (len(group)−1 per fsync-armed group): the entire point of
          group commit, and the bench `wal` role's headline gate

The robustness layer (PR 1: retry.py, informer reconnects, assume
leases) records the recovery evidence the chaos soaks assert on:

    remote.retry / remote.conflict_retry
        — remote-store requests replayed after a transient transport
          error / after a CAS Conflict the caller asked to retry
    remote.bind_retry_dedup / remote.bind_ack_replayed
        — AlreadyBound-to-our-node answers converted to success after a
          retransmission (the first attempt committed before its socket
          died); the HTTPClient facade's mirror of the same dedup
    informer.reconnect / informer.resume / informer.relist_on_410 /
    informer.open_retry
        — watch streams re-opened after a drop, resumed from the last
          seen rv, relisted after the history floor answered 410, and
          initial opens retried at boot instead of crashing the service
    informer.relist_jitter_s
        — jitter SLEEPS taken before a 410-triggered relist (a count,
          not seconds: each is a fabric-deterministic draw from
          [0, MINISCHED_RELIST_JITTER_S)) — the spread that keeps a
          mass eviction from relisting on one tick
    assume.lease_confirmed / assume.lease_expired /
    assume.lease_renewed_bound / assume.lease_renewed_unreachable /
    assume.lease_requeued / assume.lease_probe_deferred /
    assume.revalidate_on_reconnect
        — assume-lease lifecycle: confirmations by observed bind,
          TTL expiries, renewals for already-bound pods, renewals
          granted while the plane was unreachable (never expire on a
          blind spot), capacity released + pod requeued on a lost bind,
          probes deferred while the plane was unreachable, and
          post-reconnect revalidation
    engine.bind_batch_failed
        — bind transactions that failed per-item instead of stranding
          their wave

TIMERS live next door: observability/hist.py holds the live latency
histograms (time-to-bind, wave phases, HTTP request latency, watch
delivery lag, WAL append/fsync) under the same global-registry
convention, rendered together with these counters by ``/metrics``.

The gang subsystem (plugins/coscheduling + engine/gang) records under
``gang.`` — surfaced in the bench ``gang`` role's record:

    gang.admitted
        — gangs whose members ALL held assume leases and were allowed
          through Permit together (the all-or-nothing invariant)
    gang.ttl_expired
        — gang TTLs that fired on a partial gang (every waiting member
          rejected, their assumes released)
    gang.ttl_requeued
        — members a TTL release sent back through the ACTIVE queue
          (prompt retry; no cluster event would wake them from the
          unschedulableQ)
    gang.rearb_atomic_release
        — pipelined gang members released WITH a sibling that lost
          commit-time re-arbitration (a gang is kept or released whole)
    gang.preempt_shielded
        — lower-priority gang-member pods DefaultPreemption excluded
          from a victim search (gang capacity is unpreemptable until
          whole-gang eviction lands — evicting one member would strand
          the rest as a partial gang; the churn bench audits this)

The replicated control plane (controlplane/repl + the quorum hook in
durable.py — DESIGN.md §27) records under ``storage.repl.`` — the
chaos-repl soak's replication evidence:

    storage.repl.groups / storage.repl.bytes
        — commit groups (and their WAL bytes) the leader registered
          with the replication hub at the group-commit barrier: the
          unit of shipping, acking, and digest gossip
    storage.repl.acks
        — follower durability acks the leader recorded (each a
          max-monotonic "my WAL is fsynced through offset N")
    storage.repl.quorum_timeouts
        — groups the barrier FAILED because a follower quorum never
          acked in time; the group's bytes are truncated off the
          leader's WAL and the stream epoch bumps (no divergence)
    storage.repl.streams / storage.repl.bytes_shipped
        — follower tail streams the leader served, and the framed WAL
          bytes shipped down them
    storage.repl.ship_errors
        — ship/ack paths broken by a dead socket or the ``repl.ship``/
          ``repl.ack`` fault points (the follower reconnects/re-acks)
    storage.repl.applied_groups / storage.repl.applied_records
        — groups (and the mutations inside) a follower applied through
          the real recovery path; byte-order == rv-order by invariant
    storage.repl.resyncs
        — followers that re-based on the leader after local state went
          suspect or obsolete (leader epoch moved, offset
          discontinuity, digest mismatch, checkpoint generation moved);
          each resolves as a ckpt_seed or a full_retail
    storage.repl.ckpt_seeds / storage.repl.full_retails
        — how each resync re-based: seeded from a shipped checkpoint
          generation (O(state) bootstrap, DESIGN.md §28) vs wiped and
          re-tailed the leader's FULL WAL from offset 0 (only legal
          against a leader that has never compacted)
    storage.repl.ckpt_published
        — checkpoint generations a LEADING store published at
          compaction (hub rebased: epoch bump, byte space restarted)
    storage.repl.ckpt_ships / storage.repl.ckpt_bytes
        — checkpoint generations served over GET /repl/checkpoint, and
          their body bytes (the bootstrap traffic that replaces
          unbounded history re-tails)
    storage.repl.stale_acks
        — follower acks dropped because they were tagged with a
          RETIRED stream epoch (pre-rebase/pre-retract byte offsets
          must never satisfy a quorum in the restarted space)
    storage.repl.digest_mismatch
        — cross-replica scrub gossip convicted a byte range whose
          CRC32C diverged from the leader's digest ring (bit rot or a
          forked history; the follower resyncs rather than serve it)
    storage.repl.fenced_writes
        — mutations a demoted ex-leader refused with typed NotLeader
          (the fence that makes split-brain writes impossible)
    storage.repl.not_leader_errors
        — remote-client requests answered 503 not-leader (re-discover
          the leader; never blind-retried)
    storage.repl.promotions
        — follower→leader promotions won via arbiter-majority lease CAS
    storage.repl.compact_deferred
        — retired (always 0 since checkpoint shipping landed): WAL
          compactions a leading replica used to skip while a hub was
          attached; kept registered so old dashboards read zero
          instead of breaking
    storage.repl.apply_lag_rv  (gauge)
        — how many rv this follower's applied state trails the leader's
          advertised rv (refreshed on every applied group and on each
          epoch sync; 0 = caught up).  The freshness number behind the
          ``applied_rv`` field /repl/status reports and the bound
          NotYetObserved answers are judged against (DESIGN.md §29)

The follower-serving read plane (ISSUE 17, DESIGN.md §29: rv-bounded
reads off any replica, watch fanout on followers, the endpoint-aware
client) records under ``wire.read.`` / ``remote.`` — the chaos-read
soak's and the readscale bench's evidence:

    wire.read.bounded_requests
        — GET/LIST requests that carried a ``min_rv`` freshness bound
          (REST query param or gRPC List field); every read answer also
          stamps its ``X-Minisched-RV`` watermark, bounded or not
    wire.read.not_yet_observed
        — bounded reads and watch resumes this replica REFUSED typed
          (HTTP 504 / gRPC UNAVAILABLE, ``not yet observed``) because
          its applied rv still trailed the bound: the retryable lag
          signal, never a silently stale 200 — distinct from
          HistoryCompacted's 410, which means relist
    remote.read_failover
        — endpoint-aware reads rotated off a dead, fenced, or lagging
          replica onto the next endpoint (the read cursor moved; the
          request itself is then retried on the new façade)
    remote.not_yet_observed
        — 504 lag answers the endpoint-aware client absorbed (each
          rotates the read cursor in multi-endpoint mode and consumes
          one backoff slot; single-endpoint stores raise typed)
    remote.watch_failover
        — watch streams re-opened on a rotated replica after the
          serving endpoint died or lagged the resume cursor; combined
          with the server's exact rv>resume replay this is the
          exactly-once failover the chaos-read soak audits
    remote.leader_discoveries
        — leader lookups resolved by probing ``/repl/status`` across
          the endpoint list (writes route to the discovered leader;
          invalidated on NotLeader/transport failure and re-discovered)
    informer.resume_not_yet_observed
        — informer watch re-opens answered "not yet observed" by a
          lagging replica: the informer KEEPS its resume cursor and
          backs off (the cache is intact — waiting out lag is cheaper
          than a relist), unlike the 410 path which must relist

The network-fault layer (faults/net.py — the partition nemesis) records
under ``net.partition.``, the chaos-partition soak's injection evidence:

    net.partition.dropped / net.partition.blackholed /
    net.partition.delayed
        — outbound replication-plane calls the layer enforced against:
          refused immediately (drop / scheduled net.drop), hung for the
          caller's timeout then refused (blackhole), or delayed then
          allowed through (one-way latency)
    net.partition.cuts / net.partition.heals
        — link rules imposed and removed (cut()/heal(), including over
          the POST /net/partition control surface)
    net.partition.links  (gauge)
        — imposed link rules currently in force in this process

The gRPC facade's memoized LIST encode (grpcserver._SnapListCache)
mirrors the REST relist cache:

    grpc.list_cache.hits / grpc.list_cache.encodes
        — List RPCs served from the snapshot-keyed memo vs. fresh
          encodes (one per COW snapshot flip per kind; hits/encodes is
          the relist-storm sharing ratio)

The gRPC Watch facade (grpcserver._WatchHub — the REST selector
stream-loop handoff ported to the unary-stream rpc) records under
``grpc.watch.``:

    grpc.watch.streams
        — watch streams adopted by the hub after handshake + sync-line
          (one drain thread serves ALL of them; thread count must not
          scale with stream count)
    grpc.watch.events
        — store events the hub drained and fanned out to its streams
    grpc.watch.encoded / grpc.watch.shared
        — first encode of an event's framed wire bytes (memoized on the
          shared WatchEvent) vs. reuses by every other stream: the
          encode-once claim, same shape as watch.fanout.encoded/shared
    grpc.watch.evicted
        — streams evicted because their bounded buffer overflowed
          (DEFAULT_WATCH_STREAM_EVENTS): the laggard is aborted
          OUT_OF_RANGE — its history is gone from the buffer just as
          surely as from a compacted ring — and recovers via
          relist + resume, never by blocking the hub

The sharded write plane (ISSUE 18, DESIGN.md §30: controlplane/shards —
namespace-partitioned leader groups behind one logical surface) records
the router side under ``shard.`` and the façade/store side under
``storage.shard.``; surfaced in the chaos-shard audits and the bench
``shard`` role's record:

    shard.topology_refreshes
        — router re-fetches of /shards/status after a WrongShard/typed
          refusal or an explicit probe; each adopts the highest epoch
          seen across endpoints
    shard.wrong_shard_chased
        — writes/reads the router re-dispatched after a 421 WrongShard
          refusal + topology refresh (the stale-router chase; bounded
          attempts, then the typed error surfaces)
    shard.cross_bind_batches / shard.cross_bind_entries
        — bind batches that SPANNED >1 leader group (the two-shard
          commit path: one logical batch id, per-group ack ordinals,
          registry replay on retry) and the bindings inside them
    shard.watch_reopen
        — per-group component streams of a merged vector watch reopened
          at that shard's cursor component after a drop/failover (the
          other groups' streams keep flowing meanwhile)
    shard.events_suppressed
        — merged-watch events dropped because the emitting group no
          longer owns the object's namespace under the current topology
          (post-split echoes; the vector cursor still advances)
    shard.splits
        — namespace reassignments completed via the freeze → handoff →
          seed → topology-bump → unfreeze → purge protocol
    storage.shard.wrong_shard_refused / storage.shard.frozen_refused
        — façade-side typed refusals: a write for a namespace this
          group does not own under its topology epoch (421) / for a
          namespace mid-handoff write-freeze (503, retryable — the
          freeze is bounded by the split protocol)
    storage.shard.topology_updates / storage.shard.freezes
        — topology epochs adopted over POST /shards/control, and
          namespace write-freezes imposed there
    storage.shard.handoff_ships / storage.shard.handoff_objects
        — namespace handoff snapshots served over GET /shards/handoff
          (the checkpoint-seed unit of a split) and the objects inside
    storage.shard.seed_objects / storage.shard.purged_objects
        — objects applied from a handoff seed on the receiving group /
          deleted from the source group after ownership flipped
    remote.shard_frozen_retry
        — remote-client requests that absorbed a 503 "shard frozen"
          answer and retried with backoff (rides the split's bounded
          write-freeze instead of failing the caller)

The self-defending shard plane (ISSUE 20, DESIGN.md §31: freeze
leases, the cross-shard capacity mirror, autosplit) adds:

    remote.shard_frozen_timeout
        — frozen-shard waits that exhausted their OWN deadline
          (``RemoteStore(frozen_deadline_s=)``) and surfaced the typed
          ShardFrozenTimeout instead of hammering on: the freeze
          outlived every healthy split's window plus the lease TTL
    storage.shard.freeze_expired
        — freeze leases a replica auto-thawed at TTL expiry (the
          coordinator died or stalled mid-split; the namespace
          un-strands itself with no operator in the loop)
    storage.shard.purge_skipped
        — source-side objects a keyed post-split purge left in place
          because they were NOT in the handoff manifest: writes
          admitted after a lease-expiry thaw — deleting them would be
          acked-write loss
    shard.endpoint_discoveries
        — follower data urls the router's per-group endpoint discovery
          learned from /repl/status beyond the topology document (the
          §29 multi-endpoint read client folded into the shard router)
    shard.budget.mirror_syncs / shard.budget.reports
        — budget-doc refreshes a non-home group's mirror adopted
          (rv-monotonic; stale fetches dropped) / per-group usage
          reports the home group's board folded in (rv-monotonic per
          reporting group)
    shard.budget.mirror_checks / shard.budget.unknown_node /
    shard.budget.refused
        — bind budget lookups answered from the cross-shard mirror
          (Node absent from the local store), lookups the mirror could
          not answer (Node unknown — no check, matching the
          reference's unvalidated bind), and binds REFUSED on the
          mirror's verdict (the OutOfCapacity carries its
          ``budget-mirror rv=`` watermark)
    sched.bind_mirror_refusals
        — engine bind failures whose OutOfCapacity carried the
          budget-mirror watermark: cross-shard capacity said no —
          sync-lag signal, counted apart from local capacity races
    shard.autosplit.samples / shard.autosplit.hot
        — load-watcher ticks, and ticks whose windowed
          storage.group_wait_s p99 or live group-commit stage depth
          crossed the hot thresholds (hysteresis: ``hot_samples``
          consecutive hot ticks arm a split)
    shard.autosplit.triggered / shard.autosplit.skipped /
    shard.autosplit.errors
        — autosplits fired (hottest owned namespace to the rendezvous
          pick among the other groups), armed triggers skipped
          (cooldown window, fenced store, or no eligible namespace),
          and split attempts that raised (next tick retries)
"""

from __future__ import annotations

import threading
from typing import Dict, Set


class Counters:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._gauge_names: Set[str] = set()

    def inc(self, name: str, n: int = 1) -> None:
        with self._mu:
            self._counts[name] = self._counts.get(name, 0) + n

    def set_gauge(self, name: str, n: int) -> None:
        """Last-write-wins value for state-shaped entries (a mesh
        factoring, a shard count) — engine restarts and multi-engine
        processes must not sum them into nonsense.  The name is
        remembered as gauge-typed so the Prometheus exposition
        (observability/hist.render_prometheus) emits the right # TYPE."""
        with self._mu:
            self._counts[name] = n
            self._gauge_names.add(name)

    def gauge_names(self) -> Set[str]:
        with self._mu:
            return set(self._gauge_names)

    def get(self, name: str) -> int:
        with self._mu:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._counts)

    def reset(self) -> None:
        with self._mu:
            self._counts.clear()
            self._gauge_names.clear()


GLOBAL = Counters()


def inc(name: str, n: int = 1) -> None:
    GLOBAL.inc(name, n)


def set_gauge(name: str, n: int) -> None:
    GLOBAL.set_gauge(name, n)


def get(name: str) -> int:
    return GLOBAL.get(name)


def snapshot() -> Dict[str, int]:
    return GLOBAL.snapshot()


def reset() -> None:
    GLOBAL.reset()
