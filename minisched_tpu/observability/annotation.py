"""Annotation keys for per-decision scheduling results.

Re-creates ``scheduler/plugin/annotation/annotation.go:3-10`` verbatim so
consumers of the reference's simulator annotations can read ours unchanged.
"""

#: per-plugin filter reasons, JSON: {node: {plugin: reason-or-"passed"}}
FILTER_RESULT = "scheduler-simulator/filter-result"
#: per-plugin raw scores, JSON: {node: {plugin: score}}
SCORE_RESULT = "scheduler-simulator/score-result"
#: per-plugin normalized+weighted scores, JSON: {node: {plugin: score}}
FINAL_SCORE_RESULT = "scheduler-simulator/finalscore-result"
