"""Tiny standalone metrics listener for processes without a REST façade.

The control-plane façade serves ``/metrics`` and ``/debug/trace``
itself; an HA ENGINE child (ha/proc.EngineSupervisor) has no HTTP
server at all, so its histograms and trace ring would die unscraped
with the process.  ``start_metrics_server`` is the smallest possible
fix: a daemon ThreadingHTTPServer serving exactly those two read-only
endpoints off the process-global registries.  The supervisors thread a
``metrics_port`` through to their children so the parent (or a real
Prometheus) can scrape every process of the plane.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Tuple


class _MetricsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt: str, *args) -> None:  # quiet
        pass

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        from minisched_tpu.observability import hist, trace

        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = hist.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4"
        elif path == "/debug/trace":
            body = trace.dump_jsonl().encode()
            ctype = "application/x-ndjson"
        elif path == "/healthz":
            body = b"ok"
            ctype = "text/plain"
        elif path == "/debug/metrics.json":
            body = json.dumps(hist.snapshot(), default=str).encode()
            ctype = "application/json"
        else:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def start_metrics_server(
    port: int = 0, host: str = "127.0.0.1"
) -> Tuple[ThreadingHTTPServer, int, Callable[[], None]]:
    """Serve /metrics + /debug/trace (+ /healthz) on ``host:port``
    (port 0 → ephemeral).  Returns (server, bound port, shutdown)."""
    srv = ThreadingHTTPServer((host, port), _MetricsHandler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="metricsd")
    t.start()

    def shutdown() -> None:
        srv.shutdown()
        srv.server_close()

    return srv, srv.server_address[1], shutdown


def scrape_main(argv) -> int:
    """``python -m minisched_tpu metrics <url>``: fetch ``<url>/metrics``
    and pretty-print the snapshot — counters and gauges as name/value
    lines, histograms as count + p50/p99 bucket upper bounds.  Pure
    scrape consumer: works against the REST façade, a metricsd sidecar,
    or any Prometheus 0.0.4 exposition."""
    import urllib.request

    from minisched_tpu.observability.hist import (
        parse_exemplars,
        parse_prometheus,
        parsed_histogram_quantile,
    )

    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m minisched_tpu metrics <url>")
        return 0 if argv else 2
    url = argv[0].rstrip("/")
    if not url.endswith("/metrics"):
        url += "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=10.0) as r:
            text = r.read().decode()
    except OSError as e:
        print(f"metrics: scrape of {url} failed: {e}", file=__import__("sys").stderr)
        return 1
    types, samples = parse_prometheus(text)
    exemplars = parse_exemplars(text)
    hist_names = sorted(n for n, t in types.items() if t == "histogram")
    scalar = [
        (n, v) for n, labels, v in samples
        if types.get(n) in ("counter", "gauge") and not labels
    ]
    for name, val in scalar:
        print(f"{types[name]:9s} {name} = {int(val) if val == int(val) else val}")
    for name in hist_names:
        count = sum(
            v for n, labels, v in samples if n == name + "_count"
        )
        p50 = parsed_histogram_quantile(samples, name, 0.50)
        p99 = parsed_histogram_quantile(samples, name, 0.99)
        fmt = lambda b: "-" if b is None else f"<={b[1]:.6g}s"
        print(
            f"histogram {name}: count={int(count)} "
            f"p50{fmt(p50)} p99{fmt(p99)}"
        )
        # buckets render low→high, so the LAST exemplar-carrying
        # bucket line is the slowest sample stamped — the "who was
        # in the p99 bucket" answer, straight off the scrape
        exs = [e for e in exemplars if e[0] == name + "_bucket"]
        if exs:
            _n, _sl, ex_labels, ex_val = exs[-1]
            who = ex_labels.get("key", "?")
            print(f"          exemplar(slowest bucket): {who} ({ex_val:.6g}s)")
    if not samples:
        print("(empty exposition)")
    return 0
