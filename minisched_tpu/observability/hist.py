"""Live latency histograms + Prometheus text exposition (ISSUE 11).

`observability/counters.py` records what the system DID as integers;
this module records how LONG it took, live, without a bench.  One
process-global registry (``GLOBAL``) of fixed-log-bucket histograms
keeps call sites one-liners — ``hist.observe("sched.time_to_bind_s",
dt)`` — and `/metrics` on the REST façade (or the supervisors' child
metrics listeners) renders the whole registry, counters and gauges
included, as Prometheus text exposition.

**Buckets are fixed, not configurable**: every histogram shares one
geometric ladder, ``100µs · 2^k`` for k in 0..25 (upper bound ≈ 56 min)
plus +Inf overflow.  Fixed buckets mean (a) zero per-histogram config to
drift, (b) any two histograms (or the same one before/after a restart)
are mergeable bucket-by-bucket, and (c) "agrees within bucket
resolution" is a well-defined cross-check the bench roles enforce
against their offline sampled percentiles.  Factor-2 resolution is
coarse for a single sample and plenty for an SLO percentile.

The histogram registry documented here (the lint test in
tests/test_observability.py greps call sites against THIS docstring,
same contract as counters.py):

    sched.time_to_bind_s
        — arrival→bind per pod: stamped once at queue admission (the
          stamp survives requeues; the queue owns it, not the
          QueuedPodInfo), observed at bind ack, labeled
          ``priority=<pod priority>`` — the per-priority-class latency
          breakdown of "Priority Matters"
    sched.wave_build_s / sched.wave_device_s / sched.wave_commit_s /
    sched.wave_stall_s
        — the wave pipeline's phase timers (CycleMetrics forwards these
          phases here, so any engine with metrics attached feeds the
          live plane; the engine now defaults to a real CycleMetrics)
    http.request_s
        — REST façade request latency, labeled ``verb=``/``route=``
          (route is the low-cardinality shape of the path — kind +
          name/subresource markers — never raw names); long-lived watch
          streams are excluded
    http.list_s
        — LIST verb latency on the REST façade, labeled ``kind=`` (a
          handful of kinds, low cardinality), observed in BOTH read
          modes: the lock-free COW path serving the memoized shared
          payload and the ``MINISCHED_COW_READS=0`` locked re-encode
          path — the relist-storm p99 the ``relist`` bench gates
    watch.delivery_lag_s
        — store-fanout→socket-write lag per watch event, observed in
          BOTH delivery paths (selector stream loop and the legacy
          thread path) against the WatchEvent's birth stamp
    storage.wal_append_s / storage.wal_fsync_s
        — durable-store WAL frame append (write + inline fsync when
          armed) and deferred batch-barrier fsync times
    storage.group_wait_s
        — time a mutation spends parked on the group-commit barrier
          (stage → its group's fsync completing), observed by every
          waiter including the self-elected leader; the exemplar
          carries the object key of the waiter
    grpc.request_s
        — gRPC facade request latency, labeled ``method=`` (Health /
          Evaluate / List) — the wire-RPC mirror of ``http.request_s``
    storage.quorum_wait_s
        — time the leader's group-commit barrier spent awaiting a
          follower quorum's durability acks, between the group's fsync
          and its publish (DESIGN.md §27) — the replication tax every
          acked mutation pays; the bench ``repl`` role's headline
    storage.repl_ship_s
        — leader-side per-group ship time: framing one commit group and
          writing it down a follower's tail stream socket
    storage.repl_apply_s
        — follower-side per-group apply time: CRC verify + WAL append +
          fsync + replay through the real recovery path
    storage.repl.bootstrap_s
        — follower checkpoint-seeded reseed time: fetch the leader's
          checkpoint generation, verify its sha256, land + restore it
          locally (DESIGN.md §28) — the O(state) replica-bootstrap cost
          that replaced O(history) re-tails; the bench ``repl`` role's
          bootstrap-under-load gate
    shard.route_s
        — sharded-router topology refresh time: probing /shards/status
          across the known endpoints and adopting the highest epoch
          (DESIGN.md §30) — the stale-router recovery cost a WrongShard
          chase pays before its re-dispatch
    shard.crossbind_s
        — end-to-end latency of a bind batch that spanned >1 leader
          group: the two-shard commit (per-group dispatch in parallel,
          each side's group-commit barrier + registry insert) — the
          cross-shard tax the bench ``shard`` role reports separately
          from single-group binds
    shard.freeze_s
        — a split's whole write-freeze window, coordinator-side: the
          freeze fanout through handoff, seed, lease renewal, topology
          flip and unfreeze (DESIGN.md §31) — what the lease TTL must
          comfortably exceed for healthy splits
    shard.autosplit.window_p99_s
        — the autosplit watcher's WINDOWED storage.group_wait_s p99
          (bucket-count delta between consecutive ticks, nearest-rank
          over the shared ladder): the saturation signal the hot
          threshold is judged against, recoverable after a split where
          the cumulative histogram is not

**Exemplars**: ``observe(..., exemplar="default/pod-1")`` stamps the
bucket the sample lands in with that string (last writer wins, one per
bucket — bounded state, no sample log).  The exposition renders them as
OpenMetrics exemplars — `` # {key="default/pod-1"} 0.043`` appended to
the owning ``_bucket`` line — so "what was the pod in the p99 bucket?"
is answerable straight off a scrape; exemplar-free histograms render
byte-identically to before.

Pretty-print a live process: ``python -m minisched_tpu metrics <url>``.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

#: first bucket upper bound: 100µs (below the cheapest observed seam)
BUCKET_BASE_S = 1e-4
#: finite buckets: 1e-4 · 2^k, k ∈ [0, 26); last finite bound ≈ 3355s
NBUCKETS = 26

#: the shared ladder of finite upper bounds, low→high
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    BUCKET_BASE_S * (1 << k) for k in range(NBUCKETS)
)


def bucket_index(v: float) -> int:
    """Index of the finite bucket whose upper bound first covers ``v``,
    or ``NBUCKETS`` for overflow (+Inf only).  Exact at power-of-two
    boundaries (frexp, not float log2): a value equal to a bound lands
    IN that bucket, matching Prometheus ``le`` semantics."""
    if v <= BUCKET_BASE_S:
        return 0
    m, e = math.frexp(v / BUCKET_BASE_S)  # v/base = m·2^e, m ∈ [0.5, 1)
    idx = e - 1 if m == 0.5 else e
    return idx if idx < NBUCKETS else NBUCKETS


class Histogram:
    """One label-child: fixed log2 buckets + sum + count.

    Lock-cheap: one uncontended Lock per child, three integer bumps and
    a float add inside it — no allocation, no sorting, no sample list."""

    __slots__ = ("_mu", "counts", "overflow", "sum", "count", "exemplars")

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.counts = [0] * NBUCKETS
        self.overflow = 0
        self.sum = 0.0
        self.count = 0
        #: bucket index (NBUCKETS = +Inf) → (exemplar string, value);
        #: last writer wins, so state stays O(buckets) forever
        self.exemplars: Dict[int, Tuple[str, float]] = {}

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        i = bucket_index(v)
        with self._mu:
            if i < NBUCKETS:
                self.counts[i] += 1
            else:
                self.overflow += 1
            self.sum += v
            self.count += 1
            if exemplar is not None:
                self.exemplars[i] = (str(exemplar), v)

    def merge_into(self, counts: List[int]) -> Tuple[int, float, int]:
        """Add this child's buckets into ``counts`` (len NBUCKETS);
        returns (overflow, sum, count) deltas — the registry's
        cross-label aggregation primitive."""
        with self._mu:
            for i, c in enumerate(self.counts):
                counts[i] += c
            return self.overflow, self.sum, self.count

    def snapshot(self) -> Dict[str, object]:
        with self._mu:
            return {
                "counts": list(self.counts),
                "overflow": self.overflow,
                "sum": self.sum,
                "count": self.count,
                "exemplars": dict(self.exemplars),
            }


LabelsKey = Tuple[Tuple[str, str], ...]


class Histograms:
    """The registry: (name, sorted label items) → Histogram child."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._hists: Dict[Tuple[str, LabelsKey], Histogram] = {}

    def _child(self, name: str, labels: Dict[str, str]) -> Histogram:
        key = (name, tuple(sorted(labels.items())))
        with self._mu:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram()
        return h

    def observe(
        self,
        name: str,
        v: float,
        exemplar: Optional[str] = None,
        **labels: str,
    ) -> None:
        self._child(name, labels).observe(v, exemplar=exemplar)

    def get(self, name: str, **labels: str) -> Optional[Histogram]:
        key = (name, tuple(sorted(labels.items())))
        with self._mu:
            return self._hists.get(key)

    def children(self, name: str) -> List[Tuple[LabelsKey, Histogram]]:
        with self._mu:
            return [
                (k[1], h) for k, h in self._hists.items() if k[0] == name
            ]

    def names(self) -> List[str]:
        with self._mu:
            return sorted({k[0] for k in self._hists})

    def merged(self, name: str) -> Tuple[List[int], int, float, int]:
        """(bucket counts, overflow, sum, count) aggregated across every
        label child of ``name`` — mergeable because buckets are fixed."""
        counts = [0] * NBUCKETS
        overflow, total, n = 0, 0.0, 0
        for _labels, h in self.children(name):
            o, s, c = h.merge_into(counts)
            overflow += o
            total += s
            n += c
        return counts, overflow, total, n

    def quantile_bounds(
        self, name: str, q: float
    ) -> Optional[Tuple[float, float]]:
        """[lower, upper) bounds of the bucket holding the q-quantile
        across all label children, or None when empty.  The upper bound
        is the conservative point estimate; "agrees within bucket
        resolution" means a sampled quantile falls inside (or within one
        bucket of) these bounds."""
        counts, overflow, _s, n = self.merged(name)
        if n == 0:
            return None
        rank = max(1, math.ceil(q * n))  # nearest-rank, 1-based
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                return lo, BUCKET_BOUNDS[i]
        return BUCKET_BOUNDS[-1], math.inf

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """name → {count, sum, p50, p99} (bucket-upper estimates) —
        the compact block bench records embed as ``metrics_snapshot``."""
        out: Dict[str, Dict[str, object]] = {}
        for name in self.names():
            _counts, _ovf, total, n = self.merged(name)
            p50 = self.quantile_bounds(name, 0.50)
            p99 = self.quantile_bounds(name, 0.99)
            out[name] = {
                "count": n,
                "sum_s": total,
                "p50_le_s": p50[1] if p50 else None,
                "p99_le_s": p99[1] if p99 else None,
            }
        return out

    def reset(self) -> None:
        with self._mu:
            self._hists.clear()


GLOBAL = Histograms()


def observe(
    name: str, v: float, exemplar: Optional[str] = None, **labels: str
) -> None:
    GLOBAL.observe(name, v, exemplar=exemplar, **labels)


def quantile_bounds(name: str, q: float) -> Optional[Tuple[float, float]]:
    return GLOBAL.quantile_bounds(name, q)


def snapshot() -> Dict[str, Dict[str, object]]:
    return GLOBAL.snapshot()


def reset() -> None:
    GLOBAL.reset()


# -- Prometheus text exposition ---------------------------------------------

def _metric_name(name: str) -> str:
    """``sched.time_to_bind_s`` → ``sched_time_to_bind_seconds``: dots
    (and any other illegal rune) become underscores, a trailing ``_s``
    unit spells out per Prometheus naming convention."""
    out = "".join(
        ch if (ch.isascii() and (ch.isalnum() or ch in "_:")) else "_"
        for ch in name
    )
    if out.endswith("_s"):
        out = out[:-2] + "_seconds"
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(items: Iterable[Tuple[str, str]], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in items]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    return repr(float(v))


def _fmt_exemplar(ex: Optional[Tuple[str, float]]) -> str:
    """OpenMetrics exemplar suffix for a ``_bucket`` line, or "" when
    the bucket never saw an exemplar-carrying observation — keeping
    exemplar-free expositions byte-identical to the pre-exemplar
    format (the golden file relies on this)."""
    if ex is None:
        return ""
    key, v = ex
    return f' # {{key="{_escape_label(key)}"}} {_fmt_float(v)}'


def render_prometheus(
    counters_obj=None, hists: Optional[Histograms] = None
) -> str:
    """The whole registry — counters, gauges, histograms — as Prometheus
    text exposition (version 0.0.4).  Deterministic ordering so the
    golden-file test is byte-stable."""
    from minisched_tpu.observability import counters as counters_mod

    c = counters_obj if counters_obj is not None else counters_mod.GLOBAL
    h = hists if hists is not None else GLOBAL
    gauges = c.gauge_names()
    lines: List[str] = []
    for name, val in sorted(c.snapshot().items()):
        mname = _metric_name(name)
        kind = "gauge" if name in gauges else "counter"
        lines.append(f"# TYPE {mname} {kind}")
        lines.append(f"{mname} {val}")
    with h._mu:
        keys = sorted(h._hists.keys())
        children = [(k, h._hists[k]) for k in keys]
    seen_type = set()
    for (name, labels), child in children:
        mname = _metric_name(name)
        if mname not in seen_type:
            seen_type.add(mname)
            lines.append(f"# TYPE {mname} histogram")
        snap = child.snapshot()
        exemplars = snap["exemplars"]
        cum = 0
        for i, n in enumerate(snap["counts"]):
            cum += n
            le = 'le="%s"' % _fmt_float(BUCKET_BOUNDS[i])
            lines.append(
                f"{mname}_bucket{_fmt_labels(labels, extra=le)} {cum}"
                + _fmt_exemplar(exemplars.get(i))
            )
        cum += snap["overflow"]
        inf_le = 'le="+Inf"'
        lines.append(
            f"{mname}_bucket{_fmt_labels(labels, extra=inf_le)} {cum}"
            + _fmt_exemplar(exemplars.get(NBUCKETS))
        )
        lines.append(
            f"{mname}_sum{_fmt_labels(labels)} {_fmt_float(snap['sum'])}"
        )
        lines.append(f"{mname}_count{_fmt_labels(labels)} {snap['count']}")
    return "\n".join(lines) + "\n"


# -- minimal parser (the scrape consumer's half) ----------------------------

def _label_block_end(s: str) -> int:
    """Index of the ``}`` closing a label block that starts at ``s[0]``'s
    level — quote-aware, so escaped quotes and braces inside label
    values don't end the block early."""
    i, in_quote = 0, False
    while i < len(s):
        ch = s[i]
        if in_quote:
            if ch == "\\":
                i += 1
            elif ch == '"':
                in_quote = False
        elif ch == '"':
            in_quote = True
        elif ch == "}":
            return i
        i += 1
    return len(s)


def _parse_labels(s: str) -> Dict[str, str]:
    """Parse ``k="v",k2="v2"`` honoring \\\\, \\" and \\n escapes."""
    out: Dict[str, str] = {}
    i, n = 0, len(s)
    while i < n:
        j = s.index("=", i)
        key = s[i:j].strip().lstrip(",").strip()
        assert s[j + 1] == '"', f"unquoted label value at {s[j:]}"
        i = j + 2
        buf: List[str] = []
        while s[i] != '"':
            if s[i] == "\\":
                nxt = s[i + 1]
                buf.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
                i += 2
            else:
                buf.append(s[i])
                i += 1
        out[key] = "".join(buf)
        i += 1  # closing quote
        while i < n and s[i] in ", ":
            i += 1
    return out


def parse_prometheus(
    text: str,
) -> Tuple[Dict[str, str], List[Tuple[str, Dict[str, str], float]]]:
    """Minimal exposition parser: returns ``(types, samples)`` where
    types maps metric name → counter|gauge|histogram and samples is
    ``[(name, labels, value)]`` in document order.  Enough to validate
    a scrape, pretty-print a snapshot, and round-trip the golden file —
    deliberately not a full OpenMetrics implementation."""
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        if "{" in line:
            name = line[: line.index("{")]
            rest = line[line.index("{") + 1 :]
            i = _label_block_end(rest)
            labels = _parse_labels(rest[:i])
            val = rest[i + 1 :].strip()
        else:
            name, val = line.split(None, 1)
            labels = {}
        # an OpenMetrics exemplar (`` # {…} v``) may trail a _bucket
        # sample; it is annotation, not part of the sample value
        if " # " in val:
            val = val.split(" # ", 1)[0].strip()
        samples.append((name, labels, float(val)))
    return types, samples


def parse_exemplars(
    text: str,
) -> List[Tuple[str, Dict[str, str], Dict[str, str], float]]:
    """OpenMetrics exemplars from an exposition, in document order:
    ``[(sample name, sample labels, exemplar labels, exemplar value)]``.
    Kept separate from :func:`parse_prometheus` so its (types, samples)
    contract — and every existing consumer — stays untouched."""
    out: List[Tuple[str, Dict[str, str], Dict[str, str], float]] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or " # {" not in line:
            continue
        sample, ex = line.split(" # {", 1)
        j = _label_block_end(ex)
        ex_labels = _parse_labels(ex[:j])
        ex_val = float(ex[j + 1 :].strip().split()[0])
        if "{" in sample:
            name = sample[: sample.index("{")]
            rest = sample[sample.index("{") + 1 :]
            k = _label_block_end(rest)
            labels = _parse_labels(rest[:k])
        else:
            name = sample.split()[0]
            labels = {}
        out.append((name, labels, ex_labels, ex_val))
    return out


def parsed_histogram_quantile(
    samples: List[Tuple[str, Dict[str, str], float]],
    metric: str,
    q: float,
) -> Optional[Tuple[float, float]]:
    """Quantile bounds recomputed from PARSED ``_bucket`` samples —
    the scrape-side mirror of :meth:`Histograms.quantile_bounds`, used
    by the smoke tool and the CLI pretty-printer."""
    # merge cumulative buckets across label children: le → summed count
    by_le: Dict[float, float] = {}
    for name, labels, val in samples:
        if name != metric + "_bucket":
            continue
        le = labels.get("le", "")
        by_le[math.inf if le == "+Inf" else float(le)] = (
            by_le.get(math.inf if le == "+Inf" else float(le), 0.0) + val
        )
    if not by_le:
        return None
    bounds = sorted(by_le)
    total = by_le[bounds[-1]]
    if total <= 0:
        return None
    rank = max(1.0, math.ceil(q * total))
    lo = 0.0
    for b in bounds:
        if by_le[b] >= rank:
            return lo, b
        lo = b
    return lo, math.inf
