"""Profiling: per-cycle phase timings + device tracing.

The reference has NO tracing/profiling at all (SURVEY.md §5.1 — only klog
prints in the loop, minisched/minisched.go:33-87).  This module supplies
the missing layer: a lock-protected per-phase timing aggregator the engine
feeds (scheduling latency is the product metric — it's what the headline
benchmark reports), plus a thin wrapper over the JAX profiler for device
traces of the fused kernels.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class PhaseStats:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def observe(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


#: CycleMetrics phases forwarded into the live histogram plane
#: (observability/hist): any engine with metrics attached — and the
#: engine now defaults to a real CycleMetrics — feeds /metrics without
#: a bench in the loop.  Names are documented in hist.py's registry.
_PHASE_HISTS: Dict[str, str] = {
    "wave_pipeline_build": "sched.wave_build_s",
    "wave_device": "sched.wave_device_s",
    "commit": "sched.wave_commit_s",
    "wave_pipeline_stall": "sched.wave_stall_s",
}


class CycleMetrics:
    """Per-phase wall-clock aggregates for the scheduling loop.

    Attach to an engine: ``sched.metrics = CycleMetrics()`` — schedule_one
    then times snapshot / schedule / permit (and binds report themselves).
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._phases: Dict[str, PhaseStats] = {}

    def observe(self, phase: str, dt: float) -> None:
        with self._mu:
            self._phases.setdefault(phase, PhaseStats()).observe(dt)
        hname = _PHASE_HISTS.get(phase)
        if hname is not None:
            from minisched_tpu.observability import hist

            hist.observe(hname, dt)

    @contextlib.contextmanager
    def timed(self, phase: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(phase, time.monotonic() - t0)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._mu:
            return {
                name: {
                    "count": s.count,
                    "total_s": s.total_s,
                    "mean_s": s.mean_s,
                    "max_s": s.max_s,
                }
                for name, s in self._phases.items()
            }

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.snapshot().items()):
            lines.append(
                f"{name}: n={s['count']} mean={s['mean_s']*1e3:.2f}ms "
                f"max={s['max_s']*1e3:.2f}ms total={s['total_s']:.3f}s"
            )
        return "\n".join(lines)


class NullMetrics:
    """No-op stand-in so the engine can call ``metrics.timed(...)``
    unconditionally (assign a real CycleMetrics to start collecting)."""

    def observe(self, phase: str, dt: float) -> None:
        pass

    @contextlib.contextmanager
    def timed(self, phase: str) -> Iterator[None]:
        yield

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        return {}

    def report(self) -> str:
        return ""


NULL_METRICS = NullMetrics()


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """JAX profiler trace around device work (no-op when log_dir is None).
    View with TensorBoard / xprof.

    Export happens on context exit and serializes every event of the
    traced span — for a full engine run (compiles included) that takes
    ~10-30s after shutdown; keep the process alive until the trace
    directory is populated."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
