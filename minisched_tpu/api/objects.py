"""Minimal cluster object model (the corev1 subset the scheduler needs).

The reference manipulates real Kubernetes API objects via client-go
(sched.go:70-143 creates ``v1.Node``/``v1.Pod``; binding POSTs a
``v1.Binding``, minisched/minisched.go:267-273).  This module provides a
dependency-free equivalent: plain dataclasses with deep-copy semantics, a
resource-quantity model, and the label/taint/affinity fields the default
plugin roster reads.

Quantities are held in integer base units (milli-CPU, bytes) so device-side
tables (models/tables.py) can mirror them exactly in int32/int64 arrays —
bit-exact parity between the scalar oracle and the TPU kernels depends on
never touching floats for resources.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_uid_counter = 0
_uid_lock = threading.Lock()


def new_uid(prefix: str = "obj") -> str:
    global _uid_counter
    with _uid_lock:
        _uid_counter += 1
        return f"{prefix}-{_uid_counter:08d}"


def ensure_uid_floor(n: int) -> None:
    """Advance the uid sequence to at least ``n`` — crash recovery calls
    this with the highest numeric suffix found among recovered objects.
    Without it a RESTARTED control plane (fresh interpreter, counter back
    at zero) re-issues uids that recovered objects already carry: two
    DIFFERENT pods then share an identity, confusing every uid-keyed
    consumer (queue dedup, assume ledger, the double-bind audit).  The
    sequence stays deterministic — no randomness — so seeded runs still
    reproduce."""
    global _uid_counter
    with _uid_lock:
        _uid_counter = max(_uid_counter, int(n))


def uid_floor() -> int:
    """The current top of the uid sequence (checkpoints persist it so
    recovery can floor the counter even past deleted objects' uids)."""
    with _uid_lock:
        return _uid_counter


def _uid_suffix(uid: str) -> int:
    """Numeric tail of a generated uid ('pod-00000018' → 18); 0 for
    foreign/empty uids."""
    tail = uid.rsplit("-", 1)[-1] if uid else ""
    return int(tail) if tail.isdigit() else 0


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

CPU = "cpu"  # milli-cores
MEMORY = "memory"  # bytes
PODS = "pods"  # count
EPHEMERAL_STORAGE = "ephemeral-storage"  # bytes

MIB = 1024 * 1024

DEFAULT_POD_CPU_REQUEST = 100  # milli-CPU, mirrors upstream non-zero default
DEFAULT_POD_MEMORY_REQUEST = 200 * MIB  # bytes


def parse_quantity(value: Any, resource: str) -> int:
    """Parse '4', '4000m', '8Gi', '512Mi' → integer base units."""
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if resource == CPU:
        if s.endswith("m"):
            return int(s[:-1])
        return int(float(s) * 1000)
    suffixes = {
        "Ki": 1024,
        "Mi": 1024**2,
        "Gi": 1024**3,
        "Ti": 1024**4,
        "k": 1000,
        "M": 1000**2,
        "G": 1000**3,
        "T": 1000**4,
    }
    for suf, mult in suffixes.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult)
    return int(float(s))


@dataclass
class ResourceList:
    """Typed resource vector in integer base units."""

    milli_cpu: int = 0
    memory: int = 0
    pods: int = 0
    ephemeral_storage: int = 0
    scalar: Dict[str, int] = field(default_factory=dict)

    @staticmethod
    def parse(raw: Optional[Dict[str, Any]]) -> "ResourceList":
        rl = ResourceList()
        if not raw:
            return rl
        for k, v in raw.items():
            if k == CPU:
                rl.milli_cpu = parse_quantity(v, CPU)
            elif k == MEMORY:
                rl.memory = parse_quantity(v, MEMORY)
            elif k == PODS:
                rl.pods = int(v)
            elif k == EPHEMERAL_STORAGE:
                rl.ephemeral_storage = parse_quantity(v, EPHEMERAL_STORAGE)
            else:
                rl.scalar[k] = parse_quantity(v, k)
        return rl

    def add(self, other: "ResourceList") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.pods += other.pods
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) + v

    def sub(self, other: "ResourceList") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.pods -= other.pods
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) - v

    def clone(self) -> "ResourceList":
        # structural copy: clone() sits on every store read/write and every
        # snapshot — deepcopy's reflective walk measured ~450 frames per
        # Pod and dominated the bind path (1.5ms/bind), so every clone in
        # this module is hand-rolled over the known dataclass shape
        return ResourceList(
            self.milli_cpu,
            self.memory,
            self.pods,
            self.ephemeral_storage,
            dict(self.scalar),
        )


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: int = 0
    creation_timestamp: float = 0.0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "ObjectMeta":
        return ObjectMeta(
            self.name,
            self.namespace,
            self.uid,
            dict(self.labels),
            dict(self.annotations),
            self.resource_version,
            self.creation_timestamp,
        )


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------

TAINT_EFFECT_NO_SCHEDULE = "NoSchedule"
TAINT_EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_EFFECT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_EFFECT_NO_SCHEDULE


@dataclass
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            # empty key with Exists tolerates everything
            return self.operator == TOLERATION_OP_EXISTS
        if self.key != taint.key:
            return False
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        return self.value == taint.value


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    #: multi-host accelerator topology (ISSUE 6 / Tesserae): the slice
    #: this host belongs to ('' = not part of any slice), its coordinates
    #: in the slice's torus, and its host index within the slice.  Real
    #: clusters publish these as node labels (cloud.google.com/gke-tpu-*);
    #: first-class fields keep the device tables' encoding one hash away
    #: instead of a label-parse per wave.
    slice_id: str = ""
    torus_x: int = 0
    torus_y: int = 0
    torus_z: int = 0
    host_index: int = -1
    #: the slice's torus DIMENSIONS (ring size per axis; 0 = unknown).
    #: With dims on the node, the GangTopology scorer measures ring
    #: (wraparound) distance instead of non-wrapping Manhattan — ISSUE 7
    #: satellite closing the ISSUE 6 follow-up.  dims=0 keeps the exact
    #: non-wrapping behavior (identity), so dim-less clusters are
    #: placement-bit-identical to before.
    slice_dx: int = 0
    slice_dy: int = 0
    slice_dz: int = 0


@dataclass
class NodeStatus:
    capacity: ResourceList = field(default_factory=ResourceList)
    allocatable: ResourceList = field(default_factory=ResourceList)
    images: Dict[str, int] = field(default_factory=dict)  # image name → size bytes


@dataclass
class Node:
    metadata: ObjectMeta
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    @property
    def name(self) -> str:
        return self.metadata.name

    def clone(self) -> "Node":
        return Node(
            metadata=self.metadata.clone(),
            spec=NodeSpec(
                unschedulable=self.spec.unschedulable,
                taints=[Taint(t.key, t.value, t.effect) for t in self.spec.taints],
                slice_id=self.spec.slice_id,
                torus_x=self.spec.torus_x,
                torus_y=self.spec.torus_y,
                torus_z=self.spec.torus_z,
                host_index=self.spec.host_index,
                slice_dx=self.spec.slice_dx,
                slice_dy=self.spec.slice_dy,
                slice_dz=self.spec.slice_dz,
            ),
            status=NodeStatus(
                capacity=self.status.capacity.clone(),
                allocatable=self.status.allocatable.clone(),
                images=dict(self.status.images),
            ),
        )


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    requests: ResourceList = field(default_factory=ResourceList)
    limits: ResourceList = field(default_factory=ResourceList)
    ports: List[int] = field(default_factory=list)


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: str  # In, NotIn, Exists, DoesNotExist, Gt, Lt
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def clone(self) -> "LabelSelector":
        return LabelSelector(
            dict(self.match_labels),
            [
                LabelSelectorRequirement(r.key, r.operator, list(r.values))
                for r in self.match_expressions
            ],
        )

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for req in self.match_expressions:
            if not _match_expression(req, labels):
                return False
        return True


def _match_expression(req: LabelSelectorRequirement, labels: Dict[str, str]) -> bool:
    val = labels.get(req.key)
    if req.operator == "In":
        return val is not None and val in req.values
    if req.operator == "NotIn":
        return val is None or val not in req.values
    if req.operator == "Exists":
        return val is not None
    if req.operator == "DoesNotExist":
        return val is None
    if req.operator in ("Gt", "Lt"):
        # Kubernetes treats an unparsable operand or label value as no-match,
        # never as an error surfacing from the filter path.
        try:
            lhs = int(val)  # type: ignore[arg-type]
            rhs = int(req.values[0])
        except (TypeError, ValueError, IndexError):
            return False
        return lhs > rhs if req.operator == "Gt" else lhs < rhs
    return False


@dataclass
class NodeSelectorTerm:
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    def matches(self, node_labels: Dict[str, str]) -> bool:
        return all(_match_expression(r, node_labels) for r in self.match_expressions)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class NodeAffinity:
    # required: OR over terms; None means no requirement
    required_terms: Optional[List[NodeSelectorTerm]] = None
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    label_selector: LabelSelector = field(default_factory=LabelSelector)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    # DoNotSchedule | ScheduleAnyway
    when_unsatisfiable: str = "DoNotSchedule"
    label_selector: LabelSelector = field(default_factory=LabelSelector)


@dataclass
class GangSpec:
    """All-or-nothing coscheduling group (the PodGroup/gang of Tesserae
    and the out-of-tree coscheduling plugin, collapsed to the scheduler-
    relevant fields).  A gang is identified by (pod namespace, name);
    ``size`` is the member count that must ALL hold assume leases before
    any member binds; ``ttl_s`` bounds how long a partial gang may park
    capacity at Permit before every member's assume is released and the
    members requeue."""

    name: str = ""
    size: int = 1
    ttl_s: float = 30.0


def gang_key(pod: "Pod") -> Optional[str]:
    """'namespace/gangname' for a gang member, None for singletons — THE
    gang identity every layer (queue adjacency, permit ledger, table
    encoding, re-arbitration) keys on."""
    g = pod.spec.gang
    if g is None or not g.name:
        return None
    return f"{pod.metadata.namespace}/{g.name}"


@dataclass
class PodSpec:
    node_name: str = ""  # set by binding
    containers: List[Container] = field(default_factory=list)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    affinity: Optional[Affinity] = None
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list
    )
    #: names of PersistentVolumeClaims this pod mounts (the volumes list,
    #: collapsed to its scheduler-relevant content)
    volumes: List[str] = field(default_factory=list)
    priority: int = 0
    scheduler_name: str = "default-scheduler"
    #: all-or-nothing coscheduling membership; None = singleton pod
    gang: Optional[GangSpec] = None


def _clone_term(t: NodeSelectorTerm) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        [
            LabelSelectorRequirement(r.key, r.operator, list(r.values))
            for r in t.match_expressions
        ]
    )


def _clone_pod_term(t: PodAffinityTerm) -> PodAffinityTerm:
    return PodAffinityTerm(
        t.label_selector.clone(), t.topology_key, list(t.namespaces)
    )


def _clone_affinity(aff: Optional[Affinity]) -> Optional[Affinity]:
    if aff is None:
        return None
    na = aff.node_affinity
    pa = aff.pod_affinity
    paa = aff.pod_anti_affinity
    return Affinity(
        node_affinity=None
        if na is None
        else NodeAffinity(
            required_terms=None
            if na.required_terms is None
            else [_clone_term(t) for t in na.required_terms],
            preferred=[
                PreferredSchedulingTerm(p.weight, _clone_term(p.preference))
                for p in na.preferred
            ],
        ),
        pod_affinity=None
        if pa is None
        else PodAffinity(
            required=[_clone_pod_term(t) for t in pa.required],
            preferred=[
                WeightedPodAffinityTerm(w.weight, _clone_pod_term(w.term))
                for w in pa.preferred
            ],
        ),
        pod_anti_affinity=None
        if paa is None
        else PodAntiAffinity(
            required=[_clone_pod_term(t) for t in paa.required],
            preferred=[
                WeightedPodAffinityTerm(w.weight, _clone_pod_term(w.term))
                for w in paa.preferred
            ],
        ),
    )


def _clone_pod_spec(spec: "PodSpec") -> "PodSpec":
    return PodSpec(
        node_name=spec.node_name,
        containers=[
            Container(
                c.name, c.image, c.requests.clone(), c.limits.clone(), list(c.ports)
            )
            for c in spec.containers
        ],
        node_selector=dict(spec.node_selector),
        tolerations=[
            Toleration(t.key, t.operator, t.value, t.effect)
            for t in spec.tolerations
        ],
        affinity=_clone_affinity(spec.affinity),
        topology_spread_constraints=[
            TopologySpreadConstraint(
                c.max_skew,
                c.topology_key,
                c.when_unsatisfiable,
                c.label_selector.clone(),
            )
            for c in spec.topology_spread_constraints
        ],
        volumes=list(spec.volumes),
        priority=spec.priority,
        scheduler_name=spec.scheduler_name,
        gang=None
        if spec.gang is None
        else GangSpec(spec.gang.name, spec.gang.size, spec.gang.ttl_s),
    )


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: List[Dict[str, str]] = field(default_factory=list)
    #: set by a successful PostFilter (preemption): the node the pod is
    #: expected to land on once its victims terminate (upstream
    #: status.nominatedNodeName)
    nominated_node_name: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @property
    def name(self) -> str:
        return self.metadata.name

    def clone(self) -> "Pod":
        return Pod(
            metadata=self.metadata.clone(),
            spec=_clone_pod_spec(self.spec),
            status=PodStatus(
                phase=self.status.phase,
                conditions=[dict(c) for c in self.status.conditions],
                nominated_node_name=self.status.nominated_node_name,
            ),
        )

    def resource_requests(self) -> ResourceList:
        """Sum container requests, with upstream's non-zero defaults applied
        only by the LeastAllocated scorer (which asks for them explicitly).

        Memoized on the SPEC (kube semantics: container requests are
        immutable for a created pod, and the bind path shares the spec
        structurally between the pending and bound object — one walk
        serves the table build, the assume-cache, and the scheduler
        cache).  Callers must treat the result as read-only; anything that
        does mutate a spec's containers in place (tests building fixtures)
        must do so before the first call."""
        spec = self.spec
        memo = spec.__dict__.get("_req_memo")
        if memo is not None:
            return memo
        total = ResourceList()
        for c in spec.containers:
            total.add(c.requests)
        total.pods = max(total.pods, 1)
        spec.__dict__["_req_memo"] = total
        return total


@dataclass
class PVSpec:
    capacity: int = 0  # bytes
    claim_ref: str = ""  # namespace/name of bound PVC
    #: node labels a consuming pod's node must carry (the PV nodeAffinity
    #: required terms, collapsed to match-labels form)
    required_node_labels: Dict[str, str] = field(default_factory=dict)
    #: volume driver family — "ebs" / "gcepd" / "azuredisk" count against
    #: their per-cloud attach limits (EBSLimits & friends, the volume-limit
    #: members of the reference's default roster,
    #: scheduler/scheduler_test.go:314-318); anything else is generic and
    #: counts against NodeVolumeLimits
    driver: str = ""


@dataclass
class PersistentVolume:
    metadata: ObjectMeta
    spec: PVSpec = field(default_factory=PVSpec)
    kind = "PersistentVolume"

    def clone(self) -> "PersistentVolume":
        return PersistentVolume(
            metadata=self.metadata.clone(),
            spec=PVSpec(
                self.spec.capacity,
                self.spec.claim_ref,
                dict(self.spec.required_node_labels),
                self.spec.driver,
            ),
        )


@dataclass
class PVCSpec:
    request: int = 0  # bytes
    volume_name: str = ""
    #: the mount's access intent: read-only mounts of one volume may share
    #: a node (VolumeRestrictions allows co-location only when every mount
    #: of the volume is read-only)
    read_only: bool = False
    #: non-empty → the PV controller may DYNAMICALLY PROVISION a volume
    #: when no existing PV fits (upstream semantics: provisioning runs
    #: through a StorageClass; the reference enables it with
    #: hostpath/local plugins, pvcontroller.go:24-32).  A name matching a
    #: driver family ("ebs"/"gcepd"/"azuredisk") provisions that family.
    storage_class_name: str = ""


@dataclass
class PVCStatus:
    phase: str = "Pending"


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta
    spec: PVCSpec = field(default_factory=PVCSpec)
    status: PVCStatus = field(default_factory=PVCStatus)
    kind = "PersistentVolumeClaim"

    def clone(self) -> "PersistentVolumeClaim":
        return PersistentVolumeClaim(
            metadata=self.metadata.clone(),
            spec=PVCSpec(
                self.spec.request,
                self.spec.volume_name,
                self.spec.read_only,
                self.spec.storage_class_name,
            ),
            status=PVCStatus(self.status.phase),
        )


@dataclass
class LeaseSpec:
    """coordination.k8s.io/v1 Lease subset: a TTL'd, CAS-renewed claim.

    Expiry is evaluated by READERS (``renew_time + ttl_s < now``) — the
    store never reaps leases itself, exactly like the apiserver: a lease
    is just an object whose holder keeps bumping ``renew_time`` through
    optimistic-concurrency updates, and whoever observes it stale may try
    a takeover (another ``expected_rv`` CAS, 409-arbitrated)."""

    #: identity of the current holder ('' = unheld)
    holder: str = ""
    #: seconds a renewal stays valid (leaseDurationSeconds)
    ttl_s: float = 10.0
    #: wall-clock (time.time) of the holder's acquisition
    acquire_time: float = 0.0
    #: wall-clock (time.time) of the last renewal — the expiry anchor
    renew_time: float = 0.0
    #: number of holder changes (leaseTransitions)
    transitions: int = 0
    #: the holder's published membership epoch (HA engines gossip their
    #: shard-map version through renewals so external observers — tests,
    #: the bench ha role — can watch rebalances converge from the store)
    epoch: int = 0


@dataclass
class Lease:
    metadata: ObjectMeta
    spec: LeaseSpec = field(default_factory=LeaseSpec)
    kind = "Lease"

    @property
    def name(self) -> str:
        return self.metadata.name

    def expired(self, now: float) -> bool:
        return self.spec.renew_time + self.spec.ttl_s < now

    def clone(self) -> "Lease":
        return Lease(
            metadata=self.metadata.clone(),
            spec=LeaseSpec(
                self.spec.holder,
                self.spec.ttl_s,
                self.spec.acquire_time,
                self.spec.renew_time,
                self.spec.transitions,
                self.spec.epoch,
            ),
        )


@dataclass
class Binding:
    """v1.Binding equivalent (POSTed by minisched/minisched.go:267-273).

    ``expected_rv``: optional optimistic-concurrency precondition — the
    pod resource_version the placement decision was computed against.
    When set, the bind commits only if the pod is still at that version
    (Conflict otherwise): a pod whose spec changed between evaluation and
    commit must be re-evaluated, not bound on stale requirements.  The
    unset-node_name guard stays as the double-bind backstop either way.
    """

    pod_name: str
    pod_namespace: str
    node_name: str
    expected_rv: Optional[int] = None


@dataclass
class Event:
    """events.k8s.io/v1 Event equivalent — what the reference's events
    broadcaster writes through the API (scheduler/scheduler.go:55-59:
    ``events.NewBroadcaster(&events.EventSinkImpl{...})`` records real
    ``eventsv1`` objects a client can list).  Stored as a VOLATILE kind:
    list/watch-able like any object, excluded from WAL/checkpoint."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    message: str = ""
    #: "namespace/name" key of the object the event is about ('' for
    #: scheduler lifecycle events with no subject)
    regarding: str = ""
    #: the component that emitted it (reportingController)
    reporting_controller: str = "minisched-tpu"

    def clone(self) -> "Event":
        return Event(
            self.metadata.clone(),
            self.type,
            self.reason,
            self.message,
            self.regarding,
            self.reporting_controller,
        )


# ---------------------------------------------------------------------------
# Convenience constructors (the shapes sched.go:74-133 builds)
# ---------------------------------------------------------------------------


def make_node(
    name: str,
    unschedulable: bool = False,
    labels: Optional[Dict[str, str]] = None,
    capacity: Optional[Dict[str, Any]] = None,
    taints: Optional[List[Taint]] = None,
    slice_id: str = "",
    torus: Optional[tuple] = None,
    host_index: int = -1,
    slice_dims: Optional[tuple] = None,
) -> Node:
    cap = ResourceList.parse(capacity or {CPU: "4", MEMORY: "16Gi", PODS: 110})
    tx, ty, tz = (tuple(torus) + (0, 0, 0))[:3] if torus else (0, 0, 0)
    dx, dy, dz = (
        (tuple(slice_dims) + (0, 0, 0))[:3] if slice_dims else (0, 0, 0)
    )
    return Node(
        metadata=ObjectMeta(name=name, namespace="", labels=dict(labels or {})),
        spec=NodeSpec(
            unschedulable=unschedulable,
            taints=list(taints or []),
            slice_id=slice_id,
            torus_x=tx,
            torus_y=ty,
            torus_z=tz,
            host_index=host_index,
            slice_dx=dx,
            slice_dy=dy,
            slice_dz=dz,
        ),
        status=NodeStatus(capacity=cap, allocatable=cap.clone()),
    )


def make_pod(
    name: str,
    namespace: str = "default",
    requests: Optional[Dict[str, Any]] = None,
    labels: Optional[Dict[str, str]] = None,
    **spec_kwargs: Any,
) -> Pod:
    containers = [Container(requests=ResourceList.parse(requests))] if requests else [
        Container()
    ]
    return Pod(
        metadata=ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {})),
        spec=PodSpec(containers=containers, **spec_kwargs),
    )


def make_gang_pods(
    gang_name: str,
    size: int,
    namespace: str = "default",
    ttl_s: float = 30.0,
    requests: Optional[Dict[str, Any]] = None,
    labels: Optional[Dict[str, str]] = None,
    priority: int = 0,
    **spec_kwargs: Any,
) -> List[Pod]:
    """``size`` member pods of one gang (bench/test convenience).
    ``priority`` is the gang's priority CLASS — every member carries it,
    so the gang preempts (and is shielded from preemption) as a unit."""
    return [
        make_pod(
            f"{gang_name}-{i}",
            namespace=namespace,
            requests=requests,
            labels=labels,
            gang=GangSpec(gang_name, size, ttl_s),
            priority=priority,
            **spec_kwargs,
        )
        for i in range(size)
    ]
