"""Configuration: env-style process config + scheduler configuration.

Two tiers, mirroring the reference (SURVEY.md §5.6):

* ``ProcessConfig`` — the required env vars (``config/config.go:22-75``:
  PORT / KUBE_SCHEDULER_SIMULATOR_ETCD_URL / FRONTEND_URL).  Our in-memory
  control plane needs no etcd, so the etcd URL becomes an *optional*
  external-store URL; PORT/FRONTEND_URL keep their required-or-error
  semantics for drop-in familiarity.

* ``SchedulerConfig`` — the KubeSchedulerConfiguration analog: per-extension
  -point plugin enable/disable lists with ``"*"`` wildcard semantics and
  per-plugin weights + typed args (scheduler/plugin/plugins.go:77-202,
  defaultconfig.go:10-33).
"""

from __future__ import annotations

import copy
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class EmptyEnvError(Exception):
    """config/config.go:12's ErrEmptyEnv."""


@dataclass
class ProcessConfig:
    port: int
    frontend_url: str
    external_store_url: str = ""

    @staticmethod
    def from_env(env: Optional[Dict[str, str]] = None) -> "ProcessConfig":
        env = env if env is not None else dict(os.environ)

        def require(key: str) -> str:
            v = env.get(key, "")
            if not v:
                raise EmptyEnvError(f"env variable {key} is required but empty")
            return v

        return ProcessConfig(
            port=int(require("PORT")),
            frontend_url=require("FRONTEND_URL"),
            external_store_url=env.get("MINISCHED_TPU_STORE_URL", ""),
        )


# ---------------------------------------------------------------------------
# Scheduler configuration
# ---------------------------------------------------------------------------


@dataclass
class PluginEnabled:
    name: str
    weight: int = 1


@dataclass
class PluginSet:
    enabled: List[PluginEnabled] = field(default_factory=list)
    disabled: List[str] = field(default_factory=list)  # names or ["*"]


@dataclass
class SchedulerConfig:
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    plugin_args: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    queue_opts: Dict[str, Any] = field(default_factory=dict)
    time_scale: float = 1.0
    #: device-mesh pinning for the wave engine (ISSUE 7): 0 devices =
    #: defer to the startup policy (MINISCHED_MESH env / auto on >1
    #: device — parallel/sharding.resolve_mesh); a nonzero device count
    #: (and optional pod-axis factoring) builds exactly that mesh.
    #: Ignored by the scalar engine.
    mesh_devices: int = 0
    mesh_pod_shards: Optional[int] = None

    def clone(self) -> "SchedulerConfig":
        return copy.deepcopy(self)

    def score_weights(self) -> Dict[str, int]:
        return {e.name: e.weight for e in self.score.enabled}

    def extension_points(self) -> Dict[str, PluginSet]:
        return {
            "filter": self.filter,
            "post_filter": self.post_filter,
            "pre_score": self.pre_score,
            "score": self.score,
            "reserve": self.reserve,
            "permit": self.permit,
        }


def default_scheduler_config(time_scale: float = 1.0) -> SchedulerConfig:
    """The minisched default wiring (initialize.go:44-66): filter
    [NodeUnschedulable]; pre-score/score/permit [NodeNumber]."""
    return SchedulerConfig(
        filter=PluginSet(enabled=[PluginEnabled("NodeUnschedulable")]),
        pre_score=PluginSet(enabled=[PluginEnabled("NodeNumber")]),
        score=PluginSet(enabled=[PluginEnabled("NodeNumber", weight=1)]),
        permit=PluginSet(enabled=[PluginEnabled("NodeNumber")]),
        time_scale=time_scale,
    )


def default_full_roster_config(time_scale: float = 1.0) -> SchedulerConfig:
    """The upstream default plugin roster: the same 15-filter / 7-score
    enumeration (same order, same weights) the reference's defaultconfig
    produces (scheduler/defaultconfig/defaultconfig.go:17-33, enumerated
    in scheduler/scheduler_test.go:307-332 — filter :307-323, score with
    weights :324-332; NodeResourcesFit scores via its LeastAllocated
    ScoringStrategy, plugins_test.go:839-848).
    """
    return SchedulerConfig(
        filter=PluginSet(
            enabled=[
                PluginEnabled("NodeUnschedulable"),
                PluginEnabled("NodeName"),
                PluginEnabled("TaintToleration"),
                PluginEnabled("NodeAffinity"),
                PluginEnabled("NodePorts"),
                PluginEnabled("NodeResourcesFit"),
                PluginEnabled("VolumeRestrictions"),
                PluginEnabled("EBSLimits"),
                PluginEnabled("GCEPDLimits"),
                PluginEnabled("NodeVolumeLimits"),
                PluginEnabled("AzureDiskLimits"),
                PluginEnabled("VolumeBinding"),
                PluginEnabled("VolumeZone"),
                PluginEnabled("PodTopologySpread"),
                PluginEnabled("InterPodAffinity"),
            ]
        ),
        post_filter=PluginSet(enabled=[PluginEnabled("DefaultPreemption")]),
        pre_score=PluginSet(
            enabled=[
                PluginEnabled("ImageLocality"),
                PluginEnabled("InterPodAffinity"),
                PluginEnabled("PodTopologySpread"),
            ]
        ),
        score=PluginSet(
            enabled=[
                PluginEnabled("NodeResourcesBalancedAllocation", weight=1),
                PluginEnabled("ImageLocality", weight=1),
                PluginEnabled("InterPodAffinity", weight=1),
                PluginEnabled("NodeResourcesFit", weight=1),
                PluginEnabled("NodeAffinity", weight=1),
                PluginEnabled("PodTopologySpread", weight=2),
                PluginEnabled("TaintToleration", weight=1),
            ]
        ),
        time_scale=time_scale,
    )


def gang_roster_config(time_scale: float = 1.0) -> SchedulerConfig:
    """The full default roster plus the gang subsystem: Coscheduling at
    Permit (all-or-nothing admission over the waiting-pod machinery) and
    GangTopology in the score chain (slice/torus locality toward placed
    gang members).  A SEPARATE roster on purpose: the default permit
    chain is empty, which lets the wave engine skip per-pod WaitingPod
    registration entirely (_commit_winners' fast path) — workloads
    without gangs keep that; with no gang specs present this roster's
    placements are bit-identical anyway (GangTopology scores 0
    everywhere, Coscheduling passes every singleton)."""
    cfg = default_full_roster_config(time_scale=time_scale)
    # pre_score too: the scalar score reads the placed-gang aggregate
    # its pre_score derives from the snapshot (the batch path gets the
    # same aggregate through the PodTable's gang_* columns)
    cfg.pre_score.enabled.append(PluginEnabled("GangTopology"))
    cfg.score.enabled.append(PluginEnabled("GangTopology", weight=1))
    cfg.permit = PluginSet(enabled=[PluginEnabled("Coscheduling")])
    return cfg


def apply_plugin_customization(
    default: SchedulerConfig, custom: SchedulerConfig
) -> SchedulerConfig:
    """Merge a user's plugin enable/disable lists over the default config.

    Semantics of convertConfigurationForSimulator + ConvertForSimulator
    (scheduler/scheduler.go:97-142, plugins.go:146-202): only plugin
    enablement/args are accepted from the custom config; ``disabled``
    supports exact names and the ``"*"`` wildcard (drop all defaults);
    enabled entries are appended in order after surviving defaults.
    """
    out = default.clone()
    for point, merged in out.extension_points().items():
        user: PluginSet = getattr(custom, point)
        disabled = set(user.disabled)
        if "*" in disabled:
            merged.enabled = []
        else:
            merged.enabled = [e for e in merged.enabled if e.name not in disabled]
        existing = {e.name for e in merged.enabled}
        for e in user.enabled:
            if e.name not in existing:
                merged.enabled.append(copy.deepcopy(e))
    # plugin args: user entries win over defaults (Raw-vs-Object precedence
    # collapses to plain dicts here, plugins.go:77-141)
    for name, args in custom.plugin_args.items():
        out.plugin_args[name] = copy.deepcopy(args)
    out.queue_opts.update(custom.queue_opts)
    if custom.time_scale != 1.0:
        out.time_scale = custom.time_scale
    return out
