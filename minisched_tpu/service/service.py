"""Scheduler service: lifecycle wrapper around the engine.

Re-creates ``scheduler/scheduler.go:26-91`` — the ``Service`` owning
informer-factory + event-recorder creation (:54-59), engine construction
(:63), informer start/sync (:72-73), the run-loop spawn (:75), and
Restart/Shutdown via cancellation (:40-47,82-87).
"""

from __future__ import annotations

from typing import Any, Optional

from minisched_tpu.controlplane.client import Client, EventRecorder
from minisched_tpu.controlplane.informer import SharedInformerFactory
from minisched_tpu.engine.scheduler import Scheduler, new_scheduler
from minisched_tpu.service.config import SchedulerConfig, default_scheduler_config


class SchedulerService:
    def __init__(self, client: Client):
        self._client = client
        self._current_cfg: Optional[SchedulerConfig] = None
        self._scheduler: Optional[Scheduler] = None
        self._factory: Optional[SharedInformerFactory] = None
        # events land in the store as real (volatile) Event objects —
        # list/watch-able like the reference's broadcaster-written eventsv1.
        # The RAW store: event writes are control-plane internal and must
        # not consume (or block on) the client's API rate-limit tokens.
        self.recorder = EventRecorder(
            store=getattr(client.store, "_store", client.store)
        )
        self.result_store = None  # set by start_scheduler(record_results=True)
        self._record_results = False
        self._device_mode = False
        self._max_wave = 1024
        self._device_mesh = None
        self._shard_filter = None

    # scheduler/scheduler.go:50-80
    def start_scheduler(
        self,
        cfg: Optional[SchedulerConfig] = None,
        record_results: bool = False,
        device_mode: bool = False,
        max_wave: int = 1024,
        device_mesh=None,
        on_decision=None,
        metrics=None,
        prewarm: bool = False,
        prewarm_scan: bool = True,
        shard_filter=None,
    ) -> Scheduler:
        """``record_results=True`` swaps plugins for their simulator-wrapped
        versions and flushes per-decision results onto pod annotations —
        the reference ships this layer but never wires it into
        StartScheduler (SURVEY.md §2 row 8: test-only); here it's opt-in.
        The store is exposed as ``self.result_store``.

        ``device_mode=True`` runs the TPU wave engine
        (engine/device_scheduler.py) instead of the scalar loop: queue
        drained in waves of up to ``max_wave``, evaluated on device in
        conflict-repairing mode.  ``device_mesh``: a jax.sharding.Mesh —
        waves then evaluate SHARDED across the mesh (pod rows data-
        parallel, node columns model-parallel; parallel/sharding.py).

        ``shard_filter``: HA queue-admission predicate (pod → bool; see
        ha/membership.Membership.owns_pod) — installed on the engine
        BEFORE the informers start, so even the initial snapshot replay
        admits only this engine's shard.  N services with complementary
        filters run active-active against one control plane (ha/plane.py
        wires the whole participant).
        """
        if self._scheduler is not None:
            raise RuntimeError("scheduler already running; use restart_scheduler")
        cfg = (cfg or default_scheduler_config()).clone()  # deep-copy, :61
        orig_cfg = cfg.clone()  # pre-conversion: what restart re-applies
        self._factory = SharedInformerFactory(self._client.store)
        if record_results:
            from minisched_tpu.controlplane.informer import ResourceEventHandlers
            from minisched_tpu.observability.resultstore import Store
            from minisched_tpu.plugins.simulator import (
                convert_configuration_for_simulator,
                register_simulator_plugins,
            )

            self.result_store = Store(self._client)
            register_simulator_plugins(
                self.result_store,
                {e.name: e.weight for e in cfg.score.enabled},
            )
            cfg = convert_configuration_for_simulator(cfg)
            # flush hook: pod Update events write results to annotations
            # (store.go:62-67)
            self._factory.informer_for("Pod").add_event_handlers(
                ResourceEventHandlers(
                    on_update=self.result_store.add_scheduling_result_to_pod
                )
            )
        if device_mode:
            from minisched_tpu.engine.device_scheduler import new_device_scheduler

            sched = new_device_scheduler(
                self._client, self._factory, cfg, max_wave=max_wave,
                mesh=device_mesh,
            )
            if record_results:
                # the wave path records the same per-plugin artifact the
                # scalar simulator wrappers produce, via batch ingestion
                sched.result_store = self.result_store
        else:
            sched = build_scheduler_from_config(self._client, self._factory, cfg)
        # before factory.start(): the initial replay must already be
        # shard-filtered or a rebalance-sized purge follows immediately
        sched.shard_filter = shard_filter
        self.recorder.eventf(None, "Normal", "SchedulerStarted", "scheduler starting")
        self._factory.start()
        # generous timeout: over-the-wire informers (controlplane/remote.py)
        # replay the whole snapshot through JSON decode — a 100k-object
        # cluster takes tens of seconds; in-process sync returns as soon
        # as the counts match, so the ceiling costs nothing there
        if not self._factory.wait_for_cache_sync(timeout=300.0):
            raise RuntimeError("informer caches failed to sync")
        # observability hooks must be live BEFORE the engine thread starts —
        # installing them on the returned scheduler races the first waves
        if on_decision is not None:
            sched.on_decision = on_decision
        if metrics is not None:
            sched.metrics = metrics
        # per-decision cluster events (the reference's events broadcaster,
        # scheduler.go:55-59: upstream emits Scheduled/FailedScheduling)
        if sched.on_decision is None:
            def emit(pod, node_name, status):
                if node_name:
                    self.recorder.eventf(
                        pod, "Normal", "Scheduled",
                        f"Successfully assigned {pod.metadata.key} to {node_name}",
                    )
                else:
                    self.recorder.eventf(
                        pod, "Warning", "FailedScheduling",
                        "; ".join(status.reasons) or status.code.name,
                    )

            sched.on_decision = emit
        if prewarm and device_mode:
            # compile/load the wave executable for the live shapes BEFORE
            # the engine thread starts — otherwise the first wave pays it.
            # prewarm_scan=False skips the scan-lane warms for callers
            # whose workload carries no cross-pod-constrained pods.
            sched.prewarm(scan=prewarm_scan)
        sched.run()
        self._scheduler = sched
        self._current_cfg = orig_cfg
        self._record_results = record_results
        self._device_mode = device_mode
        self._max_wave = max_wave
        self._device_mesh = device_mesh
        self._shard_filter = shard_filter
        return sched

    # scheduler/scheduler.go:40-47
    def restart_scheduler(self, cfg: Optional[SchedulerConfig] = None) -> Scheduler:
        self.shutdown_scheduler()
        return self.start_scheduler(
            cfg or self._current_cfg,
            record_results=self._record_results,
            device_mode=self._device_mode,
            max_wave=self._max_wave,
            device_mesh=self._device_mesh,
            shard_filter=self._shard_filter,
        )

    # scheduler/scheduler.go:82-87
    def shutdown_scheduler(self) -> None:
        if self._scheduler is not None:
            self.recorder.eventf(None, "Normal", "SchedulerStopped", "scheduler stopping")
            self._scheduler.stop()
            self._scheduler = None
        if self._factory is not None:
            self._factory.shutdown()
            self._factory = None
        # a clean shutdown leaves every emitted Event visible in the store
        self.recorder.flush()

    def close(self) -> None:
        """Full teardown: shutdown plus the recorder's writer thread —
        call when the SERVICE is done for good (restart_scheduler keeps
        working after shutdown_scheduler alone; not after close)."""
        self.shutdown_scheduler()
        self.recorder.close()

    # scheduler/scheduler.go:89-91
    def get_scheduler_config(self) -> Optional[SchedulerConfig]:
        return self._current_cfg

    @property
    def scheduler(self) -> Optional[Scheduler]:
        return self._scheduler

    @property
    def informer_factory(self) -> Optional[SharedInformerFactory]:
        """The live factory (None before start/after shutdown) — the
        degraded-mode dashboards read ``.staleness()`` off it."""
        return self._factory


def build_scheduler_from_config(
    client: Client, factory: SharedInformerFactory, cfg: SchedulerConfig
) -> Scheduler:
    """Construct the engine from a SchedulerConfig (plugin enablement +
    weights) — the role of minisched.New + convertConfigurationForSimulator
    (initialize.go:35-78, scheduler.go:97-142)."""
    from minisched_tpu.plugins.registry import build_plugins

    chains = build_plugins(cfg)
    sched = Scheduler(
        client,
        factory,
        filter_plugins=chains.filter,
        post_filter_plugins=chains.post_filter,
        pre_score_plugins=chains.pre_score,
        score_plugins=chains.score,
        permit_plugins=chains.permit,
        reserve_plugins=chains.reserve,
        score_weights=cfg.score_weights(),
        queue_opts=cfg.queue_opts,
    )
    for p in chains.needs_handle:
        _inject(p, "h", sched)
    for p in chains.needs_client:
        _inject(p, "store_client", client)
    return sched


def _inject(plugin: Any, attr: str, value: Any) -> None:
    """Set an injected dependency on the REAL plugin: simulator wrappers
    delegate reads through ``__getattr__`` but a plain setattr would land
    on the wrapper, leaving the wrapped instance's attribute None."""
    target = plugin._inner if hasattr(plugin, "_inner") else plugin
    setattr(target, attr, value)


__all__ = [
    "SchedulerService",
    "build_scheduler_from_config",
    "new_scheduler",
]
