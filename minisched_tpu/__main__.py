"""Process entry point: boot the whole stack from environment config.

Re-creates ``sched.go``'s ``main``/``start()`` boot order (sched.go:21-68):
read the env config (PORT / FRONTEND_URL / optional external store URL),
bring up the control plane (the REST façade on PORT — the reference boots
a real apiserver), start the PV controller, start the scheduler service,
then serve until interrupted.

    PORT=10251 FRONTEND_URL=http://localhost:3000 python -m minisched_tpu

Subcommands:

    python -m minisched_tpu fsck <wal> [--checkpoint PATH]
                                       [--digests] [--compare OTHER]

        offline storage-integrity check (controlplane/fsck): WAL frame
        CRCs, checkpoint sha256 sidecars (both generations), replay
        through the real recovery path, rv/uid monotonicity, the
        per-node aggregate index, and the exactly-once bind audit.
        Prints a JSON report; exit 1 on any integrity error.
        ``--digests`` emits per-frame CRC32C digests (the offline half
        of the replicated plane's digest gossip); ``--compare OTHER``
        diffs two replica WALs — exit 1 iff the histories diverged
        (one being a prefix of the other is a follower catching up).

    python -m minisched_tpu metrics <url>

        scrape ``<url>/metrics`` (the REST façade or an engine's
        metricsd sidecar) and pretty-print the snapshot: counters,
        gauges, and per-histogram count/p50/p99/max bucket bounds.

Optional env:

    MINISCHED_TPU_STORE_URL=file:///tmp/cluster.wal   durable WAL store
                                                      (reference: etcd URL)
    MINISCHED_DEVICE_MODE=1                           TPU wave engine
    MINISCHED_MESH_DEVICES=8                          pin an N-device mesh
                                                      (overrides the policy)
    MINISCHED_MESH=0|1                                mesh policy when no pin
                                                      is set: 0 = never,
                                                      1 = always (all visible
                                                      devices), unset = auto
                                                      when >1 device
                                                      (parallel/sharding.
                                                      resolve_mesh)
"""

from __future__ import annotations

import os
import signal
import sys
import threading

from minisched_tpu.controlplane.client import DEFAULT_BURST, DEFAULT_QPS, Client
from minisched_tpu.controlplane.durable import store_from_url
from minisched_tpu.controlplane.httpserver import start_api_server
from minisched_tpu.controlplane.pvcontroller import start_pv_controller
from minisched_tpu.service.config import (
    ProcessConfig,
    default_full_roster_config,
    default_scheduler_config,
)
from minisched_tpu.service.service import SchedulerService


def start(cfg: ProcessConfig, device_mode: bool = False, mesh_devices: int = 0):
    """Boot the stack; returns (client, api_base_url, stop_fn)."""
    # validate the flag combination BEFORE booting any component — failing
    # after the store/API server/PV controller are live would leak their
    # threads and the open WAL with no stop path
    if mesh_devices and not device_mode:
        raise ValueError(
            "MINISCHED_MESH_DEVICES requires MINISCHED_DEVICE_MODE=1 — the "
            "scalar engine cannot shard waves"
        )
    store = store_from_url(cfg.external_store_url)
    # the reference's client limits (k8sapiserver.go:57-62: QPS/Burst 5000)
    client = Client(store=store, qps=DEFAULT_QPS, burst=DEFAULT_BURST)
    backing = client.store
    # the HTTP façade serves the SAME store the in-process client uses
    raw = getattr(backing, "_store", backing)  # unwrap any rate limiter
    server, base, shutdown_api = start_api_server(raw, port=cfg.port)
    pv = start_pv_controller(client)
    service = SchedulerService(client)
    scheduler_cfg = (
        default_full_roster_config() if device_mode else default_scheduler_config()
    )
    mesh = None
    if device_mode and mesh_devices:
        from minisched_tpu.parallel.sharding import make_mesh

        mesh = make_mesh(mesh_devices)
    service.start_scheduler(
        scheduler_cfg, device_mode=device_mode, device_mesh=mesh
    )

    def stop() -> None:
        service.close()
        pv.stop()
        shutdown_api()
        if hasattr(raw, "close"):
            raw.close()

    return client, base, stop


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "fsck":
        # the integrity CLI must not boot JAX or the scheduler stack —
        # it runs against dead files, often on a box mid-incident
        from minisched_tpu.controlplane.fsck import main as fsck_main

        return fsck_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "metrics":
        # scrape CLI: like fsck, must not boot JAX or the scheduler —
        # it only fetches and parses another process's exposition
        from minisched_tpu.observability.metricsd import scrape_main

        return scrape_main(sys.argv[2:])
    cfg = ProcessConfig.from_env()
    device_mode = os.environ.get("MINISCHED_DEVICE_MODE", "0") == "1"
    mesh_devices = int(os.environ.get("MINISCHED_MESH_DEVICES", "0"))
    if device_mode:
        from minisched_tpu.utils.compilecache import enable_persistent_cache

        enable_persistent_cache()
    _, base, stop = start(
        cfg, device_mode=device_mode, mesh_devices=mesh_devices
    )
    print(f"minisched_tpu: API on {base} (frontend {cfg.frontend_url})", flush=True)
    done = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
