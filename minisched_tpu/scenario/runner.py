"""Scenario runner: the scriptable L5 driver.

Re-creates ``sched.go`` — ``start()`` boots the stack in order
(config → control plane → PV-controller hook → scheduler service,
sched.go:30-68) and ``scenario()`` drives it programmatically
(sched.go:70-143).  The reference's timing-based sleeps (3s/5s,
sched.go:109,134) are replaced with condition-based waits
(``wait_for``), so scenarios are deterministic and fast (SURVEY.md §4
"implication for the new build").

Run the README scenario directly::

    python -m minisched_tpu.scenario.runner
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from minisched_tpu.api.objects import make_node, make_pod
from minisched_tpu.controlplane.client import Client
from minisched_tpu.controlplane.pvcontroller import start_pv_controller
from minisched_tpu.service.config import SchedulerConfig, default_scheduler_config
from minisched_tpu.service.service import SchedulerService


class ScenarioTimeout(AssertionError):
    pass


class ScenarioHarness:
    """Everything ``start()`` boots (sched.go:30-68), bundled."""

    def __init__(self, cfg: Optional[SchedulerConfig] = None):
        self.client = Client()
        self.pv_controller = start_pv_controller(self.client)
        self.service = SchedulerService(self.client)
        self.cfg = cfg or default_scheduler_config()

    def __enter__(self) -> "ScenarioHarness":
        self.service.start_scheduler(self.cfg)
        return self

    def __exit__(self, *exc) -> None:
        self.service.close()
        self.pv_controller.stop()

    # condition-based wait (replaces sched.go's time.Sleep)
    def wait_for(
        self,
        pred: Callable[[], bool],
        timeout: float = 10.0,
        interval: float = 0.01,
        msg: str = "condition",
    ) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(interval)
        if pred():
            return
        raise ScenarioTimeout(f"timed out waiting for {msg}")

    def pod_node(self, name: str, namespace: str = "default") -> str:
        return self.client.pods().get(name, namespace).spec.node_name


def readme_scenario(harness: ScenarioHarness, log: Callable[[str], None] = print) -> str:
    """The reference's integration scenario (sched.go:70-143):

    1. create nodes node0..node8, all unschedulable (+ pod1) — pod must
       stay pending (asserted at sched.go:115-119);
    2. create schedulable node10 — the Node/Add event requeues pod1 and it
       binds to node10 (sched.go:121-140).

    Returns the bound node name.
    """
    client = harness.client
    for i in range(9):
        client.nodes().create(make_node(f"node{i}", unschedulable=True))
    log("created 9 unschedulable nodes")
    client.pods().create(make_pod("pod1"))
    log("created pod1")

    # pod must stay pending: wait until it has been tried and parked
    harness.wait_for(
        lambda: harness.service.scheduler.queue.stats()["unschedulable"] == 1,
        msg="pod1 parked in unschedulableQ",
    )
    assert harness.pod_node("pod1") == "", "pod1 should not be bound yet"
    log("pod1 is pending (no feasible node)")

    client.nodes().create(make_node("node10", unschedulable=False))
    log("created schedulable node10")

    harness.wait_for(
        lambda: harness.pod_node("pod1") == "node10",
        timeout=15.0,
        msg="pod1 bound to node10",
    )
    bound = harness.pod_node("pod1")
    log(f"pod1 is bound to {bound}")
    return bound


def main() -> None:
    # time_scale compresses NodeNumber's permit delay (node10 suffix "0"
    # → zero delay; timeout still armed) — full-speed reference timing
    # works too, just slower.
    with ScenarioHarness(default_scheduler_config(time_scale=0.1)) as h:
        bound = readme_scenario(h)
        assert bound == "node10"
        print("scenario OK")


if __name__ == "__main__":
    main()
