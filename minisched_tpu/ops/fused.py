"""The fused (pods × nodes) device evaluator — the TPU hot path.

This is the TPU-native re-design of the reference's per-pod scheduling
cycle (minisched/minisched.go:32-113): instead of a sequential
O(pods × nodes × plugins) CPU loop with a full node re-list per pod
(minisched.go:40,124,167), every registered plugin evaluates as a
vectorized predicate/score kernel over struct-of-arrays tables
(minisched_tpu.models.tables), and the whole chain —

    filter → pre-score → score → normalize → weighted-sum → masked-argmax

— compiles into ONE jitted XLA computation (SURVEY.md §7 stage 6).
``selectHost``'s reservoir-sampled random tie-break (minisched.go:304-325)
becomes the deterministic seeded masked-argmax implemented here, bit-exact
with the scalar oracle's ``engine.tiebreak.select_host``.

Design rules (SURVEY.md §7 hard part 4): static shapes only — infeasible
and padding entries are masked, never dropped; no python control flow on
array values; everything is pure so XLA can fuse.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from minisched_tpu.framework.plugin import implements_batch

UINT32_MAX = jnp.uint32(0xFFFFFFFF)
NEG_INF_SCORE = jnp.iinfo(jnp.int32).min


@dataclass(frozen=True)
class BatchContext:
    """Static per-compilation configuration handed to batch plugin kernels.

    Everything here must be hashable / trace-constant; per-call array data
    lives in the tables, not the context.
    """

    weights: Tuple[Tuple[str, int], ...] = ()
    #: True only inside the sequential scan (ops/sequential.py): kernels
    #: whose in-scan terms are statically zero elsewhere (InterPodAffinity's
    #: combo_excl matmul) compile them only when set
    in_scan: bool = False

    def weight_of(self, name: str) -> int:
        for n, w in self.weights:
            if n == name:
                return w
        return 1


def mix32(seed, idx):
    """Vector murmur3-finalizer-style mix of (seed, idx) → uint32.

    Bit-for-bit identical to ``engine.tiebreak.mix32`` (same 32-bit ops,
    evaluated in jnp's modular uint32 arithmetic).
    """
    seed = jnp.asarray(seed, jnp.uint32)
    idx = jnp.asarray(idx, jnp.uint32)
    x = seed ^ (idx * jnp.uint32(0x9E3779B9))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


import os as _os

#: route select_hosts through the one-pass Pallas kernel
#: (ops/pallas_kernels.py).  DEFAULT ON (VERDICT r4 item 2) — the XLA
#: lowering of the tail is ~5 passes over the (P, N) planes, the kernel
#: is one; select_hosts itself still falls back to XLA off-TPU.  Disable
#: with MINISCHED_TPU_PALLAS=0 or set_pallas(False); trace-time
#: constant, so toggle before building evaluators.
_USE_PALLAS = _os.environ.get("MINISCHED_TPU_PALLAS", "1") != "0"

#: test hook: route select_hosts through the Pallas dispatch logic even
#: off-TPU (interpret mode), so the SHAPE fallback below is exercisable
#: on CPU CI — the round-5 regression (P=1 crashing every scan-lane
#: consumer) was invisible to `make test` precisely because the route
#: was dead code off-TPU.
_FORCE_PALLAS_ROUTE = False


def set_pallas(enabled: bool) -> None:
    global _USE_PALLAS
    _USE_PALLAS = enabled


def set_force_pallas_route(enabled: bool) -> None:
    global _FORCE_PALLAS_ROUTE
    _FORCE_PALLAS_ROUTE = enabled


#: trace-time depth of mesh-sharded program builds (parallel/sharding.
#: MeshPackedCaller) — a pallas_call inside a GSPMD-partitioned program
#: would need a shard_map wrapper the kernel doesn't have, so the mesh
#: path takes the (bit-identical) XLA tail instead.  A depth counter,
#: not a bool: nested/overlapping traces from several callers must not
#: clear the guard early.  THREAD-LOCAL: jax traces run on the calling
#: thread, and a multi-engine process (HA plane) can trace a mesh
#: program and a single-device program concurrently — a process-global
#: flag would make the single-device engine permanently compile without
#: its Pallas route.
_MESH_TRACING = threading.local()


class mesh_trace_guard:
    """Context manager marking 'a mesh-sharded program is being traced'
    on this thread.

    Trace-time only — dispatch of an already-compiled executable never
    re-enters select_hosts, so wrapping every sharded call site costs a
    counter bump, and the flag is only ever read during trace."""

    def __enter__(self):
        _MESH_TRACING.depth = getattr(_MESH_TRACING, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _MESH_TRACING.depth -= 1
        return False


def tracing_under_mesh() -> bool:
    return getattr(_MESH_TRACING, "depth", 0) > 0


def _pallas_shape_ok(P: int, N: int) -> bool:
    """Whether select_hosts_pallas can tile (P, N) — the kernel's
    smallest tiles are 8 (pods) × 128 (nodes) (pallas_kernels._tiling).
    The bind-exact sequential scan evaluates ONE pod per step (P=1), so
    routing unconditionally on TPU crashed every scan-lane consumer
    (VERDICT r5 headline); non-tiling shapes take the XLA tail instead."""
    return P % 8 == 0 and N % 128 == 0


def select_hosts(scores, mask, seeds):
    """Batched deterministic selectHost (minisched.go:304-325 re-designed).

    scores: i32[P, N] weighted totals; mask: bool[P, N] feasibility;
    seeds: u32[P] per-pod tie-break seeds.

    Returns (choice i32[P] — node index or -1, best_score i32[P]).

    Rule (== engine.tiebreak.select_host): among feasible max-score nodes,
    pick the one minimizing mix32(seed, node_index); remaining ties (hash
    collisions) go to the lowest index.
    """
    if _USE_PALLAS and not tracing_under_mesh():
        import jax as _jax

        # only route to Pallas where it compiles natively — interpreter
        # mode off-TPU would be far slower than the XLA path below (tests
        # exercise the kernel directly with interpret=True), never inside
        # a mesh-sharded trace (a pallas_call under GSPMD needs a
        # shard_map the kernel doesn't have) — and only
        # for shapes the kernel can tile: P=1 scan steps and other
        # non-divisible shapes fall through to the XLA tail (bit-exact
        # either way; the Pallas kernel is a perf route, not a semantic)
        P, N = scores.shape
        if (
            _FORCE_PALLAS_ROUTE or _jax.default_backend() == "tpu"
        ) and _pallas_shape_ok(P, N):
            from minisched_tpu.ops.pallas_kernels import select_hosts_pallas

            return select_hosts_pallas(
                scores, mask, seeds, interpret=_FORCE_PALLAS_ROUTE
            )
    P, N = scores.shape
    masked = jnp.where(mask, scores, NEG_INF_SCORE)
    best = masked.max(axis=1)  # i32[P]
    cand = mask & (masked == best[:, None])
    h = mix32(seeds[:, None], jnp.arange(N, dtype=jnp.uint32)[None, :])
    hkey = jnp.where(cand, h, UINT32_MAX)
    minh = hkey.min(axis=1)
    # among positions achieving the min hash, prefer real candidates (guards
    # the pathological h == UINT32_MAX collision), then the lowest index
    is_min = hkey == minh[:, None]
    pref = is_min & cand
    has_pref = pref.any(axis=1)
    pick_from = jnp.where(has_pref[:, None], pref, is_min)
    choice = jnp.argmax(pick_from, axis=1).astype(jnp.int32)
    feasible_any = mask.any(axis=1)
    choice = jnp.where(feasible_any, choice, jnp.int32(-1))
    best = jnp.where(feasible_any, best, jnp.int32(0))
    return choice, best


@jax.tree_util.register_pytree_node_class
@dataclass
class PlacementResult:
    """Device-side result of one fused evaluation."""

    choice: Any  # i32[P] node index, -1 = unschedulable
    best_score: Any  # i32[P]
    feasible_count: Any  # i32[P]
    #: bool[K, P, N] per-filter-plugin pass masks (diagnostics; K = number of
    #: filter plugins).  Present only when the evaluator was built with
    #: ``with_diagnostics=True``.
    filter_masks: Optional[Any] = None
    #: i32[K, P, N] per-score-plugin normalized × weighted matrices
    #: (diagnostics).
    score_matrices: Optional[Any] = None
    #: i32[K, P, N] per-score-plugin RAW matrices, pre-normalize/pre-weight
    #: (diagnostics) — the batch analog of the scalar AddScoreResult record.
    raw_score_matrices: Optional[Any] = None

    def tree_flatten(self):
        return (
            (
                self.choice,
                self.best_score,
                self.feasible_count,
                self.filter_masks,
                self.score_matrices,
                self.raw_score_matrices,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, _, leaves):
        return cls(*leaves)


@dataclass
class StaticWavePlanes:
    """Round-invariant planes shared by every round of a repair wave.

    Filters/scores whose kernels don't read intra-wave committed state
    (``Plugin.reads_committed_state`` False — node identity, labels,
    taints, the cross-pod combo planes) produce the same mask / RAW score
    matrix in every round; the repair loop computes them ONCE and per
    round only re-evaluates the committed-state plugins, then
    re-NORMALIZES the cached raw scores against the round's full mask —
    bit-identical to evaluating the whole chain per round (normalization
    is the only mask-dependent score step)."""

    static_mask: Any  # bool[P, N] conjunction of static filter masks
    static_names: frozenset  # names of the filters folded into static_mask
    aux: Dict[str, Dict[str, Any]]  # pre-score aux (static plugins only)
    raw_scores: Dict[str, Any]  # plugin name → i32[P, N] raw score matrix


def precompute_static(
    pods,
    nodes,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
    extra: Any = None,
    extra_dynamic: frozenset = frozenset(),
) -> StaticWavePlanes:
    """Evaluate the round-invariant half of the chain once (traceable).

    ``extra_dynamic``: plugin names to treat as round-varying on top of
    the ``reads_committed_state`` flag — the sequential scans pass the
    plugins whose carried coupling planes (combos/volumes) change mid-
    scan, which the wave/repair split never has to care about."""
    valid = pods.valid[:, None] & nodes.valid[None, :]

    def is_dynamic(pl) -> bool:
        return (
            getattr(pl, "reads_committed_state", False)
            or pl.name() in extra_dynamic
        )

    mask = valid
    names = []
    for pl in filter_plugins:
        if is_dynamic(pl):
            continue
        names.append(pl.name())
        if getattr(pl, "needs_extra", False):
            mask = mask & pl.batch_filter(ctx, pods, nodes, extra)
        else:
            mask = mask & pl.batch_filter(ctx, pods, nodes)
    aux: Dict[str, Dict[str, Any]] = {}
    for pl in pre_score_plugins:
        if not is_dynamic(pl):
            aux[pl.name()] = pl.batch_pre_score(ctx, pods, nodes)
    raw: Dict[str, Any] = {}
    for pl in score_plugins:
        if is_dynamic(pl):
            continue
        if getattr(pl, "needs_extra", False):
            s = pl.batch_score(ctx, pods, nodes, aux.get(pl.name(), {}), extra)
        else:
            s = pl.batch_score(ctx, pods, nodes, aux.get(pl.name(), {}))
        raw[pl.name()] = s
    return StaticWavePlanes(mask, frozenset(names), aux, raw)


def evaluate(
    pods,
    nodes,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
    with_diagnostics: bool = False,
    extra: Any = None,
    static: Optional[StaticWavePlanes] = None,
) -> PlacementResult:
    """One fused scheduling evaluation (traceable; call under jit).

    Mirrors the scalar oracle exactly:
    * filter chain ANDs per-plugin masks (plugin-order short-circuiting,
      minisched.go:130-137, affects only diagnostics, not the mask — the
      conjunction is order-independent);
    * pre-score produces per-plugin aux arrays (the CycleState analog,
      nodenumber.go:58-61);
    * score → per-plugin normalize (mask-aware) → weight → sum
      (minisched.go:164-199, with the weight TODO at :187 implemented);
    * deterministic seeded masked-argmax (select_hosts).

    ``static``: precomputed round-invariant planes (precompute_static) —
    filters in ``static.static_names`` contribute via ``static_mask``
    instead of re-running, and static scorers reuse their cached RAW
    matrices (normalization still runs against THIS call's full mask, so
    results are bit-identical to the unsplit evaluation).  Incompatible
    with ``with_diagnostics`` (per-plugin masks need every filter run).
    """
    valid = pods.valid[:, None] & nodes.valid[None, :]
    if static is not None:
        assert not with_diagnostics, "diagnostics need the unsplit chain"
        mask = valid & static.static_mask
        run_filters = [
            pl for pl in filter_plugins if pl.name() not in static.static_names
        ]
    else:
        mask = valid
        run_filters = list(filter_plugins)
    per_filter = []
    for pl in run_filters:
        if getattr(pl, "needs_extra", False):
            m = pl.batch_filter(ctx, pods, nodes, extra)
        else:
            m = pl.batch_filter(ctx, pods, nodes)
        if with_diagnostics:
            per_filter.append(m)
        mask = mask & m

    aux: Dict[str, Dict[str, Any]] = dict(static.aux) if static else {}
    for pl in pre_score_plugins:
        if pl.name() not in aux:
            aux[pl.name()] = pl.batch_pre_score(ctx, pods, nodes)

    P, N = mask.shape
    totals = jnp.zeros((P, N), jnp.int32)
    per_score = []
    per_raw = []
    for pl in score_plugins:
        if static is not None and pl.name() in static.raw_scores:
            s = static.raw_scores[pl.name()]
        elif getattr(pl, "needs_extra", False):
            s = pl.batch_score(ctx, pods, nodes, aux.get(pl.name(), {}), extra)
        else:
            s = pl.batch_score(ctx, pods, nodes, aux.get(pl.name(), {}))
        if with_diagnostics:
            per_raw.append(s.astype(jnp.int32))
        s = pl.batch_normalize(ctx, s, mask)
        w = s.astype(jnp.int32) * jnp.int32(ctx.weight_of(pl.name()))
        if with_diagnostics:
            per_score.append(w)
        totals = totals + w

    choice, best = select_hosts(totals, mask, pods.seed)
    return PlacementResult(
        choice=choice,
        best_score=best,
        feasible_count=mask.sum(axis=1).astype(jnp.int32),
        filter_masks=jnp.stack(per_filter) if per_filter else None,
        score_matrices=jnp.stack(per_score) if per_score else None,
        raw_score_matrices=jnp.stack(per_raw) if per_raw else None,
    )


def unschedulable_plugin_masks(filter_masks, valid):
    """bool[K, P]: is filter plugin k a FIRST-failing plugin for pod p on
    some node — the batch analog of the scalar Diagnosis collection
    (minisched.go:118-121,134): per node, only the first plugin in chain
    order that rejects is recorded (short-circuit), and a pod's
    ``unschedulable_plugins`` is the union over nodes.

    filter_masks: bool[K, P, N] per-plugin pass masks (PlacementResult
    diagnostics); valid: bool[P, N] the pod×node validity mask.
    """
    prefix = valid
    out = []
    for k in range(filter_masks.shape[0]):
        m = filter_masks[k]
        out.append(jnp.any(prefix & ~m, axis=1))
        prefix = prefix & m
    return jnp.stack(out)


def validate_batch_chains(*chains: Sequence[Any]) -> None:
    """Every plugin in a device chain must implement the batch protocol —
    fail at construction with a clear error, not at trace time."""
    for chain in chains:
        for pl in chain:
            if not implements_batch(pl):
                raise TypeError(
                    f"plugin {pl.name()} has no batch form; "
                    "scalar-only plugins must run through the engine"
                )


class FusedEvaluator:
    """Compiled wrapper: plugin chains fixed at construction; tables vary.

    The jit caches one executable per (P, N) table capacity — capacities are
    padded to lane multiples (models.tables.pad_to) precisely so this cache
    stays small (SURVEY.md §7 hard part 4).
    """

    def __init__(
        self,
        filter_plugins: Sequence[Any],
        pre_score_plugins: Sequence[Any],
        score_plugins: Sequence[Any],
        weights: Optional[Dict[str, int]] = None,
        with_diagnostics: bool = False,
    ):
        validate_batch_chains(filter_plugins, pre_score_plugins, score_plugins)
        self.ctx = BatchContext(
            weights=tuple(sorted((weights or {}).items()))
        )
        self._fn = jax.jit(
            partial(
                evaluate,
                filter_plugins=tuple(filter_plugins),
                pre_score_plugins=tuple(pre_score_plugins),
                score_plugins=tuple(score_plugins),
                ctx=self.ctx,
                with_diagnostics=with_diagnostics,
            )
        )

    def __call__(self, pods, nodes, extra: Any = None) -> PlacementResult:
        return self._fn(pods, nodes, extra=extra)
