"""Construction-time guard for the repair loop's static/dynamic split.

``repair_wave_step(split_static=True)`` computes plugins with
``reads_committed_state = False`` once per wave.  That classification is a
hand-maintained flag whose failure mode is silent: a kernel that actually
reads committed state (the planes ``ops/state.apply_placements`` scatters
into — req_*/nzreq_*/used_port — or the volume planes the repair loop
carries) would keep serving round-1 verdicts and the wave could commit
invalid placements with no error anywhere.

This module probes the classification FUNCTIONALLY: each static-classified
plugin's batch kernels run twice on a tiny probe cluster — once as built,
once with EVERY committed-state plane perturbed — on the CPU backend
(eager per-op dispatch over the TPU tunnel costs ~30ms per op; one small
CPU jit per plugin is ~free and persistent-cached).  Any output difference
means the plugin reads committed state and the constructor refuses with
the fix spelled out.  RepairingEvaluator runs this once per construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

#: NodeTable planes apply_placements updates intra-wave
_NODE_COMMITTED = (
    "req_cpu", "req_mem", "req_eph", "req_pods", "nzreq_cpu", "nzreq_mem",
    "used_port", "num_used_ports",
)
#: ConstraintTables planes the repair loop carries/updates across rounds
_EXTRA_COMMITTED = ("vol_any", "vol_rw", "node_vols_fam")


def _probe_tables():
    """A tiny cluster whose committed-state perturbation flips verdicts:
    nodes near-full on every resource, a pod carrying a host port and a
    PVC — so any kernel consulting those planes must answer differently."""
    import jax

    from minisched_tpu.api.objects import (
        PersistentVolume,
        PersistentVolumeClaim,
        ObjectMeta,
        PVCSpec,
        PVSpec,
        make_node,
        make_pod,
    )
    from minisched_tpu.models.constraints import build_constraint_tables
    from minisched_tpu.models.tables import build_node_table, build_pod_table

    nodes = [
        make_node(
            f"probe{i}",
            labels={"zone": f"z{i % 2}"},
            capacity={"cpu": "1", "memory": "1Gi", "pods": 2,
                      "ephemeral-storage": "1Gi"},
        )
        for i in range(4)
    ]
    pod = make_pod(
        "probe-pod",
        requests={"cpu": "600m", "memory": "600Mi",
                  "ephemeral-storage": "600Mi"},
        volumes=["probe-claim"],
    )
    pod.spec.containers[0].ports = [8080]
    pv = PersistentVolume(
        ObjectMeta(name="probe-pv", namespace=""),
        PVSpec(capacity=1 << 30, claim_ref="default/probe-claim", driver="ebs"),
    )
    pvc = PersistentVolumeClaim(
        ObjectMeta(name="probe-claim"),
        PVCSpec(request=1 << 30, volume_name="probe-pv"),
    )
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        node_table, _ = build_node_table(nodes)
        pod_table, _ = build_pod_table([pod])
        extra = build_constraint_tables(
            [pod], nodes, [], pod_capacity=pod_table.capacity,
            node_capacity=node_table.capacity, pvcs=[pvc], pvs=[pv],
        )
    return pod_table, node_table, extra


def _perturb(node_table, extra):
    """Every committed-state plane, substantially changed: resources near
    the allocatable ceiling, the pod's own port claimed, every volume
    mounted read-write, family counts at the cap."""
    import jax.numpy as jnp

    half = {
        "req_cpu": node_table.alloc_cpu // 2 + 300,
        "req_mem": node_table.alloc_mem // 2 + 300,
        "req_eph": node_table.alloc_eph // 2 + 300,
        "req_pods": jnp.maximum(node_table.alloc_pods - 0, 2),
        "nzreq_cpu": node_table.alloc_cpu // 2 + 300,
        "nzreq_mem": node_table.alloc_mem // 2 + 300,
        "used_port": node_table.used_port.at[:, 0].set(8080),
        "num_used_ports": jnp.ones_like(node_table.num_used_ports),
    }
    nodes_p = dataclasses.replace(node_table, **half)
    extra_p = dataclasses.replace(
        extra,
        vol_any=jnp.ones_like(extra.vol_any),
        vol_rw=jnp.ones_like(extra.vol_rw),
        node_vols_fam=extra.node_vols_fam + 39,
    )
    return nodes_p, extra_p


def verify_static_classification(
    static_filters: Sequence[Any],
    static_scores: Sequence[Any],
    ctx: Any,
) -> None:
    """Raise TypeError naming any plugin classified round-invariant whose
    batch kernels are sensitive to committed-state planes."""
    import jax

    pods, nodes, extra = _probe_tables()
    nodes_p, extra_p = _perturb(nodes, extra)
    cpu = jax.devices("cpu")[0]

    # probes compile in-memory: XLA:CPU AOT cache LOADS warn (and can
    # SIGILL) whenever the cached entry's machine features mismatch the
    # host — including XLA's own pseudo-features that host detection never
    # reports, so even same-host loads are unsafe.  A tiny per-plugin CPU
    # compile costs less than one risky load.
    import contextlib

    @contextlib.contextmanager
    def _no_compilation_cache():
        try:
            old = jax.config.jax_enable_compilation_cache
        except AttributeError:  # option absent in this jax: nothing to gate
            yield
            return
        jax.config.update("jax_enable_compilation_cache", False)
        try:
            yield
        finally:
            jax.config.update("jax_enable_compilation_cache", old)

    def run(pl, kind, n, e):
        needs = getattr(pl, "needs_extra", False)
        if kind == "filter":
            fn = (lambda p, nn, ee: pl.batch_filter(ctx, p, nn, ee)) if needs \
                else (lambda p, nn, ee: pl.batch_filter(ctx, p, nn))
        else:
            aux = (
                pl.batch_pre_score(ctx, pods, n)
                if callable(getattr(pl, "batch_pre_score", None))
                else {}
            )
            fn = (lambda p, nn, ee: pl.batch_score(ctx, p, nn, aux, ee)) if needs \
                else (lambda p, nn, ee: pl.batch_score(ctx, p, nn, aux))
        with _no_compilation_cache(), jax.default_device(cpu):
            return np.asarray(jax.jit(fn)(pods, n, e))

    for kind, chain in (("filter", static_filters), ("score", static_scores)):
        for pl in chain:
            base = run(pl, kind, nodes, extra)
            pert = run(pl, kind, nodes_p, extra_p)
            if not np.array_equal(base, pert):
                raise TypeError(
                    f"plugin {pl.name()}: batch_{kind} output changes when "
                    "committed-state planes change, but the plugin is "
                    "classified round-invariant (reads_committed_state is "
                    "False).  Set `reads_committed_state = True` on the "
                    "plugin class so the repair loop re-evaluates it every "
                    "round."
                )
