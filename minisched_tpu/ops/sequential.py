"""Sequential device engine: bind-exact scheduling via ``lax.scan``.

The reference's loop schedules ONE pod per cycle, so every pod sees the
binds of all pods before it (minisched/minisched.go:32-113).  The wave
evaluator (ops/fused.py + ops/state.py) is the throughput mode — all pods
against the pre-wave state — which is bit-exact only for plugin chains
whose decisions don't depend on earlier binds (e.g. NodeUnschedulable +
NodeNumber).  For bind-dependent chains (NodeResourcesFit/LeastAllocated,
NodePorts, …) THIS module is the parity mode: a ``lax.scan`` over the pod
axis where each step evaluates one pod row (still fully vectorized over
nodes — the per-step kernel is a (1, N) slice of the same fused chain) and
commits the placement into the carried NodeTable before the next step.

One compiled program schedules the whole table: 100k pods = one scan of
100k fused steps, no host round-trips (SURVEY.md §7 hard part 2 — the
sequential-bind-vs-batch semantic, solved by making the device loop
sequential rather than approximating with repair passes).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from minisched_tpu.models.tables import NodeTable, PodTable
from minisched_tpu.ops.fused import BatchContext, evaluate
from minisched_tpu.ops.state import apply_placements


def _slice_pod(pods: PodTable, i) -> PodTable:
    """One-row PodTable view at index i (dynamic, traceable)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, i, 1, axis=0), pods
    )


def scan_schedule(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
) -> Tuple[NodeTable, Any, Any]:
    """Schedule every pod in order with sequential-bind semantics.

    Returns (final NodeTable, choice i32[P], best_score i32[P]) — the
    placements the reference's one-pod-at-a-time loop would produce,
    computed in one jitted scan.  Cross-pod (``needs_extra``) plugins are
    not supported here yet — their coupling state would need per-step
    updates; use the wave path with per-wave table rebuilds for those.
    """
    for pl in (*filter_plugins, *score_plugins):
        if getattr(pl, "needs_extra", False):
            raise NotImplementedError(
                f"sequential scan does not support cross-pod plugin "
                f"{pl.name()} yet"
            )

    def step(carry_nodes, i):
        pod_row = _slice_pod(pods, i)
        result = evaluate(
            pod_row,
            carry_nodes,
            filter_plugins,
            pre_score_plugins,
            score_plugins,
            ctx,
        )
        carry_nodes = apply_placements(carry_nodes, pod_row, result.choice)
        return carry_nodes, (result.choice[0], result.best_score[0])

    nodes, (choice, best) = jax.lax.scan(
        step, nodes, jnp.arange(pods.valid.shape[0])
    )
    return nodes, choice, best


class SequentialScheduler:
    """Compiled wrapper (the scan analog of FusedEvaluator)."""

    def __init__(
        self,
        filter_plugins: Sequence[Any],
        pre_score_plugins: Sequence[Any],
        score_plugins: Sequence[Any],
        weights: Optional[dict] = None,
    ):
        from minisched_tpu.ops.fused import validate_batch_chains

        validate_batch_chains(filter_plugins, pre_score_plugins, score_plugins)
        ctx = BatchContext(weights=tuple(sorted((weights or {}).items())))
        self._fn = jax.jit(
            partial(
                scan_schedule,
                filter_plugins=tuple(filter_plugins),
                pre_score_plugins=tuple(pre_score_plugins),
                score_plugins=tuple(score_plugins),
                ctx=ctx,
            )
        )

    def __call__(self, pods: PodTable, nodes: NodeTable):
        """Argument order matches FusedEvaluator (pods first); the inner
        scan keeps state-first like wave_step."""
        return self._fn(nodes, pods)
