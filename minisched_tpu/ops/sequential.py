"""Sequential device engine: bind-exact scheduling via ``lax.scan``.

The reference's loop schedules ONE pod per cycle, so every pod sees the
binds of all pods before it (minisched/minisched.go:32-113).  The wave
evaluator (ops/fused.py + ops/state.py) is the throughput mode — all pods
against the pre-wave state — which is bit-exact only for plugin chains
whose decisions don't depend on earlier binds (e.g. NodeUnschedulable +
NodeNumber).  For bind-dependent chains THIS module is the parity mode: a
``lax.scan`` over the pod axis where each step evaluates one pod row
(still fully vectorized over nodes — the per-step kernel is a (1, N)
slice of the same fused chain) and commits the placement before the next
step.

Cross-pod plugins are supported by carrying their coupling state through
the scan:

* **combo aggregates** (InterPodAffinity / PodTopologySpread): a
  committed pod joins ``combo_global`` / ``combo_here`` / ``combo_dsum``
  for every combo whose selector it matches (``pod_matches_combo``,
  host-precomputed), with the domain mask derived on device from the
  topo-key planes.  Its required anti-affinity terms accumulate into
  ``combo_excl``, which the affinity filter applies to later pods — the
  in-scan version of the reverse-direction check.
* **volume planes** (VolumeRestrictions / limit family / VolumeBinding):
  the committed pod's mounts update ``vol_any`` / ``vol_rw`` /
  ``node_vols_fam`` exactly like the repair loop's commit step.

One compiled program schedules the whole table: 100k pods = one scan of
100k fused steps, no host round-trips (SURVEY.md §7 hard part 2 — the
sequential-bind-vs-batch semantic, solved by making the device loop
sequential rather than approximating with repair passes).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from minisched_tpu.models.constraints import (
    HARD_POD_AFFINITY_WEIGHT,
    POD_AXIS_FIELDS,
)
from minisched_tpu.models.tables import NodeTable, PodTable
from minisched_tpu.ops.fused import (
    BatchContext,
    StaticWavePlanes,
    evaluate,
    precompute_static,
)
from minisched_tpu.ops.state import apply_placements, mount_slot_planes


def _slice_pod(pods: PodTable, i) -> PodTable:
    """One-row PodTable view at index i (dynamic, traceable)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, i, 1, axis=0), pods
    )


def _slice_extra_row(extra: Any, i) -> Any:
    """ConstraintTables with every pod-axis plane narrowed to row i."""
    reps = {
        f: jax.lax.dynamic_slice_in_dim(getattr(extra, f), i, 1, axis=0)
        for f in POD_AXIS_FIELDS
    }
    return dataclasses.replace(extra, **reps)


def _combo_domain_masks(extra: Any, n) -> Any:
    """bool[C, N]: for each combo, the nodes sharing node ``n``'s value of
    the combo's topology key (all-False when n lacks the key).  Unique
    (hostname-like) keys collapse to {n} itself."""
    keys = extra.combo_key  # (C,)
    D = extra.topo_onehot.shape[1]
    d = extra.topo_domain[keys, n]  # (C,) domain id or D sentinel
    has_key = d != D
    dom = extra.topo_onehot[keys, jnp.minimum(d, D - 1), :]  # (C, N)
    N = dom.shape[1]
    onehot_n = jnp.arange(N) == n
    unique = extra.topo_unique[keys]  # (C,)
    return jnp.where(unique[:, None], onehot_n[None, :], dom) & has_key[:, None]


def scan_schedule(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
    extra: Any = None,
) -> Tuple[NodeTable, Any, Any]:
    """Schedule every pod in order with sequential-bind semantics.

    Returns (final NodeTable, choice i32[P], best_score i32[P]) — the
    placements the reference's one-pod-at-a-time loop would produce,
    computed in one jitted scan.  ``extra`` (the wave's ConstraintTables)
    is required when the chain contains cross-pod plugins; its coupling
    planes are carried and updated per committed pod.
    """
    needs_extra = any(
        getattr(pl, "needs_extra", False)
        for pl in (*filter_plugins, *score_plugins)
    )
    if needs_extra and extra is None:
        names = [
            pl.name()
            for pl in (*filter_plugins, *score_plugins)
            if getattr(pl, "needs_extra", False)
        ]
        raise ValueError(
            f"sequential scan with cross-pod plugins {names} needs the "
            "ConstraintTables — pass `extra`"
        )

    if extra is None:

        def step(carry_nodes, i):
            pod_row = _slice_pod(pods, i)
            result = evaluate(
                pod_row, carry_nodes, filter_plugins, pre_score_plugins,
                score_plugins, ctx,
            )
            carry_nodes = apply_placements(carry_nodes, pod_row, result.choice)
            return carry_nodes, (result.choice[0], result.best_score[0])

        nodes, (choice, best) = jax.lax.scan(
            step, nodes, jnp.arange(pods.valid.shape[0])
        )
        return nodes, choice, best

    # which coupling planes this chain actually needs carried — plugins
    # declare it (scan_carried_planes); an unknown cross-pod plugin without
    # the attribute gets everything (the safe default)
    tracked: set = set()
    for pl in (*filter_plugins, *pre_score_plugins, *score_plugins):
        if getattr(pl, "needs_extra", False):
            tracked |= set(
                getattr(pl, "scan_carried_planes", ("combos", "volumes"))
            )
    track_combos = "combos" in tracked
    track_vols = "volumes" in tracked

    if track_vols:
        slot_cnt, slot_vol, slot_ro, slot_fam, slot_dup = mount_slot_planes(
            extra
        )
        dummy_row = extra.vol_any.shape[0] - 1
        F = extra.node_vols_fam.shape[0]
    A = extra.pan_combo.shape[1]
    _z = jnp.zeros((1, 1), jnp.int32)  # placeholder for untracked carries

    def step(carry, i):
        carry_nodes, dsum, here, glob, excl, revw, va, vr, nvf = carry
        pod_row = _slice_pod(pods, i)
        reps = {}
        if track_combos:
            reps.update(
                combo_dsum=dsum, combo_here=here, combo_global=glob,
                combo_excl=excl, rev_weight=revw,
            )
        if track_vols:
            reps.update(vol_any=va, vol_rw=vr, node_vols_fam=nvf)
        extra_i = dataclasses.replace(_slice_extra_row(extra, i), **reps)
        result = evaluate(
            pod_row, carry_nodes, filter_plugins, pre_score_plugins,
            score_plugins, ctx, extra=extra_i,
        )
        choice = result.choice[0]
        committed = choice >= 0
        n = jnp.maximum(choice, 0)
        carry_nodes = apply_placements(carry_nodes, pod_row, result.choice)

        if track_combos:
            # -- combo aggregates: the committed pod becomes assigned -----
            dom = _combo_domain_masks(extra, n)  # (C, N)
            pmc = extra.pod_matches_combo[i] & committed  # (C,)
            dsum = dsum + (pmc[:, None] & dom).astype(dsum.dtype)
            here = here.at[:, n].add(pmc.astype(here.dtype))
            glob = glob + pmc.astype(glob.dtype)
            # its required anti-affinity terms ban matchers from the domain
            pan_c = extra.pan_combo[i]  # (A,)
            pan_in = (jnp.arange(A) < extra.pan_n[i]) & committed
            excl = excl.at[pan_c].max(pan_in[:, None] & dom[pan_c])
            # symmetric scoring: its preferred terms (signed weight) and
            # required affinity terms (hard weight) now score toward later
            # matching pods over its landing node's domain
            ppa_c = extra.ppa_combo[i]  # (W,)
            W = ppa_c.shape[0]
            ppa_in = (jnp.arange(W) < extra.ppa_n[i]) & committed
            revw = revw.at[ppa_c].add(
                jnp.where(ppa_in, extra.ppa_w[i], 0)[:, None]
                * dom[ppa_c].astype(revw.dtype)
            )
            pa_c = extra.pa_combo[i]  # (PA,)
            pa_in = (
                jnp.arange(pa_c.shape[0]) < extra.pa_n[i]
            ) & committed
            revw = revw.at[pa_c].add(
                jnp.where(pa_in, HARD_POD_AFFINITY_WEIGHT, 0)[:, None]
                * dom[pa_c].astype(revw.dtype)
            )

        if track_vols:
            # -- volume planes: same commit update as the repair loop -----
            sc, sv = slot_cnt[i], slot_vol[i]
            sro, sfam = slot_ro[i], slot_fam[i]
            attached = va[jnp.maximum(sc, 0), n]  # (V,)
            new_slot = committed & (sc >= 0) & ~slot_dup[i] & ~attached
            for f in range(F):
                nvf = nvf.at[f, n].add(
                    jnp.sum(new_slot & (sfam == f), dtype=nvf.dtype)
                )
            nvf = nvf.at[0, n].add(
                jnp.where(committed, extra.pod_missing[i], 0)
            )
            rows = jnp.where(committed & (sc >= 0), sc, dummy_row)
            va = va.at[rows, n].set(True)
            rw_rows = jnp.where(committed & (sv >= 0) & ~sro, sv, dummy_row)
            vr = vr.at[rw_rows, n].set(True)

        carry = (carry_nodes, dsum, here, glob, excl, revw, va, vr, nvf)
        return carry, (choice, result.best_score[0])

    carry0 = (
        nodes,
        extra.combo_dsum if track_combos else _z,
        extra.combo_here if track_combos else _z,
        extra.combo_global if track_combos else _z,
        extra.combo_excl if track_combos else _z,
        extra.rev_weight if track_combos else _z,
        extra.vol_any if track_vols else _z,
        extra.vol_rw if track_vols else _z,
        extra.node_vols_fam if track_vols else _z,
    )
    (nodes, *_), (choice, best) = jax.lax.scan(
        step, carry0, jnp.arange(pods.valid.shape[0])
    )
    return nodes, choice, best


def _slice_pods(pods: PodTable, start, size: int) -> PodTable:
    """A ``size``-row PodTable window starting at dynamic index ``start``."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, start, size, axis=0), pods
    )


def _slice_extra_rows(extra: Any, start, size: int) -> Any:
    reps = {
        f: jax.lax.dynamic_slice_in_dim(getattr(extra, f), start, size, axis=0)
        for f in POD_AXIS_FIELDS
    }
    return dataclasses.replace(extra, **reps)


def blocked_scan_schedule(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
    extra: Any,
    block_size: int = 32,
) -> Tuple[NodeTable, Any, Any, Any]:
    """Hybrid scan-repair over PRE-GROUPED blocks: the cross-pod lane's
    throughput mode (VERDICT r3 item 4).

    The caller orders pods so every consecutive ``block_size`` window has
    pairwise-DISJOINT cross-pod interaction sets (no pod matches another's
    selector combos or shares a volume — engine/scan_groups.py).  Each
    step then evaluates a whole block against the carried coupling state,
    commits the subset passing repair's deterministic acceptance
    (ops/repair.accept_placements — capacity/port/volume safe), and
    applies every committed pod's plane updates.  Within an interaction
    group the semantics stay sequentially exact — one member per block,
    FIFO across blocks — which is what DoNotSchedule spread / required
    (anti-)affinity correctness needs; across groups, capacity coupling
    gets the repair wave's safety guarantee instead of sequential
    score-exactness (the same trade already accepted for plain pods).

    Returns (nodes, choice i32[P], best i32[P], accepted bool[P]): a pod
    with ``choice >= 0 & ~accepted`` was feasible but lost a same-node
    capacity race to an earlier-in-block pod — the caller retries it (a
    sequential order would never fail it); ``choice < 0`` means
    infeasible against the state its block observed.

    The commit math routes through small matmul chains against the
    hoisted topology one-hot planes — the earlier per-pod (B, A, N)
    domain-mask materializations and the (C, D, N) one-hot einsum read
    ~30MB/step and dominated the step wall, and TPU lowers the obvious
    gather/scatter forms to scalar-core loops.  Fully-padded trailing
    blocks (capacity tiers pad the pod axis) skip the whole step via
    ``lax.cond``.
    """
    from minisched_tpu.ops.repair import accept_placements

    P = pods.valid.shape[0]
    if P % block_size:
        raise ValueError(f"pod capacity {P} not divisible by {block_size}")
    names = {pl.name() for pl in filter_plugins}
    check_resources = "NodeResourcesFit" in names
    check_ports = "NodePorts" in names
    fam_limits = tuple(
        (pl.volume_family_index, pl.max_volumes)
        for pl in filter_plugins
        if getattr(pl, "volume_family_index", None) is not None
    )
    check_restr = any(
        getattr(pl, "enforces_volume_restrictions", False)
        for pl in filter_plugins
    )
    tracked: set = set()
    for pl in (*filter_plugins, *pre_score_plugins, *score_plugins):
        if getattr(pl, "needs_extra", False):
            tracked |= set(
                getattr(pl, "scan_carried_planes", ("combos", "volumes"))
            )
    track_combos = "combos" in tracked
    track_vols = "volumes" in tracked or bool(fam_limits) or check_restr
    if track_vols:
        slot_cnt, slot_vol, slot_ro, slot_fam, slot_dup = mount_slot_planes(
            extra
        )
        dummy_row = extra.vol_any.shape[0] - 1
        F = extra.node_vols_fam.shape[0]
    A = extra.pan_combo.shape[1]
    W = extra.ppa_combo.shape[1]
    PA = extra.pa_combo.shape[1]
    _z = jnp.zeros((1, 1), jnp.int32)
    B = block_size
    # static/dynamic roster split (the repair waves' precompute_static,
    # extended): plugins whose verdict can change mid-scan — committed
    # node state or the carried coupling planes — re-evaluate per step;
    # everything else evaluates ONCE over the whole chunk at batched
    # throughput and enters each step as sliced mask/raw-score rows.
    # HBM residency note: the cached planes are (P_cap, N) per static
    # scorer plus the bool mask — ~1.1GB at the 8192×10k tier with the
    # full roster's three static scorers.  Measured fine on a 16GB v5e
    # next to the node tables; shrink BLOCKED_MAX_CHUNK before adding
    # many static scorers on smaller parts.
    # evaluate() re-normalizes cached raw scores against each step's full
    # mask, so the split is bit-identical to the unsplit chain.  The
    # full-roster step was ~5.5ms of evaluate at (32, 10k) — op-count
    # bound, dominated by the ~14 static plugins this hoists.
    scan_dynamic = frozenset(
        pl.name()
        for pl in (*filter_plugins, *pre_score_plugins, *score_plugins)
        if getattr(pl, "needs_extra", False)
        and set(getattr(pl, "scan_carried_planes", ("combos", "volumes")))
        & tracked
    )
    static_planes = precompute_static(
        pods, nodes, filter_plugins, pre_score_plugins, score_plugins,
        ctx, extra=extra, extra_dynamic=scan_dynamic,
    )
    # per-pod pre-score aux re-derives from each step's sliced rows
    # instead of slicing cached entries (none of the cacheable plugins'
    # aux is worth the slicing machinery)
    static_planes = StaticWavePlanes(
        static_planes.static_mask, static_planes.static_names, {},
        static_planes.raw_scores,
    )

    def _slice_static(start):
        return StaticWavePlanes(
            jax.lax.dynamic_slice_in_dim(
                static_planes.static_mask, start, B, 0
            ),
            static_planes.static_names,
            {},
            {
                k: jax.lax.dynamic_slice_in_dim(v, start, B, 0)
                for k, v in static_planes.raw_scores.items()
            },
        )

    if track_combos:
        # hoisted per-call tensors: every step's zone-domain commit
        # updates are expressed as small matmul chains through these —
        # TPU lowers big gathers/scatters to slow per-element loops, so
        # the step routes (combo, domain) increments through the MXU
        # instead (counts/weights are small ints, exact in f32)
        keys = extra.combo_key  # (C,) combo → topo key id
        C = keys.shape[0]
        K = extra.topo_onehot.shape[0]
        D = extra.topo_onehot.shape[1]
        uniq_c = extra.topo_unique[keys]  # (C,)
        arange_c = jnp.arange(C)
        onehot_f = extra.topo_onehot.astype(jnp.float32)  # (K, D, N)
        key_oh = (keys[None, :] == jnp.arange(K)[:, None]).astype(
            jnp.float32
        )  # (K, C)

    def step(carry, b):
        start = b * B
        pod_block = _slice_pods(pods, start, B)

        def skip_step(carry):
            # fully-padded trailing block (capacity tier > pod count):
            # the whole evaluate/commit body would be masked no-ops
            return carry, (
                jnp.full((B,), -1, jnp.int32),
                jnp.zeros((B,), jnp.int32),
                jnp.zeros((B,), bool),
            )

        def live_step(carry):
            carry_nodes, dsum, here, glob, excl, revw, va, vr, nvf = carry
            reps = {}
            if track_combos:
                reps.update(
                    combo_dsum=dsum, combo_here=here, combo_global=glob,
                    combo_excl=excl, rev_weight=revw,
                )
            if track_vols:
                reps.update(vol_any=va, vol_rw=vr, node_vols_fam=nvf)
            extra_b = dataclasses.replace(
                _slice_extra_rows(extra, start, B), **reps
            )
            result = evaluate(
                pod_block, carry_nodes, filter_plugins, pre_score_plugins,
                score_plugins, ctx, extra=extra_b,
                static=_slice_static(start),
            )
            choice = result.choice  # (B,)
            accept = accept_placements(
                carry_nodes, pod_block, choice, pod_block.valid,
                check_resources=check_resources, check_ports=check_ports,
                vol_state=(
                    [
                        (extra_b.pod_vols_fam[:, f], nvf[f], mx)
                        for f, mx in fam_limits
                    ]
                    if fam_limits
                    else None
                ),
                restr_state=(
                    (
                        jax.lax.dynamic_slice_in_dim(slot_vol, start, B, 0),
                        jax.lax.dynamic_slice_in_dim(slot_ro, start, B, 0),
                        extra.vol_any.shape[0],
                    )
                    if check_restr
                    else None
                ),
            )
            committed = accept & (choice >= 0)
            n_b = jnp.maximum(choice, 0)  # (B,)
            carry_nodes = apply_placements(
                carry_nodes, pod_block, jnp.where(committed, choice, -1)
            )

            if track_combos:
                # -- combo-count updates as matmul chains: each committed
                # pod's landing node defines, per topology key, a one-hot
                # domain row; (K, B, D) one-hots matmul through the
                # hoisted (K, D, N) planes into per-pod domain masks, and
                # a second matmul distributes them onto the (C, N)
                # planes.  The former per-combo einsum read the full
                # (C, D, N) one-hot (~21MB/step); this reads (K, D, N)
                # once and rides the MXU (~5MB/step at K=4).
                pmc = extra_b.pod_matches_combo & committed[:, None]  # (B, C)
                d_kb = extra.topo_domain[:, n_b]  # (K, B)
                has_kb = d_kb != D
                oh_kbd = (
                    (d_kb[..., None] == jnp.arange(D)) & has_kb[..., None]
                ).astype(jnp.float32)  # (K, B, D)
                dom_kbn = jnp.einsum(
                    "kbd,kdn->kbn", oh_kbd, onehot_f
                )  # (K, B, N) — pod j's domain mask under key k
                has = jnp.einsum("kc,kb->cb", key_oh, has_kb.astype(
                    jnp.float32)) > 0  # (C, B) — selects each combo's key
                zone_ok = has & ~uniq_c[:, None] & pmc.T  # (C, B)
                zkc = zone_ok.astype(jnp.float32)[None] * key_oh[
                    :, :, None
                ]  # (K, C, B)
                dsum = dsum + jnp.einsum(
                    "kcb,kbn->cn", zkc, dom_kbn
                ).astype(dsum.dtype)
                # hostname-like (unique) keys: the domain is the node itself
                uniq_add = (uniq_c[:, None] & has & pmc.T).astype(dsum.dtype)
                dsum = dsum.at[:, n_b].add(uniq_add)
                here = here.at[:, n_b].add(pmc.T.astype(here.dtype))
                glob = glob + jnp.sum(pmc, axis=0).astype(glob.dtype)

                def _term_chain(combo_rows, weights_z, valid):
                    # Σ over a pod's terms: weighted (C, B) membership by
                    # combo, split zone-like vs unique, then the zone part
                    # matmuls through the per-pod domain masks onto (C, N).
                    # Precision.HIGHEST: summed weights exceed 256, and the
                    # TPU default would feed them to the MXU as bf16
                    row_oh = (
                        combo_rows[..., None] == arange_c
                    )  # (B, T, C) — tiny
                    u_r = uniq_c[combo_rows]  # (B, T)
                    wz = jnp.where(valid & ~u_r, weights_z, 0).astype(
                        jnp.float32
                    )
                    m_cb = jnp.einsum(
                        "btc,bt->cb", row_oh.astype(jnp.float32), wz,
                        precision=jax.lax.Precision.HIGHEST,
                    )  # (C, B) zone-weight by combo
                    mk = m_cb[None] * key_oh[:, :, None]  # (K, C, B)
                    inc = jnp.einsum(
                        "kcb,kbn->cn", mk, dom_kbn,
                        precision=jax.lax.Precision.HIGHEST,
                    )  # (C, N)
                    return inc, (valid & u_r)

                # the committed pod's required anti-affinity terms ban
                # matchers from its landing domain
                pan_c = extra_b.pan_combo  # (B, A)
                pan_in = (
                    jnp.arange(A)[None, :] < extra_b.pan_n[:, None]
                ) & committed[:, None]
                pan_has = extra.topo_domain[keys[pan_c], n_b[:, None]] != D
                inc, vu = _term_chain(
                    pan_c, jnp.ones_like(pan_c), pan_in & pan_has
                )
                excl = excl | (inc > 0)
                excl = excl.at[
                    pan_c, jnp.broadcast_to(n_b[:, None], pan_c.shape)
                ].max(vu)

                # symmetric scoring: preferred terms (signed weight) and
                # required-affinity terms (hard weight) in one signed-add
                # increment
                rev_rows = jnp.concatenate(
                    [extra_b.ppa_combo, extra_b.pa_combo], axis=1
                )  # (B, W + PA)
                ppa_in = (
                    jnp.arange(W)[None, :] < extra_b.ppa_n[:, None]
                ) & committed[:, None]
                pa_in = (
                    jnp.arange(PA)[None, :] < extra_b.pa_n[:, None]
                ) & committed[:, None]
                rev_in = jnp.concatenate([ppa_in, pa_in], axis=1)
                rev_w = jnp.concatenate(
                    [
                        extra_b.ppa_w,
                        jnp.full(
                            (B, PA), HARD_POD_AFFINITY_WEIGHT,
                            extra_b.ppa_w.dtype,
                        ),
                    ],
                    axis=1,
                )
                rev_has = (
                    extra.topo_domain[keys[rev_rows], n_b[:, None]] != D
                )
                inc, vu = _term_chain(rev_rows, rev_w, rev_in & rev_has)
                revw = revw + inc.astype(revw.dtype)
                revw = revw.at[
                    rev_rows,
                    jnp.broadcast_to(n_b[:, None], rev_rows.shape),
                ].add(jnp.where(vu, rev_w, 0).astype(revw.dtype))

            if track_vols:
                # batched volume-plane commit (same math as the repair
                # round, over the block): disjointness guarantees no two
                # block pods share a volume, so per-pod scatters never
                # collide
                sc = jax.lax.dynamic_slice_in_dim(slot_cnt, start, B, 0)
                sv = jax.lax.dynamic_slice_in_dim(slot_vol, start, B, 0)
                sro = jax.lax.dynamic_slice_in_dim(slot_ro, start, B, 0)
                sfam = jax.lax.dynamic_slice_in_dim(slot_fam, start, B, 0)
                sdup = jax.lax.dynamic_slice_in_dim(slot_dup, start, B, 0)
                attached = va[jnp.maximum(sc, 0), n_b[:, None]]  # (B, V)
                new_slot = committed[:, None] & (sc >= 0) & ~sdup & ~attached
                for f in range(F):
                    counts_f = jnp.sum(
                        new_slot & (sfam == f), axis=1, dtype=nvf.dtype
                    )
                    nvf = nvf.at[f, n_b].add(counts_f)
                nvf = nvf.at[0, n_b].add(
                    jnp.where(committed, extra_b.pod_missing, 0)
                )
                rows = jnp.where(
                    committed[:, None] & (sc >= 0), sc, dummy_row
                )
                cols = jnp.broadcast_to(n_b[:, None], rows.shape)
                va = va.at[rows, cols].set(True)
                rw_rows = jnp.where(
                    committed[:, None] & (sv >= 0) & ~sro, sv, dummy_row
                )
                vr = vr.at[rw_rows, cols].set(True)

            carry = (carry_nodes, dsum, here, glob, excl, revw, va, vr, nvf)
            return carry, (choice, result.best_score, accept)

        return jax.lax.cond(
            jnp.any(pod_block.valid), live_step, skip_step, carry
        )

    carry0 = (
        nodes,
        extra.combo_dsum if track_combos else _z,
        extra.combo_here if track_combos else _z,
        extra.combo_global if track_combos else _z,
        extra.combo_excl if track_combos else _z,
        extra.rev_weight if track_combos else _z,
        extra.vol_any if track_vols else _z,
        extra.vol_rw if track_vols else _z,
        extra.node_vols_fam if track_vols else _z,
    )
    (nodes, *_), (choice, best, accepted) = jax.lax.scan(
        step, carry0, jnp.arange(P // B)
    )
    return (
        nodes,
        choice.reshape(P),
        best.reshape(P),
        accepted.reshape(P),
    )


def _make_packed_caller(consume, mesh: Any):
    """PackedCaller for the scan lanes: single-device by default; under
    a mesh the scan layout (node axis sharded, pods replicated — the
    scan is sequential over pods by construction, so only the node-side
    reductions parallelize)."""
    if mesh is not None:
        from minisched_tpu.parallel.sharding import MeshPackedCaller

        return MeshPackedCaller(consume, mesh, scan_layout=True)
    from minisched_tpu.models.tables import PackedCaller

    return PackedCaller(consume)


class BlockedSequentialScheduler:
    """Compiled wrapper for ``blocked_scan_schedule`` — same calling
    surface as SequentialScheduler plus the returned ``accepted`` mask."""

    def __init__(
        self,
        filter_plugins: Sequence[Any],
        pre_score_plugins: Sequence[Any],
        score_plugins: Sequence[Any],
        weights: Optional[dict] = None,
        block_size: int = 32,
        mesh: Any = None,
    ):
        from minisched_tpu.ops.fused import validate_batch_chains

        validate_batch_chains(filter_plugins, pre_score_plugins, score_plugins)
        ctx = BatchContext(
            weights=tuple(sorted((weights or {}).items())), in_scan=True
        )
        self._chains = (tuple(filter_plugins), tuple(pre_score_plugins),
                        tuple(score_plugins))
        self._ctx = ctx
        self._block_size = block_size
        #: jax.sharding.Mesh — packed chunks then run with the node axis
        #: sharded (pods replicated; see sharded_scan_step's layout rule)
        self._mesh = mesh
        self._packed_caller = None
        self._fn = jax.jit(
            partial(
                blocked_scan_schedule,
                filter_plugins=self._chains[0],
                pre_score_plugins=self._chains[1],
                score_plugins=self._chains[2],
                ctx=ctx,
                block_size=block_size,
            )
        )

    def __call__(self, pods: PodTable, nodes: NodeTable, extra: Any):
        return self._fn(nodes, pods, extra=extra)

    def call_packed(
        self,
        pod_packed: Any,
        node_static: Any,
        node_agg_packed: Any,
        extra_packed: Any,
    ):
        if self._packed_caller is None:
            filters, pre_scores, scores = self._chains
            block_size = self._block_size

            def consume(pods, nodes, extra):
                return blocked_scan_schedule(
                    nodes, pods,
                    filter_plugins=filters,
                    pre_score_plugins=pre_scores,
                    score_plugins=scores,
                    ctx=self._ctx,
                    extra=extra,
                    block_size=block_size,
                )

            self._packed_caller = _make_packed_caller(consume, self._mesh)
        return self._packed_caller(
            pod_packed, node_static, node_agg_packed, extra_packed
        )


class SequentialScheduler:
    """Compiled wrapper (the scan analog of FusedEvaluator)."""

    def __init__(
        self,
        filter_plugins: Sequence[Any],
        pre_score_plugins: Sequence[Any],
        score_plugins: Sequence[Any],
        weights: Optional[dict] = None,
        mesh: Any = None,
    ):
        from minisched_tpu.ops.fused import validate_batch_chains

        validate_batch_chains(filter_plugins, pre_score_plugins, score_plugins)
        ctx = BatchContext(
            weights=tuple(sorted((weights or {}).items())), in_scan=True
        )
        self._chains = (tuple(filter_plugins), tuple(pre_score_plugins),
                        tuple(score_plugins))
        self._ctx = ctx
        self._mesh = mesh
        self._packed_caller = None
        self._fn = jax.jit(
            partial(
                scan_schedule,
                filter_plugins=tuple(filter_plugins),
                pre_score_plugins=tuple(pre_score_plugins),
                score_plugins=tuple(score_plugins),
                ctx=ctx,
            )
        )

    def __call__(self, pods: PodTable, nodes: NodeTable, extra: Any = None):
        """Argument order matches FusedEvaluator (pods first); the inner
        scan keeps state-first like wave_step."""
        if extra is not None:
            return self._fn(nodes, pods, extra=extra)
        return self._fn(nodes, pods)

    def call_packed(
        self,
        pod_packed: Any,
        node_static: Any,
        node_agg_packed: Any,
        extra_packed: Any = None,
    ):
        """Single-program scan chunk: tables arrive as packed host flat
        buffers (+ device-resident static node columns) and are unpacked
        INSIDE the jitted program (models/tables.PackedCaller — same
        rationale as RepairingEvaluator.call_packed).  Under a mesh the
        chunk runs node-sharded (see _make_packed_caller)."""
        if self._packed_caller is None:
            filters, pre_scores, scores = self._chains

            def consume(pods, nodes, extra):
                return scan_schedule(
                    nodes, pods,
                    filter_plugins=filters,
                    pre_score_plugins=pre_scores,
                    score_plugins=scores,
                    ctx=self._ctx,
                    extra=extra,
                )

            self._packed_caller = _make_packed_caller(consume, self._mesh)
        return self._packed_caller(
            pod_packed, node_static, node_agg_packed, extra_packed
        )
