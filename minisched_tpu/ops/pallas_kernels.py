"""Pallas TPU kernels for the scheduling hot path.

``select_hosts`` (ops/fused.py) is the reduction tail of every fused
evaluation: masked max over nodes, tie-candidate mask, per-candidate
mix32 hash, hash argmin — ~5 XLA passes over the (P, N) matrices.  The
Pallas kernel here does it in ONE pass: tiles of the score/mask matrices
stream HBM→VMEM once, and per-pod running (best score, best hash, best
index) accumulators merge lexicographically across node tiles in VMEM
scratch.  Bit-exact with ``fused.select_hosts`` (tested), including the
hash-collision and no-feasible-node edge cases.

Enable with ``MINISCHED_TPU_PALLAS=1`` (the benchmark does) or
``fused.set_pallas(True)``; off CPU the kernel runs in interpreter mode
(tests), on TPU it compiles to Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# plain Python ints: a module-level jnp scalar would be a captured constant
# inside the pallas kernel, which pallas_call rejects
UINT32_MAX = 0xFFFFFFFF
NEG_INF_SCORE = int(jnp.iinfo(jnp.int32).min)
IDX_INF = 0x7FFFFFFF

POD_TILE = 128  # sublane dim of one grid step
NODE_TILE = 2048  # lane dim of one grid step (multiple of 128)


def _tiling(P: int, N: int):
    """(pod_tile, node_tile, grid) with loud validation — a non-divisible
    shape would silently truncate the grid and return garbage."""
    pod_tile = POD_TILE if P % POD_TILE == 0 else 8
    node_tile = NODE_TILE if N % NODE_TILE == 0 else 128
    if P % pod_tile or N % node_tile:
        raise ValueError(
            f"pallas select_hosts needs P % {pod_tile} == 0 and "
            f"N % {node_tile} == 0; got P={P}, N={N} "
            "(pad tables with models.tables.pad_to)"
        )
    return pod_tile, node_tile, (P // pod_tile, N // node_tile)


def _mix32(seed, idx):
    """== fused.mix32 (same modular uint32 ops)."""
    x = seed ^ (idx * jnp.uint32(0x9E3779B9))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _reduce_and_merge(
    masked, mask, seeds, choice_ref, best_ref, acc_score, acc_hash, acc_idx,
    node_tile: int,
):
    """Shared reduction tail of both kernels: per-tile lexicographic winner
    (score desc, hash asc, idx asc) merged into the VMEM accumulators,
    with init on the first node tile and the final write on the last."""
    nj = pl.program_id(1)
    n_tiles = pl.num_programs(1)

    @pl.when(nj == 0)
    def _init():
        acc_score[:] = jnp.full_like(acc_score, NEG_INF_SCORE)
        acc_hash[:] = jnp.full_like(acc_hash, IDX_INF)
        acc_idx[:] = jnp.full_like(acc_idx, IDX_INF)

    base = nj * node_tile
    gidx = base + jax.lax.broadcasted_iota(jnp.int32, masked.shape, 1)
    h = _mix32(seeds, gidx.astype(jnp.uint32))  # (TP, TN) uint32
    # Mosaic has no uint32 reductions: bitcast + sign-bit flip is an
    # order-isomorphic map onto int32 (uint32 0xFFFFFFFF ↦ int32 max)
    h_i = jax.lax.bitcast_convert_type(h, jnp.int32) ^ jnp.int32(-(1 << 31))

    # tile-local winner per pod row; hkey only competes among max-score
    # candidates
    tile_best = jnp.max(masked, axis=1, keepdims=True)  # (TP, 1)
    cand = mask & (masked == tile_best)
    hkey = jnp.where(cand, h_i, IDX_INF)
    tile_minh = jnp.min(hkey, axis=1, keepdims=True)
    # lowest index among positions at (cand & min hash); if no cand (all
    # infeasible), tile_best = NEG_INF and the merge below discards it
    at_min = cand & (hkey == tile_minh)
    idx_key = jnp.where(at_min, gidx, IDX_INF)
    tile_idx = jnp.min(idx_key, axis=1, keepdims=True)

    better = (tile_best > acc_score[:]) | (
        (tile_best == acc_score[:])
        & (
            (tile_minh < acc_hash[:])
            | ((tile_minh == acc_hash[:]) & (tile_idx < acc_idx[:]))
        )
    )
    acc_score[:] = jnp.where(better, tile_best, acc_score[:])
    acc_hash[:] = jnp.where(better, tile_minh, acc_hash[:])
    acc_idx[:] = jnp.where(better, tile_idx, acc_idx[:])

    @pl.when(nj == n_tiles - 1)
    def _finish():
        feasible = acc_score[:] > NEG_INF_SCORE
        choice_ref[:] = jnp.where(feasible, acc_idx[:], -1)
        best_ref[:] = jnp.where(feasible, acc_score[:], 0)


def _select_kernel(
    scores_ref,
    mask_ref,
    seeds_ref,
    choice_ref,
    best_ref,
    acc_score,
    acc_hash,
    acc_idx,
    *,
    node_tile: int,
):
    """Grid (pods/pod_tile, nodes/node_tile); node axis is the reduction."""
    scores = scores_ref[:]  # (TP, TN) i32
    mask = mask_ref[:]  # (TP, TN) bool
    masked = jnp.where(mask, scores, NEG_INF_SCORE)
    _reduce_and_merge(
        masked, mask, seeds_ref[:], choice_ref, best_ref,
        acc_score, acc_hash, acc_idx, node_tile,
    )


def _nn_fused_kernel(
    unsched_ref,
    nsuffix_ref,
    nvalid_ref,
    tol_ref,
    psuffix_ref,
    seeds_ref,
    pvalid_ref,
    choice_ref,
    best_ref,
    acc_score,
    acc_hash,
    acc_idx,
    *,
    node_tile: int,
    match_score: int,
):
    """Fully-fused flagship chain (NodeUnschedulable filter + NodeNumber
    score + seeded argmax): inputs are table COLUMNS only — the (P, N)
    mask/score matrices exist solely in VMEM registers, never in HBM."""
    unsched = unsched_ref[:]  # (1, TN) bool
    nsuffix = nsuffix_ref[:]  # (1, TN) i32
    nvalid = nvalid_ref[:]  # (1, TN) bool
    tol = tol_ref[:]  # (TP, 1) bool
    psuffix = psuffix_ref[:]  # (TP, 1) i32
    pvalid = pvalid_ref[:]  # (TP, 1) bool

    mask = (pvalid & nvalid) & (~unsched | tol)  # (TP, TN)
    match = (psuffix == nsuffix) & (psuffix >= 0) & (nsuffix >= 0)
    scores = jnp.where(match, match_score, 0)
    masked = jnp.where(mask, scores, NEG_INF_SCORE)
    _reduce_and_merge(
        masked, mask, seeds_ref[:], choice_ref, best_ref,
        acc_score, acc_hash, acc_idx, node_tile,
    )


@functools.partial(jax.jit, static_argnames=("interpret", "match_score"))
def nodenumber_select_hosts(
    pods, nodes, match_score: int = 10, interpret: bool = False
):
    """(choice, best_score) for the flagship NodeUnschedulable+NodeNumber
    chain, fully fused — bit-exact with FusedEvaluator on that chain, but
    with only O(P + N) HBM traffic per wave."""
    from minisched_tpu.plugins.nodeunschedulable import tolerates_unschedulable

    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    pod_tile, node_tile, grid = _tiling(P, N)
    tol = tolerates_unschedulable(pods)  # (P,) — tiny XLA prologue

    node_spec = pl.BlockSpec((1, node_tile), lambda i, j: (0, j), memory_space=pltpu.VMEM)
    pod_spec = pl.BlockSpec((pod_tile, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _nn_fused_kernel, node_tile=node_tile, match_score=match_score
    )
    choice, best = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[node_spec, node_spec, node_spec, pod_spec, pod_spec, pod_spec,
                  pod_spec],
        out_specs=[pod_spec, pod_spec],
        out_shape=[
            jax.ShapeDtypeStruct((P, 1), jnp.int32),
            jax.ShapeDtypeStruct((P, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((pod_tile, 1), jnp.int32),
            pltpu.VMEM((pod_tile, 1), jnp.int32),
            pltpu.VMEM((pod_tile, 1), jnp.int32),
        ],
        interpret=interpret,
    )(
        nodes.unschedulable[None, :],
        nodes.suffix[None, :],
        nodes.valid[None, :],
        tol[:, None],
        pods.suffix[:, None],
        pods.seed[:, None],
        pods.valid[:, None],
    )
    return choice[:, 0], best[:, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def select_hosts_pallas(scores, mask, seeds, interpret: bool = False):
    """One-pass (choice, best_score) — drop-in for fused.select_hosts.

    scores i32[P, N]; mask bool[P, N]; seeds u32[P].  P and N must be
    multiples of the tile sizes (tables.pad_to guarantees 128; POD_TILE=8
    divides 128).
    """
    P, N = scores.shape
    pod_tile, node_tile, grid = _tiling(P, N)

    kernel = functools.partial(_select_kernel, node_tile=node_tile)
    choice, best = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (pod_tile, node_tile), lambda i, j: (i, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (pod_tile, node_tile), lambda i, j: (i, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((pod_tile, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((pod_tile, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((pod_tile, 1), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((P, 1), jnp.int32),
            jax.ShapeDtypeStruct((P, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((pod_tile, 1), jnp.int32),
            pltpu.VMEM((pod_tile, 1), jnp.int32),  # hash in biased-int32 order
            pltpu.VMEM((pod_tile, 1), jnp.int32),
        ],
        interpret=interpret,
    )(scores, mask, seeds[:, None])
    return choice[:, 0], best[:, 0]
