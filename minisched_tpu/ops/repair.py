"""Wave scheduling with conflict repair: throughput mode that never
double-books.

The stateless wave (ops/state.wave_step) evaluates every pod against the
pre-wave state and commits all placements — two pods can double-book a
node that single-pod semantics would have caught (SURVEY.md §7 hard part
2).  The sequential scan (ops/sequential.py) is bind-exact but serial.
This module is the middle mode: per round, evaluate all uncommitted pods,
then ACCEPT the conflict-free subset under a deterministic rule — pods in
index order per node, while cumulative demand still fits (cpu / memory /
ephemeral / pod count) and no same-round host-port collision — commit
them, and re-evaluate the rejected remainder against the updated table.
Every round commits at least the lowest-indexed contender per node, so the
``lax.while_loop`` converges; infeasible pods (choice −1) are terminal
because commits only consume resources.

Placements are NOT bit-exact with the sequential loop (scores within a
round see round-start state); the guarantee is safety: the final table
never exceeds any node's allocatable, verified by tests/test_repair.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from minisched_tpu.models.tables import NodeTable, PodTable
from minisched_tpu.ops.fused import BatchContext, evaluate, precompute_static
from minisched_tpu.ops.state import apply_placements

_INF32 = jnp.int32(2**31 - 1)


def _segment_starts(sorted_keys):
    """positions of each segment's first element under a sorted key array."""
    pos = jnp.arange(sorted_keys.shape[0])
    is_start = jnp.concatenate(
        [jnp.array([True]), sorted_keys[1:] != sorted_keys[:-1]]
    )
    return jax.lax.cummax(jnp.where(is_start, pos, 0))


def accept_placements(
    nodes: NodeTable,
    pods: PodTable,
    choice,
    active,
    check_resources: bool = True,
    check_ports: bool = True,
    vol_state=None,
    restr_state=None,
):
    """bool[P]: which tentative placements commit this round.

    Deterministic rule: group pods by chosen node, take them in pod-index
    order while the node's remaining allocatable covers the cumulative
    demand; among same-round claims of one host port on one node only the
    first pod survives.

    ``check_resources`` / ``check_ports`` mirror whether NodeResourcesFit /
    NodePorts are in the filter chain — acceptance must enforce exactly
    what the chain enforces (a config without the Fit filter over-commits
    on purpose, like the reference would), and with the Fit filter present
    the first candidate per node always fits, which is what guarantees a
    commit per contested node per round (convergence).

    ``vol_state``: list of (pod_amt i32[P], node_count i32[N], max) triples,
    one per volume-limit plugin in the chain (the EBS/GCEPD/Azure/generic
    family split — plugins/volumelimits.py) — each family's counts then
    join the cumulative-demand rule.  (Same-round double-booking of one
    FREE PersistentVolume is out of acceptance's scope: the PV controller
    binds a claim exactly once, so the loser fails at bind time and
    requeues — the same race two racing schedulers have upstream.)

    ``restr_state``: (pod_vol i32[P, V], pod_ro bool[P, V]) — per mount
    slot, the volume row (−1 = unbound/none) and read-only flag — when
    VolumeRestrictions is in the chain.  Same-round claims of one volume
    on one node then follow the sequential-equivalent rule: the first pod
    (index order) always survives; later pods survive only if both they
    and the first are read-only.  (Exactly what a sequential bind order
    yields: a writable first mount blocks everyone, a read-only first
    mount admits read-only followers and rejects writable ones — a
    rejected writable never blocks later read-only mounts.)
    """
    P = choice.shape[0]
    live = active & (choice >= 0)
    if (
        not check_resources
        and not check_ports
        and vol_state is None
        and restr_state is None
    ):
        return live
    # sort by (node, pod index): key groups node segments, index-ordered
    key = jnp.where(live, choice, _INF32 // (P + 1)) * (P + 1) + jnp.arange(P)
    order = jnp.argsort(key)
    s_choice = choice[order]
    s_live = live[order]
    seg = _segment_starts(jnp.where(s_live, s_choice, -2))

    # same-round port dedup: claims of (node, port) keep the first pod
    if check_ports:
        W = pods.port.shape[1]
        slot_in_range = jnp.arange(W)[None, :] < pods.num_ports[:, None]
        # a pod repeating one port across its own containers is a single
        # claim — drop intra-pod duplicate slots so it can't lose to itself
        dup_within = jnp.any(
            (pods.port[:, :, None] == pods.port[:, None, :])
            & (jnp.arange(W)[None, None, :] < jnp.arange(W)[None, :, None])
            & slot_in_range[:, None, :],
            axis=2,
        )  # (P, W): an earlier slot already claims this port
        pair_key = (
            jnp.where(live, choice, -1)[:, None] * jnp.int32(65536) + pods.port
        )  # (P, W); ports < 65536
        pair_live = live[:, None] & slot_in_range & ~dup_within
        flat_key = jnp.where(pair_live, pair_key, _INF32).reshape(-1)
        # jnp.argsort is stable: pod-index order survives within equal keys
        porder = jnp.argsort(flat_key)
        sflat = flat_key[porder]
        first = jnp.concatenate([jnp.array([True]), sflat[1:] != sflat[:-1]])
        loses = jnp.zeros(P * W, bool).at[porder].set(~first & (sflat < _INF32))
        port_ok = ~jnp.any(loses.reshape(P, W), axis=1)  # (P,)
    else:
        port_ok = jnp.ones(P, bool)

    # same-round volume dedup (VolumeRestrictions): per (node, volume),
    # sequential-equivalent rule — first pod in index order survives,
    # later pods only when both they and the first mount read-only
    if restr_state is not None:
        pod_vol, pod_ro, n_vol_rows = restr_state
        V = pod_vol.shape[1]
        # a pod mounting one volume through two claims is a single mount —
        # drop intra-pod duplicate slots so it can't lose to itself (the
        # scalar filter only compares against OTHER pods)
        dup_within = jnp.any(
            (pod_vol[:, :, None] == pod_vol[:, None, :])
            & (pod_vol[:, None, :] >= 0)
            & (jnp.arange(V)[None, None, :] < jnp.arange(V)[None, :, None]),
            axis=2,
        )  # (P, V): an earlier slot already mounts this volume
        slot_live = live[:, None] & (pod_vol >= 0) & ~dup_within
        # key packs (node, volume); requires n_vol_rows * N < 2^31 (same
        # discipline as the port key's node * 65536 above)
        pair_key = choice[:, None] * jnp.int32(n_vol_rows) + pod_vol
        flat_key = jnp.where(slot_live, pair_key, _INF32).reshape(-1)
        # jnp.argsort is stable: pod-index order survives within equal keys
        vorder = jnp.argsort(flat_key)
        s_key = flat_key[vorder]
        s_ro = pod_ro.reshape(-1)[vorder]
        v_first = jnp.concatenate([jnp.array([True]), s_key[1:] != s_key[:-1]])
        first_ro = s_ro[_segment_starts(s_key)]
        ok_slot = v_first | (s_ro & first_ro)
        v_loses = jnp.zeros(P * V, bool).at[vorder].set(
            ~ok_slot & (s_key < _INF32)
        )
        restr_ok = ~jnp.any(v_loses.reshape(P, V), axis=1)  # (P,)
    else:
        restr_ok = jnp.ones(P, bool)

    eligible = s_live & (port_ok & restr_ok)[order]
    if not check_resources and vol_state is None:
        return jnp.zeros(P, bool).at[order].set(eligible) & live

    def prefix_fits(pod_amt, node_req, node_alloc):
        amt = jnp.where(eligible, pod_amt[order], 0)
        incl = jnp.cumsum(amt)
        ex = incl - amt  # exclusive cumsum
        within_ex = ex - ex[seg]  # demand of earlier accepted-candidates
        idx = jnp.where(s_live, s_choice, 0)
        headroom = (node_alloc - node_req)[idx]
        # zero-demand pods always pass, mirroring the filters (a pod that
        # requests nothing fits even an over-committed node — the scalar
        # NodeResourcesFit/NodeVolumeLimits semantics)
        return (amt == 0) | (within_ex + amt <= headroom)

    ones = jnp.ones(P, jnp.int32)
    fits = eligible
    if check_resources:
        fits = (
            fits
            & prefix_fits(pods.req_cpu, nodes.req_cpu, nodes.alloc_cpu)
            & prefix_fits(pods.req_mem, nodes.req_mem, nodes.alloc_mem)
            & prefix_fits(pods.req_eph, nodes.req_eph, nodes.alloc_eph)
            & prefix_fits(ones, nodes.req_pods, nodes.alloc_pods)
        )
    if vol_state is not None:
        for pod_amt, node_count, max_volumes in vol_state:
            fits = fits & prefix_fits(
                pod_amt, node_count, jnp.full_like(node_count, max_volumes)
            )
    # NOTE: the prefix rule is conservative only w.r.t. earlier *candidates*
    # that themselves fit — an earlier pod that does NOT fit still occupies
    # prefix demand this round; it is rejected and retried next round, so
    # convergence and safety both hold (never over-commit: the prefix is an
    # upper bound on what actually commits ahead of a pod).
    accept = jnp.zeros(P, bool).at[order].set(fits)
    return accept & live


def repair_wave_step(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
    extra: Any = None,
    max_rounds: int = 16,
    with_diagnostics: bool = False,
    split_static: bool = True,
) -> Tuple[Any, ...]:
    """Evaluate-accept-commit rounds until every pod is placed or
    infeasible (bounded by ``max_rounds``).  Traceable; call under jit.

    Returns (updated NodeTable, choice i32[P] with −1 = unplaced,
    rounds_used i32); with ``with_diagnostics`` a fourth element — bool
    [K, P] per-filter-plugin first-failure masks for the UNPLACED pods
    against the final table (ops/fused.unschedulable_plugin_masks) — so
    the engine's FitError names the actually-failing plugin(s), like the
    scalar Diagnosis (minisched.go:118-121,134).

    ``split_static``: compute the round-invariant planes (filters/raw
    scores of plugins with ``reads_committed_state`` False) ONCE and only
    re-evaluate the committed-state plugins per round — bit-identical
    results (ops/fused.StaticWavePlanes), at a fraction of the per-round
    FLOPs (the full default roster re-ran 15 filter kernels per round;
    only 7 read intra-wave state).  Off switch exists for the equivalence
    test.
    """
    P = pods.valid.shape[0]
    names = {pl.name() for pl in filter_plugins}
    check_resources = "NodeResourcesFit" in names
    check_ports = "NodePorts" in names
    # volume-limit plugins in the chain, as (family index, max) pairs —
    # EBS/GCEPD/Azure/generic all carry volume_family_index
    # (plugins/volumelimits.py); detection is attribute-based so simulator
    # wrappers (which forward attributes) are seen too
    fam_limits: Tuple[Tuple[int, int], ...] = ()
    check_restr = False
    if extra is not None:
        fam_limits = tuple(
            (pl.volume_family_index, pl.max_volumes)
            for pl in filter_plugins
            if getattr(pl, "volume_family_index", None) is not None
        )
        check_restr = any(
            getattr(pl, "enforces_volume_restrictions", False)
            for pl in filter_plugins
        )

    # the volume planes are carried whenever something reads them across
    # rounds: VolumeRestrictions (conflicts) or any limit plugin (its
    # unique-attach dedup reads vol_any)
    track_vols = check_restr or bool(fam_limits)
    if track_vols:
        # per-mount-slot volume rows / read-only flags, fixed across rounds
        from minisched_tpu.ops.state import mount_slot_planes

        slot_cnt, slot_vol, slot_ro, slot_fam, slot_dup = mount_slot_planes(
            extra
        )
        n_vol_rows = extra.vol_any.shape[0]
        dummy_row = n_vol_rows - 1  # never referenced by any claim row

    static = (
        precompute_static(
            pods, nodes, filter_plugins, pre_score_plugins, score_plugins,
            ctx, extra=extra,
        )
        if split_static
        else None
    )

    def cond(carry):
        nodes_, committed, final, rnd, progress, vols_fam, va, vr = carry
        return progress & (rnd < max_rounds)

    def body(carry):
        nodes_, committed, final, rnd, _, vols_fam, va, vr = carry
        import dataclasses

        active_pods = dataclasses.replace(
            pods, valid=pods.valid & ~committed
        )
        # feed committed volume state back into the FILTER too — otherwise
        # a node filled to its volume limit (or holding a conflicting
        # mount) in an earlier round keeps winning the argmax and the
        # contender never moves to its runner-up
        extra_ = extra
        if fam_limits:
            extra_ = dataclasses.replace(extra_, node_vols_fam=vols_fam)
        if track_vols:
            extra_ = dataclasses.replace(extra_, vol_any=va, vol_rw=vr)
        result = evaluate(
            active_pods, nodes_, filter_plugins, pre_score_plugins,
            score_plugins, ctx, extra=extra_, static=static,
        )
        accept = accept_placements(
            nodes_, active_pods, result.choice, active_pods.valid,
            check_resources=check_resources, check_ports=check_ports,
            vol_state=(
                [
                    (extra.pod_vols_fam[:, f], vols_fam[f], mx)
                    for f, mx in fam_limits
                ]
                if fam_limits
                else None
            ),
            restr_state=(
                (slot_vol, slot_ro, n_vol_rows) if check_restr else None
            ),
        )
        nodes_ = apply_placements(
            nodes_, active_pods, jnp.where(accept, result.choice, -1)
        )
        idx = jnp.where(accept, result.choice, 0)
        if fam_limits:
            # carry the committed attach counts so later rounds (which see
            # the static extra tables) can't blow the per-node limit —
            # counting only NEW attachments (a volume already on the node,
            # per pre-update vol_any, is not a new attach)
            attached = va[jnp.maximum(slot_cnt, 0), idx[:, None]]  # (P, V)
            new_slot = accept[:, None] & (slot_cnt >= 0) & ~slot_dup & ~attached
            for f in range(vols_fam.shape[0]):
                counts_f = jnp.sum(
                    new_slot & (slot_fam == f), axis=1, dtype=jnp.int32
                )
                vols_fam = vols_fam.at[f, idx].add(counts_f)
            vols_fam = vols_fam.at[0, idx].add(
                jnp.where(accept, extra.pod_missing, 0)
            )
        if track_vols:
            # record the committed pods' mounts in the volume planes;
            # non-accepted slots scatter into the dummy row.  vol_any rows
            # are counting keys (bound PV or unbound claim — the limit
            # plugins' dedup); vol_rw only tracks bound, writable mounts
            # (the restriction conflicts)
            slot_acc = accept[:, None] & (slot_cnt >= 0)
            rows = jnp.where(slot_acc, slot_cnt, dummy_row)
            cols = jnp.broadcast_to(idx[:, None], rows.shape)
            va = va.at[rows, cols].set(True)
            rw_rows = jnp.where(
                accept[:, None] & (slot_vol >= 0) & ~slot_ro, slot_vol, dummy_row
            )
            vr = vr.at[rw_rows, cols].set(True)
        final = jnp.where(accept, result.choice, final)
        committed = committed | accept
        # stop when nothing committed AND no uncommitted pod is feasible
        retryable = active_pods.valid & (result.choice >= 0) & ~accept
        progress = jnp.any(accept) & jnp.any(retryable)
        return nodes_, committed, final, rnd + 1, progress, vols_fam, va, vr

    committed0 = ~pods.valid  # padding rows never schedule
    final0 = jnp.full((P,), -1, jnp.int32)
    vols_fam0 = (
        extra.node_vols_fam
        if fam_limits
        else jnp.zeros((1, nodes.valid.shape[0]), jnp.int32)
    )
    va0 = extra.vol_any if track_vols else jnp.zeros((1, 1), bool)
    vr0 = extra.vol_rw if track_vols else jnp.zeros((1, 1), bool)
    nodes, committed, final, rounds, _, vols_fam, va, vr = jax.lax.while_loop(
        cond,
        body,
        (
            nodes, committed0, final0, jnp.int32(0), jnp.bool_(True),
            vols_fam0, va0, vr0,
        ),
    )
    if not with_diagnostics:
        return nodes, final, rounds

    # one diagnostic evaluation of the unplaced remainder against the
    # FINAL state (committed volume/limit planes included) — filters only
    # (the score chain can't affect unschedulable_plugins), and skipped
    # outright when every pod placed
    import dataclasses

    from minisched_tpu.ops.fused import unschedulable_plugin_masks

    K = len(filter_plugins)
    if K == 0:
        return nodes, final, rounds, jnp.zeros((0, P), bool)
    losers = dataclasses.replace(pods, valid=pods.valid & ~committed)
    extra_f = extra
    if extra is not None and fam_limits:
        extra_f = dataclasses.replace(extra_f, node_vols_fam=vols_fam)
    if extra is not None and track_vols:
        extra_f = dataclasses.replace(extra_f, vol_any=va, vol_rw=vr)

    def diag(_):
        result = evaluate(
            losers, nodes, filter_plugins, (), (), ctx,
            with_diagnostics=True, extra=extra_f,
        )
        valid = losers.valid[:, None] & nodes.valid[None, :]
        return unschedulable_plugin_masks(result.filter_masks, valid)

    unsched = jax.lax.cond(
        jnp.any(losers.valid),
        diag,
        lambda _: jnp.zeros((K, P), bool),
        None,
    )
    return nodes, final, rounds, unsched


class RepairingEvaluator:
    """Compiled wrapper (argument order matches FusedEvaluator).

    ``mesh``: a jax.sharding.Mesh — the repair loop then runs SHARDED over
    the (pods × nodes) device mesh (parallel/sharding.py), inputs are
    re-placed onto the mesh per call, and the SAME construction-time
    guards run (batch-protocol validation + the static-classification
    probe) — a config must behave identically single-device and sharded.
    """

    def __init__(
        self,
        filter_plugins: Sequence[Any],
        pre_score_plugins: Sequence[Any],
        score_plugins: Sequence[Any],
        weights: Optional[dict] = None,
        max_rounds: int = 16,
        with_diagnostics: bool = False,
        split_static: bool = True,
        mesh: Any = None,
    ):
        from minisched_tpu.ops.fused import validate_batch_chains

        validate_batch_chains(filter_plugins, pre_score_plugins, score_plugins)
        ctx = BatchContext(weights=tuple(sorted((weights or {}).items())))
        if split_static:
            # functional guard: a plugin misclassified as round-invariant
            # would silently serve stale verdicts every round — probe each
            # static-classified kernel against perturbed committed-state
            # planes and refuse construction on any sensitivity
            from minisched_tpu.ops.staticcheck import verify_static_classification

            verify_static_classification(
                [
                    pl
                    for pl in filter_plugins
                    if not getattr(pl, "reads_committed_state", False)
                ],
                [
                    pl
                    for pl in score_plugins
                    if not getattr(pl, "reads_committed_state", False)
                ],
                ctx,
            )
        self._mesh = mesh
        # packed-mode state: jitted (flat buffers → results) entry points,
        # keyed on the (pod, node-agg, extra) schemas — see call_packed
        self._chains = (tuple(filter_plugins), tuple(pre_score_plugins),
                        tuple(score_plugins))
        self._ctx = ctx
        self._max_rounds = max_rounds
        self._with_diagnostics = with_diagnostics
        self._split_static = split_static
        self._packed_caller = None
        if mesh is not None:
            from minisched_tpu.parallel.sharding import sharded_repair_step

            self._fn = sharded_repair_step(
                mesh,
                filter_plugins,
                pre_score_plugins,
                score_plugins,
                ctx,
                max_rounds=max_rounds,
                with_diagnostics=with_diagnostics,
                split_static=split_static,
            )
        else:
            self._fn = jax.jit(
                partial(
                    repair_wave_step,
                    filter_plugins=tuple(filter_plugins),
                    pre_score_plugins=tuple(pre_score_plugins),
                    score_plugins=tuple(score_plugins),
                    ctx=ctx,
                    max_rounds=max_rounds,
                    with_diagnostics=with_diagnostics,
                    split_static=split_static,
                ),
            )

    def call_packed(
        self,
        pod_packed: Any,
        node_static: Any,
        node_agg_packed: Any,
        extra_packed: Any = None,
    ):
        """Single-program wave: tables arrive as PACKED host buffers plus
        the device-resident static node columns and are unpacked inside
        the one jitted program (models/tables.PackedCaller — program
        alternation on the tunneled runtime stalled ~1.4s per switch).
        Under a mesh the SAME packed contract holds, but the unpacked
        tables get sharding constraints so GSPMD partitions the wave over
        the (pods × nodes) device mesh and the static node columns are
        expected to arrive node-sharded
        (parallel/sharding.MeshPackedCaller — the ISSUE 7 live path)."""
        if self._packed_caller is None:
            filters, pre_scores, scores = self._chains

            def consume(pods, nodes, extra):
                return repair_wave_step(
                    nodes, pods,
                    filter_plugins=filters,
                    pre_score_plugins=pre_scores,
                    score_plugins=scores,
                    ctx=self._ctx,
                    extra=extra,
                    max_rounds=self._max_rounds,
                    with_diagnostics=self._with_diagnostics,
                    split_static=self._split_static,
                )

            if self._mesh is not None:
                from minisched_tpu.parallel.sharding import MeshPackedCaller

                self._packed_caller = MeshPackedCaller(consume, self._mesh)
            else:
                from minisched_tpu.models.tables import PackedCaller

                self._packed_caller = PackedCaller(consume)
        return self._packed_caller(
            pod_packed, node_static, node_agg_packed, extra_packed
        )

    def __call__(self, pods: PodTable, nodes: NodeTable, extra: Any = None):
        if self._mesh is not None:
            from minisched_tpu.parallel.sharding import (
                constraint_sharding,
                shard_tables,
            )

            pods, nodes = shard_tables(self._mesh, pods, nodes)
            if extra is not None:
                extra = jax.device_put(
                    extra, constraint_sharding(self._mesh, extra)
                )
            return self._fn(nodes, pods, extra)
        return self._fn(nodes, pods, extra=extra)
