"""Wave scheduling with conflict repair: throughput mode that never
double-books.

The stateless wave (ops/state.wave_step) evaluates every pod against the
pre-wave state and commits all placements — two pods can double-book a
node that single-pod semantics would have caught (SURVEY.md §7 hard part
2).  The sequential scan (ops/sequential.py) is bind-exact but serial.
This module is the middle mode: per round, evaluate all uncommitted pods,
then ACCEPT the conflict-free subset under a deterministic rule — pods in
index order per node, while cumulative demand still fits (cpu / memory /
ephemeral / pod count) and no same-round host-port collision — commit
them, and re-evaluate the rejected remainder against the updated table.
Every round commits at least the lowest-indexed contender per node, so the
``lax.while_loop`` converges; infeasible pods (choice −1) are terminal
because commits only consume resources.

Placements are NOT bit-exact with the sequential loop (scores within a
round see round-start state); the guarantee is safety: the final table
never exceeds any node's allocatable, verified by tests/test_repair.py.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from minisched_tpu.models.tables import NodeTable, PodTable
from minisched_tpu.ops.fused import BatchContext, evaluate
from minisched_tpu.ops.state import apply_placements

_INF32 = jnp.int32(2**31 - 1)


def _segment_starts(sorted_keys):
    """positions of each segment's first element under a sorted key array."""
    pos = jnp.arange(sorted_keys.shape[0])
    is_start = jnp.concatenate(
        [jnp.array([True]), sorted_keys[1:] != sorted_keys[:-1]]
    )
    return jax.lax.cummax(jnp.where(is_start, pos, 0))


def accept_placements(
    nodes: NodeTable,
    pods: PodTable,
    choice,
    active,
    check_resources: bool = True,
    check_ports: bool = True,
    vol_state=None,
):
    """bool[P]: which tentative placements commit this round.

    Deterministic rule: group pods by chosen node, take them in pod-index
    order while the node's remaining allocatable covers the cumulative
    demand; among same-round claims of one host port on one node only the
    first pod survives.

    ``check_resources`` / ``check_ports`` mirror whether NodeResourcesFit /
    NodePorts are in the filter chain — acceptance must enforce exactly
    what the chain enforces (a config without the Fit filter over-commits
    on purpose, like the reference would), and with the Fit filter present
    the first candidate per node always fits, which is what guarantees a
    commit per contested node per round (convergence).

    ``vol_state``: (pod_n_vols i32[P], node_vol_count i32[N], max_volumes)
    when NodeVolumeLimits is in the chain — volume counts then join the
    cumulative-demand rule.  (Same-round double-booking of one FREE
    PersistentVolume is out of acceptance's scope: the PV controller binds
    a claim exactly once, so the loser fails at bind time and requeues —
    the same race two racing schedulers have upstream.)
    """
    P = choice.shape[0]
    live = active & (choice >= 0)
    if not check_resources and not check_ports and vol_state is None:
        return live
    # sort by (node, pod index): key groups node segments, index-ordered
    key = jnp.where(live, choice, _INF32 // (P + 1)) * (P + 1) + jnp.arange(P)
    order = jnp.argsort(key)
    s_choice = choice[order]
    s_live = live[order]
    seg = _segment_starts(jnp.where(s_live, s_choice, -2))

    # same-round port dedup: claims of (node, port) keep the first pod
    if check_ports:
        W = pods.port.shape[1]
        slot_in_range = jnp.arange(W)[None, :] < pods.num_ports[:, None]
        # a pod repeating one port across its own containers is a single
        # claim — drop intra-pod duplicate slots so it can't lose to itself
        dup_within = jnp.any(
            (pods.port[:, :, None] == pods.port[:, None, :])
            & (jnp.arange(W)[None, None, :] < jnp.arange(W)[None, :, None])
            & slot_in_range[:, None, :],
            axis=2,
        )  # (P, W): an earlier slot already claims this port
        pair_key = (
            jnp.where(live, choice, -1)[:, None] * jnp.int32(65536) + pods.port
        )  # (P, W); ports < 65536
        pair_live = live[:, None] & slot_in_range & ~dup_within
        flat_key = jnp.where(pair_live, pair_key, _INF32).reshape(-1)
        # jnp.argsort is stable: pod-index order survives within equal keys
        porder = jnp.argsort(flat_key)
        sflat = flat_key[porder]
        first = jnp.concatenate([jnp.array([True]), sflat[1:] != sflat[:-1]])
        loses = jnp.zeros(P * W, bool).at[porder].set(~first & (sflat < _INF32))
        port_ok = ~jnp.any(loses.reshape(P, W), axis=1)  # (P,)
    else:
        port_ok = jnp.ones(P, bool)

    eligible = s_live & port_ok[order]
    if not check_resources and vol_state is None:
        return jnp.zeros(P, bool).at[order].set(eligible) & live

    def prefix_fits(pod_amt, node_req, node_alloc):
        amt = jnp.where(eligible, pod_amt[order], 0)
        incl = jnp.cumsum(amt)
        ex = incl - amt  # exclusive cumsum
        within_ex = ex - ex[seg]  # demand of earlier accepted-candidates
        idx = jnp.where(s_live, s_choice, 0)
        headroom = (node_alloc - node_req)[idx]
        # zero-demand pods always pass, mirroring the filters (a pod that
        # requests nothing fits even an over-committed node — the scalar
        # NodeResourcesFit/NodeVolumeLimits semantics)
        return (amt == 0) | (within_ex + amt <= headroom)

    ones = jnp.ones(P, jnp.int32)
    fits = eligible
    if check_resources:
        fits = (
            fits
            & prefix_fits(pods.req_cpu, nodes.req_cpu, nodes.alloc_cpu)
            & prefix_fits(pods.req_mem, nodes.req_mem, nodes.alloc_mem)
            & prefix_fits(pods.req_eph, nodes.req_eph, nodes.alloc_eph)
            & prefix_fits(ones, nodes.req_pods, nodes.alloc_pods)
        )
    if vol_state is not None:
        pod_n_vols, node_vol_count, max_volumes = vol_state
        fits = fits & prefix_fits(
            pod_n_vols, node_vol_count, jnp.full_like(node_vol_count, max_volumes)
        )
    # NOTE: the prefix rule is conservative only w.r.t. earlier *candidates*
    # that themselves fit — an earlier pod that does NOT fit still occupies
    # prefix demand this round; it is rejected and retried next round, so
    # convergence and safety both hold (never over-commit: the prefix is an
    # upper bound on what actually commits ahead of a pod).
    accept = jnp.zeros(P, bool).at[order].set(fits)
    return accept & live


def repair_wave_step(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins: Sequence[Any],
    pre_score_plugins: Sequence[Any],
    score_plugins: Sequence[Any],
    ctx: BatchContext,
    extra: Any = None,
    max_rounds: int = 16,
) -> Tuple[NodeTable, Any, Any]:
    """Evaluate-accept-commit rounds until every pod is placed or
    infeasible (bounded by ``max_rounds``).  Traceable; call under jit.

    Returns (updated NodeTable, choice i32[P] with −1 = unplaced,
    rounds_used i32).
    """
    P = pods.valid.shape[0]
    names = {pl.name() for pl in filter_plugins}
    check_resources = "NodeResourcesFit" in names
    check_ports = "NodePorts" in names
    vol_limit = None
    if extra is not None:
        for pl in filter_plugins:
            if pl.name() == "NodeVolumeLimits":
                vol_limit = pl.max_volumes

    def cond(carry):
        nodes_, committed, final, rnd, progress, vol_count = carry
        return progress & (rnd < max_rounds)

    def body(carry):
        nodes_, committed, final, rnd, _, vol_count = carry
        import dataclasses

        active_pods = dataclasses.replace(
            pods, valid=pods.valid & ~committed
        )
        # feed committed volume counts back into the FILTER too — otherwise
        # a node filled to its volume limit in an earlier round keeps
        # winning the argmax and the contender never moves to its runner-up
        extra_ = (
            dataclasses.replace(extra, node_vol_count=vol_count)
            if vol_limit is not None
            else extra
        )
        result = evaluate(
            active_pods, nodes_, filter_plugins, pre_score_plugins,
            score_plugins, ctx, extra=extra_,
        )
        accept = accept_placements(
            nodes_, active_pods, result.choice, active_pods.valid,
            check_resources=check_resources, check_ports=check_ports,
            vol_state=(
                (extra.pod_n_vols, vol_count, vol_limit)
                if vol_limit is not None
                else None
            ),
        )
        nodes_ = apply_placements(
            nodes_, active_pods, jnp.where(accept, result.choice, -1)
        )
        if vol_limit is not None:
            # carry the committed volume counts so later rounds (which see
            # the static extra tables) can't blow the per-node limit
            idx = jnp.where(accept, result.choice, 0)
            vol_count = vol_count.at[idx].add(
                jnp.where(accept, extra.pod_n_vols, 0)
            )
        final = jnp.where(accept, result.choice, final)
        committed = committed | accept
        # stop when nothing committed AND no uncommitted pod is feasible
        retryable = active_pods.valid & (result.choice >= 0) & ~accept
        progress = jnp.any(accept) & jnp.any(retryable)
        return nodes_, committed, final, rnd + 1, progress, vol_count

    committed0 = ~pods.valid  # padding rows never schedule
    final0 = jnp.full((P,), -1, jnp.int32)
    vol_count0 = (
        extra.node_vol_count
        if vol_limit is not None
        else jnp.zeros((nodes.valid.shape[0],), jnp.int32)
    )
    nodes, committed, final, rounds, _, _ = jax.lax.while_loop(
        cond,
        body,
        (nodes, committed0, final0, jnp.int32(0), jnp.bool_(True), vol_count0),
    )
    return nodes, final, rounds


class RepairingEvaluator:
    """Compiled wrapper (argument order matches FusedEvaluator)."""

    def __init__(
        self,
        filter_plugins: Sequence[Any],
        pre_score_plugins: Sequence[Any],
        score_plugins: Sequence[Any],
        weights: Optional[dict] = None,
        max_rounds: int = 16,
    ):
        from minisched_tpu.ops.fused import validate_batch_chains

        validate_batch_chains(filter_plugins, pre_score_plugins, score_plugins)
        ctx = BatchContext(weights=tuple(sorted((weights or {}).items())))
        self._fn = jax.jit(
            partial(
                repair_wave_step,
                filter_plugins=tuple(filter_plugins),
                pre_score_plugins=tuple(pre_score_plugins),
                score_plugins=tuple(score_plugins),
                ctx=ctx,
                max_rounds=max_rounds,
            ),
        )

    def __call__(self, pods: PodTable, nodes: NodeTable, extra: Any = None):
        return self._fn(nodes, pods, extra=extra)
