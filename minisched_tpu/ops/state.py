"""On-device cluster-state updates: apply placements to the NodeTable.

The reference re-lists every node from the apiserver on every scheduling
cycle (minisched/minisched.go:40) — the #1 pattern not to copy (SURVEY.md §7
stage 7).  Here bind results are applied to the resident NodeTable with a
scatter-add, so scheduling 100k pods against 10k nodes never re-uploads
cluster state: the host only streams pod waves in and placements out.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from minisched_tpu.models import tables
from minisched_tpu.models.tables import NodeTable, PodTable


def _commit_ports(nodes: NodeTable, pods: PodTable, placed, choice):
    """Append each placed pod's host ports to its node's ``used_port`` slots.

    Slot assignment needs a per-node *rank* for the incoming ports (two
    ports landing on the same node must take consecutive free slots); rank
    is computed sort-free-ranking style: sort all (node, port) pairs by
    node, then rank = position − segment start.  Ports beyond a node's
    MAX_PORTS slot capacity are dropped (the host-side builder enforces
    the same ceiling with a ValueError).

    Returns (used_port, num_used_ports).  O(K log K), K = P × MAX_PORTS.
    """
    P, W = pods.port.shape
    N = nodes.valid.shape[0]
    slot_in_range = jnp.arange(W)[None, :] < pods.num_ports[:, None]
    pair_live = placed[:, None] & slot_in_range  # (P, W)
    pair_node = jnp.where(pair_live, choice[:, None], N).reshape(-1)  # K
    pair_port = jnp.where(pair_live, pods.port, 0).reshape(-1)
    order = jnp.argsort(pair_node)  # dead pairs (node=N) sort last
    snode = pair_node[order]
    sport = pair_port[order]
    pos = jnp.arange(snode.shape[0])
    is_start = jnp.concatenate([jnp.array([True]), snode[1:] != snode[:-1]])
    seg_start = jax.lax.cummax(jnp.where(is_start, pos, 0))
    rank = pos - seg_start
    slot = nodes.num_used_ports[jnp.minimum(snode, N - 1)] + rank
    ok = (snode < N) & (slot < nodes.used_port.shape[1])
    tgt_node = jnp.where(ok, snode, N)  # out-of-range → dropped
    tgt_slot = jnp.where(ok, slot, 0)
    used_port = nodes.used_port.at[tgt_node, tgt_slot].set(sport, mode="drop")
    num_used = nodes.num_used_ports.at[tgt_node].add(
        jnp.where(ok, 1, 0), mode="drop"
    )
    return used_port, num_used


def mount_slot_planes(extra) -> Tuple[Any, Any, Any, Any, Any]:
    """Per-mount-slot volume planes shared by the repair and sequential
    commit updates: (slot_cnt, slot_vol, slot_ro, slot_fam, slot_dup), all
    (P, V).  slot_cnt is the counting row (−1 = empty slot), slot_vol the
    bound-volume row (−1 = unbound/empty), slot_dup marks later mounts of
    a volume the pod already mounts (they count once)."""
    V = extra.pod_claims.shape[1]
    in_range = jnp.arange(V)[None, :] < extra.pod_n_vols[:, None]
    slot_valid = in_range & extra.pod_claim_valid
    slot_cnt = jnp.where(slot_valid, extra.claim_cnt[extra.pod_claims], -1)
    slot_vol = jnp.where(slot_valid, extra.claim_vol[extra.pod_claims], -1)
    slot_ro = extra.claim_ro[extra.pod_claims]
    slot_fam = extra.claim_family[extra.pod_claims]
    slot_dup = jnp.any(
        (slot_cnt[:, :, None] == slot_cnt[:, None, :])
        & (slot_cnt[:, None, :] >= 0)
        & (jnp.arange(V)[None, None, :] < jnp.arange(V)[None, :, None]),
        axis=2,
    )
    return slot_cnt, slot_vol, slot_ro, slot_fam, slot_dup


def apply_placements(nodes: NodeTable, pods: PodTable, choice) -> NodeTable:
    """Commit chosen placements: add each placed pod's resource requests to
    its node's ``req_*`` accounting and its host ports to the node's
    used-port slots (the array analog of NodeInfo.AddPod).

    choice: i32[P] node index per pod, -1 = unplaced (dropped).
    Traceable; runs under jit as part of the wave step.
    """
    placed = (choice >= 0) & pods.valid
    idx = jnp.where(placed, choice, 0)

    def scatter(col, amount):
        amount = jnp.where(placed, amount, 0).astype(col.dtype)
        return col.at[idx].add(amount)

    used_port, num_used_ports = _commit_ports(nodes, pods, placed, choice)
    return replace(
        nodes,
        req_cpu=scatter(nodes.req_cpu, pods.req_cpu),
        req_mem=scatter(nodes.req_mem, pods.req_mem),
        req_eph=scatter(nodes.req_eph, pods.req_eph),
        req_pods=scatter(nodes.req_pods, jnp.ones_like(pods.req_pods)),
        nzreq_cpu=scatter(
            nodes.nzreq_cpu,
            jnp.where(pods.req_cpu == 0, tables.DEFAULT_NONZERO_CPU, pods.req_cpu),
        ),
        nzreq_mem=scatter(
            nodes.nzreq_mem,
            jnp.where(pods.req_mem == 0, tables.DEFAULT_NONZERO_MEM_MIB, pods.req_mem),
        ),
        used_port=used_port,
        num_used_ports=num_used_ports,
    )


def wave_step(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins,
    pre_score_plugins,
    score_plugins,
    ctx,
    extra=None,
) -> Tuple[NodeTable, Any, Any]:
    """One full device step: evaluate a pod wave against the resident
    NodeTable, then commit the placements (SURVEY.md §7 stage 7).

    Returns (updated NodeTable, choice i32[P], best_score i32[P]).
    Traceable — this is the function the driver's ``dryrun_multichip``
    jits over a sharded Mesh and the benchmark loops over waves.
    """
    from minisched_tpu.ops.fused import evaluate

    result = evaluate(
        pods, nodes, filter_plugins, pre_score_plugins, score_plugins, ctx,
        extra=extra,
    )
    nodes = apply_placements(nodes, pods, result.choice)
    return nodes, result.choice, result.best_score
