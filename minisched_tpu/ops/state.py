"""On-device cluster-state updates: apply placements to the NodeTable.

The reference re-lists every node from the apiserver on every scheduling
cycle (minisched/minisched.go:40) — the #1 pattern not to copy (SURVEY.md §7
stage 7).  Here bind results are applied to the resident NodeTable with a
scatter-add, so scheduling 100k pods against 10k nodes never re-uploads
cluster state: the host only streams pod waves in and placements out.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any, Tuple

import jax.numpy as jnp

from minisched_tpu.models.tables import NodeTable, PodTable


def apply_placements(nodes: NodeTable, pods: PodTable, choice) -> NodeTable:
    """Commit chosen placements: add each placed pod's resource requests to
    its node's ``req_*`` accounting (the array analog of NodeInfo.AddPod).

    choice: i32[P] node index per pod, -1 = unplaced (dropped).
    Traceable; runs under jit as part of the wave step.
    """
    placed = (choice >= 0) & pods.valid
    idx = jnp.where(placed, choice, 0)

    def scatter(col, amount):
        amount = jnp.where(placed, amount, 0).astype(col.dtype)
        return col.at[idx].add(amount)

    return replace(
        nodes,
        req_cpu=scatter(nodes.req_cpu, pods.req_cpu),
        req_mem=scatter(nodes.req_mem, pods.req_mem),
        req_pods=scatter(nodes.req_pods, jnp.ones_like(pods.req_pods)),
    )


def wave_step(
    nodes: NodeTable,
    pods: PodTable,
    filter_plugins,
    pre_score_plugins,
    score_plugins,
    ctx,
) -> Tuple[NodeTable, Any, Any]:
    """One full device step: evaluate a pod wave against the resident
    NodeTable, then commit the placements (SURVEY.md §7 stage 7).

    Returns (updated NodeTable, choice i32[P], best_score i32[P]).
    Traceable — this is the function the driver's ``dryrun_multichip``
    jits over a sharded Mesh and the benchmark loops over waves.
    """
    from minisched_tpu.ops.fused import evaluate

    result = evaluate(
        pods, nodes, filter_plugins, pre_score_plugins, score_plugins, ctx
    )
    nodes = apply_placements(nodes, pods, result.choice)
    return nodes, result.choice, result.best_score
