"""Incremental scheduler cache: NodeInfos maintained by informer events.

The upstream scheduler keeps a ``cache.Cache`` of NodeInfos updated by
informer events so each cycle's snapshot is O(changes), not O(cluster);
the reference skips it and re-lists + re-wraps every node and pod per
cycle (minisched/minisched.go:40,126-127 — SURVEY.md §7's "#1 pattern not
to copy").  At wave-engine scale the difference is decisive: a 100k-pod
cluster costs ~1s per snapshot to rebuild, and the wave engine snapshots
every wave.

``SchedulerCache`` subscribes to Pod/Node events (registered FIRST on the
informers, so the cache is current before any requeue handler fires) and
maintains per-node aggregates through ``NodeInfo.add_pod/remove_pod``.
``snapshot()`` returns name-sorted CLONES — callers own them (the wave
engine folds assumed pods in; preemption evicts from them) and clone cost
is O(nodes), not O(pods).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from minisched_tpu.framework.nodeinfo import NodeInfo


class SchedulerCache:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._nodes: Dict[str, NodeInfo] = {}
        self._pod_node: Dict[str, str] = {}  # pod uid → node name
        #: assigned pods whose node the cache hasn't seen yet (event-order
        #: tolerance: a pod bound to a node whose ADD arrives later)
        self._orphans: Dict[str, Any] = {}
        self._sorted: Optional[List[NodeInfo]] = None
        # dirty-set for incremental table builds: names of nodes whose
        # AGGREGATES (assigned-pod sums) changed since the last drain.
        # None = "everything" (initial state, or node membership/order
        # changed — row indices shifted, so per-row patching is unsound).
        # Drained ONLY by snapshot_for_tables (the wave path); plain
        # snapshots leave it alone so the wave builder misses nothing.
        self._dirty: Optional[Set[str]] = None
        # membership/content epoch: bumped on EVERY mutation that can
        # change what a node-table build would produce (node add/update/
        # delete, assigned-pod place/remove/refresh).  The wave builder
        # uses it as the idle-wave gate (ISSUE 8): a snapshot whose epoch
        # equals the last built one — with an unchanged assume-delta —
        # can reuse the previous tables wholesale, no per-node signature
        # walk needed.  Orphan staging does NOT bump (an orphan is
        # invisible to builds until its node registers, which bumps).
        self._epoch = 0

    # -- node events -------------------------------------------------------
    def _create_node(self, node: Any) -> None:
        """Caller holds the lock.  Creates the NodeInfo and adopts any
        orphans bound to it — shared by add_node and the update-for-an-
        unknown-node path (a live MODIFIED can reach a late-registered
        handler before its cache replay drains)."""
        ni = NodeInfo(node)
        self._nodes[node.metadata.name] = ni
        self._sorted = None
        self._dirty = None  # membership changed: row indices shifted
        self._epoch += 1
        for uid, pod in list(self._orphans.items()):
            if pod.spec.node_name == node.metadata.name:
                del self._orphans[uid]
                ni.add_pod(pod)
                self._pod_node[uid] = node.metadata.name

    def add_node(self, node: Any) -> None:
        with self._mu:
            ni = self._nodes.get(node.metadata.name)
            if ni is None:
                self._create_node(node)
            else:
                ni.node = node
                self._epoch += 1

    def update_node(self, old: Any, new: Any) -> None:
        with self._mu:
            ni = self._nodes.get(new.metadata.name)
            if ni is not None:
                ni.node = new
                self._epoch += 1
            else:  # update for a node we never saw: treat as add
                self._create_node(new)

    def delete_node(self, node: Any) -> None:
        with self._mu:
            self._delete_node_locked(node)

    def _delete_node_locked(self, node: Any) -> None:
        ni = self._nodes.pop(node.metadata.name, None)
        self._sorted = None
        self._dirty = None  # membership changed: row indices shifted
        self._epoch += 1
        if ni is not None:
            # the pods are still bound in the cluster view and will
            # emit no further events — re-orphan them so a node
            # re-registration with the same name re-adopts their
            # accounting instead of starting from an empty NodeInfo
            for p in ni.pods:
                self._pod_node.pop(p.metadata.uid, None)
                self._orphans[p.metadata.uid] = p

    # -- pod events (assigned pods only — the informer filter gates) ------
    def add_pod(self, pod: Any) -> None:
        with self._mu:
            self._place(pod)

    def update_pod(self, old: Any, new: Any) -> None:
        with self._mu:
            self._update_pod_locked(new)

    def _update_pod_locked(self, new: Any) -> None:
        uid = new.metadata.uid
        prev = self._pod_node.get(uid)
        if prev == new.spec.node_name:
            # same node: refresh the stored object (requests can't
            # change post-bind in kube semantics, but keep exact)
            ni = self._nodes.get(prev)
            if ni is not None:
                ni.remove_pod(new)
                ni.add_pod(new)
                self._mark_dirty(prev)
            return
        self._remove(new)
        self._place(new)

    def _mark_dirty(self, name: str) -> None:
        self._epoch += 1  # every caller just changed a node's aggregates
        if self._dirty is not None:
            self._dirty.add(name)

    def delete_pod(self, pod: Any) -> None:
        with self._mu:
            self._remove(pod)

    def _place(self, pod: Any) -> None:
        uid = pod.metadata.uid
        if uid in self._pod_node or uid in self._orphans:
            return  # duplicate event
        ni = self._nodes.get(pod.spec.node_name)
        if ni is None:
            self._orphans[uid] = pod
            return
        ni.add_pod(pod)
        self._pod_node[uid] = pod.spec.node_name
        self._mark_dirty(pod.spec.node_name)

    def _remove(self, pod: Any) -> None:
        uid = pod.metadata.uid
        self._orphans.pop(uid, None)
        name = self._pod_node.pop(uid, None)
        if name is not None:
            ni = self._nodes.get(name)
            if ni is not None:
                ni.remove_pod(pod)
                self._mark_dirty(name)

    # -- reads -------------------------------------------------------------
    def snapshot(self) -> List[NodeInfo]:
        """Name-sorted clones of every NodeInfo — caller-owned."""
        return self.snapshot_with_assigned()[0]

    def snapshot_with_assigned(self):
        """(snapshot, assigned-pod uids) from ONE locked read — callers
        that prune an assume-cache against the snapshot need the two views
        to be of the same instant, or a bind landing between two reads is
        dropped from the assumptions without being counted in the
        snapshot."""
        with self._mu:
            if self._sorted is None:
                self._sorted = sorted(
                    self._nodes.values(), key=lambda ni: ni.name
                )
            return [ni.clone() for ni in self._sorted], set(self._pod_node)

    def snapshot_for_tables(self):
        """(snapshot, assigned-pod uids, dirty node names, epoch) from ONE
        locked read — the wave table builder's entry point.  ``dirty`` is
        the set of node names whose aggregates changed since the PREVIOUS
        drain (None = full rebuild needed: first snapshot, or node
        membership changed and row indices shifted); draining it here,
        atomically with the snapshot, is what makes the incremental
        aggregate base exact — the builder re-encodes exactly the rows
        this snapshot changed, in snapshot order (the wave path is
        single-threaded).  ``epoch`` is the cache's mutation counter AT
        the snapshot — the idle-wave gate: a later snapshot with the same
        epoch is guaranteed byte-identical, so the builder may reuse the
        previous tables wholesale (ISSUE 8).  Consumers that don't feed
        the builder use snapshot_with_assigned, which leaves the
        dirty-set alone."""
        with self._mu:
            if self._sorted is None:
                self._sorted = sorted(
                    self._nodes.values(), key=lambda ni: ni.name
                )
            dirty = self._dirty
            self._dirty = set()
            return (
                [ni.clone() for ni in self._sorted],
                set(self._pod_node),
                dirty,
                self._epoch,
            )

    @property
    def epoch(self) -> int:
        """The mutation counter (see snapshot_for_tables) — observability
        and tests; the wave path reads it atomically with its snapshot."""
        with self._mu:
            return self._epoch

    def capacity_view(
        self, names: Any
    ) -> Tuple[Dict[str, List[int]], Dict[str, Set[str]]]:
        """({name: [free milli_cpu, free mem MiB, free eph MiB, free pod
        slots]}, {name: uids of pods the cache already counts there}) for
        the given nodes, from the LIVE NodeInfos under one lock hold —
        the pipelined wave's commit-time re-arbitration base, single-
        device and mesh engines alike (the mesh shards the DEVICE
        compute; this host-side capacity view is whole either way, which
        is what keeps re-arbitration mesh-agnostic — ISSUE 7).  The
        counted-uid sets let the caller fold its assume-cache WITHOUT
        double-subtracting a pod whose bind event already landed (the
        assumption outlives the event until the next snapshot prune).
        Same MiB-floored integer quantization as the table builders."""
        from minisched_tpu.api.objects import MIB

        free: Dict[str, List[int]] = {}
        counted: Dict[str, Set[str]] = {}
        with self._mu:
            for name in names:
                ni = self._nodes.get(name)
                if ni is None:
                    continue
                alloc = ni.node.status.allocatable
                free[name] = [
                    alloc.milli_cpu - ni.requested.milli_cpu,
                    alloc.memory // MIB - ni.req_mem_mib,
                    alloc.ephemeral_storage // MIB - ni.req_eph_mib,
                    alloc.pods - len(ni.pods),
                ]
                counted[name] = {p.metadata.uid for p in ni.pods}
        return free, counted

    # -- batch ingestion (informer on_batch fast path) ---------------------
    def _pod_batch(self, events: List[Any]) -> None:
        """A whole informer batch under ONE lock hold — a wave's thousands
        of bind events each cost dict ops, not a lock round-trip.  Applies
        the assigned-pod filter itself (batch handlers see the raw batch);
        errors are contained PER EVENT (one malformed object must not
        drop the rest of the batch from this consumer while others apply
        it — the per-event dispatch path had that containment)."""
        from minisched_tpu.controlplane.store import EventType

        with self._mu:
            for ev in events:
                try:
                    if not ev.obj.spec.node_name:
                        continue
                    if ev.type == EventType.DELETED:
                        self._remove(ev.obj)
                    elif ev.type == EventType.ADDED:
                        self._place(ev.obj)
                    else:
                        self._update_pod_locked(ev.obj)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def _node_batch(self, events: List[Any]) -> None:
        from minisched_tpu.controlplane.store import EventType

        with self._mu:
            for ev in events:
                try:
                    node = ev.obj
                    if ev.type == EventType.DELETED:
                        self._delete_node_locked(node)
                        continue
                    ni = self._nodes.get(node.metadata.name)
                    if ni is None:
                        self._create_node(node)
                    else:
                        ni.node = node
                except Exception:
                    import traceback

                    traceback.print_exc()

    def wire(self, informer_factory: Any) -> None:
        """Register the cache's handlers.  MUST run before the queue's
        handlers are registered so a requeued pod's next snapshot already
        reflects the event that woke it."""
        from minisched_tpu.controlplane.informer import ResourceEventHandlers

        # batch handlers: the dispatch thread hands over whole event
        # batches; the pod path gates on assignment internally (pending
        # pods never reach the cache; a bind arrives as MODIFIED whose new
        # object is assigned, deletes of assigned pods pass)
        informer_factory.informer_for("Pod").add_event_handlers(
            ResourceEventHandlers(on_batch=self._pod_batch)
        )
        informer_factory.informer_for("Node").add_event_handlers(
            ResourceEventHandlers(on_batch=self._node_batch)
        )
