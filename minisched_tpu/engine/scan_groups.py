"""Interaction grouping for the blocked scan lane (ops/sequential.
blocked_scan_schedule).

Two cross-pod-constrained pods INTERACT when one's commit can change what
the other observes: they share a selector group (one's labels match a
selector another's constraint carries — in either direction), or they
reference a shared volume identity.  Pods that don't interact can be
evaluated in one block: their carried-plane updates commute, so the block
result equals a sequential order — capacity races are separately caught
by repair acceptance and retried.

``order_into_blocks`` assigns pods first-fit into fixed-size blocks whose
member interaction sets stay pairwise disjoint.  First-fit preserves
per-group FIFO order: a block rejected for an earlier same-group pod
keeps rejecting later ones (blocks only grow), so a group's members land
in strictly increasing blocks — the within-group sequential semantics the
blocked kernel's exactness claim rests on.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from minisched_tpu.models.constraints import (
    _matches,
    _selector_sig,
    _term_namespaces,
    rev_pref_terms_of,
)


def _own_terms(pod: Any):
    """Every (namespaces, selector) group a pod's constraints carry —
    spread constraints, required/preferred (anti-)affinity both signs."""
    ns = pod.metadata.namespace
    for c in pod.spec.topology_spread_constraints:
        yield ((ns,), c.label_selector)
    aff = pod.spec.affinity
    if aff is None:
        return
    pa, pan = aff.pod_affinity, aff.pod_anti_affinity
    if pa is not None:
        for term in pa.required:
            yield (_term_namespaces(term, ns), term.label_selector)
        for wt in pa.preferred:
            yield (_term_namespaces(wt.term, ns), wt.term.label_selector)
    if pan is not None:
        for term in pan.required:
            yield (_term_namespaces(term, ns), term.label_selector)
        for wt in pan.preferred:
            yield (_term_namespaces(wt.term, ns), wt.term.label_selector)


def interaction_sets(pods: Sequence[Any]) -> List[Set]:
    """Per-pod interaction-identity sets over the given pods.

    Identities: selector-group ids (a pod holds a group if its constraints
    carry it OR its labels match it — matching covers both directions of
    every coupling, incl. the symmetric rev_weight scoring, whose term
    stream is a subset of ``_own_terms``) and volume claim keys."""
    group_ids: Dict[Tuple, int] = {}
    group_sel: List[Tuple[Tuple[str, ...], Any]] = []

    def gid(nss: Tuple[str, ...], sel: Any) -> int:
        key = (nss, _selector_sig(sel))
        g = group_ids.get(key)
        if g is None:
            g = group_ids[key] = len(group_sel)
            group_sel.append((nss, sel))
        return g

    own: List[Set] = []
    for pod in pods:
        s: Set = {gid(nss, sel) for nss, sel in _own_terms(pod)}
        for _nss, _sel, _topo, _w in rev_pref_terms_of(pod):
            s.add(gid(_nss, _sel))
        for vol in pod.spec.volumes:
            s.add(("vol", f"{pod.metadata.namespace}/{vol}"))
        own.append(s)
    # matching direction: pod's labels hit a group's selector
    for i, pod in enumerate(pods):
        for g, (nss, sel) in enumerate(group_sel):
            if g not in own[i] and _matches(sel, nss, pod):
                own[i].add(g)
    return own


def order_into_blocks(
    items: Sequence[Any], sets: Sequence[Set], block_size: int
) -> List[List[Optional[Any]]]:
    """First-fit the items into blocks of ``block_size`` with pairwise-
    disjoint sets; short blocks are padded with None.  Items appear in
    non-decreasing block order per interaction group (see module doc)."""
    blocks: List[Tuple[List[Any], Set]] = []
    for item, s in zip(items, sets):
        placed = False
        for members, union in blocks:
            if len(members) < block_size and not (union & s):
                members.append(item)
                union |= s
                placed = True
                break
        if not placed:
            blocks.append(([item], set(s)))
    return [
        members + [None] * (block_size - len(members))
        for members, _ in blocks
    ]
