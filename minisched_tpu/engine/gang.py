"""Gang placement directory: where a gang's members already landed.

The GangTopology scorer (plugins/gangtopology.py) pulls each gang
member toward its ALREADY-PLACED peers: same slice first, then torus
proximity to the placed centroid.  The placed view has to be cheap per
wave — walking the pod population per build is exactly the pattern this
repo exists to avoid — so this module keeps an informer-wired
incremental index (``GangIndex``: gang key → member uid → node, plus a
node-name → topology map), and the engine folds its assume-cache on top
at table-build time (an assumed member is placed capacity even before
its bind event lands).

The SCALAR path (parity oracle, scalar engine) derives the identical
view from a NodeInfo snapshot instead (``gang_view_from_infos``) —
both paths share ``aggregate_coords`` so the encoded gang_* columns
are bit-identical given the same placed set.

Aggregate format (the tuple every consumer passes around):

    (majority_slice_hash, sum_x, sum_y, sum_z, n)

Integer sums, never a centroid float: the scorer divides on device with
the same floor semantics the scalar plugin uses, so parity holds.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from minisched_tpu.api.objects import gang_key
from minisched_tpu.models.tables import fnv1a32

#: node topology tuple: (slice_hash, torus_x, torus_y, torus_z)
Topo = Tuple[int, int, int, int]
#: gang aggregate tuple: (majority_slice_hash, sx, sy, sz, n)
GangAgg = Tuple[int, int, int, int, int]


def node_topo(node: Any) -> Topo:
    """A node's topology tuple, with the SAME zeroing rule the node
    table encodes (sliceless nodes contribute zero coordinates) — the
    scalar and device views must sum identical numbers."""
    spec = node.spec
    if not spec.slice_id:
        return (0, 0, 0, 0)
    return (fnv1a32(spec.slice_id), spec.torus_x, spec.torus_y, spec.torus_z)


def node_dims(node: Any) -> Tuple[int, int, int]:
    """The node's slice torus DIMENSIONS (ring size per axis), with the
    table's zeroing rule (sliceless → all zero; 0 = unknown, the scorer
    then measures non-wrapping distance on that axis — the identity the
    parity tests pin)."""
    spec = node.spec
    if not spec.slice_id:
        return (0, 0, 0)
    return (spec.slice_dx, spec.slice_dy, spec.slice_dz)


def aggregate_coords(coords: Iterable[Topo]) -> Optional[GangAgg]:
    """Fold placed-member topology tuples into the gang aggregate.
    Majority slice is deterministic: highest count, ties to the SMALLEST
    hash (a stable rule both the host paths share)."""
    counts: Dict[int, int] = {}
    sx = sy = sz = n = 0
    for sh, x, y, z in coords:
        n += 1
        sx += x
        sy += y
        sz += z
        if sh:
            counts[sh] = counts.get(sh, 0) + 1
    if n == 0:
        return None
    slice_hash = 0
    if counts:
        best = max(counts.values())
        slice_hash = min(k for k, v in counts.items() if v == best)
    return (slice_hash, sx, sy, sz, n)


def gang_view_from_infos(
    node_infos: Iterable[Any], keys: Optional[set] = None
) -> Dict[str, GangAgg]:
    """The placed-gang view derived from a NodeInfo snapshot (scalar
    engine / parity oracle path).  ``keys`` restricts to the gangs of
    interest; None aggregates every gang found."""
    coords: Dict[str, List[Topo]] = {}
    for ni in node_infos:
        topo = node_topo(ni.node)
        for pod in ni.pods:
            key = gang_key(pod)
            if key is None or (keys is not None and key not in keys):
                continue
            coords.setdefault(key, []).append(topo)
    return {k: aggregate_coords(v) for k, v in coords.items()}


class GangIndex:
    """Incremental placed-gang-member index, informer-wired like the
    ConstraintIndex: Pod events maintain gang membership (bound members
    only), Node events the topology map.  All reads/writes under one
    lock; gangs are small (a slice is tens of hosts), so per-wave
    aggregation over members of the WAVE'S gangs is O(gang members),
    never O(pod population)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        #: gang key → member uid → node name (BOUND members only)
        self._members: Dict[str, Dict[str, str]] = {}
        self._pod_gang: Dict[str, str] = {}  # uid → gang key
        self._node_topo: Dict[str, Topo] = {}

    def wire(self, informer_factory: Any) -> None:
        from minisched_tpu.controlplane.informer import ResourceEventHandlers

        informer_factory.informer_for("Pod").add_event_handlers(
            ResourceEventHandlers(on_batch=self._pod_batch)
        )
        informer_factory.informer_for("Node").add_event_handlers(
            ResourceEventHandlers(
                on_add=lambda node: self._node_changed(node),
                on_update=lambda old, new: self._node_changed(new),
                on_delete=lambda node: self._node_gone(node),
            )
        )

    # -- event handlers ----------------------------------------------------
    def _pod_batch(self, events: List[Any]) -> None:
        from minisched_tpu.controlplane.store import EventType

        with self._mu:
            for ev in events:
                try:
                    pod = ev.obj
                    key = gang_key(pod)
                    if key is None:
                        continue
                    uid = pod.metadata.uid
                    if ev.type == EventType.DELETED or not pod.spec.node_name:
                        self._drop_locked(uid)
                    else:
                        self._drop_locked(uid)  # node may have changed
                        self._members.setdefault(key, {})[uid] = (
                            pod.spec.node_name
                        )
                        self._pod_gang[uid] = key
                except Exception:
                    continue  # contain per event (informer batch contract)

    def _drop_locked(self, uid: str) -> None:
        key = self._pod_gang.pop(uid, None)
        if key is not None:
            bucket = self._members.get(key)
            if bucket is not None:
                bucket.pop(uid, None)
                if not bucket:
                    del self._members[key]

    def _node_changed(self, node: Any) -> None:
        with self._mu:
            self._node_topo[node.metadata.name] = node_topo(node)

    def _node_gone(self, node: Any) -> None:
        with self._mu:
            self._node_topo.pop(node.metadata.name, None)

    # -- reads -------------------------------------------------------------
    def placed_count(self, key: str, exclude: Iterable[str] = ()) -> int:
        """How many members of ``key`` are bound (uid-distinct), minus
        any in ``exclude`` — the Coscheduling plugin counts a gang's
        already-bound members toward admission so a rebound straggler
        (bind conflict after its peers landed) can complete the gang
        alone instead of waiting for N fresh arrivals."""
        ex = set(exclude)
        with self._mu:
            bucket = self._members.get(key)
            if not bucket:
                return 0
            return sum(1 for uid in bucket if uid not in ex)

    def view_for(
        self,
        keys: Iterable[str],
        extra_members: Iterable[Tuple[str, str, str]] = (),
    ) -> Dict[str, GangAgg]:
        """Aggregates for the given gang keys.  ``extra_members`` are
        (gang key, uid, node name) triples folded on top — the engine's
        assume-cache (placed this wave, bind not yet landed); uids
        already in the index are skipped (no double count)."""
        want = set(keys)
        coords: Dict[str, List[Topo]] = {}
        with self._mu:
            for key in want:
                bucket = self._members.get(key)
                if bucket:
                    coords[key] = [
                        self._node_topo.get(node, (0, 0, 0, 0))
                        for node in bucket.values()
                    ]
            for key, uid, node in extra_members:
                if key not in want:
                    continue
                bucket = self._members.get(key)
                if bucket is not None and uid in bucket:
                    continue
                coords.setdefault(key, []).append(
                    self._node_topo.get(node, (0, 0, 0, 0))
                )
        return {
            k: agg
            for k, v in coords.items()
            if (agg := aggregate_coords(v)) is not None
        }
