"""Deterministic host-selection tie-break.

The reference breaks score ties with reservoir-sampled uniform randomness
(minisched/minisched.go:316-325), which makes placements irreproducible.
SURVEY.md §7 ("hard parts" #1) requires a deterministic total order so the
scalar oracle and the TPU kernel agree bit-exactly.

Rule: among max-score nodes, pick the node minimizing ``mix32(pod_seed,
node_index)`` — a stateless integer hash evaluated identically (same 32-bit
ops) in pure Python here and in jnp inside the fused kernel
(minisched_tpu.ops.fused).  Still "uniform-ish" across pods (different pods
break ties differently), but reproducible given the pod's uid-derived seed.
"""

from __future__ import annotations

_M32 = 0xFFFFFFFF


def mix32(seed: int, idx: int) -> int:
    """murmur3-finalizer-style mix of (seed, idx) → uint32."""
    x = (seed ^ ((idx * 0x9E3779B9) & _M32)) & _M32
    x ^= x >> 16
    x = (x * 0x85EBCA6B) & _M32
    x ^= x >> 13
    x = (x * 0xC2B2AE35) & _M32
    x ^= x >> 16
    return x


def select_host(scores, feasible, seed: int) -> int:
    """Pick argmax score over feasible node indices; ties broken by
    minimal mix32(seed, idx).  Returns -1 if nothing is feasible.

    ``scores``: sequence of ints; ``feasible``: sequence of bools.
    """
    best_idx = -1
    best_score = None
    best_hash = None
    for idx, (score, ok) in enumerate(zip(scores, feasible)):
        if not ok:
            continue
        h = mix32(seed, idx)
        if (
            best_idx < 0
            or score > best_score
            or (score == best_score and h < best_hash)
        ):
            best_idx, best_score, best_hash = idx, score, h
    return best_idx
