"""Vectorized host oracles: full-run parity verification at bench scale.

The scalar engine (engine/scheduler.schedule_pod_once — the re-creation of
the reference loop at /root/reference/minisched/minisched.go:115-199 with
the deterministic tie-break of engine/tiebreak.py) is the ground truth for
placement parity, but at 3-30 pods/s it can only ever spot-check a sample.
These oracles re-derive the SAME decision rule in vectorized NumPy — fast
enough to verify EVERY placement of a 100k-pod bench run — while staying
independent of the device path (no jax, no tables, no kernels; plain
host integer math over the API objects).

Two layers of trust:
* device output vs vectorized oracle — checked for ALL pods;
* vectorized oracle vs scalar oracle — spot-checked on a sample by the
  bench (and in tests/test_oracle.py on randomized clusters), anchoring
  the fast oracle to the reference-shaped loop.

Each oracle targets a specific plugin chain and VALIDATES its
preconditions; a workload outside them raises ``OracleUnsupported`` so a
caller can fall back to sampling rather than silently mis-verify.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

MAX_NODE_SCORE = 100
FRAC_SCALE = 10_000  # plugins/noderesources.py quantization
MATCH_SCORE = 10  # plugins/nodenumber.py


class OracleUnsupported(Exception):
    """The workload uses features outside this oracle's modeled chain."""


def mix32_np(seed, idx: np.ndarray) -> np.ndarray:
    """engine.tiebreak.mix32 vectorized (uint32 wraparound semantics).
    ``seed`` may be a scalar or an array broadcasting against ``idx``."""
    x = np.asarray(seed, np.uint32) ^ (
        np.asarray(idx, np.uint32) * np.uint32(0x9E3779B9)
    )
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> np.uint32(16))
    return x


def _pod_seeds(pods: Sequence[Any]) -> np.ndarray:
    from minisched_tpu import native

    return np.asarray(
        native.pod_seed_batch(
            [p.metadata.uid or p.metadata.name for p in pods]
        ),
        np.uint32,
    )


def _suffix(name: str) -> int:
    return int(name[-1]) if name and name[-1].isdigit() else -1


# ---------------------------------------------------------------------------
# headline oracle: NodeUnschedulable filter + NodeNumber score
# ---------------------------------------------------------------------------

def headline_oracle(pods: Sequence[Any], nodes: Sequence[Any]) -> np.ndarray:
    """Choices (node row index, -1 = unschedulable) for the headline chain
    [NodeUnschedulable] / [NodeNumber], for every pod.

    Decision rule (== schedule_pod_once + tiebreak.select_host): among
    schedulable nodes, prefer those whose trailing digit matches the
    pod's (score 10 vs 0, nodenumber.go:73-95); break ties by minimal
    mix32(pod_seed, node_index).  A pod with no digit suffix errors in
    the scalar Score (the reference's PreScore-state quirk) — outside
    this oracle's model, so it raises.
    """
    n = len(nodes)
    unsched = np.fromiter(
        (node.spec.unschedulable for node in nodes), bool, count=n
    )
    node_suf = np.fromiter(
        (_suffix(node.metadata.name) for node in nodes), np.int64, count=n
    )
    feasible = np.flatnonzero(~unsched)
    pod_suf = np.fromiter(
        (_suffix(p.metadata.name) for p in pods), np.int64, count=len(pods)
    )
    if (pod_suf < 0).any():
        raise OracleUnsupported("pod without digit suffix (Score errors)")
    seeds = _pod_seeds(pods)

    # candidate sets: per digit, the feasible matching nodes (score 10
    # beats 0, so when any exist the choice is among them); else all
    # feasible.  Scores within a candidate set are uniform, so the pick
    # is pure argmin-mix32 — vectorized pods × candidates per digit.
    choices = np.full(len(pods), -1, np.int64)
    if feasible.size == 0:
        return choices
    for d in range(10):
        rows = np.flatnonzero(pod_suf == d)
        if rows.size == 0:
            continue
        cand = feasible[node_suf[feasible] == d]
        if cand.size == 0:
            cand = feasible
        # (Pd, Nd) hash matrix; argmin is the stable first-minimum, which
        # equals select_host's strict-< rule (lowest index wins hash ties)
        hm = mix32_np(seeds[rows, None], cand[None, :])
        choices[rows] = cand[np.argmin(hm, axis=1)]
    return choices


# ---------------------------------------------------------------------------
# full-roster sequential-scan oracle (config5-shaped workloads)
# ---------------------------------------------------------------------------

def _require(cond: bool, what: str) -> None:
    if not cond:
        raise OracleUnsupported(what)


class FullRosterScanOracle:
    """Sequential-bind placements for the default full roster on workloads
    where the node-VARYING score terms are exactly NodeResourcesFit
    (LeastAllocated strategy) + NodeResourcesBalancedAllocation, and the
    active filters are NodeUnschedulable + NodeResourcesFit + NodeAffinity
    (match-labels node selectors only).

    Preconditions (validated; violations raise OracleUnsupported):
    no taints, no node images, no host ports, no volumes/claims, no
    pod/anti-affinity, no topology spread, no preferred node affinity,
    single container.  Under them every other roster plugin scores a
    constant across nodes (TaintToleration's reverse-normalize of all-
    zero counts, ImageLocality with no images, spread/IPA with no
    constraints), so the argmax set — and the scalar engine's decision —
    is fully determined by w·(LeastAllocated + BalancedAllocation) over
    the feasible set, tie-broken by mix32 exactly like
    engine/tiebreak.select_host.

    Placements are sequential-bind exact: pod i scores against node state
    that includes pods < i (the scan/bind-exact semantics of
    minisched.go:32-113's one-at-a-time loop).

    Incremental evaluation: per placement only ONE node's sums change, so
    per-(request-shape) score/fit caches refresh just the dirty rows —
    ~O(candidates) per pod instead of O(N × plugins).
    """

    def __init__(self, nodes: Sequence[Any], default_nz_cpu: int,
                 default_nz_mem_mib: int, with_balanced: bool = True):
        #: with_balanced: include BalancedAllocation in the score (the
        #: full default roster).  False models the config-3 chain
        #: (Fit + LeastAllocated only, scheduler_test.go config shapes).
        self._with_balanced = with_balanced
        n = len(nodes)
        self.n = n
        MIB = 1 << 20
        for node in nodes:
            _require(not node.spec.taints, "node taints")
            _require(not node.status.images, "node images")
        self.unsched = np.fromiter(
            (node.spec.unschedulable for node in nodes), bool, count=n
        )
        self.alloc_cpu = np.fromiter(
            (node.status.allocatable.milli_cpu for node in nodes),
            np.int64, count=n,
        )
        self.alloc_mem = np.fromiter(
            (node.status.allocatable.memory // MIB for node in nodes),
            np.int64, count=n,
        )
        self.alloc_eph = np.fromiter(
            (
                node.status.allocatable.ephemeral_storage // MIB
                for node in nodes
            ),
            np.int64, count=n,
        )
        self.alloc_pods = np.fromiter(
            (node.status.allocatable.pods for node in nodes), np.int64, count=n
        )
        self.labels = [node.metadata.labels for node in nodes]
        # committed state (plain requests for Fit, non-zero for scores)
        self.req_cpu = np.zeros(n, np.int64)
        self.req_mem = np.zeros(n, np.int64)
        self.req_eph = np.zeros(n, np.int64)
        self.req_cnt = np.zeros(n, np.int64)
        self.nzreq_cpu = np.zeros(n, np.int64)
        self.nzreq_mem = np.zeros(n, np.int64)
        self._default_nz_cpu = default_nz_cpu
        self._default_nz_mem = default_nz_mem_mib
        # per-(request shape, selector) groups: cached score/feas arrays
        # refreshed lazily for nodes dirtied since the group's last use
        self._groups: Dict[Tuple, Dict[str, Any]] = {}
        self._version = 0
        self._node_version = np.zeros(n, np.int64)

    # -- per-pod encode -----------------------------------------------------
    def _pod_key(self, pod: Any) -> Tuple:
        MIB = 1 << 20
        spec = pod.spec
        _require(len(spec.containers) <= 1, ">1 container")
        _require(not spec.tolerations, "tolerations")
        _require(not (spec.containers and spec.containers[0].ports), "ports")
        _require(not spec.volumes, "volumes")
        _require(spec.affinity is None, "affinity")
        _require(not spec.topology_spread_constraints, "topology spread")
        _require(not spec.node_name, "pre-bound pod")
        req = pod.resource_requests()
        sel = tuple(sorted((spec.node_selector or {}).items()))
        return (
            req.milli_cpu, req.memory // MIB,
            req.ephemeral_storage // MIB, sel,
        )

    def _group(self, key: Tuple) -> Dict[str, Any]:
        g = self._groups.get(key)
        if g is None:
            cpu, mem, eph, sel = key
            sel_ok = np.fromiter(
                (
                    all(lbl.get(k) == v for k, v in sel)
                    for lbl in self.labels
                ),
                bool, count=self.n,
            )
            g = self._groups[key] = {
                "static_ok": sel_ok & ~self.unsched,
                "score": np.zeros(self.n, np.int64),
                "feas": np.zeros(self.n, bool),
                "seen": np.full(self.n, -1, np.int64),
            }
        return g

    def _refresh(self, g: Dict[str, Any], key: Tuple, rows: np.ndarray) -> None:
        """Recompute score+feasibility for ``rows`` against current sums."""
        cpu, mem, eph, _sel = key
        nz_cpu = cpu or self._default_nz_cpu
        nz_mem = mem or self._default_nz_mem
        a_cpu, a_mem = self.alloc_cpu[rows], self.alloc_mem[rows]
        # NodeResourcesFit filter: plain requests vs allocatable
        fits = (
            (self.req_cpu[rows] + cpu <= a_cpu)
            & (self.req_mem[rows] + mem <= a_mem)
            & (self.req_eph[rows] + eph <= self.alloc_eph[rows])
            & (self.req_cnt[rows] + 1 <= self.alloc_pods[rows])
        )
        g["feas"][rows] = g["static_ok"][rows] & fits
        # LeastAllocated (plugins/noderesources.py:146-163)
        r_cpu = self.nzreq_cpu[rows] + nz_cpu
        r_mem = self.nzreq_mem[rows] + nz_mem

        def least(requested, alloc):
            s = (alloc - requested) * MAX_NODE_SCORE // np.maximum(alloc, 1)
            return np.where((alloc <= 0) | (requested > alloc), 0, s)

        la = (least(r_cpu, a_cpu) + least(r_mem, a_mem)) // 2

        if self._with_balanced:
            # BalancedAllocation (plugins/noderesources.py:196-221)
            def frac(requested, alloc):
                clamped = np.minimum(requested, 2 * alloc)
                return np.where(
                    alloc > 0,
                    clamped * FRAC_SCALE // np.maximum(alloc, 1),
                    FRAC_SCALE,
                )

            cpu_f, mem_f = frac(r_cpu, a_cpu), frac(r_mem, a_mem)
            ba = (
                (FRAC_SCALE - np.abs(cpu_f - mem_f))
                * MAX_NODE_SCORE // FRAC_SCALE
            )
            ba = np.where(
                (cpu_f >= FRAC_SCALE) | (mem_f >= FRAC_SCALE), 0, ba
            )
            la = la + ba  # both weight 1 in the default roster
        g["score"][rows] = la
        g["seen"][rows] = self._node_version[rows]

    def place(self, pod: Any) -> int:
        """Choice for one pod (node index or -1), committing the placement."""
        key = self._pod_key(pod)
        g = self._group(key)
        dirty = np.flatnonzero(g["seen"] != self._node_version)
        if dirty.size:
            self._refresh(g, key, dirty)
        feas = g["feas"]
        if not feas.any():
            return -1
        score = g["score"]
        best = score[feas].max()
        cand = np.flatnonzero(feas & (score == best))
        from minisched_tpu import native

        seed = native.pod_seed_batch(
            [pod.metadata.uid or pod.metadata.name]
        )[0]
        j = int(cand[np.argmin(mix32_np(seed, cand))])
        # commit
        cpu, mem, eph = key[0], key[1], key[2]
        self.req_cpu[j] += cpu
        self.req_mem[j] += mem
        self.req_eph[j] += eph
        self.req_cnt[j] += 1
        self.nzreq_cpu[j] += cpu or self._default_nz_cpu
        self.nzreq_mem[j] += mem or self._default_nz_mem
        self._version += 1
        self._node_version[j] = self._version
        return j

    def place_all(self, pods: Sequence[Any]) -> np.ndarray:
        return np.fromiter(
            (self.place(p) for p in pods), np.int64, count=len(pods)
        )


def fullchain_scan_oracle(
    pods: Sequence[Any], nodes: Sequence[Any]
) -> np.ndarray:
    """Sequential full-roster placements for every pod (see
    FullRosterScanOracle for the modeled chain + preconditions)."""
    from minisched_tpu.models.tables import (
        DEFAULT_NONZERO_CPU,
        DEFAULT_NONZERO_MEM_MIB,
    )

    oracle = FullRosterScanOracle(
        nodes, DEFAULT_NONZERO_CPU, DEFAULT_NONZERO_MEM_MIB
    )
    return oracle.place_all(pods)
