"""The core scheduling engine: the scheduleOne loop and plugin runners.

Re-creates ``minisched/minisched.go`` + ``minisched/initialize.go``: the
four plugin chains (initialize.go:25-28), the per-pod
filter → pre-score → score → normalize → select-host → permit → bind cycle
(minisched.go:32-113), the detached binding goroutine per pod
(minisched.go:96-112), ``ErrorFunc`` requeueing (minisched.go:283-298), and
the waiting-pod registry (minisched.go:300-302).

This scalar engine is also the **parity oracle** (SURVEY.md §7 stage 4):
the TPU batch path must place pods identically, so every semantic here —
plugin order short-circuiting (minisched.go:130-137), score summation with
weights, the deterministic tie-break — is the ground truth the fused kernel
is tested against.

Fixed reference bugs (SURVEY.md §7): real errors passed to ErrorFunc
(vs stale/nil at minisched.go:64,73,92), score-plugin weights applied
(the TODO at minisched.go:187), nodes snapshotted from the informer cache
instead of a full re-list per cycle (minisched.go:40).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from minisched_tpu.api.objects import Binding, Pod
from minisched_tpu.controlplane.client import Client
from minisched_tpu.controlplane.informer import SharedInformerFactory
from minisched_tpu.engine import eventhandlers
from minisched_tpu.engine.tiebreak import select_host
from minisched_tpu.engine.waitingpod import WaitingPod
from minisched_tpu.framework.events import (
    ClusterEventMap,
    merge_event_registrations,
    unioned_gvks,
)
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.plugin import implements_enqueue, implements_pre_filter
from minisched_tpu.framework.types import (
    CycleState,
    Diagnosis,
    FitError,
    MAX_NODE_SCORE,
    QueuedPodInfo,
    Status,
    is_success,
)
from minisched_tpu.models.tables import pod_seed
from minisched_tpu.queue.queue import SchedulingQueue


# ---------------------------------------------------------------------------
# Pure extension-point runners (minisched.go:115-199) — module-level so the
# live engine and the stateless parity oracle share ONE implementation
# ---------------------------------------------------------------------------


def run_pre_filter_plugins(
    filter_plugins: List[Any], state: CycleState, pod: Pod, node_infos: List[NodeInfo]
) -> Tuple[Status, str]:
    """Once-per-pod PreFilter pass (upstream framework.PreFilterPlugin) for
    filter plugins that aggregate cluster-wide state.  Returns the first
    non-success status and the plugin that produced it."""
    for pl in filter_plugins:
        if implements_pre_filter(pl):
            status = pl.pre_filter(state, pod, node_infos)
            if not is_success(status):
                return status.with_plugin(status.plugin or pl.name()), pl.name()
    return Status.success(), ""


def run_filter_plugins(
    filter_plugins: List[Any], state: CycleState, pod: Pod, node_infos: List[NodeInfo]
) -> Tuple[List[NodeInfo], Diagnosis]:
    """Per node × per plugin with short-circuit on first failure
    (minisched.go:115-151); collects Diagnosis for event-gated requeue."""
    feasible: List[NodeInfo] = []
    diagnosis = Diagnosis()
    for ni in node_infos:
        ok = True
        for pl in filter_plugins:
            status = pl.filter(state, pod, ni)
            if not is_success(status):
                ok = False
                status.with_plugin(status.plugin or pl.name())
                diagnosis.node_to_status[ni.name] = status
                diagnosis.unschedulable_plugins.add(pl.name())
                if status.code.name == "ERROR":
                    raise status.as_error()
                break  # short-circuit this node (minisched.go:136)
        if ok:
            feasible.append(ni)
    return feasible, diagnosis


def run_post_filter_plugins(
    post_filter_plugins: List[Any],
    state: CycleState,
    pod: Pod,
    node_infos: List[NodeInfo],
    diagnosis: Diagnosis,
) -> Tuple[Optional[str], Status]:
    """Upstream RunPostFilterPlugins: runs after filtering leaves no
    feasible node; the first plugin returning Success wins (its nominated
    node is the result), an Error aborts, otherwise Unschedulable."""
    for pl in post_filter_plugins:
        nominated, status = pl.post_filter(state, pod, node_infos, diagnosis)
        if status.is_success():
            return nominated, status
        if status.code.name == "ERROR":
            return None, status.with_plugin(status.plugin or pl.name())
    return None, Status.unschedulable("no postFilter plugin made the pod schedulable")


def run_pre_score_plugins(
    pre_score_plugins: List[Any], state: CycleState, pod: Pod, nodes: List[Any]
) -> Status:
    for pl in pre_score_plugins:
        status = pl.pre_score(state, pod, nodes)
        if not is_success(status):
            return status.with_plugin(status.plugin or pl.name())
    return Status.success()


def run_score_plugins(
    score_plugins: List[Any],
    score_weights: Dict[str, int],
    state: CycleState,
    pod: Pod,
    node_names: List[str],
) -> Dict[str, int]:
    """Score + normalize + weighted sum (minisched.go:164-199 — with the
    weight TODO at :187 actually implemented)."""
    totals: Dict[str, int] = {name: 0 for name in node_names}
    for pl in score_plugins:
        scores: List[int] = []
        for name in node_names:
            s, status = pl.score(state, pod, name)
            if not is_success(status):
                raise status.as_error()
            scores.append(s)
        ext = pl.score_extensions() if hasattr(pl, "score_extensions") else None
        if ext is not None:
            from minisched_tpu.framework.types import NodeScore

            lst = [NodeScore(n, s) for n, s in zip(node_names, scores)]
            status = ext.normalize_score(state, pod, lst)
            if not is_success(status):
                raise status.as_error()
            scores = [ns.score for ns in lst]
        weight = score_weights.get(pl.name(), 1)
        for name, s in zip(node_names, scores):
            totals[name] += s * weight
    return totals


def schedule_pod_once(
    filter_plugins: List[Any],
    pre_score_plugins: List[Any],
    score_plugins: List[Any],
    score_weights: Dict[str, int],
    pod: Pod,
    node_infos: List[NodeInfo],
    state: Optional[CycleState] = None,
) -> str:
    """One stateless scheduling decision: filter → pre-score → score →
    select host (minisched.go:50-80).  Raises FitError/plugin errors on
    failure; returns the chosen node name.

    This is the **parity oracle** the fused TPU kernel
    (minisched_tpu.ops.fused) is tested against — the live engine's
    ``_schedule_pod`` is this exact code path.
    """
    state = state if state is not None else CycleState()
    # snapshot lister: plugins read per-node aggregates from CycleState under
    # "nodeinfo/<name>" and the full snapshot under "nodeinfos" (the role of
    # upstream's SnapshotSharedLister handle)
    for ni in node_infos:
        state.write("nodeinfo/" + ni.name, ni)
    state.write("nodeinfos", node_infos)
    pf_status, pf_plugin = run_pre_filter_plugins(
        filter_plugins, state, pod, node_infos
    )
    if not is_success(pf_status):
        if pf_status.code.name == "ERROR":
            raise pf_status.as_error()
        diagnosis = Diagnosis()
        diagnosis.unschedulable_plugins.add(pf_plugin)
        raise FitError(pod, len(node_infos), diagnosis)
    feasible, diagnosis = run_filter_plugins(filter_plugins, state, pod, node_infos)
    if not feasible:
        raise FitError(pod, len(node_infos), diagnosis)

    status = run_pre_score_plugins(
        pre_score_plugins, state, pod, [ni.node for ni in feasible]
    )
    if not is_success(status):
        raise status.as_error()

    totals = run_score_plugins(
        score_plugins, score_weights, state, pod, [ni.name for ni in feasible]
    )

    # deterministic seeded argmax (replaces reservoir sampling,
    # minisched.go:304-325).  The tie-break hash is keyed on the node's
    # GLOBAL index in the name-sorted snapshot — the same indexing the
    # fused batch kernel uses (ops/fused.py) — so oracle and kernel
    # agree bit-exactly even though scoring only ran on feasible nodes.
    seed = pod_seed(pod.metadata.uid or pod.metadata.name)
    feasible_names = {ni.name for ni in feasible}
    idx = select_host(
        [totals.get(ni.name, 0) for ni in node_infos],
        [ni.name in feasible_names for ni in node_infos],
        seed,
    )
    return node_infos[idx].name


def schedule_pods_sequentially(
    filter_plugins: List[Any],
    pre_score_plugins: List[Any],
    score_plugins: List[Any],
    score_weights: Dict[str, int],
    pods: List[Pod],
    node_infos: List[NodeInfo],
) -> List[str]:
    """Scalar oracle with sequential-bind semantics: each placement is
    committed into the NodeInfo snapshot before the next pod — exactly the
    reference loop's visibility (minisched.go:32-113, one pod per cycle).
    Returns one node name per pod ('' = unschedulable).  This is the
    parity ground truth for the device scan engine (ops/sequential.py).
    """
    by_name = {ni.name: ni for ni in node_infos}
    out: List[str] = []
    for pod in pods:
        try:
            name = schedule_pod_once(
                filter_plugins,
                pre_score_plugins,
                score_plugins,
                score_weights,
                pod,
                node_infos,
            )
        except FitError:
            out.append("")
            continue
        out.append(name)
        bound = pod.clone()
        bound.spec.node_name = name
        by_name[name].add_pod(bound)
    return out


class Scheduler:
    """The engine (minisched/initialize.go:18-29's Scheduler struct)."""

    def __init__(
        self,
        client: Client,
        informer_factory: SharedInformerFactory,
        filter_plugins: List[Any],
        pre_score_plugins: List[Any],
        score_plugins: List[Any],
        permit_plugins: List[Any],
        score_weights: Optional[Dict[str, int]] = None,
        queue_opts: Optional[dict] = None,
        reserve_plugins: Optional[List[Any]] = None,
        post_filter_plugins: Optional[List[Any]] = None,
    ):
        self.client = client
        self.informer_factory = informer_factory
        self.filter_plugins = filter_plugins
        self.post_filter_plugins = post_filter_plugins or []
        self.pre_score_plugins = pre_score_plugins
        self.score_plugins = score_plugins
        self.permit_plugins = permit_plugins
        self.reserve_plugins = reserve_plugins or []
        self.score_weights = score_weights or {}

        # EventsToRegister → ClusterEventMap (initialize.go:68-75)
        self.event_map: ClusterEventMap = {}
        all_plugins = {
            id(p): p
            for p in filter_plugins
            + pre_score_plugins
            + score_plugins
            + self.reserve_plugins
            + permit_plugins
        }
        merge_event_registrations(
            (
                (p.name(), p.events_to_register())
                for p in all_plugins.values()
                if implements_enqueue(p)
            ),
            self.event_map,
        )
        self.queue = SchedulingQueue(event_map=self.event_map, **(queue_opts or {}))

        #: HA shard filter (ha/membership.Membership.owns_pod): when set,
        #: the event handlers admit only this engine's shard into the
        #: queue — None (the default) admits everything (single-engine
        #: mode is a plane of one).  Installed BEFORE the informers start
        #: (service.start_scheduler) so the initial replay is filtered.
        self.shard_filter: Optional[Callable[[Pod], bool]] = None

        self._waiting_pods: Dict[str, WaitingPod] = {}
        self._waiting_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._bind_lock = threading.Lock()
        self._bind_threads: set = set()
        # observability hooks: fn(pod, node_name_or_None, status), and
        # per-phase timing — a REAL CycleMetrics by default (ISSUE 11):
        # the wave phases it forwards into observability/hist are what
        # /metrics serves, and live telemetry must not depend on a bench
        # attaching a collector.  Assign NULL_METRICS to opt out.
        self.on_decision: Optional[Callable[[Any, Optional[str], Status], None]] = None
        from minisched_tpu.observability.profiling import CycleMetrics

        self.metrics: Any = CycleMetrics()

        # incremental NodeInfo cache (upstream cache.Cache analog) — wired
        # BEFORE the queue handlers so a requeued pod's next snapshot
        # already reflects the event that woke it (same dispatch thread,
        # registration order = invocation order)
        from minisched_tpu.engine.cache import SchedulerCache

        # engine-specific handlers that must register before the cache's
        # (the device engine's ConstraintIndex: the assume-cache is pruned
        # against the cache, so the index may never lag it)
        self._wire_pre_cache(informer_factory)
        self.cache = SchedulerCache()
        self.cache.wire(informer_factory)

        eventhandlers.add_all_event_handlers(
            self, informer_factory, unioned_gvks(self.event_map)
        )

        # gang-aware permit plugins (Coscheduling) count a gang's
        # already-BOUND members toward admission; inject the engine's
        # placed-member lookup (the device engine overrides it with its
        # incremental GangIndex)
        for p in permit_plugins:
            if hasattr(p, "gang_lister") and p.gang_lister is None:
                p.gang_lister = self._gang_placed_count

    def _gang_placed_count(self, key: str, exclude=()) -> int:
        """Bound members of gang ``key`` (uid-distinct, minus
        ``exclude``) from the informer cache — O(pods), fine at scalar-
        engine scale; DeviceScheduler overrides with its GangIndex."""
        from minisched_tpu.api.objects import gang_key

        try:
            pods = self.informer_factory.informer_for("Pod").lister()
        except Exception:
            return 0
        ex = set(exclude)
        return sum(
            1
            for p in pods
            if p.spec.node_name
            and p.metadata.uid not in ex
            and gang_key(p) == key
        )

    def _wire_pre_cache(self, informer_factory: Any) -> None:
        """Hook for subclasses that need informer handlers registered
        BEFORE the NodeInfo cache's (see __init__)."""

    def admits(self, pod: Pod) -> bool:
        """Queue-admission predicate: does this engine schedule ``pod``?
        The event handlers consult it on every pending-pod event; an HA
        plane sets ``shard_filter`` so N engines partition the keyspace."""
        f = self.shard_filter
        return True if f is None else f(pod)

    # ------------------------------------------------------------------
    # lifecycle (minisched.go:28-30)
    # ------------------------------------------------------------------
    def run(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="scheduleOne-loop", daemon=True
        )
        self._thread.start()

    #: cadence of the unschedulableQ leftover flush (upstream runs
    #: flushUnschedulableQLeftover every 30s; pods parked longer than the
    #: queue's unschedulable_timeout_s replay even with no helping event)
    UNSCHEDULABLE_FLUSH_INTERVAL_S = 30.0

    def _loop(self) -> None:
        last_flush = time.monotonic()
        while not self._stop.is_set():
            try:
                now = time.monotonic()
                if now - last_flush >= self.UNSCHEDULABLE_FLUSH_INTERVAL_S:
                    last_flush = now
                    self.queue.flush_unschedulable_leftover()
                self.schedule_one()
            except Exception:  # the loop must survive anything
                import traceback

                traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        with self._bind_lock:
            binds = list(self._bind_threads)
        for t in binds:
            t.join(timeout=2.0)

    # ------------------------------------------------------------------
    # the hot loop (minisched.go:32-113)
    # ------------------------------------------------------------------
    def snapshot_nodes(self) -> List[NodeInfo]:
        """Name-sorted NodeInfo snapshot from the incremental cache —
        O(nodes) clones per cycle instead of the reference's full re-list
        + re-wrap of every node AND pod (minisched.go:40,126-127)."""
        return self.cache.snapshot()

    def schedule_one(self, timeout: Optional[float] = 0.5) -> bool:
        qpi = self.queue.pop(timeout=timeout)
        if qpi is None:
            return False
        pod = qpi.pod
        state = CycleState()
        t_cycle = time.monotonic()
        with self.metrics.timed("snapshot"):
            node_infos = self.snapshot_nodes()

        try:
            with self.metrics.timed("schedule"):
                node_name = self._schedule_pod(state, pod, node_infos, qpi)
        except Exception as err:
            # park the pod BEFORE preempting: the victims' Pod/DELETE
            # requeue events must find it in the unschedulableQ — deleting
            # first opens a window where the only wake-up event fires while
            # the pod is in neither queue (upstream closes the same window
            # with moveRequestCycle)
            self.error_func(qpi, err)
            if isinstance(err, FitError):
                # PostFilter runs when filtering fails (upstream
                # RunPostFilterPlugins) — preemption may free a node; the
                # parked pod lands once the victims' DELETE events replay it
                self.run_post_filter(state, pod, node_infos, err.diagnosis)
            if self.on_decision:
                self.on_decision(pod, None, Status.from_error(err))
            self.metrics.observe("cycle_failed", time.monotonic() - t_cycle)
            return True

        forked = self._reserve_permit_and_fork(qpi, pod, node_name, state)
        self.metrics.observe(
            "cycle" if forked else "cycle_failed", time.monotonic() - t_cycle
        )
        return True

    def _reserve_permit_and_fork(
        self,
        qpi: QueuedPodInfo,
        pod: Pod,
        node_name: str,
        state: CycleState,
        inline: bool = False,
    ) -> bool:
        """The host-side tail every engine shares: reserve (upstream
        RunReservePlugins — rolled back on any later failure) → permit
        (minisched.go:89-94) → detach the binding cycle (minisched.go:96-112).
        Returns False when the pod failed (already sent through error_func).

        ``inline=True`` runs the binding cycle on the calling thread when no
        permit plugin asked to Wait — the wave engine binds thousands of
        pods per wave and a thread per bind is pure overhead there; with a
        Wait pending the cycle still detaches (the wait can be seconds).
        """
        status = self.run_reserve_plugins(state, pod, node_name)
        if not status.is_success():
            self.error_func(qpi, status.as_error(), plugin=status.plugin)
            if self.on_decision:
                self.on_decision(pod, None, status)
            return False

        with self.metrics.timed("permit"):
            status = self.run_permit_plugins(state, pod, node_name)
        if not status.is_success() and not status.is_wait():
            self.run_unreserve_plugins(state, pod, node_name)
            self.error_func(qpi, status.as_error(), plugin=status.plugin)
            if self.on_decision:
                self.on_decision(pod, None, status)
            return False

        if inline and not status.is_wait():
            self._binding_cycle(qpi, pod, node_name, state)
            return True
        t = threading.Thread(
            target=self._binding_cycle,
            args=(qpi, pod, node_name, state),
            name=f"bind-{pod.metadata.name}",
            daemon=True,
        )
        with self._bind_lock:
            self._bind_threads.add(t)
        t.start()
        return True

    def _schedule_pod(
        self,
        state: CycleState,
        pod: Pod,
        node_infos: List[NodeInfo],
        qpi: QueuedPodInfo,
    ) -> str:
        return schedule_pod_once(
            self.filter_plugins,
            self.pre_score_plugins,
            self.score_plugins,
            self.score_weights,
            pod,
            node_infos,
            state=state,
        )

    def run_post_filter(
        self,
        state: CycleState,
        pod: Pod,
        node_infos: List[NodeInfo],
        diagnosis: Diagnosis,
    ) -> Optional[str]:
        """Run the PostFilter chain on a scheduling failure; on success the
        nominated node lands in status.nominated_node_name through the
        API (upstream's nominatedNodeName).  Never raises — a preemption
        failure must not mask the original FitError path."""
        if not self.post_filter_plugins:
            return None
        try:
            nominated, status = run_post_filter_plugins(
                self.post_filter_plugins, state, pod, node_infos, diagnosis
            )
        except Exception:
            import traceback

            traceback.print_exc()
            return None
        if status.is_success() and nominated:
            # the nomination goes through the API ONLY (upstream patches
            # status.nominatedNodeName); the informer MODIFIED event then
            # refreshes the parked pod in the queue.  Never write the
            # local object in place: pods flow into the engine as watch-
            # event objects, which since the fanout-clone removal ARE the
            # store's canonical objects — an in-place write would mutate
            # the store outside its lock, unversioned and un-WAL-logged.
            def set_nominated(p):
                p.status.nominated_node_name = nominated
                return p

            try:
                self.client.pods(pod.metadata.namespace).mutate(
                    pod.metadata.name, set_nominated
                )
            except KeyError:
                pass  # pod deleted meanwhile
            return nominated
        return None

    # -- extension-point runners (thin wrappers over the module fns) ----
    def run_filter_plugins(
        self, state: CycleState, pod: Pod, node_infos: List[NodeInfo]
    ) -> Tuple[List[NodeInfo], Diagnosis]:
        return run_filter_plugins(self.filter_plugins, state, pod, node_infos)

    def run_pre_score_plugins(
        self, state: CycleState, pod: Pod, nodes: List[Any]
    ) -> Status:
        return run_pre_score_plugins(self.pre_score_plugins, state, pod, nodes)

    def run_score_plugins(
        self, state: CycleState, pod: Pod, node_names: List[str]
    ) -> Dict[str, int]:
        return run_score_plugins(
            self.score_plugins, self.score_weights, state, pod, node_names
        )

    def run_permit_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Status:
        """minisched.go:201-237: statuses Wait are pooled into one
        WaitingPod with per-plugin timeouts.

        The WaitingPod is registered BEFORE plugins run so a plugin that
        fires Allow during its own Permit call (NodeNumber with a 0-suffix
        node arms a zero-delay timer, nodenumber.go:112) cannot lose the
        signal — the race the reference has (see waitingpod.py docstring).
        """
        if not self.permit_plugins:
            # empty chain: nothing could ever Allow/Reject — skip the
            # WaitingPod registration (per-pod lock + allocation; a wave
            # commits thousands)
            return Status.success()
        wp = WaitingPod(pod)
        with self._waiting_lock:
            self._waiting_pods[pod.metadata.uid] = wp
        any_wait = False
        for pl in self.permit_plugins:
            status, timeout_s = pl.permit(state, pod, node_name)
            if status is None or status.is_success():
                continue
            if status.is_wait():
                any_wait = True
                wp.add_pending(pl.name(), timeout_s)
            else:
                with self._waiting_lock:
                    self._waiting_pods.pop(pod.metadata.uid, None)
                return status.with_plugin(status.plugin or pl.name())
        wp.seal()
        if not any_wait:
            with self._waiting_lock:
                self._waiting_pods.pop(pod.metadata.uid, None)
            return Status.success()
        return Status.wait()

    def run_reserve_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> Status:
        """Upstream RunReservePlugins: first failure unreserves, in reverse,
        every plugin that already reserved (including the failing one)."""
        done: List[Any] = []
        for pl in self.reserve_plugins:
            done.append(pl)
            status = pl.reserve(state, pod, node_name)
            if status is not None and not status.is_success():
                for prev in reversed(done):
                    prev.unreserve(state, pod, node_name)
                return status.with_plugin(status.plugin or pl.name())
        return Status.success()

    def run_unreserve_plugins(
        self, state: CycleState, pod: Pod, node_name: str
    ) -> None:
        for pl in reversed(self.reserve_plugins):
            pl.unreserve(state, pod, node_name)

    def get_waiting_pod(self, uid: str) -> Optional[WaitingPod]:
        with self._waiting_lock:
            return self._waiting_pods.get(uid)

    # -- binding cycle (minisched.go:96-112,240-277) --------------------
    def wait_on_permit(self, pod: Pod) -> Status:
        wp = self.get_waiting_pod(pod.metadata.uid)
        if wp is None:
            return Status.success()
        try:
            return wp.get_signal()
        finally:
            with self._waiting_lock:
                self._waiting_pods.pop(pod.metadata.uid, None)

    def bind(self, pod: Pod, node_name: str) -> None:
        # expected_rv: the optimistic-concurrency precondition the device
        # wave path already stamps (_bind_batch) — bind only if the pod is
        # STILL at the version this cycle evaluated.  A Conflict rides the
        # normal error_func → requeue path, where the MODIFIED event's
        # queue.update has already refreshed the parked pod.  In an HA
        # plane this is also the cross-engine arbitration: two engines
        # racing one pod commit exactly one bind.
        self.client.pods().bind(
            Binding(
                pod.metadata.name,
                pod.metadata.namespace,
                node_name,
                expected_rv=pod.metadata.resource_version or None,
            )
        )

    def _bind_race_refresh(self, qpi: QueuedPodInfo) -> bool:
        """A bind lost a race (Conflict on ``expected_rv``, AlreadyBound
        from a peer engine).  The MODIFIED event that made our copy stale
        was delivered while the pod was IN-FLIGHT — invisible to
        queue.update (pop had discarded the uid) — so a re-parked qpi
        would carry the stale resource_version forever and every retry
        would conflict again (livelock).  Consult the informer cache,
        which DID apply that event: returns True when the pod left the
        schedulable population (bound by anyone / deleted / recreated) —
        drop it instead of requeueing; False when it is still pending —
        the queued copy was refreshed so the retry carries the current
        version."""
        try:
            cur = self.informer_factory.informer_for("Pod").get(
                qpi.pod.metadata.key
            )
        except Exception:
            return False  # no cache view: park as before, retry later
        if (
            cur is None
            or cur.metadata.uid != qpi.pod.metadata.uid
            or cur.spec.node_name
        ):
            return True
        qpi.pod_info.pod = cur
        return False

    @staticmethod
    def _is_bind_race(err: BaseException) -> bool:
        from minisched_tpu.controlplane.client import (
            AlreadyBound,
            OutOfCapacity,
        )
        from minisched_tpu.controlplane.store import Conflict

        # OutOfCapacity included: the pod itself may be stale too, and
        # the refresh costs one cache lookup
        return isinstance(err, (AlreadyBound, Conflict, OutOfCapacity))

    def _binding_cycle(
        self,
        qpi: QueuedPodInfo,
        pod: Pod,
        node_name: str,
        state: Optional[CycleState] = None,
    ) -> None:
        state = state if state is not None else CycleState()
        try:
            with self.metrics.timed("wait_on_permit"):
                status = self.wait_on_permit(pod)
            if not status.is_success():
                self.run_unreserve_plugins(state, pod, node_name)
                from minisched_tpu.plugins.coscheduling import (
                    is_gang_ttl_status,
                )

                if is_gang_ttl_status(status):
                    # gang TTL release: the member was FEASIBLE — its
                    # peers just never arrived.  No cluster event is
                    # coming to wake it from the unschedulableQ, so the
                    # assume lease is forgotten (capacity released) and
                    # the member requeues through the ACTIVE queue for a
                    # prompt retry; the queue's gang-adjacent pop order
                    # then serializes competing gangs instead of
                    # re-interleaving them (deadlock-freedom).
                    forget = getattr(self, "_forget", None)
                    if forget is not None:
                        forget(pod.metadata.uid)
                    from minisched_tpu.observability import counters

                    counters.inc("gang.ttl_requeued")
                    # requeue: a TTL-released member retries promptly,
                    # never quota-held behind its tenant's arrivals
                    self.queue.add(qpi.pod, requeue=True)
                    if self.on_decision:
                        self.on_decision(pod, None, status)
                    return
                self.error_func(qpi, status.as_error(), plugin=status.plugin)
                if self.on_decision:
                    self.on_decision(pod, None, status)
                return
            with self.metrics.timed("bind"):
                self.bind(pod, node_name)
            from minisched_tpu.observability import trace

            trace.span_pod(
                "bind", pod, node=node_name,
                wave=getattr(self, "_wave_seq", None),
            )
            self.queue.observe_bind(pod, node_name)
            if self.on_decision:
                self.on_decision(pod, node_name, Status.success())
        except Exception as err:
            self.run_unreserve_plugins(state, pod, node_name)
            from minisched_tpu.controlplane.client import OutOfCapacity

            if isinstance(err, OutOfCapacity) and "budget-mirror" in str(err):
                # refused by a non-home shard's capacity MIRROR
                # (DESIGN.md §31): the cross-shard budget view said no —
                # counted apart from local OutOfCapacity races because a
                # stale mirror rv is a sync-lag signal, not contention
                from minisched_tpu.observability import counters

                counters.inc("sched.bind_mirror_refusals")
            if self._is_bind_race(err) and self._bind_race_refresh(qpi):
                # bound elsewhere or gone: no longer schedulable work —
                # requeueing would retry (and re-conflict) forever.  A
                # device engine's assumption must still release (the
                # authoritative state owns the capacity now).
                forget = getattr(self, "_forget", None)
                if forget is not None:
                    forget(pod.metadata.uid)
                if self.on_decision:
                    self.on_decision(pod, None, Status.from_error(err))
                return
            from minisched_tpu.controlplane.store import StorageDegraded

            if isinstance(err, StorageDegraded):
                # degraded WAL (ENOSPC/EIO latch): park-and-retry, the
                # same path the device engine's wave takes — capacity
                # releases with the requeue, and the retry lands once
                # the store's recovery probe re-arms appends
                from minisched_tpu.observability import counters

                counters.inc("storage.degraded_parks")
            self.error_func(qpi, err)
            if self.on_decision:
                self.on_decision(pod, None, Status.from_error(err))
        finally:
            with self._bind_lock:
                self._bind_threads.discard(threading.current_thread())

    # -- failure path (minisched.go:283-298) ----------------------------
    def error_func(
        self, qpi: QueuedPodInfo, err: Optional[BaseException], plugin: str = ""
    ) -> None:
        if isinstance(err, FitError):
            qpi.unschedulable_plugins = set(err.diagnosis.unschedulable_plugins)
        elif plugin:
            qpi.unschedulable_plugins = {plugin}
        self.queue.add_unschedulable(qpi)


# ---------------------------------------------------------------------------
# wiring (minisched/initialize.go:35-78's New)
# ---------------------------------------------------------------------------


def new_scheduler(
    client: Client,
    informer_factory: SharedInformerFactory,
    time_scale: float = 1.0,
    queue_opts: Optional[dict] = None,
) -> Scheduler:
    """Default wiring: filter=[NodeUnschedulable],
    pre-score/score/permit=[NodeNumber] (initialize.go:44-66)."""
    from minisched_tpu.plugins.nodenumber import NodeNumber
    from minisched_tpu.plugins.nodeunschedulable import NodeUnschedulable

    node_number = NodeNumber(time_scale=time_scale)
    sched = Scheduler(
        client,
        informer_factory,
        filter_plugins=[NodeUnschedulable()],
        pre_score_plugins=[node_number],
        score_plugins=[node_number],
        permit_plugins=[node_number],
        queue_opts=queue_opts,
    )
    node_number.h = sched  # Scheduler implements the waitingpod Handle
    return sched
