"""Two-stage wave pipeline: host build overlapped with device evaluate.

BENCH_r05 showed the device kernel placing 70M pods/s while the full
chain landed at 10.4k: of a 7.9s wave loop, device evaluate was 4.0s and
the host-side phases (snapshot, pack tables, build constraints, commit,
gc) ran strictly serially around it — the TPU sat idle for most of every
wave.  This module overlaps them: a BUILD WORKER thread pops wave N+1
from the scheduling queue, snapshots, and packs its tables while the
loop thread blocks (GIL released) in wave N's device call; a bounded
handoff queue (depth 1) is the backpressure between the stages, and the
loop thread's commit/losers handling for wave N overlaps the worker's
build of wave N+2 the same way.

Correctness: wave N+1's snapshot predates wave N's commits, so its
winners are RE-ARBITRATED on the loop thread against the current
capacity view before assume/commit (DeviceScheduler._rearbitrate_winners
— losers requeue and re-place against a fresh snapshot), and the bind
transaction's AlreadyBound / Conflict / OutOfCapacity preconditions
remain the store-side backstop, unchanged.  Anything the build stage
cannot handle (encode overflow, an empty roster, the cross-pod priority
bypass, an injected build fault) is handed back RAW and takes the exact
serial wave path.

Mesh composition (ISSUE 7): the build stage's output is packed HOST
buffers, so the same pipeline drives the mesh-sharded evaluator
unchanged — the shared table builder pads capacities to the mesh-axis
multiples and keeps the static node columns device-resident sharded;
the loop thread's device call dispatches the sharded program
(DeviceScheduler._eval_packed_wave, with its own per-wave single-device
fallback ladder).  Nothing in this module is mesh-aware by design.

``MINISCHED_PIPELINE=0`` disables the whole stage — the engine then runs
the untouched serial loop (DeviceScheduler._schedule_one_serial).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from typing import Any, List, Optional


class PreparedWave:
    """One wave's build-stage output, handed loop-ward over the queue."""

    __slots__ = (
        "qpis",
        "constrained",
        "partial",
        "node_infos",
        "node_names",
        "node_static",
        "node_agg",
        "pod_table",
        "extra",
        "build_s",
        "dirty_rows",
        "build_skipped",
    )

    def __init__(self) -> None:
        self.qpis: List[Any] = []
        self.constrained: List[Any] = []
        self.partial = True
        self.node_infos: List[Any] = []
        self.node_names: List[str] = []
        self.node_static: Any = None
        self.node_agg: Any = None
        self.pod_table: Any = None
        self.extra: Any = None
        self.build_s = 0.0
        self.dirty_rows = 0
        #: the node-table build was skipped wholesale (idle-wave gate:
        #: nothing dirty, roster epoch unchanged, same assume-delta —
        #: ISSUE 8); the loop thread counts these per wave
        self.build_skipped = False


class _BuildFallback(Exception):
    """Internal: this batch must take the serial wave path."""


class WavePipeline:
    """The build worker + bounded handoff for one DeviceScheduler.

    Items on the handoff queue:

    * ``("wave", PreparedWave)`` — tables built, ready for the device.
    * ``("raw", qpis, partial)`` — build-stage fallback; the loop thread
      runs the serial ``schedule_wave`` over the original batch.
    * ``("empty",)`` — a pop window elapsed with nothing to do; the loop
      thread runs its idle path (lease expiry, backlog flush, gc).

    The worker is the ONLY queue popper while the pipeline is active, so
    pop order (priority/FIFO) is preserved; the handoff depth of 1 means
    at most two waves' pods are ever outside the queues (one on device,
    one built/building), and ``drain()`` hands any stranded ones back to
    the loop thread at shutdown.
    """

    def __init__(self, sched: Any, depth: int = 1, pop_timeout: float = 0.5):
        self._sched = sched
        self._handoff: _queue.Queue = _queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._pop_timeout = pop_timeout
        self._thread: Optional[threading.Thread] = None
        #: qpis popped but never handed over (stop raced the put) — the
        #: loop thread's shutdown drain parks them through error_func
        self._leftover: List[Any] = []

    # -- loop-thread surface -----------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="wave-build", daemon=True
        )
        self._thread.start()

    def get(self, timeout: Optional[float] = None):
        """Next item, or None on timeout (the worker emits at least one
        item per pop window, so None means it is stopping or wedged)."""
        try:
            return self._handoff.get(timeout=timeout)
        except _queue.Empty:
            return None

    def stop(self, join_timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout)

    def drain(self) -> List[Any]:
        """Popped-but-unscheduled qpis after stop() — cross-pod deferrals
        included; the caller parks them so no pod is silently lost."""
        out = list(self._leftover)
        self._leftover = []
        while True:
            try:
                item = self._handoff.get_nowait()
            except _queue.Empty:
                return out
            if item[0] == "wave":
                out.extend(item[1].qpis)
                out.extend(item[1].constrained)
            elif item[0] == "raw":
                out.extend(item[1])

    # -- worker ------------------------------------------------------------
    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._handoff.put(item, timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def _strand(self, item) -> None:
        if item[0] == "wave":
            self._leftover.extend(item[1].qpis)
            self._leftover.extend(item[1].constrained)
        elif item[0] == "raw":
            self._leftover.extend(item[1])

    def _run(self) -> None:
        sched = self._sched
        while not self._stop.is_set():
            try:
                with sched.metrics.timed("pipeline_pop"):
                    qpis = sched.queue.pop_batch(
                        sched.max_wave, timeout=self._pop_timeout
                    )
            except Exception:
                # a closing queue mid-shutdown must not kill the worker
                # before stop() is observed
                if self._stop.is_set():
                    return
                qpis = None
            if self._stop.is_set():
                self._leftover.extend(qpis or ())
                return
            if not qpis:
                self._put(("empty",))
                continue
            item = self._build_item(qpis, len(qpis) < sched.max_wave)
            if not self._put(item):
                self._strand(item)
                return

    def _build_item(self, qpis: List[Any], partial: bool):
        from minisched_tpu.observability import counters

        try:
            t0 = time.monotonic()
            with self._sched.metrics.timed("wave_pipeline_build"):
                prepared = self._build(qpis)
            prepared.partial = partial
            prepared.build_s = time.monotonic() - t0
            return ("wave", prepared)
        except _BuildFallback:
            return ("raw", qpis, partial)
        except Exception:
            # encode overflow (ValueError), an injected store fault in
            # the constraint build, anything unforeseen: the serial path
            # owns the retry/park machinery for all of them
            counters.inc("wave_pipeline.build_fallback")
            return ("raw", qpis, partial)

    def _build(self, qpis: List[Any]) -> PreparedWave:
        from minisched_tpu.engine.device_scheduler import _is_cross_pod
        from minisched_tpu.models.tables import build_pod_table

        sched = self._sched
        prepared = PreparedWave()
        prepared.qpis = qpis
        if sched._has_cross_pod:
            constrained = [q for q in qpis if _is_cross_pod(q.pod)]
            if constrained:
                prepared.constrained = constrained
                prepared.qpis = [
                    q for q in qpis if not _is_cross_pod(q.pod)
                ]
            # priority-inversion bypass (see _schedule_wave_inner): when
            # a deferred constrained pod outranks a plain pod about to
            # run, the backlog must flush FIRST — backlog flushing is
            # loop-thread work, so hand the batch back raw.  The backlog
            # read is a cross-thread peek; the GIL makes it safe and the
            # loop re-checks authoritatively on the serial path.
            pool = list(sched._scan_backlog) + prepared.constrained
            if pool and prepared.qpis:
                hi = max(q.pod.spec.priority for q in pool)
                if hi > min(q.pod.spec.priority for q in prepared.qpis):
                    raise _BuildFallback()
        if not prepared.qpis:
            raise _BuildFallback()  # all-constrained batch: serial path
        pods_ = [q.pod for q in prepared.qpis]
        # leases expire on the loop thread (store probes must not stall
        # the overlap window); the dirty-set drain is atomic with the
        # snapshot and this worker is the only wave-path snapshotter
        with sched.metrics.timed("wave_snapshot"):
            node_infos, agg_delta, assumed_pods, dirty, epoch = (
                sched._snapshot_for_tables(expire_leases=False)
            )
        if not node_infos:
            raise _BuildFallback()  # empty roster: serial error path
        prepared.node_infos = node_infos
        nodes = [ni.node for ni in node_infos]
        with sched.metrics.timed("wave_assigned_list"):
            assigned = (
                ()
                if sched.constraint_index is not None
                else [p for ni in node_infos for p in ni.pods]
                + assumed_pods
            )
        pod_capacity = sched._wave_cap(len(pods_))
        # placed-gang aggregates for this wave's members (assume-cache
        # folded in): computed on the worker against the same snapshot
        # the tables encode; the loop thread's re-arbitration handles
        # anything the overlapped wave commits after this
        gang_view = sched._gang_view(pods_)
        with sched.metrics.timed("wave_build_tables"):
            node_static, node_agg, node_names = (
                sched._table_builder.build_packed(
                    node_infos, agg_delta=agg_delta, dirty=dirty,
                    epoch=epoch,
                )
            )
            prepared.dirty_rows = sched._table_builder.last_dirty_rows
            prepared.build_skipped = (
                sched._table_builder.last_build_skipped
            )
            pod_table, _ = build_pod_table(
                pods_, capacity=pod_capacity, device=False,
                gang_view=gang_view,
            )
        prepared.node_static = node_static
        prepared.node_agg = node_agg
        prepared.node_names = node_names
        prepared.pod_table = pod_table
        if sched._needs_extra:
            with sched.metrics.timed("wave_build_constraints"):
                prepared.extra = sched._build_constraints(
                    pods_, nodes, assigned,
                    pod_capacity=pod_capacity,
                    node_capacity=node_agg.capacity,
                    scan_planes=False,  # wave mode never runs the scan
                    device=False,
                )
        return prepared
