"""Device-backed scheduling engine: the TPU path wired to the live
control plane.

The scalar engine (engine/scheduler.py) is the reference-shaped loop: one
pod per cycle.  This engine is the TPU-native alternative behind the same
control-plane contract: it drains the scheduling queue in WAVES
(queue.pop_batch), builds the struct-of-arrays tables for the snapshot,
evaluates the whole wave on device in repair mode (ops/repair.py — commits
are conflict-free), then runs the host-side permit machinery and binds
each placed pod.  Unplaced pods flow through the same ErrorFunc →
unschedulableQ → event-gated requeue path as the scalar engine.

Cross-pod plugins get per-wave constraint tables (models/constraints.py);
the informer/event machinery, waiting-pod registry, and queue are shared
with the scalar engine via subclassing — the device part replaces only
the evaluate step, exactly the boundary SURVEY.md §7's design stance
draws (host control plane / device batch evaluator).
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from minisched_tpu.api.objects import Pod
from minisched_tpu.engine.scheduler import Scheduler
from minisched_tpu.framework.types import (
    CycleState,
    Diagnosis,
    FitError,
    QueuedPodInfo,
    Status,
)
from minisched_tpu.models.constraints import (
    SCAN_ELIDE_GROUPS,
    build_constraint_tables,
)
from minisched_tpu.models.tables import (
    CachedNodeTableBuilder,
    DIRTY_UNTRACKED,
    build_pod_table,
    pad_to,
)
from minisched_tpu.ops.repair import RepairingEvaluator


import os as _os

#: env-gated per-wave stderr trace (timeline debugging at bench scale)
_WAVE_LOG = _os.environ.get("MINISCHED_WAVE_LOG", "") not in ("", "0")


def _is_cross_pod(pod: Pod) -> bool:
    """Pods that read or write intra-wave cross-pod coupling state
    (topology spread / pod (anti-)affinity).  The repair wave evaluates
    every pod against wave-start combo planes, so two such pods in one
    wave would be blind to each other — they ride the sequential scan
    instead (bind-exact; ops/sequential.py carries the combo planes)."""
    if pod.spec.topology_spread_constraints:
        return True
    aff = pod.spec.affinity
    if aff is None:
        return False
    return aff.pod_affinity is not None or aff.pod_anti_affinity is not None


class DeviceScheduler(Scheduler):
    """Scheduler whose evaluation step runs on device, a wave at a time."""

    def __init__(
        self,
        *args,
        max_wave: int = 1024,
        mesh: Any = None,
        assume_ttl_s: float = 30.0,
        faults: Any = None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.max_wave = max_wave
        #: assume-lease TTL: every assumption expires after this many
        #: seconds unless the informer confirms the bind first.  A pod
        #: whose bind was LOST to a fault (transport failure whose error
        #: path itself failed, a crashed bind thread) would otherwise
        #: double-book its node forever — at expiry the AUTHORITATIVE
        #: store decides: bound → renew (informer merely lagging);
        #: unbound → release the capacity and requeue the pod; store
        #: unreachable → renew and retry next check.  None disables.
        self.assume_ttl_s: Optional[float] = assume_ttl_s
        #: optional faults.FaultFabric for the engine-side injection
        #: points (``engine.bind``) — tests/chaos soak arm it
        self.faults = faults
        #: optional jax.sharding.Mesh — waves then evaluate SHARDED over
        #: the (pods × nodes) device mesh (parallel/sharding.py): pod rows
        #: data-parallel, node columns model-parallel, XLA collectives
        #: over ICI.  None at construction resolves the startup policy
        #: (parallel/sharding.resolve_mesh): MINISCHED_MESH=1 forces a
        #: mesh over every visible device (a degenerate 1-device mesh
        #: keeps current behavior), MINISCHED_MESH=0 pins single-device,
        #: unset auto-shards exactly when jax.device_count() > 1.
        #: ``mesh=False`` pins single-device EXPLICITLY (bypassing the
        #: policy) — the mesh bench's baseline lap needs it on a box
        #: whose device count would auto-shard.
        if mesh is None:
            from minisched_tpu.parallel.sharding import resolve_mesh

            mesh = resolve_mesh()
        elif mesh is False:
            mesh = None
        self.mesh = mesh
        #: pod-table capacity quantum: lane-padded AND divisible by the
        #: mesh pod axis so every shard gets equal whole tiles (the node
        #: quantum lives in the table builder)
        self._pod_cap_mult = 128
        #: per-wave single-device fallback evaluator (mesh mode only) —
        #: mirrors the pipeline's _BuildFallback: any sharded-evaluate
        #: failure re-runs THAT wave on one device, later waves retry
        #: the mesh (see _eval_packed_wave)
        self._mesh_fallback_evaluator: Any = None
        #: monotonic wave id stamped on trace spans (observability/trace)
        #: so a pod's enqueue→bind chain joins its wave's build/evaluate
        #: spans; (pod_shards, node_shards) rides along in mesh mode
        self._wave_seq = 0
        self._mesh_shards: Any = None
        if mesh is not None:
            from minisched_tpu.observability import counters
            from minisched_tpu.parallel.sharding import (
                cap_multiple,
                mesh_axis_sizes,
            )

            pod_ax, node_ax = mesh_axis_sizes(mesh)
            self._pod_cap_mult = cap_multiple(128, pod_ax)
            self._mesh_shards = (pod_ax, node_ax)
            # gauges, not counters: the factoring is state — restarts and
            # multi-engine processes must not sum 2x4 into 4x8
            counters.set_gauge("wave_mesh.pod_shards", pod_ax)
            counters.set_gauge("wave_mesh.node_shards", node_ax)
        # chains with a combo-carrying (cross-pod) plugin route constrained
        # pods through the sequential scan; volume-only chains never do —
        # nothing in them evaluates spread/affinity constraints.  Unknown
        # cross-pod plugins without the attribute get the safe default.
        self._has_cross_pod = any(
            getattr(p, "needs_extra", False)
            and "combos" in getattr(
                p, "scan_carried_planes", ("combos", "volumes")
            )
            for p in (*self.filter_plugins, *self.score_plugins)
        )
        self._evaluator: Optional[RepairingEvaluator] = None
        self._scan_scheduler: Any = None  # lazy SequentialScheduler
        self._blocked_scheduler: Any = None  # lazy BlockedSequentialScheduler
        #: two-stage wave pipeline (engine/pipeline.py): the host build
        #: stage for wave N+1 runs on a worker thread while the device
        #: evaluates wave N.  MINISCHED_PIPELINE=0 is the kill-switch —
        #: the loop then takes the exact serial path (pop → snapshot →
        #: build → evaluate → commit on one thread, byte-for-byte the
        #: pre-pipeline code).  The pipeline engages only in packed
        #: single-device mode (see _pipeline_active).
        self.pipeline_enabled = _os.environ.get(
            "MINISCHED_PIPELINE", "1"
        ) not in ("", "0")
        self._pipeline: Any = None
        #: commit-time re-arbitration only matters when the chain
        #: actually filters on capacity — chains without NodeResourcesFit
        #: accept over-booking by design (the serial engine would too),
        #: and rejecting there would CHANGE placements vs serial
        self._rearb_capacity = any(
            p.name() == "NodeResourcesFit" for p in self.filter_plugins
        )
        # static node columns cached across waves, keyed on each node's
        # (name, resource_version) — only the assigned-pod aggregates are
        # re-encoded per wave.  Under a mesh the device-resident statics
        # live SHARDED on the node axis (the packed mesh program consumes
        # them in place; nothing donates them)
        self._table_builder = CachedNodeTableBuilder(
            device_static=True, mesh=self.mesh
        )
        #: observability.resultstore.Store — set by the service when
        #: record_results is on: each wave then also runs a diagnostics
        #: evaluation and records the same per-plugin artifact scalar
        #: cycles produce (O(pods × nodes × plugins) host dicts — a
        #: simulator feature, not for headline-scale waves)
        self.result_store: Any = None
        self._diag_evaluator: Any = None
        # cross-pod pods deferred across waves (see schedule_wave): the
        # scan lane's cost is per-CALL (packed transfer + dispatch on the
        # tunneled runtime), so constrained pods accumulate here and the
        # lane runs once per ~BLOCKED_MAX_CHUNK of them — or at queue
        # drain, whichever comes first.  Pop order is preserved, so
        # per-group FIFO (the lane's exactness contract) is unchanged.
        self._scan_backlog: List[QueuedPodInfo] = []
        self._scan_backlog_waves = 0  # full waves survived since first defer
        # assume-pod cache (upstream's scheduler cache AssumePod): a placed
        # pod counts against its node IMMEDIATELY, before the async bind
        # lands in the informer cache — without it, the next wave snapshots
        # stale state and can double-book the capacity wave N just used
        self._assumed: dict = {}  # uid → pod clone with node_name set
        #: uid → (milli_cpu, mem_mib, eph_mib, nz_milli_cpu, nz_mem_mib,
        #: ports) with NodeInfo.add_pod's exact quantization — computed
        #: once at assume time so per-wave snapshots fold assumptions as
        #: numeric aggregate deltas instead of per-pod add_pod calls
        #: (~250ms/16k-pod wave of duplicated host work)
        self._assumed_agg: dict = {}
        #: uid → monotonic deadline; see assume_ttl_s
        self._assumed_expiry: dict = {}
        self._assumed_lock = threading.Lock()
        # control-plane reconnect (watch resumed OR relisted — either way
        # the stream broke, and a server RESTART may sit behind it):
        # every assumption's lease is marked due immediately, so the next
        # snapshot/idle check re-arbitrates each against the AUTHORITATIVE
        # store instead of trusting pre-crash memory — a bind the dead
        # server never committed is released+requeued, one that committed
        # without an event is confirmed (see _expire_assume_leases)
        self.informer_factory.informer_for("Pod").on_reconnect.append(
            self._revalidate_assume_ledger
        )

    def _revalidate_assume_ledger(self) -> None:
        from minisched_tpu.observability import counters

        now = time.monotonic()
        with self._assumed_lock:
            n = len(self._assumed_expiry)
            for uid in self._assumed_expiry:
                self._assumed_expiry[uid] = now
        if n:
            counters.inc("assume.revalidate_on_reconnect", n)

    def _wire_pre_cache(self, informer_factory: Any) -> None:
        """Create + wire the incremental constraint index when the chains
        read cross-pod/volume planes.  Registered BEFORE the NodeInfo
        cache (see Scheduler.__init__): the assume-cache prunes against
        the cache, so an index that lagged it could drop a just-confirmed
        bind from the planes for one wave; index-ahead is harmless (the
        assumed fold checks index membership first)."""
        self._needs_extra = any(
            getattr(p, "needs_extra", False)
            for p in (*self.filter_plugins, *self.score_plugins)
        )
        self.constraint_index = None
        if self._needs_extra:
            from minisched_tpu.models.constraint_index import ConstraintIndex

            self.constraint_index = ConstraintIndex()
            self.constraint_index.wire(informer_factory)
        # gang placement directory: wired pre-cache for the same reason
        # the constraint index is — the assume-cache prunes against the
        # NodeInfo cache, so the gang view must never lag it
        self.gang_index = None
        if any(
            p.name() in ("GangTopology", "Coscheduling")
            for p in (
                *self.filter_plugins,
                *self.score_plugins,
                *self.permit_plugins,
            )
        ):
            from minisched_tpu.engine.gang import GangIndex

            self.gang_index = GangIndex()
            self.gang_index.wire(informer_factory)

    def _build_constraints(self, pods_, nodes, assigned, **kw) -> Any:
        """Constraint tables for one wave/chunk.  With a live index the
        assumed-pod membership check and the aggregate reads happen under
        ONE index lock hold — otherwise a bind event landing in between
        would count its pod both as "assumed" and in the index planes
        (TOCTOU double-count)."""
        import contextlib

        index = self.constraint_index
        with self.metrics.timed("constraints_lock_wait"):
            lock_cm = (
                index.lock() if index is not None else contextlib.nullcontext()
            )
            lock_cm.__enter__()
        try:
            extra: Any = ()
            if index is not None:
                uids = index.assigned_uids()
                with self._assumed_lock:
                    extra = [
                        a for uid, a in self._assumed.items()
                        if uid not in uids
                    ]
            with self.metrics.timed("constraints_store_list"):
                pvcs = self.client.store.list("PersistentVolumeClaim")
                pvs = self.client.store.list("PersistentVolume")
            return build_constraint_tables(
                pods_, nodes, assigned,
                pvcs=pvcs,
                pvs=pvs,
                index=index,
                extra_assigned=extra,
                **kw,
            )
        finally:
            lock_cm.__exit__(None, None, None)

    def _gang_placed_count(self, key: str, exclude=()) -> int:
        """GangIndex-backed placed-member count (O(gang), not O(pods))."""
        if self.gang_index is None:
            return super()._gang_placed_count(key, exclude)
        return self.gang_index.placed_count(key, exclude)

    def _gang_view(self, pods_) -> Any:
        """Placed-gang aggregates for this wave's gang members: the
        incremental GangIndex plus the assume-cache folded on top (an
        assumed member is placed capacity before its bind event lands).
        None when the wave carries no gang members — build_pod_table
        then skips the columns entirely."""
        if self.gang_index is None:
            return None
        from minisched_tpu.api.objects import gang_key

        keys = {gang_key(p) for p in pods_}
        keys.discard(None)
        if not keys:
            return None
        with self._assumed_lock:
            extra = [
                (k, uid, a.spec.node_name)
                for uid, a in self._assumed.items()
                if (k := gang_key(a)) is not None
            ]
        return self.gang_index.view_for(keys, extra)

    # -- assume-pod cache ---------------------------------------------------
    def _assume(self, pod: Pod, node_name: str) -> None:
        from minisched_tpu.api.objects import (
            DEFAULT_POD_CPU_REQUEST,
            DEFAULT_POD_MEMORY_REQUEST,
            MIB,
        )

        assumed = pod.clone()
        assumed.spec.node_name = node_name
        req = pod.resource_requests()
        mem_mib = req.memory // MIB
        agg = (
            req.milli_cpu,
            mem_mib,
            req.ephemeral_storage // MIB,
            req.milli_cpu or DEFAULT_POD_CPU_REQUEST,
            mem_mib or (DEFAULT_POD_MEMORY_REQUEST // MIB),
            tuple(
                port for c in pod.spec.containers if c.ports for port in c.ports
            ),
        )
        with self._assumed_lock:
            self._assumed[pod.metadata.uid] = assumed
            self._assumed_agg[pod.metadata.uid] = agg
            if self.assume_ttl_s is not None:
                self._assumed_expiry[pod.metadata.uid] = (
                    time.monotonic() + self.assume_ttl_s
                )

    def _forget(self, uid: str) -> None:
        with self._assumed_lock:
            self._assumed.pop(uid, None)
            self._assumed_agg.pop(uid, None)
            self._assumed_expiry.pop(uid, None)

    def _expire_assume_leases(self) -> None:
        """Release (or renew) assumptions whose lease ran out — the
        backstop that keeps a lost bind from double-booking a node for
        the life of the process.  Runs at every snapshot AND on the idle
        path: with the queue drained there is no wave left to notice the
        leak.  The authoritative-store read happens OUTSIDE the assume
        lock (it may be a network call)."""
        if self.assume_ttl_s is None:
            return
        now = time.monotonic()
        # pods re-deferred to the scan backlog keep their assumption ON
        # PURPOSE (_park_scan_failures: commit unverifiable, a later flush
        # arbitrates) — expiring them here would put the same pod live in
        # two lanes at once (queue.add dedupes against queues, not the
        # backlog), and whichever lane ran second would overwrite the
        # first's assumption.  The backlog and this method both run on
        # the loop thread, so the read is unsynchronized but safe.
        backlog_uids = {q.pod.metadata.uid for q in self._scan_backlog}
        with self._assumed_lock:
            expired = [
                (uid, self._assumed[uid])
                for uid, deadline in self._assumed_expiry.items()
                if deadline <= now
                and uid in self._assumed
                and uid not in backlog_uids
            ]
        if not expired:
            return
        from minisched_tpu.observability import counters

        # bound the authoritative probes per round: each is a store
        # round-trip ON the scheduling-loop thread, and a lost big wave
        # can expire hundreds of leases at once — probe a slice now,
        # leave the rest expired for the next round (snapshot or idle,
        # both frequent) instead of stalling the loop for N × RTT
        probe, deferred = (
            expired[: self.MAX_LEASE_PROBES_PER_ROUND],
            expired[self.MAX_LEASE_PROBES_PER_ROUND :],
        )
        if deferred:
            counters.inc("assume.lease_probe_deferred", len(deferred))
        expired = probe
        for i, (uid, assumed) in enumerate(expired):
            try:
                cur = self.client.pods().get(
                    assumed.metadata.name, assumed.metadata.namespace
                )
            except KeyError:
                # pod deleted while assumed: just release the capacity
                self._forget(uid)
                counters.inc("assume.lease_expired")
                continue
            except Exception:
                # store unreachable: keep the capacity reserved (the bind
                # may have landed), re-arm the lease — and for EVERY
                # remaining expired lease too, without probing: each get
                # pays the remote client's whole retry budget while the
                # plane is down, and N sequential probes would stall the
                # scheduling loop for N × that budget to learn the same
                # answer N times
                with self._assumed_lock:
                    for uid2, _ in expired[i:]:
                        if uid2 in self._assumed_expiry:
                            self._assumed_expiry[uid2] = (
                                now + self.assume_ttl_s
                            )
                counters.inc(
                    "assume.lease_renewed_unreachable", len(expired) - i
                )
                return
            if cur.metadata.uid != uid:
                self._forget(uid)  # recreated under the same name
                counters.inc("assume.lease_expired")
            elif cur.spec.node_name:
                # bound per the authority.  If the informer cache has
                # caught up, the assumption is redundant — forget it (this
                # is how the assume counter reaches zero at quiesce: the
                # wave-snapshot prune only runs while waves run).  Cache
                # still behind: renew so capacity stays booked until it is.
                cached = self.informer_factory.informer_for("Pod").get(
                    assumed.metadata.key
                )
                if cached is not None and cached.spec.node_name:
                    self._forget(uid)
                    counters.inc("assume.lease_confirmed")
                else:
                    with self._assumed_lock:
                        if uid in self._assumed_expiry:
                            self._assumed_expiry[uid] = now + self.assume_ttl_s
                    counters.inc("assume.lease_renewed_bound")
            else:
                # the bind never landed anywhere: release the capacity and
                # put the pod back through the queue (deduped by uid, so a
                # pod that somehow also sits in a queue segment is safe;
                # requeue: a retry must never be quota-held)
                self._forget(uid)
                self.queue.add(cur, requeue=True)
                counters.inc("assume.lease_requeued")

    def snapshot_nodes(self):
        """Object-level snapshot (scalar cycles, tests): the surviving
        assumptions are folded INTO the cloned NodeInfos.  One prune
        implementation — this is _snapshot_for_wave plus the per-pod
        fold the wave path replaces with the numeric delta."""
        infos, _delta, leftover = self._snapshot_for_wave()
        if leftover:
            by_name = {ni.name: ni for ni in infos}
            for assumed in leftover:
                ni = by_name.get(assumed.spec.node_name)
                if ni is not None:
                    ni.add_pod(assumed)
        return infos

    def _snapshot_for_wave(self):
        """(node infos, aggregate delta, surviving assumed pods) — the
        scan lanes' snapshot; see ``_snapshot_for_tables`` for the wave
        paths' dirty-tracking variant (this wrapper leaves the cache's
        dirty-set alone, so the wave builder misses nothing)."""
        infos, delta, leftover, _, _ = self._snapshot_for_tables(
            want_dirty=False
        )
        return infos, delta, leftover

    def _snapshot_for_tables(
        self, want_dirty: bool = True, expire_leases: bool = True
    ):
        """(node infos, aggregate delta, surviving assumed pods, dirty,
        epoch) — the wave path's snapshot.  Unlike ``snapshot_nodes`` the
        assume-cache is NOT folded into the NodeInfos pod-by-pod; it
        comes back as a numeric per-node delta (see
        CachedNodeTableBuilder._apply_agg_delta) that the table build
        adds into the aggregate columns.  Same pruning rule: an
        assumption confirmed by the cache or whose pod vanished is
        dropped.  Consumers that need assumed pods as OBJECTS
        (preemption's _merged_infos, the index-less constraint build)
        use the returned list or the live assume-cache — both disjoint
        from the snapshot's pod population by this prune.

        ``want_dirty`` drains the cache's dirty node-set atomically with
        the snapshot (SchedulerCache.snapshot_for_tables) — the builder
        then re-encodes only those aggregate rows; the wave paths are
        single-threaded (loop thread, or the pipeline's build worker),
        so drained sets reach the builder in snapshot order.
        ``expire_leases=False`` skips the lease-expiry store probes —
        the pipeline's build worker must not stall its overlap window on
        store round-trips (the loop thread expires leases per wave)."""
        if expire_leases:
            self._expire_assume_leases()
        if want_dirty:
            infos, cache_assigned, dirty, epoch = (
                self.cache.snapshot_for_tables()
            )
        else:
            infos, cache_assigned = self.cache.snapshot_with_assigned()
            dirty, epoch = DIRTY_UNTRACKED, None
        delta: dict = {}
        with self._assumed_lock:
            if not self._assumed:
                return infos, delta, [], dirty, epoch
            uids = list(self._assumed)
            keys = [self._assumed[u].metadata.key for u in uids]
        # one bulk cache read outside the assume lock (the informer lock is
        # held batch-long by the dispatch thread; nesting the two invites
        # stalls); re-check each uid under the lock after
        currents = self.informer_factory.informer_for("Pod").get_many(keys)
        leftover = []
        with self._assumed_lock:
            for uid, current in zip(uids, currents):
                assumed = self._assumed.get(uid)
                if assumed is None:
                    continue  # forgotten (failed bind) meanwhile
                exists = current is not None and current.metadata.uid == uid
                if uid in cache_assigned or not exists:
                    del self._assumed[uid]
                    self._assumed_agg.pop(uid, None)
                    self._assumed_expiry.pop(uid, None)
                    continue
                agg = self._assumed_agg[uid]
                leftover.append(assumed)
                d = delta.get(assumed.spec.node_name)
                if d is None:
                    delta[assumed.spec.node_name] = d = [0, 0, 0, 0, 0, 0, []]
                d[0] += agg[0]
                d[1] += agg[1]
                d[2] += agg[2]
                d[3] += 1
                d[4] += agg[3]
                d[5] += agg[4]
                if agg[5]:
                    d[6].extend(agg[5])
        return infos, delta, leftover, dirty, epoch

    def error_func(self, qpi: QueuedPodInfo, err, plugin: str = "") -> None:
        # a failed permit/bind releases the assumed capacity
        self._forget(qpi.pod.metadata.uid)
        super().error_func(qpi, err, plugin)

    @property
    def _packed_mode(self) -> bool:
        """Single-program packed waves: tables ride as flat host buffers
        unpacked inside the evaluator's program — WITH or WITHOUT a mesh
        (under one, the unpacked tables get sharding constraints and
        GSPMD partitions the program; parallel/sharding.MeshPackedCaller).
        Off only under record_results (the diagnostics evaluation needs
        device tables).  One definition — prewarm and the live paths must
        never disagree, or the first live wave compiles mid-run (~30s on
        the tunnel)."""
        return self.result_store is None

    def _get_evaluator(self) -> RepairingEvaluator:
        if self._evaluator is None:
            self._evaluator = RepairingEvaluator(
                self.filter_plugins,
                self.pre_score_plugins,
                self.score_plugins,
                weights=self.score_weights,
                # per-pod first-failing-plugin masks for the losers, so
                # event-gated requeue sees the ACTUAL failing plugins, not
                # the whole chain
                with_diagnostics=True,
                mesh=self.mesh,
            )
        return self._evaluator

    def _get_mesh_fallback_evaluator(self) -> RepairingEvaluator:
        """Single-device twin of the mesh evaluator — consumes the same
        packed wave the build stage produced (against the builder's
        default-device static copy), so a sharded failure costs one
        re-dispatch, never a rebuild."""
        if self._mesh_fallback_evaluator is None:
            self._mesh_fallback_evaluator = RepairingEvaluator(
                self.filter_plugins,
                self.pre_score_plugins,
                self.score_plugins,
                weights=self.score_weights,
                with_diagnostics=True,
                mesh=None,
            )
        return self._mesh_fallback_evaluator

    def _eval_packed_wave(
        self, pod_table, node_static, node_agg, extra,
        n_pods: int, n_nodes: int,
    ):
        """One packed repair-wave dispatch with the mesh ladder (ISSUE 7):
        sharded evaluate when a mesh is live, single-device re-dispatch of
        the SAME packed wave on any sharding failure (mirroring the build
        stage's _BuildFallback: this wave degrades, later waves retry the
        mesh), the caller's _evaluate_or_park park as the last rung."""
        ev = self._get_evaluator()
        if self.mesh is None:
            return ev.call_packed(pod_table, node_static, node_agg, extra)
        import jax

        from minisched_tpu.observability import counters

        # pad-waste ledger: rows shipped beyond the live roster/wave —
        # the bench divides by waves to watch mesh-alignment overhead
        counters.inc("wave_mesh.pad_pod_rows", pod_table.capacity - n_pods)
        counters.inc("wave_mesh.pad_node_rows", node_agg.capacity - n_nodes)
        try:
            if self.faults is not None:
                self.faults.check("mesh.evaluate", str(n_pods))
            out = ev.call_packed(pod_table, node_static, node_agg, extra)
            # execution is async — block HERE so a sharded-dispatch
            # failure surfaces inside this handler, not at the caller's
            # device_get past the fallback's chance
            jax.block_until_ready(out[1])
            counters.inc("wave_mesh.waves")
            return out
        except Exception as err:
            import sys as _sys

            counters.inc("wave_mesh.fallbacks")
            print(
                f"[wave-mesh] sharded evaluate failed, single-device "
                f"fallback: {type(err).__name__}: {str(err)[-160:]}",
                file=_sys.stderr,
                flush=True,
            )
            return self._get_mesh_fallback_evaluator().call_packed(
                pod_table,
                self._table_builder.static_dev_default(),
                node_agg,
                extra,
            )

    #: scan chunks pad to power-of-two capacities ≥ this (few executables,
    #: each persistent-cached) and never exceed this many pods per chunk
    #: times 8 — chunking bounds executable size; chunk k+1 re-snapshots so
    #: it sees chunk k's binds (sequential semantics across chunks)
    SCAN_MIN_CAP = 128
    SCAN_MAX_CHUNK = 1024
    #: blocked-lane chunk stride/top tier: per-call overhead on the
    #: tunneled runtime (dispatch + the packed node/constraint transfer,
    #: ~0.6-0.9s) dominates the blocked chunk's device compute, so the
    #: blocked lane takes FEWER, BIGGER calls than the exact lane — with
    #: cross-wave deferral (schedule_wave) a 5k-pod cross-pod burst is
    #: ONE call at this tier; fully-padded trailing blocks skip their
    #: step via lax.cond, so the tier's padding costs ~nothing on device
    BLOCKED_MAX_CHUNK = 8192
    #: small-wave pod capacity: partial and requeue waves (a 2k-pod
    #: backoff replay after a 16k-pod drain) evaluate at this capacity
    #: instead of the full max_wave executable — the (P, N) planes scale
    #: with capacity, so a 2k wave on a 16384-cap program paid ~8× its
    #: share of device time.  Exactly TWO wave shapes ever run (both
    #: prewarmed); engines with max_wave <= this keep one.
    WAVE_SMALL_CAP = 2048

    def _wave_cap(self, n_pods: int) -> int:
        # capacities quantize to the mesh pod-axis multiple too (equal
        # whole tiles per shard); off-mesh this is the plain 128 padding
        full = pad_to(max(self.max_wave, 128), self._pod_cap_mult)
        small = min(pad_to(self.WAVE_SMALL_CAP, self._pod_cap_mult), full)
        return small if n_pods <= small else full
    #: blocked-scan lane (VERDICT r3 item 4): cross-pod pods pre-grouped
    #: into blocks of pairwise-disjoint interaction sets, each block one
    #: kernel step (ops/sequential.blocked_scan_schedule) — within-group
    #: sequential exactness, repair-acceptance safety across groups.
    #: ≤1 disables it (every cross-pod pod rides the exact per-pod scan).
    SCAN_BLOCK_SIZE = 32
    #: blocked rounds before leftover capacity-race losers fall back to
    #: the exact per-pod scan
    SCAN_BLOCK_RETRIES = 3
    #: deferral age bound: flush the cross-pod backlog after this many
    #: consecutive FULL waves even if neither the size threshold nor a
    #: queue drain arrives — a sustained stream of plain waves must not
    #: starve constrained pods indefinitely
    SCAN_DEFER_MAX_WAVES = 8
    #: cap on PostFilter (preemption) passes per wave — each is
    #: O(nodes × pods) host work (see _handle_wave_losers)
    MAX_PREEMPT_PER_WAVE = 256
    #: cap on authoritative-store probes per lease-expiry round (see
    #: _expire_assume_leases) — bounds loop-thread stall after a lost
    #: wave expires many leases at once
    MAX_LEASE_PROBES_PER_ROUND = 64

    @classmethod
    def _scan_cap(cls, n_pods: int) -> int:
        """Exactly TWO chunk capacities (128 for small waves, 1024
        otherwise): every distinct cap is a scan-executable shape, and a
        ~30s tunnel compile inside a wave costs more than masked no-op
        steps ever will.  tests/test_shape_discipline.py pins this."""
        return cls.SCAN_MIN_CAP if n_pods <= cls.SCAN_MIN_CAP else cls.SCAN_MAX_CHUNK

    @classmethod
    def _blocked_cap(cls, n_pods: int) -> int:
        """Blocked-lane capacity tiers: {128, 1024, 8192}.  Same shape
        discipline as _scan_cap, one more tier — the blocked kernel's
        padded blocks skip their whole step via lax.cond, so the big
        tier costs (almost) only its live blocks while amortizing the
        per-call tunnel overhead the lane is bound by."""
        if n_pods <= cls.SCAN_MIN_CAP:
            return cls.SCAN_MIN_CAP
        if n_pods <= cls.SCAN_MAX_CHUNK:
            return cls.SCAN_MAX_CHUNK
        return cls.BLOCKED_MAX_CHUNK

    def prewarm(self, scan: bool = True) -> None:
        """Compile (or cache-load) the wave evaluator executable for the
        shapes this engine will use, before the run loop starts.  The
        full-roster repair graph costs 30-50s to compile (~15s to load
        from the persistent cache over the tunnel); paying that inside the
        first wave stalls the whole first drain.  Called by the service
        when ``prewarm=True`` — between informer sync and run().

        ``scan=False`` skips the sequential/blocked scan-lane warms (the
        biggest share of the wall for cross-pod-capable rosters: two
        schedulers × capacity tiers × schema corners): callers that KNOW
        their workload carries no cross-pod-constrained pods never run
        those lanes, and a workload that surprises them merely pays the
        compile at first use.

        Shapes must match the live waves exactly or the warm executable is
        wasted: pod capacity is the wave capacity (_build_and_evaluate
        pads to max_wave), node capacity is pad_to(current node count).
        A throwaway table builder keeps the real one's static-column cache
        out of it.
        """
        import jax

        from minisched_tpu.api.objects import make_node, make_pod
        from minisched_tpu.framework.nodeinfo import build_node_infos

        # shapes from the (already-synced) informer cache — store.list
        # would deep-clone every Node object just to take len().  The
        # PROFILE capacity must come from the real roster too: a cluster
        # with >64 label/taint signatures would otherwise warm at the
        # synthetic nodes' Dp=64 and recompile on the first live wave.
        from minisched_tpu.models.tables import node_profile_capacity

        live_nodes = self.informer_factory.informer_for("Node").lister()
        # mesh-aligned: the live builder quantizes node capacity to the
        # mesh node-axis multiple; a warm at plain pad_to would compile
        # the wrong shape and be wasted
        node_capacity = self._table_builder.node_capacity(
            max(len(live_nodes), 2)
        )
        prof_capacity = node_profile_capacity(live_nodes)
        # pod capacity quantizes to the mesh pod-axis multiple exactly
        # like the live _wave_cap — a plain pad_to warm would compile the
        # wrong full-tier shape on a non-128-divisor pod axis (e.g. 3)
        pod_capacity = pad_to(max(self.max_wave, 128), self._pod_cap_mult)
        # both wave tiers compile: the full max_wave shape and the small
        # one partial/requeue waves take (identical when max_wave is small)
        wave_caps = sorted({pod_capacity, self._wave_cap(1)})
        nodes = [make_node("warm0"), make_node("warm1")]
        pods = [make_pod("warmpod", requests={"cpu": "1"})]
        # pod tables have TWO packed-transfer schemas per capacity: the
        # vectorized fast path (simple pods; zero columns declared, not
        # shipped) and the full slow path (any pod with tolerations/
        # selector/affinity).  The fast schemas are warmed by the table
        # builds below; warm the SLOW one per capacity the engine uses —
        # the first wave containing a non-simple pod otherwise compiles
        # its splitter mid-run (~10-20s on the tunnel).  force_packed:
        # small-capacity slow tables fall under the packed-path size
        # threshold and would silently warm nothing.
        complex_pod = make_pod(
            "warmsel", requests={"cpu": "1"}, node_selector={"warm": "true"}
        )
        packed_mode = self._packed_mode
        if not packed_mode:
            # the unpacked path ships pod tables through per-capacity
            # splitter executables; packed mode never invokes them
            warm_caps = set(wave_caps)
            if self._has_cross_pod and scan:
                warm_caps |= {self.SCAN_MIN_CAP, self.SCAN_MAX_CHUNK}
                if self.SCAN_BLOCK_SIZE > 1:
                    warm_caps.add(self.BLOCKED_MAX_CHUNK)
            for cap in warm_caps:
                build_pod_table([complex_pod], capacity=cap, force_packed=True)
        infos = build_node_infos(nodes, [])
        if packed_mode:
            # warm the single-program packed entry points for BOTH pod
            # schemas a live wave can take: the fast (simple-pod) schema
            # and the slow one (any pod with selector/affinity/...), each
            # a distinct executable keyed on the packed metas.  The
            # throwaway builder carries the mesh so the warm statics are
            # sharded exactly like the live ones.
            node_static, node_agg, _ = CachedNodeTableBuilder(
                mesh=self.mesh
            ).build_packed(
                infos, capacity=node_capacity, prof_capacity=prof_capacity
            )
            for wave_cap in wave_caps:
                for warm_pods in (pods, pods + [complex_pod]):
                    pt, _ = build_pod_table(
                        warm_pods, capacity=wave_cap, device=False
                    )
                    extra = None
                    if self._needs_extra:
                        extra = build_constraint_tables(
                            warm_pods, nodes, [],
                            pod_capacity=wave_cap,
                            node_capacity=node_capacity,
                            scan_planes=False, device=False,
                        )
                    out = self._get_evaluator().call_packed(
                        pt, node_static, node_agg, extra
                    )
                    jax.block_until_ready(out[1])
        else:
            for wave_cap in wave_caps:
                node_table, _ = CachedNodeTableBuilder().build(
                    infos, capacity=node_capacity, prof_capacity=prof_capacity
                )
                pod_table, _ = build_pod_table(pods, capacity=wave_cap)
                extra = None
                if self._needs_extra:
                    extra = build_constraint_tables(
                        pods, nodes, [],
                        pod_capacity=wave_cap, node_capacity=node_capacity,
                        scan_planes=False,
                    )
                out = self._get_evaluator()(pod_table, node_table, extra)
                jax.block_until_ready(out[1])
        if self._has_cross_pod and scan:
            # cross-pod-constrained pods ride the sequential scan — warm
            # BOTH chunk capacities (_schedule_scan uses exactly these
            # two; a partial chunk compiling the small one mid-run cost
            # ~13s).  Fresh node table: the mesh-mode repair warm above
            # donates its (re-sharded) argument and must not alias this.
            # the blocked lane has one extra (bigger) tier than the exact
            # lane — warm each executable only at the caps it runs
            scan_caps = {self.SCAN_MIN_CAP, self.SCAN_MAX_CHUNK}
            blocked_caps = (
                scan_caps | {self.BLOCKED_MAX_CHUNK}
                if self.SCAN_BLOCK_SIZE > 1
                else set()
            )
            all_caps = sorted(scan_caps | blocked_caps)
            if packed_mode:
                # scan chunks carry cross-pod pods, which are never
                # "simple" — the live schema is the SLOW pod table; warm
                # exactly that packed entry per chunk capacity.  The
                # blocked lane's schema also depends on which
                # SCAN_ELIDE_GROUPS the chunk's workload leaves all-zero:
                # warm its two common corners — a spread-only burst
                # (affinity + volume groups elided) and the kitchen sink
                # (nothing elided); a mixed burst in between compiles
                # once mid-run and persists in the compile cache.
                from minisched_tpu.api.objects import (
                    Affinity,
                    LabelSelector,
                    PodAffinity,
                    PodAffinityTerm,
                    PodAntiAffinity,
                    TopologySpreadConstraint,
                    WeightedPodAffinityTerm,
                )

                def _spread(name):
                    p = make_pod(
                        name, requests={"cpu": "1"}, labels={"app": "warm"}
                    )
                    p.spec.topology_spread_constraints = [
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key="warmzone",
                            when_unsatisfiable="DoNotSchedule",
                            label_selector=LabelSelector(
                                match_labels={"app": "warm"}
                            ),
                        )
                    ]
                    return p

                sink_pod = _spread("warmsink")
                sel = LabelSelector(match_labels={"app": "warm"})
                sink_pod.spec.affinity = Affinity(
                    pod_affinity=PodAffinity(
                        required=[
                            PodAffinityTerm(
                                label_selector=sel, topology_key="warmzone"
                            )
                        ],
                        preferred=[
                            WeightedPodAffinityTerm(
                                weight=1,
                                term=PodAffinityTerm(
                                    label_selector=sel,
                                    topology_key="warmzone",
                                ),
                            )
                        ],
                    ),
                    pod_anti_affinity=PodAntiAffinity(
                        required=[
                            PodAffinityTerm(
                                label_selector=LabelSelector(
                                    match_labels={"app": "other"}
                                ),
                                topology_key="warmzone",
                            )
                        ]
                    ),
                )
                sink_pod.spec.volumes = ["warmclaim"]
                blocked_sets = ([_spread("warmspread")], [sink_pod])
                for cap in all_caps:
                    if cap in scan_caps:
                        scan_pods, _ = build_pod_table(
                            pods + [complex_pod], capacity=cap, device=False
                        )
                        scan_extra = build_constraint_tables(
                            pods + [complex_pod], nodes, [],
                            pod_capacity=cap,
                            node_capacity=node_capacity,
                            scan_planes=True, device=False,
                            elide_zeros=False,
                        )
                        _, choice, _ = self._get_scan_scheduler().call_packed(
                            scan_pods, node_static, node_agg, scan_extra
                        )
                        jax.block_until_ready(choice)
                    if cap in blocked_caps:
                        for warm_set in blocked_sets:
                            bp, _ = build_pod_table(
                                warm_set, capacity=cap, device=False
                            )
                            bx = build_constraint_tables(
                                warm_set, nodes, [],
                                pod_capacity=cap,
                                node_capacity=node_capacity,
                                scan_planes=True, device=False,
                                elide_zeros=False,
                                elide_groups=SCAN_ELIDE_GROUPS,
                            )
                            _, bc, _, _ = (
                                self._get_blocked_scheduler().call_packed(
                                    bp, node_static, node_agg, bx
                                )
                            )
                            jax.block_until_ready(bc)
                return
            node_table, _ = CachedNodeTableBuilder().build(
                infos, capacity=node_capacity, prof_capacity=prof_capacity
            )
            for cap in all_caps:
                scan_pods, _ = build_pod_table(pods, capacity=cap)
                scan_extra = build_constraint_tables(
                    pods, nodes, [],
                    pod_capacity=cap,
                    node_capacity=node_capacity,
                    scan_planes=True,
                )
                if cap in scan_caps:
                    _, choice, _ = self._get_scan_scheduler()(
                        scan_pods, node_table, scan_extra
                    )
                    jax.block_until_ready(choice)
                if cap in blocked_caps:
                    _, bc, _, _ = self._get_blocked_scheduler()(
                        scan_pods, node_table, scan_extra
                    )
                    jax.block_until_ready(bc)

    def _get_scan_scheduler(self):
        if self._scan_scheduler is None:
            from minisched_tpu.ops.sequential import SequentialScheduler

            self._scan_scheduler = SequentialScheduler(
                self.filter_plugins,
                self.pre_score_plugins,
                self.score_plugins,
                weights=self.score_weights,
                mesh=self.mesh,
            )
        return self._scan_scheduler

    def _get_blocked_scheduler(self):
        if self._blocked_scheduler is None:
            from minisched_tpu.ops.sequential import BlockedSequentialScheduler

            self._blocked_scheduler = BlockedSequentialScheduler(
                self.filter_plugins,
                self.pre_score_plugins,
                self.score_plugins,
                weights=self.score_weights,
                block_size=self.SCAN_BLOCK_SIZE,
                mesh=self.mesh,
            )
        return self._blocked_scheduler

    def _evaluate_or_park(self, qpis: List[QueuedPodInfo], build_fn):
        """The shared park-on-failure scaffold around a device evaluation:
        a ValueError means some pod exceeds a static table capacity — drop
        the offenders (parked individually) and retry once; any other
        failure requeues the whole batch via error_func.  Returns
        (surviving qpis, build_fn result or None)."""
        try:
            return qpis, build_fn(qpis)
        except ValueError:
            qpis = self._drop_unencodable(qpis)
            if not qpis:
                return qpis, None
            try:
                return qpis, build_fn(qpis)
            except Exception as err:
                for qpi in qpis:  # never lose a popped wave: requeue all
                    self.error_func(qpi, err)
                return qpis, None
        except Exception as err:
            import os as _os
            if _os.environ.get("MINISCHED_DEBUG_HEAL"):
                import traceback as _tb
                print("[wave] parked batch on:", type(err).__name__,
                      str(err)[-220:], flush=True)
                _tb.print_exc()
            for qpi in qpis:
                self.error_func(qpi, err)
            return qpis, None

    def _schedule_scan(
        self,
        qpis: List[QueuedPodInfo],
        node_infos: List[Any],
        agg_delta: Any = None,
        assumed_pods: Any = (),
    ) -> None:
        """The cross-pod lane: blocked scan for throughput (disjoint
        interaction groups per kernel step), exact per-pod scan for the
        remainder and as the configured fallback."""
        if (
            self.SCAN_BLOCK_SIZE > 1
            and len(qpis) > self.SCAN_BLOCK_SIZE
        ):
            self._schedule_scan_blocked(
                qpis, node_infos, agg_delta, assumed_pods
            )
            return
        self._schedule_scan_exact(qpis, node_infos, agg_delta, assumed_pods)

    def _schedule_scan_blocked(
        self,
        qpis: List[QueuedPodInfo],
        node_infos: List[Any],
        agg_delta: Any,
        assumed_pods: Any,
    ) -> None:
        """Blocked lane: group → order → chunked blocked-kernel calls;
        feasible pods that lose a same-node capacity race retry in later
        rounds (re-grouped against fresh state); leftovers after
        SCAN_BLOCK_RETRIES ride the exact per-pod scan — a sequential
        order never fails them, so neither may this lane."""
        from minisched_tpu.engine.scan_groups import (
            interaction_sets,
            order_into_blocks,
        )

        # wave-style dispatch gating (see _bind_batch): the previous
        # wave's thousands of bind events drain inside this lane's
        # GIL-free device calls, not against its host builds — ungated,
        # the grouping/build stretches ran ~10× slower under dispatch
        # GIL pressure.  Snapshots stay correct while gated: the
        # assume-cache folds not-yet-dispatched binds as numeric deltas.
        self.informer_factory.pause_dispatch()
        B = self.SCAN_BLOCK_SIZE
        pending = qpis
        fresh = (node_infos, agg_delta, assumed_pods)
        try:
            for _attempt in range(self.SCAN_BLOCK_RETRIES):
                with self.metrics.timed("scan_grouping"):
                    sets = interaction_sets([q.pod for q in pending])
                    blocks = order_into_blocks(pending, sets, B)
                    flat = [m for blk in blocks for m in blk]
                retry: List[QueuedPodInfo] = []
                for start in range(0, len(flat), self.BLOCKED_MAX_CHUNK):
                    if fresh is None:
                        fresh = self._snapshot_for_wave()
                    part = flat[start : start + self.BLOCKED_MAX_CHUNK]
                    retry += self._run_blocked_chunk(part, *fresh)
                    fresh = None
                if not retry:
                    return
                pending = retry
        finally:
            # a raise anywhere above must not leave the dispatch gate
            # closed for good (events would stall until the next bind);
            # resume is idempotent, the success paths share this exit
            self.informer_factory.resume_dispatch()
        if pending:
            # capacity-race stragglers: the exact lane finishes them
            self._schedule_scan_exact(pending, *self._snapshot_for_wave())

    def _run_blocked_chunk(
        self,
        part: List[Optional[QueuedPodInfo]],
        node_infos: List[Any],
        agg_delta: Any,
        assumed_pods: Any,
    ) -> List[QueuedPodInfo]:
        """One blocked-kernel call over ``part`` (None = block padding).
        Commits winners, parks infeasible pods, returns the capacity-race
        retries."""
        import jax

        from minisched_tpu.api.objects import make_pod

        nodes = [ni.node for ni in node_infos]
        assigned = (
            ()
            if self.constraint_index is not None
            else [p for ni in node_infos for p in ni.pods]
            + list(assumed_pods)
        )
        dummy = make_pod("scan-pad")
        cap = self._blocked_cap(len(part))

        def build_and_scan(part_live):
            # the padded layout, restricted to the currently-live qpis —
            # _evaluate_or_park may retry after dropping unencodable pods,
            # and the dropped ones must leave the table too
            live_ids = {id(m) for m in part_live}
            cur = [
                m if (m is not None and id(m) in live_ids) else None
                for m in part
            ]
            pad_rows = [i for i, m in enumerate(cur) if m is None]
            pods_ = [m.pod if m is not None else dummy for m in cur]
            gang_view = self._gang_view(pods_)
            packed_mode = self._packed_mode
            if packed_mode:
                with self.metrics.timed("scan_build"):
                    with self.metrics.timed("scan_build_nodes"):
                        node_static, node_agg, node_names = (
                            self._table_builder.build_packed(
                                node_infos, agg_delta=agg_delta
                            )
                        )
                    with self.metrics.timed("scan_build_pods"):
                        pod_table, _ = build_pod_table(
                            pods_, capacity=cap, device=False,
                            invalid_rows=pad_rows, gang_view=gang_view,
                        )
                    with self.metrics.timed("scan_build_constraints"):
                        extra = self._build_constraints(
                            pods_, nodes, assigned,
                            pod_capacity=cap,
                            node_capacity=node_agg.capacity,
                            scan_planes=True,
                            device=False,
                            # per-capacity schema discipline: full elision
                            # made every STATE-driven zero-set flip (combo
                            # counts appearing mid-run) a fresh executable
                            # compile/load on the tunnel — but the
                            # WORKLOAD-driven groups (affinity terms, pod
                            # volumes, spread slots) elide as units, so a
                            # spread-only burst's program folds the other
                            # lanes entirely (~2× per-step)
                            elide_zeros=False,
                            elide_groups=SCAN_ELIDE_GROUPS,
                        )
                # gate opens for the device call: held event batches
                # drain against GIL-free device compute
                self.informer_factory.resume_dispatch()
                with self.metrics.timed("scan_evaluate"):
                    _, choice, _, accepted = (
                        self._get_blocked_scheduler().call_packed(
                            pod_table, node_static, node_agg, extra
                        )
                    )
                    choice, accepted = jax.device_get((choice, accepted))
            else:
                with self.metrics.timed("scan_build"):
                    node_table, node_names = self._table_builder.build(
                        node_infos, agg_delta=agg_delta
                    )
                    pod_table, _ = build_pod_table(
                        pods_, capacity=cap, invalid_rows=pad_rows,
                        gang_view=gang_view,
                    )
                    extra = self._build_constraints(
                        pods_, nodes, assigned,
                        pod_capacity=cap,
                        node_capacity=node_table.capacity,
                        scan_planes=True,
                    )
                self.informer_factory.resume_dispatch()
                with self.metrics.timed("scan_evaluate"):
                    _, choice, _, accepted = self._get_blocked_scheduler()(
                        pod_table, node_table, extra
                    )
                    choice, accepted = jax.device_get((choice, accepted))
            return node_names, choice.tolist(), accepted.tolist()

        live = [m for m in part if m is not None]
        live, result = self._evaluate_or_park(live, build_and_scan)
        if result is None:
            return []
        node_names, choice, accepted = result
        live_set = {id(m) for m in live}

        winners: List[Any] = []
        losers: List[Any] = []
        retry: List[QueuedPodInfo] = []
        for i, qpi in enumerate(part):
            if qpi is None or id(qpi) not in live_set:
                continue
            c = choice[i]
            if c >= 0 and accepted[i]:
                self._assume(qpi.pod, node_names[c])
                winners.append((qpi, qpi.pod, node_names[c]))
            elif c >= 0:
                retry.append(qpi)  # feasible; lost a same-node race
            else:
                losers.append((qpi, qpi.pod, set()))
        self._commit_winners(winners)
        # keep the next chunk's grouping/build gated: _bind_batch closes
        # the gate when it runs, but a chunk whose winners all parked in
        # permit-wait (or that had none) never reaches it — re-close
        # explicitly (idempotent Event) so victims' DELETE events from
        # the loser handling below drain in the next device call too
        self.informer_factory.pause_dispatch()
        if losers:
            self._handle_wave_losers(losers, node_infos, len(nodes))
        return retry

    def _schedule_scan_exact(
        self,
        qpis: List[QueuedPodInfo],
        node_infos: List[Any],
        agg_delta: Any = None,
        assumed_pods: Any = (),
    ) -> None:
        """Bind-exact path for cross-pod-constrained pods: chunks of the
        sequential device scan, committed chunk by chunk."""
        import jax

        # the scan interleaves host builds with device chunks too finely
        # for wave-style dispatch gating to pay — run it ungated
        self.informer_factory.resume_dispatch()
        chunk = self.SCAN_MAX_CHUNK
        for start in range(0, len(qpis), chunk):
            part = qpis[start : start + chunk]
            if start > 0:
                node_infos, agg_delta, assumed_pods = self._snapshot_for_wave()
            nodes = [ni.node for ni in node_infos]
            assigned = (
                ()
                if self.constraint_index is not None
                else [p for ni in node_infos for p in ni.pods]
                + list(assumed_pods)
            )
            cap = self._scan_cap(len(part))

            def build_and_scan(part_):
                pods_ = [qpi.pod for qpi in part_]
                gang_view = self._gang_view(pods_)
                packed_mode = self._packed_mode
                if packed_mode:
                    # single-program chunk: flat host buffers unpacked
                    # inside the scan executable (see _build_and_evaluate)
                    with self.metrics.timed("scan_build"):
                        node_static, node_agg, node_names = (
                            self._table_builder.build_packed(
                                node_infos, agg_delta=agg_delta
                            )
                        )
                        pod_table, _ = build_pod_table(
                            pods_, capacity=cap, device=False,
                            gang_view=gang_view,
                        )
                        extra = self._build_constraints(
                            pods_, nodes, assigned,
                            pod_capacity=cap,
                            node_capacity=node_agg.capacity,
                            scan_planes=True,  # the scan's commits need it
                            device=False,
                            elide_zeros=False,  # one packed schema per cap
                        )
                    with self.metrics.timed("scan_evaluate"):
                        _, choice, _ = self._get_scan_scheduler().call_packed(
                            pod_table, node_static, node_agg, extra
                        )
                        choice = jax.device_get(choice)
                    return node_names, choice.tolist()[: len(pods_)]
                with self.metrics.timed("scan_build"):
                    node_table, node_names = self._table_builder.build(
                        node_infos, agg_delta=agg_delta
                    )
                    pod_table, _ = build_pod_table(
                        pods_, capacity=cap, gang_view=gang_view
                    )
                    extra = self._build_constraints(
                        pods_, nodes, assigned,
                        pod_capacity=cap,
                        node_capacity=node_table.capacity,
                        scan_planes=True,  # the scan's commits need it
                    )
                if self.result_store is not None:
                    # scan pods get the same per-plugin artifact as wave
                    # pods (diagnostics against the pre-decision snapshot)
                    self._record_wave(
                        pods_, pod_table, node_table, node_names, extra
                    )
                with self.metrics.timed("scan_evaluate"):
                    _, choice, _ = self._get_scan_scheduler()(
                        pod_table, node_table, extra
                    )
                    choice = jax.device_get(choice)
                return node_names, choice.tolist()[: len(pods_)]

            part, result = self._evaluate_or_park(part, build_and_scan)
            if result is None:
                continue
            node_names, placements = result

            losers: List[Any] = []
            winners: List[Any] = []
            for qpi, c in zip(part, placements):
                if c < 0:
                    # no per-plugin masks from the scan: fall back to the
                    # whole chain so event-gated requeue can't strand
                    losers.append((qpi, qpi.pod, set()))
                    continue
                self._assume(qpi.pod, node_names[c])
                winners.append((qpi, qpi.pod, node_names[c]))
            self._commit_winners(winners)
            # _bind_batch re-closed the gate; this lane stays ungated (the
            # next chunk's re-snapshot needs the bind events applied)
            self.informer_factory.resume_dispatch()
            if losers:
                self._handle_wave_losers(losers, node_infos, len(nodes))

    # -- GC discipline ------------------------------------------------------
    # At 100k-pod scale the process holds ~10⁶ tracked Python objects
    # (pods, containers, label dicts, caches); CPython's automatic
    # collections rescan them on allocation-heavy phases and cost more
    # than the phases themselves (a 16k-pod batch bind: 130ms of work,
    # ~340ms of GC).  The wave loop therefore freezes the stable heap,
    # turns the automatic collector off, and collects explicitly at wave
    # boundaries — young-gen every wave (bounds cyclic garbage), full
    # periodically (bounds promoted-cycle leaks in long-running services).
    FULL_GC_EVERY_WAVES = 64

    def stop(self) -> None:
        super().stop()
        # profiling: the trace exports on loop exit (~10-30s for a full
        # run) — the base stop()'s 2s join would let process exit kill
        # the daemon thread mid-write and truncate the trace
        if _os.environ.get("MINISCHED_JAX_PROFILE") and self._thread is not None:
            self._thread.join(timeout=120.0)

    def _loop(self) -> None:
        import gc

        from minisched_tpu.observability.profiling import device_trace

        gc.collect()
        gc.freeze()
        was_enabled = gc.isenabled()
        gc.disable()
        self._waves_since_full_gc = 0
        try:
            # MINISCHED_JAX_PROFILE=<dir>: JAX profiler trace of the whole
            # run loop (device kernels + host gaps) for TensorBoard/xprof
            with device_trace(_os.environ.get("MINISCHED_JAX_PROFILE")):
                super()._loop()
        finally:
            if was_enabled:
                gc.enable()
            gc.unfreeze()
            # a stop with constrained pods still deferred must not drop
            # them silently (advisor r4): park them through error_func so
            # the queue reflects their Pending state.  This runs ON the
            # loop thread — the backlog's owner — so it cannot race a
            # wave that would re-populate it (stop()'s 2s join can time
            # out mid-wave and a park from there could be overwritten).
            backlog, self._scan_backlog = self._scan_backlog, []
            if backlog:
                try:
                    self._park_scan_failures(
                        backlog,
                        RuntimeError("scheduler stopped with deferred pods"),
                    )
                except Exception:
                    pass  # shutdown path: queue/informers may be gone
            # pipelined shutdown: the build worker may hold popped waves
            # (in the handoff queue or mid-build) — park them through
            # error_func so the queue reflects their Pending state, same
            # contract as the backlog drain above.  Runs ON the loop
            # thread after the worker joined, so nothing races it.
            pipe = self._pipeline
            if pipe is not None:
                try:
                    pipe.stop()
                    for qpi in pipe.drain():
                        try:
                            self.error_func(
                                qpi,
                                RuntimeError(
                                    "scheduler stopped with pipelined "
                                    "wave pending"
                                ),
                            )
                        except Exception:
                            pass  # shutdown path: queue may be closed
                except Exception:
                    pass

    def _wave_gc(self) -> None:
        import gc

        if gc.isenabled():
            return  # not running under the loop's GC discipline
        self._waves_since_full_gc = getattr(self, "_waves_since_full_gc", 0) + 1
        if self._waves_since_full_gc >= self.FULL_GC_EVERY_WAVES:
            self._waves_since_full_gc = 0
            gc.collect()
        else:
            gc.collect(0)

    # the loop: one wave per iteration instead of one pod ------------------
    def _pipeline_active(self) -> bool:
        """Pipelined waves in packed mode — single-device AND mesh (the
        mesh-packed program consumes the same host-built flat buffers, so
        depth-1 overlap, incremental dirty-row encoding, and commit-time
        re-arbitration survive unchanged; ISSUE 7 tentpole).  Only
        record_results keeps the serial loop (it needs device tables).
        Latched once the worker exists (it owns queue popping from then
        on)."""
        if self._pipeline is not None:
            return True
        return self.pipeline_enabled and self.result_store is None

    def schedule_one(self, timeout: Optional[float] = 0.5) -> bool:
        if self._pipeline_active():
            return self._schedule_one_pipelined(timeout)
        return self._schedule_one_serial(timeout)

    def _schedule_one_pipelined(self, timeout: Optional[float]) -> bool:
        """One loop-thread turn of the two-stage pipeline: take the next
        item off the bounded handoff queue (the build worker pops,
        snapshots, and builds tables concurrently with this thread's
        device waits), evaluate it on device, re-arbitrate, commit.
        Handoff wait lands in ``loop_pop`` (the accounting identity
        pop+wave+scan_flush+gc ≈ loop wall must keep summing) and — when
        the item is a wave — in ``wave_pipeline_stall``: time the device
        sat idle because the next build wasn't ready.  A fully-serial
        regression shows stall ≈ build; `make bench-wave` gates on it."""
        from minisched_tpu.observability import counters

        pipe = self._pipeline
        if pipe is None:
            from minisched_tpu.engine.pipeline import WavePipeline

            pipe = self._pipeline = WavePipeline(self)
            pipe.start()
        t0 = time.monotonic()
        # the worker emits an item at least once per pop window, so this
        # wait is bounded by (pop timeout + one build) — block past the
        # caller's timeout rather than spuriously reporting idle mid-build
        item = pipe.get(timeout=max(timeout or 0.5, 1.0) + 1.0)
        wait = time.monotonic() - t0
        self.metrics.observe("loop_pop", wait)
        prev_was_wave = getattr(self, "_pipe_prev_wave", False)
        self._pipe_prev_wave = item is not None and item[0] == "wave"
        if item is None or item[0] == "empty":
            if self._scan_backlog:
                # queue drained with constrained pods still deferred:
                # flush the lane now (same as the serial idle path)
                try:
                    with self.metrics.timed("scan_flush"):
                        self._flush_scan_backlog()
                finally:
                    with self.metrics.timed("loop_gc"):
                        self._wave_gc()
                return True
            self.informer_factory.resume_dispatch()
            self._expire_assume_leases()
            with self.metrics.timed("loop_gc"):
                self._wave_gc()
            return False
        partial = True
        try:
            if item[0] == "raw":
                # build-stage fallback (encode overflow, empty roster,
                # priority bypass, injected build fault): the serial wave
                # path owns every one of those cases already
                _tag, qpis, partial = item
                self.schedule_wave(qpis)
            else:
                prepared = item[1]
                partial = prepared.partial
                if prev_was_wave:
                    # stall = device idle because the NEXT build wasn't
                    # ready while the pipeline was hot.  A wave starting
                    # from idle always waits its whole build (nothing to
                    # overlap with) — counting it would read cold starts
                    # as regressions, so only back-to-back waves count.
                    self.metrics.observe("wave_pipeline_stall", wait)
                counters.inc("wave_pipeline.waves")
                if prepared.constrained:
                    self._scan_backlog.extend(prepared.constrained)
                # priority-inversion bypass, re-checked HERE: the worker
                # peeked the backlog at build time, but the overlapped
                # previous wave (this very iteration's predecessor) may
                # have deferred a higher-priority constrained pod after
                # that peek.  Flushing first restores the order the queue
                # popped them in — the prepared wave then re-arbitrates
                # against whatever the flush committed.
                if self._scan_backlog and prepared.qpis:
                    hi = max(
                        q.pod.spec.priority for q in self._scan_backlog
                    )
                    if hi > min(
                        q.pod.spec.priority for q in prepared.qpis
                    ):
                        with self.metrics.timed("scan_flush"):
                            self._flush_scan_backlog()
                self._run_prepared_wave(prepared)
            if self._scan_backlog:
                self._scan_backlog_waves += 1
                if (
                    partial
                    or len(self._scan_backlog) >= self.BLOCKED_MAX_CHUNK
                    or self._scan_backlog_waves >= self.SCAN_DEFER_MAX_WAVES
                ):
                    with self.metrics.timed("scan_flush"):
                        self._flush_scan_backlog()
        finally:
            with self.metrics.timed("loop_gc"):
                self._wave_gc()
        return True

    def _run_prepared_wave(self, prepared: Any) -> None:
        # same metric contract as schedule_wave: every exit observes
        t_wave = time.monotonic()
        self.metrics.observe("wave_size", float(len(prepared.qpis)))
        try:
            self._run_prepared_wave_inner(prepared)
        finally:
            self.metrics.observe("wave", time.monotonic() - t_wave)

    def _run_prepared_wave_inner(self, prepared: Any) -> None:
        """Device-evaluate a wave the worker built, then re-arbitrate its
        winners against state the OVERLAPPED previous wave committed
        after the build's snapshot, and commit through the unchanged
        permit/bind tail (AlreadyBound / Conflict / OutOfCapacity still
        backstop at the store)."""
        import jax

        from minisched_tpu.observability import counters, trace

        qpis = prepared.qpis
        # the worker skips lease expiry (store probes would stall its
        # overlap window); the loop thread keeps the serial cadence
        self._expire_assume_leases()
        counters.inc("wave_pipeline.dirty_rows", prepared.dirty_rows)
        if prepared.build_skipped:
            # idle-wave gate fired: this wave reused the previous tables
            # wholesale (zero node-table build work; ISSUE 8)
            counters.inc("wave_pipeline.zero_build_waves")
        self._wave_seq += 1
        wave_id = self._wave_seq
        trace.span(
            "wave_build", wave=wave_id, size=len(qpis),
            build_s=round(prepared.build_s, 6),
            skipped=prepared.build_skipped or None,
            dirty_rows=prepared.dirty_rows or None,
            mesh=self._mesh_shards,
        )
        # gate opens for the device call: the previous wave's held bind
        # events drain against GIL-free device compute — and the build
        # worker gets the GIL for wave N+2's host stretch in this window
        self.informer_factory.resume_dispatch()
        try:
            with self.metrics.timed("wave_evaluate"):
                with self.metrics.timed("wave_device"):
                    _, choice, _, unsched = self._eval_packed_wave(
                        prepared.pod_table,
                        prepared.node_static,
                        prepared.node_agg,
                        prepared.extra,
                        len(qpis),
                        len(prepared.node_infos),
                    )
                    choice, unsched = jax.device_get((choice, unsched))
                with self.metrics.timed("wave_postfetch"):
                    unsched = unsched.tolist()
                    plugin_names = [p.name() for p in self.filter_plugins]
                    fail_sets = [
                        {
                            name
                            for k, name in enumerate(plugin_names)
                            if unsched[k][i]
                        }
                        for i in range(len(qpis))
                    ]
                    placements = choice.tolist()[: len(qpis)]
        except Exception as err:
            # tables were already built, so no encode retry applies here
            # — park the batch exactly like the serial exception path
            trace.span(
                "wave_park", wave=wave_id, size=len(qpis),
                cause=type(err).__name__, error=str(err)[:200],
            )
            trace.flight_dump("wave-park")
            for qpi in qpis:
                self.error_func(qpi, err)
            return
        trace.span("wave_evaluate", wave=wave_id, size=len(qpis),
                   mesh=self._mesh_shards)
        node_names = prepared.node_names
        losers: List[Any] = []
        winners: List[Any] = []
        with self.metrics.timed("wave_winners"):
            for qpi, c, fails in zip(qpis, placements, fail_sets):
                if c < 0:
                    losers.append((qpi, qpi.pod, fails))
                else:
                    winners.append((qpi, qpi.pod, node_names[c]))
            winners, rejected = self._rearbitrate_winners(winners)
            for _qpi, pod, node_name in winners:
                self._assume(pod, node_name)
            for _qpi, pod, _node in rejected:
                # capacity the overlapped wave committed while this one
                # was on device: the pod is feasible, it just raced —
                # straight back through the active queue so the next
                # wave's FRESH snapshot re-places it (requeue: never
                # quota-held behind its tenant's newer arrivals)
                trace.span_pod(
                    "rearb_requeue", pod, wave=wave_id,
                    cause="capacity_raced",
                )
                self.queue.add(pod, requeue=True)
        self._commit_winners(winners)
        if losers:
            self._handle_wave_losers(
                losers, prepared.node_infos, len(prepared.node_infos)
            )

    def _rearbitrate_winners(self, winners: List[Any]):
        """(kept, rejected) — validate each pipelined winner against the
        CURRENT capacity view (live cache NodeInfos + assume-cache, with
        double-count protection for assumptions whose bind events already
        landed), debiting locally so this wave's own winners arbitrate
        among themselves on the refreshed base.  Only chains that filter
        on capacity re-arbitrate (see _rearb_capacity); a node absent
        from the cache passes through — the bind transaction's commit-
        time validation is the final arbiter either way."""
        if not winners or not self._rearb_capacity:
            return winners, []
        from minisched_tpu.api.objects import MIB

        free, counted = self.cache.capacity_view(
            {node_name for _, _, node_name in winners}
        )
        with self._assumed_lock:
            for uid, assumed in self._assumed.items():
                node = assumed.spec.node_name
                b = free.get(node)
                if b is None or uid in counted.get(node, ()):
                    continue
                agg = self._assumed_agg[uid]
                b[0] -= agg[0]
                b[1] -= agg[1]
                b[2] -= agg[2]
                b[3] -= 1
        keep: List[Any] = []
        reject: List[Any] = []
        for win in winners:
            _qpi, pod, node_name = win
            b = free.get(node_name)
            if b is None:
                keep.append(win)
                continue
            req = pod.resource_requests()
            mem = req.memory // MIB
            eph = req.ephemeral_storage // MIB
            if (
                req.milli_cpu <= b[0]
                and mem <= b[1]
                and eph <= b[2]
                and b[3] >= 1
            ):
                b[0] -= req.milli_cpu
                b[1] -= mem
                b[2] -= eph
                b[3] -= 1
                keep.append(win)
            else:
                reject.append(win)
        if reject:
            from minisched_tpu.observability import counters

            # gang atomicity: a gang is released or kept WHOLE.  A member
            # rejected here means the overlapped wave took capacity the
            # build assumed free — keeping its siblings would admit a
            # partial gang that parks at Permit burning its TTL for a
            # member that cannot come.  Moving keepers to reject only
            # FREES locally-debited capacity, so earlier keep decisions
            # stay conservative-valid.
            from minisched_tpu.api.objects import gang_key

            hit = {gang_key(pod) for _q, pod, _n in reject}
            hit.discard(None)
            if hit:
                moved = [w for w in keep if gang_key(w[1]) in hit]
                if moved:
                    keep = [w for w in keep if gang_key(w[1]) not in hit]
                    reject = reject + moved
                    counters.inc("gang.rearb_atomic_release", len(moved))
            counters.inc("wave_pipeline.rearb_requeued", len(reject))
        return keep, reject

    def _schedule_one_serial(self, timeout: Optional[float] = 0.5) -> bool:
        # loop_pop/loop_gc/scan_flush: together with "wave" these account
        # for the engine thread's whole wall — the e2e budget must sum
        # (VERDICT r4: ~1.5s of 9.5s was invisible to the breakdown)
        with self.metrics.timed("loop_pop"):
            qpis = self.queue.pop_batch(self.max_wave, timeout=timeout)
        if not qpis:
            if self._scan_backlog:
                # queue drained with constrained pods still deferred:
                # flush the lane now (the backlog, not the queue, holds
                # the remaining work)
                try:
                    with self.metrics.timed("scan_flush"):
                        self._flush_scan_backlog()
                finally:
                    with self.metrics.timed("loop_gc"):
                        self._wave_gc()
                return True
            # idle: the gate a bind may have closed (see _bind_batch) must
            # not delay the events that will wake us; and with the
            # automatic collector off, idle churn (informer handlers,
            # exception cycles) still needs a periodic sweep.  Assume
            # leases must expire HERE too — with the queue drained, no
            # wave snapshot is coming to notice a lost bind's leak.
            self.informer_factory.resume_dispatch()
            self._expire_assume_leases()
            with self.metrics.timed("loop_gc"):
                self._wave_gc()
            return False
        partial = len(qpis) < self.max_wave
        try:
            self.schedule_wave(qpis)
            # a partial pop means the queue is (momentarily) drained —
            # don't sit on deferred constrained pods waiting for a burst
            # that may never come; the wave-count bound keeps a sustained
            # stream of full plain waves from starving them indefinitely
            if self._scan_backlog:
                self._scan_backlog_waves += 1
                if (
                    partial
                    or len(self._scan_backlog) >= self.BLOCKED_MAX_CHUNK
                    or self._scan_backlog_waves >= self.SCAN_DEFER_MAX_WAVES
                ):
                    with self.metrics.timed("scan_flush"):
                        self._flush_scan_backlog()
        finally:
            # every exit path (incl. scan-only waves and early returns)
            # collects; schedule_wave's own call was only on the main path
            with self.metrics.timed("loop_gc"):
                self._wave_gc()
        return True

    def _flush_scan_backlog(self) -> None:
        """Run the deferred cross-pod lane over everything accumulated.
        Snapshots fresh state — the backlog outlives the wave snapshots
        it was deferred from."""
        backlog, self._scan_backlog = self._scan_backlog, []
        self._scan_backlog_waves = 0
        # the deferral window is minutes, not milliseconds: a pod can be
        # DELETED, RECREATED, or UPDATED while parked here, and the
        # queue's own update/delete handling can no longer reach it (it
        # was popped).  Re-validate every entry: drop the gone and the
        # renamed-uid recreations (the informer ADD already enqueued the
        # new incarnation), refresh the spec of the changed.
        live_backlog: List[QueuedPodInfo] = []
        for qpi, cur in self._revalidate_backlog(backlog):
            if (
                cur.metadata.resource_version
                != qpi.pod.metadata.resource_version
            ):
                qpi.pod_info.pod = cur
            live_backlog.append(qpi)
        if not live_backlog:
            return
        try:
            node_infos, agg_delta, assumed_pods = self._snapshot_for_wave()
            if not node_infos:
                for qpi in live_backlog:
                    self.error_func(qpi, FitError(qpi.pod, 0, Diagnosis()))
                return
            self._schedule_scan(
                live_backlog, node_infos, agg_delta, assumed_pods
            )
        except Exception as err:
            # advisor r4: the run loop's catch-all would swallow this and
            # the (already-swapped-out) backlog pods would sit Pending
            # until an unrelated event — the wave path parks its batch
            # via error_func on exception, this lane must too
            self._park_scan_failures(live_backlog, err)

    def _revalidate_backlog(self, qpis: List[QueuedPodInfo]):
        """The shared liveness rule for backlog entries: (qpi, current
        pod) pairs for those still present, same-uid, and unbound — one
        informer lock hold (get_many; no per-pod store round-trips in
        front of the single device call the deferral amortizes).  Flush
        schedules the survivors; park error_funcs them."""
        pod_inf = self.informer_factory.informer_for("Pod")
        keys = [
            f"{q.pod.metadata.namespace}/{q.pod.metadata.name}" for q in qpis
        ]
        out = []
        for qpi, cur in zip(qpis, pod_inf.get_many(keys)):
            if cur is None:
                continue  # deleted while deferred
            if cur.metadata.uid != qpi.pod.metadata.uid:
                continue  # recreated under the same name: not this entry
            if cur.spec.node_name:
                continue  # bound elsewhere while deferred
            out.append((qpi, cur))
        return out

    def _park_scan_failures(self, qpis: List[QueuedPodInfo], err) -> None:
        """Route the still-unplaced pods of a failed scan through
        error_func → unschedulableQ.  Pods the lane already committed
        before the raise (assumed and/or bound — chunks commit as they
        go) are skipped: error_func would forget a live assumption and
        requeue a pod that was in fact placed.  The assume snapshot is
        taken BEFORE the informer read: a pod leaves _assumed only after
        the informer reflects its bind, so this order can't miss a
        commit that confirms between the two reads (the reverse could).

        An assumption alone does NOT prove commitment: the batch bind can
        raise AFTER the assume (transport failure on a remote store) —
        for assumed-but-informer-unbound pods the AUTHORITATIVE store
        decides.  Bound there: a real commit whose event just hasn't
        dispatched — skip.  Unbound there: the bind never landed — park
        (error_func also forgets the assumption, releasing the capacity
        that would otherwise stay double-booked for the process life).
        Store UNREACHABLE: keep the assumption (the bind may be real) but
        re-defer the qpi instead of dropping it — a later flush retries
        the park decision; dropping it here left the pod Pending forever
        while its assumption double-booked the node (advisor r5)."""
        with self._assumed_lock:
            assumed = set(self._assumed)
        for qpi, cur_cache in self._revalidate_backlog(qpis):
            if qpi.pod.metadata.uid in assumed:
                try:
                    cur = self.client.pods().get(
                        qpi.pod.metadata.name, qpi.pod.metadata.namespace
                    )
                except KeyError:
                    continue  # deleted meanwhile: nothing to requeue
                except Exception:
                    self._scan_backlog.append(qpi)
                    continue
                if cur.spec.node_name:
                    continue  # committed by an earlier chunk
            # mirror _flush_scan_backlog: a pod updated while deferred must
            # be requeued with its REFRESHED spec — the update event
            # already fired and can't reach this popped copy (advisor r5)
            if (
                cur_cache.metadata.resource_version
                != qpi.pod.metadata.resource_version
            ):
                qpi.pod_info.pod = cur_cache
            self.error_func(qpi, err)

    def schedule_wave(self, qpis: List[QueuedPodInfo]) -> None:
        # the 'wave' metric must observe EVERY exit path (empty-node
        # return, parked batch, scan-only wave, a raise) — the bench's
        # e2e accounting asserts pop+wave+scan_flush+gc sums to the loop
        # wall, and an invisible exit breaks the invariant (advisor r5)
        t_wave = time.monotonic()
        self._wave_seq += 1
        from minisched_tpu.observability import trace

        trace.span(
            "wave_build", wave=self._wave_seq, size=len(qpis),
            serial=True, mesh=self._mesh_shards,
        )
        self.metrics.observe("wave_size", float(len(qpis)))
        try:
            self._schedule_wave_inner(qpis, t_wave)
        finally:
            self.metrics.observe("wave", time.monotonic() - t_wave)

    def _schedule_wave_inner(
        self, qpis: List[QueuedPodInfo], t_wave: float
    ) -> None:

        # cross-pod-constrained pods run on device via the sequential scan
        # (they see each other's commits in the carried combo planes —
        # bind-exact semantics the repair wave cannot give them).  They are
        # DEFERRED rather than run per wave: the lane's cost on the
        # tunneled runtime is per-call (packed transfer + dispatch), so
        # constrained pods accumulate in pop order across waves and the
        # lane runs once per ~BLOCKED_MAX_CHUNK — or when the queue drains
        # (schedule_one).  The global order is thus [plain…×k, constrained…]
        # — per-group FIFO (the exactness contract) is untouched, and the
        # lane's acceptance/audit guarantees don't depend on WHEN it runs.
        # A chain WITHOUT cross-pod plugins never evaluates the constraints
        # at all (reference semantics with the plugin disabled) — no scan.
        # The split runs BEFORE the snapshot: the priority bypass below
        # may flush (and commit) the backlog, which a snapshot already in
        # hand would not see — capacity double-booking.
        if self._has_cross_pod:
            constrained = [qpi for qpi in qpis if _is_cross_pod(qpi.pod)]
            if constrained:
                self._scan_backlog.extend(constrained)
                plain = [qpi for qpi in qpis if not _is_cross_pod(qpi.pod)]
                if not plain:
                    return  # schedule_wave's finally observes the metric
                qpis = plain
            # priority-inversion bypass (advisor r4): deferral reorders
            # constrained pods behind up to SCAN_DEFER_MAX_WAVES full
            # waves of later-arriving plain pods.  Near capacity a plain
            # wave could consume resources that priority/FIFO pop order
            # had given an earlier, HIGHER-priority constrained pod — so
            # when any deferred pod outranks any plain pod about to run,
            # the backlog flushes first (restoring the order the queue
            # popped them in).  Same-priority workloads (the common case)
            # never trigger this and keep the amortized single-call lane.
            # The max is derived at the read site — the backlog is
            # bounded by ~BLOCKED_MAX_CHUNK, and cached state would need
            # resets at every site that mutates the backlog.
            if self._scan_backlog:
                hi = max(q.pod.spec.priority for q in self._scan_backlog)
                if hi > min(q.pod.spec.priority for q in qpis):
                    self._flush_scan_backlog()

        with self.metrics.timed("wave_snapshot"):
            if self._pipeline is not None:
                # raw-fallback wave while the pipeline runs: the build
                # worker is the single ordered consumer of the cache's
                # dirty-set — draining it here too would interleave two
                # snapshot orders into one aggregate base (stale-row
                # overwrites).  Untracked builds never touch the base;
                # the accumulated dirt stays pending for the worker.
                node_infos, agg_delta, assumed_pods = (
                    self._snapshot_for_wave()
                )
                dirty, epoch = DIRTY_UNTRACKED, None
            else:
                node_infos, agg_delta, assumed_pods, dirty, epoch = (
                    self._snapshot_for_tables()
                )
        if not node_infos:
            for qpi in qpis:
                self.error_func(qpi, FitError(qpi.pod, 0, Diagnosis()))
            return

        with self.metrics.timed("wave_assigned_list"):
            nodes = [ni.node for ni in node_infos]  # name-sorted by snapshot
            # with a live index the build never walks the population; the
            # index-less build must see the assumed pods explicitly now
            # that the snapshot no longer folds them into NodeInfos
            assigned = (
                ()
                if self.constraint_index is not None
                else [p for ni in node_infos for p in ni.pods] + assumed_pods
            )

        def build_and_evaluate(qpis_):
            with self.metrics.timed("wave_evaluate"):
                return self._build_and_evaluate(
                    qpis_, node_infos, nodes, assigned, agg_delta, dirty,
                    epoch,
                )

        qpis, result = self._evaluate_or_park(qpis, build_and_evaluate)
        if result is None:
            return
        node_names, placements, fail_sets = result
        pods = [qpi.pod for qpi in qpis]

        losers: List[Any] = []
        winners: List[Any] = []
        with self.metrics.timed("wave_winners"):
            for qpi, pod, c, fails in zip(qpis, pods, placements, fail_sets):
                if c < 0:
                    losers.append((qpi, pod, fails))
                    continue
                self._assume(pod, node_names[c])
                winners.append((qpi, pod, node_names[c]))
        self._commit_winners(winners)
        if losers:
            self._handle_wave_losers(losers, node_infos, len(nodes))
        dur = time.monotonic() - t_wave
        if _WAVE_LOG:
            import sys

            print(
                f"[wave t={time.monotonic():.2f}] size={len(qpis)} "
                f"dur={dur:.2f}s winners={len(winners)} losers={len(losers)}",
                file=sys.stderr,
                flush=True,
            )

    def _build_and_evaluate(
        self, qpis_, node_infos, nodes, assigned, agg_delta=None,
        dirty=DIRTY_UNTRACKED, epoch=None,
    ):
        """One repair-wave evaluation: tables → fused repair evaluator →
        (node_names, placements, per-pod failing-plugin sets).

        Single-device waves take the PACKED path: tables stay host-side as
        flat buffers and the evaluator unpacks them inside its one jitted
        program — separate per-table splitter programs alternating with
        the evaluator stalled ~1.4s per wave on the tunneled runtime
        (program-switch cost).  Mesh mode and record_results (which needs
        device tables for the diagnostics evaluation) keep the unpacked
        path."""
        import jax

        pods_ = [qpi.pod for qpi in qpis_]
        packed_mode = self._packed_mode
        pod_capacity = self._wave_cap(len(pods_))
        gang_view = self._gang_view(pods_)
        with self.metrics.timed("wave_build_tables"):
            if packed_mode:
                node_static, node_agg, node_names = (
                    self._table_builder.build_packed(
                        node_infos, agg_delta=agg_delta, dirty=dirty,
                        epoch=epoch,
                    )
                )
                node_capacity = node_agg.capacity
                pod_table, _ = build_pod_table(
                    pods_, capacity=pod_capacity, device=False,
                    gang_view=gang_view,
                )
            else:
                node_table, node_names = self._table_builder.build(
                    node_infos, agg_delta=agg_delta, dirty=dirty,
                    epoch=epoch,
                )
                node_capacity = node_table.capacity
                pod_table, _ = build_pod_table(
                    pods_, capacity=pod_capacity, gang_view=gang_view
                )
        extra = None
        if self._needs_extra:
            with self.metrics.timed("wave_build_constraints"):
                extra = self._build_constraints(
                    pods_, nodes, assigned,
                    pod_capacity=pod_capacity,
                    node_capacity=node_capacity,
                    scan_planes=False,  # wave mode never runs the scan
                    device=not packed_mode,
                )
        if self.result_store is not None:
            self._record_wave(pods_, pod_table, node_table, node_names, extra)
        # the device call releases the GIL for the whole evaluation —
        # let the event handlers for the previous wave's binds run there
        self.informer_factory.resume_dispatch()
        with self.metrics.timed("wave_device"):
            if packed_mode:
                _, choice, _, unsched = self._eval_packed_wave(
                    pod_table, node_static, node_agg, extra,
                    len(pods_), len(node_infos),
                )
            else:
                _, choice, _, unsched = self._get_evaluator()(
                    pod_table, node_table, extra
                )
            # ONE host fetch for both results (each device_get is a tunnel
            # round-trip); bool[K, P] → per-pod failing-plugin sets
            choice, unsched = jax.device_get((choice, unsched))
        with self.metrics.timed("wave_postfetch"):
            unsched = unsched.tolist()
            plugin_names = [p.name() for p in self.filter_plugins]
            fail_sets = [
                {name for k, name in enumerate(plugin_names) if unsched[k][i]}
                for i in range(len(pods_))
            ]
            return node_names, choice.tolist()[: len(pods_)], fail_sets

    def _handle_wave_losers(
        self, losers: List[Any], node_infos: List[Any], n_nodes: int
    ) -> None:
        """Park every wave loser, then run the host-side PostFilter chain
        (preemption) for each preemption-ELIGIBLE one — like the scalar
        engine's failure path.

        Parking happens FIRST so victims' Pod/DELETE requeue events find
        the losers in the unschedulableQ.  Losers whose recorded failures
        are all node-static (NodeAffinity & co — eviction can't flip them,
        ``preemption_might_help``) skip the chain outright: a wave can park
        thousands of such pods and each PostFilter pass walks the whole
        snapshot.  Each eligible loser preempts against a snapshot adjusted
        for the wave: this wave's assumed winners, the victims earlier
        losers already evicted, and earlier losers' nominated pods (which
        will consume the capacity they freed) — otherwise several losers
        select the same victims and over-evict.
        """
        self.metrics.observe("wave_losers", float(len(losers)))
        with self.metrics.timed("losers_handle"):
            self._handle_wave_losers_inner(losers, node_infos, n_nodes)

    def _handle_wave_losers_inner(
        self, losers: List[Any], node_infos: List[Any], n_nodes: int
    ) -> None:
        from minisched_tpu.plugins.defaultpreemption import preemption_might_help

        diagnoses = {}
        for qpi, pod, fails in losers:
            diagnosis = Diagnosis()
            # the fused evaluator's per-plugin masks name the actual
            # first-failing plugin(s) per pod (minisched.go:118-121,134
            # semantics); an empty set (e.g. empty-chain configs) falls
            # back to the whole chain so event-gated requeue can't strand
            diagnosis.unschedulable_plugins = set(fails) or {
                p.name() for p in self.filter_plugins
            }
            diagnoses[pod.metadata.uid] = diagnosis
            self.error_func(qpi, FitError(pod, n_nodes, diagnosis))
            if self.on_decision:
                self.on_decision(
                    pod, None, Status.unschedulable("no feasible node")
                )
        if not self.post_filter_plugins:
            return
        eligible = [
            (qpi, pod)
            for qpi, pod, _fails in losers
            if preemption_might_help(diagnoses[pod.metadata.uid])
        ]
        if not eligible:
            return
        # victim-availability gate: preemption can only evict pods with
        # priority BELOW the loser's, so a loser at or under the cluster's
        # lowest assigned priority has zero possible victims — running
        # DefaultPreemption for it would walk every node's pod list for
        # nothing.  A replay wave can strand thousands of equal-priority
        # losers at once (config5: ~2k losers × 10k nodes × ~10 pods each
        # ground the engine for minutes finding no victims); the floor
        # check skips the whole pass in O(assigned).
        prio_floor = None
        for ni in node_infos:
            for p in ni.pods:
                if prio_floor is None or p.spec.priority < prio_floor:
                    prio_floor = p.spec.priority
        with self._assumed_lock:
            for a in self._assumed.values():
                if prio_floor is None or a.spec.priority < prio_floor:
                    prio_floor = a.spec.priority
        eligible = [
            (qpi, pod)
            for qpi, pod in eligible
            if prio_floor is not None and pod.spec.priority > prio_floor
        ]
        if not eligible:
            return
        # ONE full merged snapshot (informer state + this wave's assumed
        # winners); per-loser deltas (evictions, phantoms) are applied
        # incrementally to just the touched NodeInfos
        self.metrics.observe("wave_preempt_eligible", float(len(eligible)))
        base = self._merged_infos(node_infos)
        by_name = {ni.name: ni for ni in base}
        # a wave processes at most MAX_PREEMPT_PER_WAVE losers through the
        # PostFilter chain (each pass is O(nodes × pods) host work; upstream
        # runs preemption once per scheduling cycle, so its throughput is
        # naturally bounded — an 8k-pod wave's losers are not).  Budget
        # goes to the HIGHEST-priority losers (stable within a class), so
        # truncation can never starve a high-priority pod behind a crowd
        # of lower ones; the skipped rest are already parked and retry.
        if len(eligible) > self.MAX_PREEMPT_PER_WAVE:
            eligible = sorted(
                eligible, key=lambda e: -e[1].spec.priority
            )[: self.MAX_PREEMPT_PER_WAVE]
        for qpi, pod in eligible:
            nominated = self.run_post_filter(
                CycleState(), pod, base, diagnoses[pod.metadata.uid]
            )
            # victims reported by the plugins (DefaultPreemption records
            # them) — diffing full store listings per loser would clone
            # the whole pod population each time
            for pl in self.post_filter_plugins:
                # consume-on-read: run_post_filter short-circuits on the
                # first Success, so a plugin NOT invoked for this loser
                # must not replay victims recorded for an earlier one
                victims = getattr(pl, "last_victims", ())
                if victims:
                    pl.last_victims = []
                for victim in victims:
                    ni = by_name.get(victim.spec.node_name)
                    if ni is not None:
                        ni.remove_pod(victim)
            if nominated:
                # the phantom consumes the freed capacity so later losers
                # can't select the same victims and over-evict
                ph = pod.clone()
                ph.spec.node_name = nominated
                target = by_name.get(nominated)
                if target is not None:
                    target.add_pod(ph)

    def _merged_infos(self, node_infos: List[Any]) -> List[Any]:
        """Clone of the wave snapshot with the assume-cache folded in —
        the preemption base: capacity this wave's winners just took must
        not be offered to victims' replacements."""
        known = {
            p.metadata.uid for ni in node_infos for p in ni.pods
        }
        with self._assumed_lock:
            assumed = [
                a for a in self._assumed.values() if a.metadata.uid not in known
            ]
        merged = [ni.clone() for ni in node_infos]
        by_name = {ni.name: ni for ni in merged}
        for a in assumed:
            ni = by_name.get(a.spec.node_name)
            if ni is not None:
                ni.add_pod(a)
        return merged

    def _drop_unencodable(self, qpis: List[QueuedPodInfo]) -> List[QueuedPodInfo]:
        """Park pods whose specs exceed the static table capacities (they
        can never be device-scheduled; the scalar engine could still take
        them).  Each offender goes through error_func with its encode
        error; the rest of the wave proceeds."""
        good: List[QueuedPodInfo] = []
        for qpi in qpis:
            try:
                build_pod_table([qpi.pod], capacity=128)
                if self._needs_extra:  # only caps the wave actually encodes
                    build_constraint_tables([qpi.pod], [], [], pod_capacity=128,
                                            node_capacity=128,
                                            scan_planes=False)
            except ValueError as err:
                self.error_func(qpi, err)
                if self.on_decision:
                    self.on_decision(qpi.pod, None, Status.from_error(err))
                continue
            good.append(qpi)
        return good

    def _record_wave(
        self, pods_, pod_table, node_table, node_names, extra
    ) -> None:
        """record_results support for the wave path: one diagnostics-
        enabled fused evaluation of the wave against the pre-wave snapshot
        (the decision basis), ingested via ``Store.record_batch_result`` —
        the wave emits the same per-plugin artifact the scalar recorders
        produce (SURVEY §2 row 10): same annotation keys, same canonical
        rejection strings — flushed onto pod annotations by the store's
        update hook when the binds land."""
        from minisched_tpu.ops.fused import FusedEvaluator
        from minisched_tpu.plugins.registry import canonical_filter_reasons

        if self._diag_evaluator is None:
            self._diag_evaluator = FusedEvaluator(
                self.filter_plugins,
                self.pre_score_plugins,
                self.score_plugins,
                weights=self.score_weights,
                with_diagnostics=True,
            )
        try:
            result = self._diag_evaluator(pod_table, node_table, extra)
        except Exception:
            import traceback

            traceback.print_exc()
            return

        def unwrap(pl) -> str:
            return getattr(pl, "original_name", None) or pl.name()

        self.result_store.record_batch_result(
            result,
            [p.metadata.key for p in pods_],
            node_names,
            [unwrap(pl) for pl in self.filter_plugins],
            [unwrap(pl) for pl in self.score_plugins],
            reasons=canonical_filter_reasons(),
        )

    def _commit_winners(self, winners: List[Any]) -> None:
        """Host-side tail of the wave for every placed pod: reserve →
        permit per pod (host plugin chains, minisched.go:89-112), then ONE
        batched bind transaction for all immediately-bindable pods — a
        wave commits thousands of placements and a store round-trip per
        bind dominated the e2e profile.  Pods a permit plugin parked in
        Wait still get a detached binding cycle (the wait can be seconds).

        ``winners``: (qpi, pod, node_name) triples, already assumed.
        """
        with self.metrics.timed("commit"):
            self._commit_winners_inner(winners)

    def _commit_winners_inner(self, winners: List[Any]) -> None:
        from minisched_tpu.framework.types import CycleState

        ready: List[Any] = []
        if not self.reserve_plugins and not self.permit_plugins:
            # both chains empty (the default full roster): nothing to run
            # per pod — go straight to the batched bind.  One shared
            # CycleState is safe: it is only consulted by unreserve on a
            # failed bind, and there is nothing to unreserve.
            state = CycleState()
            ready = [(qpi, pod, node_name, state) for qpi, pod, node_name in winners]
            winners = []
        for qpi, pod, node_name in winners:
            state = CycleState()
            status = self.run_reserve_plugins(state, pod, node_name)
            if not status.is_success():
                self.error_func(qpi, status.as_error(), plugin=status.plugin)
                if self.on_decision:
                    self.on_decision(pod, None, status)
                continue
            with self.metrics.timed("permit"):
                status = self.run_permit_plugins(state, pod, node_name)
            if not status.is_success() and not status.is_wait():
                self.run_unreserve_plugins(state, pod, node_name)
                self.error_func(qpi, status.as_error(), plugin=status.plugin)
                if self.on_decision:
                    self.on_decision(pod, None, status)
                continue
            if status.is_wait():
                from minisched_tpu.observability import trace

                trace.span_pod(
                    "permit_wait", pod, wave=self._wave_seq,
                    node=node_name, plugin=status.plugin,
                )
                t = threading.Thread(
                    target=self._binding_cycle,
                    args=(qpi, pod, node_name, state),
                    name=f"bind-{pod.metadata.name}",
                    daemon=True,
                )
                with self._bind_lock:
                    self._bind_threads.add(t)
                t.start()
                continue
            ready.append((qpi, pod, node_name, state))
        if not ready:
            return
        # the batch bind runs ON the engine thread: a worker-thread
        # pipeline was tried and regressed ~40% — the bind is pure-Python
        # host work, so overlapping it with the next wave's (also
        # Python) snapshot/build just thrashes the GIL.  The informer
        # dispatch of its events naturally overlaps the next wave's
        # GIL-free device call instead.
        self._bind_batch(ready)

    def _bind_batch(self, ready: List[Any]) -> None:
        from minisched_tpu.api.objects import Binding

        # expected_rv: the optimistic-concurrency precondition — bind only
        # if the pod is STILL at the version this wave evaluated (a spec
        # changed under us must re-evaluate, not land on stale
        # requirements).  The unset-node_name guard remains the wire-level
        # double-bind backstop; a Conflict comes back per-item and rides
        # the normal error_func → requeue path, where the refreshed pod
        # re-enters a later wave.
        bindings = [
            Binding(
                pod.metadata.name, pod.metadata.namespace, node_name,
                expected_rv=pod.metadata.resource_version or None,
            )
            for _, pod, node_name, _ in ready
        ]
        # close the dispatch gate BEFORE the events fan out: the informer
        # threads then hold this wave's thousands of bind events through
        # the next wave's host stretch (pop/snapshot/build) and process
        # them inside its GIL-free device call — _build_and_evaluate
        # reopens the gate, schedule_one reopens it when the queue idles.
        # The handler work is identical either way (the assume-cache
        # carries placements until the events land); only WHEN it contends
        # for the GIL changes.
        self.informer_factory.pause_dispatch()
        with self.metrics.timed("bind"):
            try:
                if self.faults is not None:
                    self.faults.check("engine.bind", str(len(ready)))
                # return_objects=False: the engine only inspects failures —
                # cloning 8k bound pods back to a caller that drops them
                # was a third of the bind's copy cost
                results = self.client.pods().bind_many(
                    bindings, return_objects=False
                )
            except Exception as err:
                # the TRANSACTION failed (store unreachable after the
                # remote client's own retries, WAL refusal, injected
                # fault) — before this catch the raise escaped through
                # schedule_one to the loop's catch-all and the whole
                # wave's winners were stranded: popped, assumed, in no
                # queue.  Fail every item instead: error_func forgets the
                # assumption and requeues; if the commit actually landed
                # server-side (response lost), the retried pod's next
                # bind returns AlreadyBound and the informer's bind event
                # settles it — converges either way, and the assume-lease
                # TTL backstops anything this path itself loses.
                from minisched_tpu.controlplane.store import StorageDegraded
                from minisched_tpu.observability import counters

                counters.inc("engine.bind_batch_failed")
                results = [err] * len(ready)
        # the binds changed cluster state NOW; the informer events land on
        # the dispatch thread later.  Record the move request so losers
        # whose attempts overlapped the commit re-queue through backoff
        # instead of parking past the event (the event-to-park race).
        from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK

        self.queue.note_move_request(ClusterEvent(GVK.POD, ActionType.UPDATE))
        from minisched_tpu.observability import trace

        degraded_dumped = False
        for (qpi, pod, node_name, state), res in zip(ready, results):
            if isinstance(res, BaseException):
                from minisched_tpu.controlplane.store import StorageDegraded

                trace.span_pod(
                    "bind_failed", pod, wave=self._wave_seq,
                    node=node_name, cause=type(res).__name__,
                )
                if isinstance(res, StorageDegraded):
                    # the control plane's DISK gave out (ENOSPC/EIO, or
                    # HTTP 507 outlasting the remote client's backoff):
                    # the wave PARKS instead of crashing — error_func
                    # below forgets the assumption (releasing the
                    # capacity) and requeues, so the pod retries once
                    # the store's recovery probe re-arms appends
                    from minisched_tpu.observability import counters

                    counters.inc("storage.degraded_parks")
                    if not degraded_dumped:
                        degraded_dumped = True
                        trace.flight_dump("storage-degraded-park")
                self.run_unreserve_plugins(state, pod, node_name)
                if self._is_bind_race(res) and self._bind_race_refresh(qpi):
                    # bound by a peer / deleted while in-flight: drop
                    # instead of requeue (see Scheduler._bind_race_refresh
                    # — a re-parked stale copy would conflict forever),
                    # releasing the assumed capacity
                    self._forget(pod.metadata.uid)
                    if self.on_decision:
                        self.on_decision(pod, None, Status.from_error(res))
                    continue
                self.error_func(qpi, res)
                if self.on_decision:
                    self.on_decision(pod, None, Status.from_error(res))
            else:
                trace.span_pod(
                    "bind", pod, wave=self._wave_seq, node=node_name,
                )
                self.queue.observe_bind(pod, node_name)
                if self.on_decision:
                    self.on_decision(pod, node_name, Status.success())


def new_device_scheduler(
    client: Any,
    informer_factory: Any,
    cfg: Any = None,
    max_wave: int = 1024,
    mesh: Any = None,
) -> DeviceScheduler:
    """Build a DeviceScheduler from a SchedulerConfig (default: the full
    roster) — the device-mode analog of service.build_scheduler_from_config.
    ``mesh``: evaluate waves sharded over a jax.sharding.Mesh; None defers
    to the config's ``mesh_devices`` pin, then the MINISCHED_MESH startup
    policy (auto-shard when >1 device; see parallel/sharding.resolve_mesh)."""
    from minisched_tpu.plugins.registry import build_plugins
    from minisched_tpu.service.config import default_full_roster_config

    cfg = cfg or default_full_roster_config()
    if mesh is None and (cfg.mesh_devices or cfg.mesh_pod_shards):
        from minisched_tpu.parallel.sharding import make_mesh

        mesh = make_mesh(
            cfg.mesh_devices or None, pod_shards=cfg.mesh_pod_shards
        )
    chains = build_plugins(cfg)
    sched = DeviceScheduler(
        client,
        informer_factory,
        filter_plugins=chains.filter,
        post_filter_plugins=chains.post_filter,
        pre_score_plugins=chains.pre_score,
        score_plugins=chains.score,
        permit_plugins=chains.permit,
        reserve_plugins=chains.reserve,
        score_weights=cfg.score_weights(),
        queue_opts=cfg.queue_opts,
        max_wave=max_wave,
        mesh=mesh,
    )
    from minisched_tpu.service.service import _inject

    for p in chains.needs_handle:
        _inject(p, "h", sched)
    for p in chains.needs_client:
        _inject(p, "store_client", client)
    return sched
