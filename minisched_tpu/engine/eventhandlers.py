"""Informer → queue event wiring.

Re-creates ``minisched/eventhandler.go:14-77``: unassigned pods feed the
active queue; node (and other GVK) events trigger event-gated requeue of
unschedulable pods.  Where the reference leaves most GVK handlers commented
out (eventhandler.go:66-73) and pod update/delete unimplemented, this wires
the full set the upstream scheduler uses for the kinds our control plane
serves (Pod, Node, PV, PVC).
"""

from __future__ import annotations

from typing import Any, Dict

from minisched_tpu.controlplane.informer import (
    ResourceEventHandlers,
    SharedInformerFactory,
)
from minisched_tpu.framework.events import ActionType, ClusterEvent, GVK


def assigned(pod: Any) -> bool:
    """eventhandler.go:80-82."""
    return bool(pod.spec.node_name)


def node_update_action_type(old: Any, new: Any) -> ActionType:
    """Diff old/new node into the specific UPDATE_NODE_* flags (upstream
    computes these so event gating stays precise)."""
    action = ActionType(0)
    if old is None:
        return ActionType.UPDATE
    if old.status.allocatable != new.status.allocatable:
        action |= ActionType.UPDATE_NODE_ALLOCATABLE
    if old.metadata.labels != new.metadata.labels:
        action |= ActionType.UPDATE_NODE_LABEL
    if old.spec.taints != new.spec.taints or old.spec.unschedulable != new.spec.unschedulable:
        # spec.unschedulable is surfaced as a taint upstream
        action |= ActionType.UPDATE_NODE_TAINT
    return action or ActionType.UPDATE


def add_all_event_handlers(
    sched: Any,
    informer_factory: SharedInformerFactory,
    gvk_actions: Dict[GVK, ActionType],
) -> None:
    """eventhandler.go:14-77, driven by the unioned GVK→ActionType map from
    plugin registrations (initialize.go:169-179)."""
    # --- pods: the scheduling workload itself (always wired) -----------
    from minisched_tpu.controlplane.store import EventType

    pod_informer = informer_factory.informer_for("Pod")

    def unassigned_batch(events):
        """Pending pods feed the queue — gated on the engine's shard
        filter (``sched.admits``: always-true single-engine, the HA
        membership's rendezvous map otherwise).  ADD floods (cluster
        creation replays every pending pod) take the one-lock batch path;
        a MODIFIED that leaves the engine's schedulable population —
        bound (possibly by a PEER engine in an HA plane) or re-sharded
        away — is dropped via the batched ``delete_many`` (one lock +
        set-intersect for the whole batch: in a single-engine plane every
        bind event of a wave lands here, and a per-event queue scan would
        be O(events × queue))."""
        adds = [
            ev.obj
            for ev in events
            if ev.type == EventType.ADDED
            and not assigned(ev.obj)
            and sched.admits(ev.obj)
        ]
        if adds:
            sched.queue.add_batch(adds)
        drops = []
        for ev in events:
            try:
                if ev.type == EventType.ADDED:
                    continue
                if ev.type == EventType.MODIFIED:
                    if assigned(ev.obj) or not sched.admits(ev.obj):
                        drops.append(ev.obj)
                    else:
                        sched.queue.update(ev.old_obj, ev.obj)
                elif not assigned(ev.obj):
                    sched.queue.delete(ev.obj)
            except Exception:  # one bad event must not drop the rest
                import traceback

                traceback.print_exc()
        if drops:
            try:
                sched.queue.delete_many(drops)
            except Exception:
                import traceback

                traceback.print_exc()

    pod_informer.add_event_handlers(
        ResourceEventHandlers(on_batch=unassigned_batch)
    )

    # assigned pods may unblock pods waiting on inter-pod constraints;
    # their DELETION frees capacity (it is how preemption victims make
    # room), so it replays pods whose failed plugins registered Pod/DELETE.
    # move_all_to_active_or_backoff is pod-independent — one call per
    # action type present covers the whole batch (a wave's 8k binds used
    # to cost 8k queue-lock round-trips finding the same empty candidates)
    def assigned_batch(events):
        types = {ev.type for ev in events if assigned(ev.obj)}
        if EventType.ADDED in types:
            sched.queue.move_all_to_active_or_backoff(
                ClusterEvent(GVK.POD, ActionType.ADD)
            )
        if EventType.MODIFIED in types:
            sched.queue.move_all_to_active_or_backoff(
                ClusterEvent(GVK.POD, ActionType.UPDATE)
            )
        if EventType.DELETED in types:
            sched.queue.move_all_to_active_or_backoff(
                ClusterEvent(GVK.POD, ActionType.DELETE)
            )

    pod_informer.add_event_handlers(
        ResourceEventHandlers(on_batch=assigned_batch)
    )

    # --- other GVKs, gated on what plugins registered -------------------
    def requeue(event: ClusterEvent):
        return lambda *_args: sched.queue.move_all_to_active_or_backoff(event)

    for gvk, actions in gvk_actions.items():
        if gvk in (GVK.POD, GVK.WILDCARD):
            continue
        kind = gvk.value.split("/")[-1]
        handlers = ResourceEventHandlers()
        if actions & ActionType.ADD:
            handlers.on_add = requeue(ClusterEvent(gvk, ActionType.ADD))
        if actions & ActionType.UPDATE:
            if gvk == GVK.NODE:

                def on_node_update(old: Any, new: Any, _gvk=gvk) -> None:
                    action = node_update_action_type(old, new)
                    sched.queue.move_all_to_active_or_backoff(
                        ClusterEvent(_gvk, action)
                    )

                handlers.on_update = on_node_update
            else:
                handlers.on_update = lambda old, new, _g=gvk: sched.queue.move_all_to_active_or_backoff(
                    ClusterEvent(_g, ActionType.UPDATE)
                )
        if actions & ActionType.DELETE:
            handlers.on_delete = requeue(ClusterEvent(gvk, ActionType.DELETE))
        informer_factory.informer_for(kind).add_event_handlers(handlers)
