"""Permit "Wait" machinery: pods parked until allowed, rejected, or timed out.

Re-creates ``minisched/waitingpod/waitingpod.go``: a waiting pod holds one
pending entry per permit plugin that returned Wait, each with its own
timeout timer (waitingpod.go:42-49); ``Allow`` by the *last* pending plugin
releases the pod (waitingpod.go:80-99), any ``Reject`` or timer fire fails
it (waitingpod.go:102-115).  The Go buffered-channel signal becomes a
set-once status guarded by a condition variable — same semantics
(late Allow/Reject after resolution is a no-op, matching the non-blocking
channel send at waitingpod.go:93-98,109-114).

Design fix over the reference: the reference's permit plugin can fire
``Allow`` *before* the scheduler registers the WaitingPod (nodenumber.go:112
arms its timer inside ``Permit``, registration happens after it returns,
minisched.go:228-233) — a zero-delay allow is silently lost and the pod
times out.  Here the engine registers the WaitingPod *before* invoking
permit plugins, pending entries are added as each plugin returns Wait, and
an ``allow``/``reject`` arriving before its ``add_pending`` is buffered
(``_pre_allowed``) so nothing is lost.  ``seal()`` marks the end of the
permit phase; resolution to Success requires the pod to be sealed with no
pending plugins.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Protocol, Set

from minisched_tpu.framework.types import Status


class Handle(Protocol):
    """Plugin-facing accessor (waitingpod.go:14-17), implemented by the
    engine's get_waiting_pod (minisched/minisched.go:300-302)."""

    def get_waiting_pod(self, uid: str) -> Optional["WaitingPod"]: ...


class WaitingPod:
    def __init__(self, pod: Any, plugin_timeouts: Optional[Dict[str, float]] = None):
        self.pod = pod
        self._cond = threading.Condition()
        self._pending: Dict[str, threading.Timer] = {}
        self._pre_allowed: Set[str] = set()
        self._sealed = False
        self._result: Optional[Status] = None
        for name, timeout_s in (plugin_timeouts or {}).items():
            self.add_pending(name, timeout_s)
        if plugin_timeouts is not None:
            self.seal()

    def add_pending(self, plugin_name: str, timeout_s: float) -> None:
        """Arm a pending entry + timeout timer for one permit plugin
        (waitingpod.go:42-49)."""
        with self._cond:
            if self._result is not None:
                return
            if plugin_name in self._pre_allowed:
                self._pre_allowed.discard(plugin_name)
                self._maybe_resolve_locked()
                return
            t = threading.Timer(
                timeout_s,
                self.reject,
                args=(plugin_name, f"timed out waiting on permit plugin {plugin_name}"),
            )
            t.daemon = True
            self._pending[plugin_name] = t
            t.start()

    def seal(self) -> None:
        """All permit plugins have been consulted; Success becomes possible."""
        with self._cond:
            self._sealed = True
            self._maybe_resolve_locked()

    def pending_plugins(self) -> list:
        with self._cond:
            return list(self._pending)

    def get_signal(self, timeout: Optional[float] = None) -> Status:
        """Block until resolution (waitingpod.go:61-63)."""
        with self._cond:
            if self._result is None:
                self._cond.wait(timeout)
            if self._result is None:
                return Status.error("waiting pod signal wait timed out")
            return self._result

    def allow(self, plugin_name: str) -> None:
        """waitingpod.go:80-99: drop the plugin's pending entry; when the
        last one clears (and the permit phase is sealed), resolve Success.
        An allow arriving before the entry exists is buffered."""
        with self._cond:
            if self._result is not None:
                return
            timer = self._pending.pop(plugin_name, None)
            if timer is not None:
                timer.cancel()
            else:
                self._pre_allowed.add(plugin_name)
            self._maybe_resolve_locked()

    def reject(self, plugin_name: str, msg: str) -> None:
        """waitingpod.go:102-115: any reject resolves Unschedulable."""
        with self._cond:
            for t in self._pending.values():
                t.cancel()
            self._pending.clear()
            if self._result is not None:
                return
            self._result = Status.unschedulable(
                f"pod {self.pod.metadata.name} rejected while waiting on permit: {msg}"
            ).with_plugin(plugin_name)
            self._cond.notify_all()

    def _maybe_resolve_locked(self) -> None:
        if self._sealed and not self._pending and self._result is None:
            self._result = Status.success()
            self._cond.notify_all()
