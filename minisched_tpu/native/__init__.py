"""ctypes bindings for the native host-table kernels (native/tablebuilder.cc).

Loads ``libminisched_native.so`` from this package directory; if absent,
compiles it on first import with g++ (cached thereafter).  Every entry
point has a NumPy fallback (``HAVE_NATIVE`` False) so the package works
without a toolchain — the fallbacks are the same code the slow path always
used, just batched.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libminisched_native.so")
_SRC = os.path.join(
    os.path.dirname(os.path.dirname(_HERE)), "native", "tablebuilder.cc"
)

HAVE_NATIVE = False
_lib: Optional[ctypes.CDLL] = None


def _try_build() -> bool:
    if not os.path.exists(_SRC):
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> None:
    global _lib, HAVE_NATIVE
    if not os.path.exists(_SO) and not _try_build():
        return
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return
    c_char_p = ctypes.c_char_p
    i64_p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32_p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    u32_p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    for name, out_t in (
        ("fnv1a32_batch", i32_p),
        ("name_suffix_batch", i32_p),
        ("pod_seed_batch", u32_p),
    ):
        fn = getattr(lib, name)
        fn.argtypes = [c_char_p, i64_p, ctypes.c_int64, out_t]
        fn.restype = None
    _lib = lib
    HAVE_NATIVE = True


_load()


def pack_strings(strings: Sequence[str]) -> Tuple[bytes, np.ndarray]:
    """Arrow-style packing: (joined UTF-8 buffer, int64 offsets[n+1])."""
    encoded: List[bytes] = [s.encode("utf-8") for s in strings]
    offsets = np.zeros(len(encoded) + 1, np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    return b"".join(encoded), offsets


def fnv1a32_batch(strings: Sequence[str]) -> np.ndarray:
    """Signed-int32 FNV-1a hash per string (== tables.fnv1a32)."""
    n = len(strings)
    out = np.empty(n, np.int32)
    if HAVE_NATIVE and n:
        buf, offsets = pack_strings(strings)
        _lib.fnv1a32_batch(buf, offsets, n, out)
        return out
    from minisched_tpu.models.tables import fnv1a32  # canonical scalar form

    for i, s in enumerate(strings):
        out[i] = fnv1a32(s)
    return out


def name_suffix_batch(strings: Sequence[str]) -> np.ndarray:
    """Trailing ASCII digit per name, -1 if absent (== tables._name_suffix)."""
    n = len(strings)
    out = np.empty(n, np.int32)
    if HAVE_NATIVE and n:
        buf, offsets = pack_strings(strings)
        _lib.name_suffix_batch(buf, offsets, n, out)
        return out
    from minisched_tpu.models.tables import _name_suffix

    for i, s in enumerate(strings):
        out[i] = _name_suffix(s)
    return out


def pod_seed_batch(strings: Sequence[str]) -> np.ndarray:
    """uint32 tie-break seed per uid (== tables.pod_seed)."""
    n = len(strings)
    out = np.empty(n, np.uint32)
    if HAVE_NATIVE and n:
        buf, offsets = pack_strings(strings)
        _lib.pod_seed_batch(buf, offsets, n, out)
        return out
    from minisched_tpu.models.tables import pod_seed

    for i, s in enumerate(strings):
        out[i] = pod_seed(s)
    return out
