"""The scheduling queue: active / backoff / unschedulable.

Re-creates ``minisched/queue/queue.go`` — the three-queue design mirroring
kube-scheduler (activeQ FIFO, podBackoffQ, unschedulableQ map keyed
namespace/name, queue.go:16-24,152-154) with event-driven requeue gated on
whether the event can help the pod's failed plugins (queue.go:65-82,167-190)
and per-pod exponential backoff (initial 1s, max 10s, doubling per attempt —
queue.go:218-235).

Deliberate departures from the reference (SURVEY.md §7 "known bugs — do not
copy"):

* ``NextPod``'s lock-free busy-spin + unlocked pop (queue.go:86-91) is
  replaced by a condition variable — ``pop`` blocks without burning CPU and
  is race-free.
* The reference's ``panic("not implemented")`` methods (Update / Delete /
  AssignedPodAdded / AssignedPodUpdated / flushBackoffQCompleted /
  flushUnschedulableQLeftover, queue.go:109-146) are implemented with
  upstream kube-scheduler semantics.
* Pop order within a wave is deterministic (FIFO + heap by expiry), which
  the TPU wave scheduler relies on for reproducible placement.

``pop_batch`` is the TPU-native addition: the batch evaluator drains a whole
wave of pods in one call instead of one pod per cycle.

``namespace_quota`` is the multi-tenant admission gate (ISSUE 8, the
churn-serving regime of "Priority Matters" arXiv:2511.08373): per-namespace
caps on how many pods may be TRACKED by the queue at once (active + backoff
+ unschedulable — i.e. pending admission to a wave).  Over-cap adds park in
a per-namespace FIFO and admit as tenants' earlier pods leave tracking
(popped for a wave, or deleted) — bounding any one tenant's share of every
wave without touching pop order for admitted pods.  Two deliberate
carve-outs: REQUEUES (a popped pod failing back through add_unschedulable,
or an engine retry via ``add(requeue=True)``) always re-admit — holding
them would strand an in-flight attempt behind its own tenant's newer
arrivals; and GANG members always admit
(``queue.quota_gang_bypass``) — holding part of a gang would park the rest
at Permit burning the gang TTL.  Opt-in: the default (None) changes no
behavior at all.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Set

from minisched_tpu.api.objects import gang_key
from minisched_tpu.observability import counters, hist, trace
from minisched_tpu.framework.events import (
    GVK,
    ClusterEvent,
    ClusterEventMap,
    event_helps_pod,
)
from minisched_tpu.framework.types import PodInfo, QueuedPodInfo

DEFAULT_INITIAL_BACKOFF_S = 1.0  # queue.go:219
DEFAULT_MAX_BACKOFF_S = 10.0  # queue.go:220
DEFAULT_UNSCHEDULABLE_TIMEOUT_S = 60.0  # upstream unschedulableQTimeInterval


class SchedulingQueue:
    def __init__(
        self,
        event_map: Optional[ClusterEventMap] = None,
        initial_backoff_s: float = DEFAULT_INITIAL_BACKOFF_S,
        max_backoff_s: float = DEFAULT_MAX_BACKOFF_S,
        unschedulable_timeout_s: float = DEFAULT_UNSCHEDULABLE_TIMEOUT_S,
        clock: Callable[[], float] = time.monotonic,
        namespace_quota: Optional[Dict[str, int]] = None,
    ):
        self._cond = threading.Condition()
        # per-namespace admission quota (see module docstring).  The map
        # is namespace → cap; "*" is the default cap for namespaces not
        # named.  None (default) disables the gate entirely.
        self._quota_limits: Optional[Dict[str, int]] = (
            dict(namespace_quota) if namespace_quota else None
        )
        self._ns_admitted: Dict[str, int] = {}
        self._quota_held: Dict[str, Deque] = {}  # ns → FIFO of held pods
        self._held_uids: Set[str] = set()
        # while a pop_batch gather is open, EVERY promotion defers here
        # (not just the batch's own pops): a delete_many landing in the
        # gather's cond-wait window would otherwise promote straight
        # into the activeQ the drain loop is consuming — held pods in
        # the very wave whose cap they were held for.  None = no gather
        # open, promotions run inline.  Single-consumer queues make this
        # safe: only pop_batch opens/seals it.
        self._deferred_promos: Optional[List[str]] = None
        self._active: Deque[QueuedPodInfo] = deque()
        # heap of (ready_time, seq, QueuedPodInfo)
        self._backoff: List[tuple] = []
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        # event-interest index over the unschedulableQ: key → the GVKs whose
        # events could help the pod (from its failed plugins' registered
        # events), and the reverse map an incoming event consults.  Without
        # it every cluster event — including each of the 100k binds a full-
        # scale run produces — scans the whole unschedulableQ
        # (move_all_to_active_or_backoff would be O(events × parked)).
        self._unsched_gvks: Dict[str, Set[GVK]] = {}
        self._unsched_by_gvk: Dict[GVK, Set[str]] = {}
        self._event_map: ClusterEventMap = event_map or {}
        self._initial_backoff_s = initial_backoff_s
        self._max_backoff_s = max_backoff_s
        self._unschedulable_timeout_s = unschedulable_timeout_s
        self._clock = clock
        self._seq = 0
        self._closed = False
        # identity keys currently tracked, to drop duplicate adds
        self._queued_uids: Set[str] = set()
        # upstream's schedulingCycle / moveRequestCycle pair: pops stamp
        # the pod with the current cycle; cluster move requests record the
        # cycle they fired in.  A pod whose attempt OVERLAPPED a move
        # request (move >= its stamp) failed against state the event may
        # have changed — it re-queues through backoff instead of parking,
        # closing the event-to-park race that otherwise strands it until
        # the 60s leftover flush (queue.go's unimplemented analog; upstream
        # PriorityQueue.AddUnschedulableIfNotPresent).
        self._scheduling_cycle = 0
        self._move_request_cycle = -1
        # per-event move-request cycles: WHICH event fired at which cycle,
        # so the event-to-park race check can stay event-GATED.  Upstream's
        # single moveRequestCycle routes every concurrently-failing pod
        # through backoff on ANY move request; at wave scale every wave's
        # own binds are a move request, so genuinely-unschedulable pods
        # never park — they replay through backoff for the whole run,
        # doubling their backoff each lap (a 2k-pod replay wave per lap,
        # and seconds of leftover backoff when the helping event finally
        # arrives).  The None key is the conservative wildcard (a move
        # request with no event attached helps everyone).
        self._move_events: Dict[Optional[ClusterEvent], int] = {}
        # event-storm tracking for pop_batch's debounce: the GVK whose
        # event last re-activated parked pods, the wall-clock time of the
        # most recent same-GVK event while the storm lasts, and when the
        # storm OPENED — the gather cap counts from there, not from
        # pop_batch entry (an engine idling in pop() for up to its poll
        # timeout before the storm begins must not have the cap already
        # spent).  (Wall clock on purpose: the debounce interacts with
        # real condition waits, not the injectable backoff clock.)
        self._storm_gvk: Optional[GVK] = None
        self._last_move_walltime = 0.0
        self._storm_open_walltime = 0.0
        # arrival stamps for the live time-to-bind histogram: uid → first
        # admission time.  QUEUE-owned, not QueuedPodInfo-owned, because
        # engine requeues (re-arbitration rejects, expired assume leases,
        # gang-TTL releases) build FRESH QueuedPodInfos — a per-QPI stamp
        # would reset the clock on every retry and flatter the tail.
        # Consumed at bind ack (observe_bind), purged on delete_many
        # (bound-by-peer / removed pods must not pin entries forever).
        self._arrival_ts: Dict[str, float] = {}

    @staticmethod
    def _uid(pod) -> str:
        # objects created outside the store may have no uid yet; fall back
        # to namespace/name identity so distinct pods never collapse
        return pod.metadata.uid or pod.metadata.key

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _key(pod) -> str:
        # keyed name_namespace, queue.go:152-154
        return f"{pod.metadata.name}_{pod.metadata.namespace}"

    def _backoff_duration(self, qpi: QueuedPodInfo) -> float:
        """Exponential per-attempt backoff (queue.go:225-235)."""
        duration = self._initial_backoff_s
        for _ in range(max(qpi.attempts - 1, 0)):
            duration *= 2
            if duration >= self._max_backoff_s:
                return self._max_backoff_s
        return duration

    def _backoff_ready_time(self, qpi: QueuedPodInfo) -> float:
        return qpi.timestamp + self._backoff_duration(qpi)

    def _is_backing_off(self, qpi: QueuedPodInfo) -> bool:
        return self._backoff_ready_time(qpi) > self._clock()

    def _push_active(self, qpi: QueuedPodInfo) -> None:
        self._active.append(qpi)
        self._cond.notify_all()

    def _push_backoff(self, qpi: QueuedPodInfo) -> None:
        self._seq += 1
        heapq.heappush(self._backoff, (self._backoff_ready_time(qpi), self._seq, qpi))
        # wake blocked consumers: their wait deadline is computed from the
        # earliest backoff expiry, which this push may have just moved up
        self._cond.notify_all()

    # -- namespace quota admission (see module docstring) ------------------
    def _quota_limit(self, ns: str) -> Optional[int]:
        if self._quota_limits is None:
            return None
        return self._quota_limits.get(ns, self._quota_limits.get("*"))

    def _track_locked(self, pod) -> None:
        """uid enters queue tracking: count it against its namespace."""
        self._queued_uids.add(self._uid(pod))
        self._stamp_arrival_locked(pod)
        if self._quota_limits is not None:
            ns = pod.metadata.namespace
            self._ns_admitted[ns] = self._ns_admitted.get(ns, 0) + 1

    def _stamp_arrival_locked(self, pod, held: bool = False) -> None:
        """First admission (quota-held arrivals included — their wait in
        the hold FIFO IS part of time-to-bind): stamp the arrival clock
        and record the enqueue trace span.  Idempotent per uid, so
        requeues and promotions never reset the clock."""
        uid = self._uid(pod)
        if uid in self._arrival_ts:
            return
        self._arrival_ts[uid] = self._clock()
        trace.span_pod("enqueue", pod, held=held or None)

    def _untrack_locked(self, pod, promote: bool = True) -> Optional[str]:
        """uid leaves tracking (popped for a wave, or deleted): release
        its namespace's quota slot and promote held arrivals into it.
        ``promote=False`` defers the promotion (callers iterating the
        activeQ must not have it appended to under them) and returns the
        released namespace for a later _promote_held_locked."""
        uid = self._uid(pod)
        if uid not in self._queued_uids:
            return None
        self._queued_uids.discard(uid)
        if self._quota_limits is None:
            return None
        ns = pod.metadata.namespace
        n = self._ns_admitted.get(ns, 0) - 1
        if n > 0:
            self._ns_admitted[ns] = n
        else:
            self._ns_admitted.pop(ns, None)
        if promote:
            self._promote_held_locked(ns)
            return None
        return ns

    def _promote_held_locked(self, ns: str) -> None:
        """FIFO-admit held pods of ``ns`` into freed quota slots."""
        if self._deferred_promos is not None:
            # a pop_batch gather is open: promote at its seal (see
            # _deferred_promos) so no held pod rides the current wave
            self._deferred_promos.append(ns)
            return
        held = self._quota_held.get(ns)
        if not held:
            return
        limit = self._quota_limit(ns)
        promoted = False
        while held and (
            limit is None or self._ns_admitted.get(ns, 0) < limit
        ):
            pod = held.popleft()
            self._held_uids.discard(self._uid(pod))
            self._track_locked(pod)
            if (
                limit is not None
                and self._ns_admitted.get(ns, 0) > limit
            ):
                # can't happen by construction (the loop guard admits
                # strictly under the cap) — a nonzero count here is a
                # quota-accounting BUG, and the churn bench gates on it
                counters.inc("queue.quota_violation")
            self._active.append(QueuedPodInfo(PodInfo(pod)))
            counters.inc("queue.quota_admitted")
            promoted = True
        if not held:
            self._quota_held.pop(ns, None)
        if promoted:
            self._cond.notify_all()

    # -- producer side -----------------------------------------------------
    def _add_locked(self, pod, requeue: bool = False) -> None:
        """Caller holds self._cond and notifies afterwards.  ``requeue``
        marks a pod an ENGINE is putting back (re-arbitration reject,
        expired assume lease, gang-TTL release): it re-admits past any
        quota cap — the hold gates NEW arrivals only (module docstring);
        holding an in-flight retry behind its own tenant's newer
        arrivals could defer it indefinitely while admitted pods pin
        the cap."""
        uid = self._uid(pod)
        if uid in self._queued_uids or uid in self._held_uids:
            return
        if self._quota_limits is not None and not requeue:
            ns = pod.metadata.namespace
            limit = self._quota_limit(ns)
            if limit is not None and self._ns_admitted.get(ns, 0) >= limit:
                if gang_key(pod) is not None:
                    # all-or-nothing gangs never split across the quota
                    # boundary: holding part of one parks the rest at
                    # Permit burning the gang TTL (module docstring)
                    counters.inc("queue.quota_gang_bypass")
                else:
                    self._quota_held.setdefault(ns, deque()).append(pod)
                    self._held_uids.add(uid)
                    self._stamp_arrival_locked(pod, held=True)
                    counters.inc("queue.quota_held")
                    return
            self._track_locked(pod)
            if (
                limit is not None
                and self._ns_admitted.get(ns, 0) > limit
                and gang_key(pod) is None
            ):
                # tripwire, not a code path: a non-gang NEW arrival must
                # never land past the cap (the hold above gates >= limit;
                # only requeues and gang bypass may exceed).  The churn
                # bench gates on this staying zero.
                counters.inc("queue.quota_violation")
            self._active.append(QueuedPodInfo(PodInfo(pod)))
            return
        self._track_locked(pod)
        self._active.append(QueuedPodInfo(PodInfo(pod)))

    def add(self, pod, requeue: bool = False) -> None:
        """New pending pod → activeQ (queue.go:35-43).  ``requeue=True``
        bypasses quota holds (see _add_locked) — engine retry paths pass
        it; informer arrival paths never do."""
        with self._cond:
            self._add_locked(pod, requeue=requeue)
            self._cond.notify_all()

    def add_batch(self, pods) -> None:
        """Batch add under ONE lock hold + one notify — the informer's
        batch dispatch feeds a 100k-pod creation flood through here."""
        with self._cond:
            for pod in pods:
                self._add_locked(pod)
            self._cond.notify_all()

    def _interest_gvks(self, failed_plugins: Set[str]) -> Set[GVK]:
        """Which GVKs' events could help a pod that failed on these plugins
        — the index key mirroring ``event_helps_pod``'s outer loop.  A pod
        with no recorded failures retries on ANY event (upstream), as does
        one whose plugins registered the wildcard resource."""
        if not failed_plugins:
            return {GVK.WILDCARD}
        out: Set[GVK] = set()
        for registered, plugin_names in self._event_map.items():
            if plugin_names & failed_plugins:
                out.add(registered.resource)
        return out

    def _index_unschedulable(self, key: str, qpi: QueuedPodInfo) -> None:
        gvks = self._interest_gvks(qpi.unschedulable_plugins)
        self._unsched_gvks[key] = gvks
        for gvk in gvks:
            self._unsched_by_gvk.setdefault(gvk, set()).add(key)

    def _unindex_unschedulable(self, key: str) -> None:
        for gvk in self._unsched_gvks.pop(key, ()):
            bucket = self._unsched_by_gvk.get(gvk)
            if bucket is not None:
                bucket.discard(key)

    def add_unschedulable(self, qpi: QueuedPodInfo) -> None:
        """Failed pod → unschedulableQ, stamped now (queue.go:95-107) —
        unless a move request that could HELP this pod fired during its
        attempt, in which case it goes through backoff (upstream
        AddUnschedulableIfNotPresent, with the event-gating refinement:
        upstream's single moveRequestCycle would re-queue it on any
        overlapping event, helping or not — see _move_events)."""
        with self._cond:
            uid = self._uid(qpi.pod)
            if uid in self._queued_uids or uid in self._held_uids:
                # upstream's IfNotPresent: the pod is already in some
                # queue segment — a second routing (e.g. a failed scan
                # lane re-parking a chunk loser it already error_func'd)
                # must not insert a duplicate entry that would be popped
                # and scheduled twice.  The held FIFO counts as presence
                # too: tracking a second copy while one sits held would
                # double-count the namespace at promotion and let the
                # pod schedule twice.
                return
            qpi.timestamp = self._clock()
            # requeues re-admit unconditionally (quota counts them; the
            # hold only ever gates NEW arrivals — module docstring)
            self._track_locked(qpi.pod)
            helped = any(
                cycle >= qpi.scheduling_cycle
                and (
                    ev is None
                    or event_helps_pod(
                        ev, qpi.unschedulable_plugins, self._event_map
                    )
                )
                for ev, cycle in self._move_events.items()
            )
            if helped:
                if self._is_backing_off(qpi):
                    self._push_backoff(qpi)
                else:
                    self._push_active(qpi)
                return
            key = self._key(qpi.pod)
            self._unindex_unschedulable(key)  # re-park refreshes interest
            self._unschedulable[key] = qpi
            self._index_unschedulable(key, qpi)

    def update(self, old_pod, new_pod) -> None:
        """Pod object changed while queued — refresh stored pod; if it was
        unschedulable, an update may make it schedulable (upstream moves it
        through backoff gating).  Implements queue.go:109-112's panic."""
        with self._cond:
            uid = self._uid(new_pod)
            if uid in self._held_uids:
                # quota-held arrivals track object refreshes too (they
                # re-enter the active queue with whatever spec is current)
                held = self._quota_held.get(new_pod.metadata.namespace)
                if held is not None:
                    for i, p in enumerate(held):
                        if self._uid(p) == uid:
                            held[i] = new_pod
                            return
            for qpi in self._active:
                if self._uid(qpi.pod) == uid:
                    qpi.pod_info.pod = new_pod
                    return
            for _, _, qpi in self._backoff:
                if self._uid(qpi.pod) == uid:
                    qpi.pod_info.pod = new_pod
                    return
            key = self._key(new_pod)
            qpi = self._unschedulable.get(key)
            if qpi is not None:
                qpi.pod_info.pod = new_pod
                if _spec_changed(old_pod, new_pod):
                    del self._unschedulable[key]
                    self._unindex_unschedulable(key)
                    if self._is_backing_off(qpi):
                        self._push_backoff(qpi)
                    else:
                        self._push_active(qpi)

    def delete(self, pod) -> None:
        """Pod removed from the cluster — drop it everywhere
        (queue.go:113-116's panic).  One implementation: delete_many."""
        self.delete_many([pod])

    def observe_bind(self, pod, node_name: Optional[str] = None) -> None:
        """Bind ack: consume the arrival stamp into the live
        ``sched.time_to_bind_s`` histogram (per priority-class label)
        and close the pod's trace chain.  Called by BOTH bind paths —
        the device engine's batch binder and the scalar/Wait-permit
        binding cycle.  A missing stamp (the informer's bind event
        already routed the pod through delete_many, or the pod bound
        before this queue existed) is silently skipped — the histogram
        records latencies, not population."""
        uid = self._uid(pod)
        with self._cond:
            t0 = self._arrival_ts.pop(uid, None)
        if t0 is None:
            return
        dt = max(self._clock() - t0, 0.0)
        prio = getattr(pod.spec, "priority", 0) or 0
        # exemplar: the p99 bucket on /metrics names the slow pod
        hist.observe(
            "sched.time_to_bind_s", dt,
            exemplar=pod.metadata.key, priority=str(prio),
        )
        trace.span_pod("bind_ack", pod, node=node_name, ttb_s=dt)

    def delete_many(self, pods) -> None:
        """Batch delete under ONE lock hold, with a set-intersection fast
        path for pods not queued at all.  The HA event handlers route
        every bound-elsewhere / shard-moved-away MODIFIED through here —
        in a single-engine plane that is EVERY bind event (a wave's
        thousands), and per-event delete() would rescan the queue each
        time to remove nothing."""
        with self._cond:
            all_uids = {self._uid(p) for p in pods}
            # arrival stamps die with the pod — but a departing pod that
            # is BOUND is a bind ack arriving via the EVENT path: the HA
            # handlers route every bind MODIFIED through here, and on
            # the dispatch thread it can beat the binding thread's own
            # observe_bind (the stamp pop is atomic, so exactly one of
            # the two paths records the sample).  Unbound departures
            # (true deletes, bound-elsewhere races that lost the
            # node_name) still just drop — latencies, not population.
            for p in pods:
                t0 = self._arrival_ts.pop(self._uid(p), None)
                if t0 is not None and getattr(p.spec, "node_name", None):
                    dt = max(self._clock() - t0, 0.0)
                    prio = getattr(p.spec, "priority", 0) or 0
                    hist.observe(
                        "sched.time_to_bind_s", dt,
                        exemplar=p.metadata.key, priority=str(prio),
                    )
                    trace.span_pod(
                        "bind_ack", p, node=p.spec.node_name, ttb_s=dt
                    )
            held_hits = all_uids & self._held_uids
            if held_hits:
                # deleted while quota-held: drop from the hold FIFO too
                for ns in {
                    p.metadata.namespace
                    for p in pods
                    if self._uid(p) in held_hits
                }:
                    held = self._quota_held.get(ns)
                    if held is not None:
                        kept = deque(
                            p for p in held if self._uid(p) not in held_hits
                        )
                        if kept:
                            self._quota_held[ns] = kept
                        else:
                            self._quota_held.pop(ns, None)
                self._held_uids -= held_hits
            uids = all_uids & self._queued_uids
            if not uids:
                return
            self._active = deque(
                q for q in self._active if self._uid(q.pod) not in uids
            )
            self._backoff = [
                e for e in self._backoff if self._uid(e[2].pod) not in uids
            ]
            heapq.heapify(self._backoff)
            for pod in pods:
                if self._uid(pod) in uids:
                    key = self._key(pod)
                    if self._unschedulable.pop(key, None) is not None:
                        self._unindex_unschedulable(key)
                    self._untrack_locked(pod)

    # -- event-driven requeue ---------------------------------------------
    def note_move_request(self, event: Optional[ClusterEvent] = None) -> None:
        """Record a cluster state change as a move request WITHOUT a scan:
        pods currently mid-attempt whose failures ``event`` could help will
        re-queue through backoff on failure.  The wave engine calls this
        synchronously after a batch bind (event = Pod/UPDATE, mirroring
        what the dispatch thread will fire when the bind events land) —
        those events arrive later, after the wave's losers may already
        have parked.  ``event=None`` is the conservative wildcard."""
        with self._cond:
            self._move_request_cycle = self._scheduling_cycle
            self._move_events[event] = self._scheduling_cycle

    def move_all_to_active_or_backoff(self, event: ClusterEvent) -> None:
        """queue.go:54-82: on a cluster event, re-activate every
        unschedulable pod the event might help."""
        with self._cond:
            self._move_request_cycle = self._scheduling_cycle
            self._move_events[event] = self._scheduling_cycle
            # the interest index narrows the scan to pods whose failed
            # plugins registered for this event's resource (or wildcard);
            # event_helps_pod then applies the precise action-type match
            candidates = self._unsched_by_gvk.get(event.resource, set()) | (
                self._unsched_by_gvk.get(GVK.WILDCARD, set())
            )
            moved: List[str] = []
            for key in candidates:
                qpi = self._unschedulable.get(key)
                if qpi is not None and event_helps_pod(
                    event, qpi.unschedulable_plugins, self._event_map
                ):
                    moved.append(key)
            for key in moved:
                qpi = self._unschedulable.pop(key)
                self._unindex_unschedulable(key)
                if self._is_backing_off(qpi):
                    self._push_backoff(qpi)
                else:
                    self._push_active(qpi)
            # storm tracking: a move that re-activated pods opens a storm
            # for this GVK; further same-GVK events extend it while it
            # lasts (a burst of node-label updates re-activates everything
            # on the FIRST event — the follow-on events must still hold
            # the wave boundary or it evaluates against half-updated
            # state, fails half the burst, and pays a doubled backoff)
            now_w = time.monotonic()
            if moved:
                if (
                    self._storm_gvk != event.resource
                    or now_w - self._last_move_walltime
                    >= self.STORM_DEBOUNCE_S
                ):
                    self._storm_open_walltime = now_w  # fresh storm
                self._storm_gvk = event.resource
                self._last_move_walltime = now_w
            elif (
                self._storm_gvk == event.resource
                and now_w - self._last_move_walltime < self.STORM_MAX_GATHER_S
            ):
                self._last_move_walltime = now_w

    def assigned_pod_added(self, pod) -> None:
        """A pod got bound somewhere — may unblock pods with (anti)affinity
        on it (queue.go:117-120's panic; upstream moves on AssignedPodAdd)."""
        from minisched_tpu.framework.events import ActionType, GVK

        self.move_all_to_active_or_backoff(ClusterEvent(GVK.POD, ActionType.ADD))

    def assigned_pod_updated(self, pod) -> None:
        from minisched_tpu.framework.events import ActionType, GVK

        self.move_all_to_active_or_backoff(
            ClusterEvent(GVK.POD, ActionType.UPDATE)
        )

    # -- periodic flushes (queue.go:121-146's panics) ----------------------
    def flush_backoff_completed(self) -> None:
        with self._cond:
            now = self._clock()
            while self._backoff and self._backoff[0][0] <= now:
                _, _, qpi = heapq.heappop(self._backoff)
                self._push_active(qpi)

    def flush_unschedulable_leftover(self) -> None:
        with self._cond:
            now = self._clock()
            stale = [
                key
                for key, qpi in self._unschedulable.items()
                if now - qpi.timestamp > self._unschedulable_timeout_s
            ]
            for key in stale:
                qpi = self._unschedulable.pop(key)
                self._unindex_unschedulable(key)
                if self._is_backing_off(qpi):
                    self._push_backoff(qpi)
                else:
                    self._push_active(qpi)

    # -- consumer side -----------------------------------------------------
    def pop(
        self,
        timeout: Optional[float] = None,
        _released: Optional[List[str]] = None,
    ) -> Optional[QueuedPodInfo]:
        """Blocking NextPod (replaces the busy-spin at queue.go:86-91).

        Increments ``attempts`` on the way out, as upstream does when a pod
        leaves the queue for a scheduling attempt.

        ``_released`` (internal, pop_batch): collect the freed quota
        namespace instead of promoting held pods inline — a promotion
        here would land at the activeQ tail and be drained into the SAME
        wave, defeating the per-wave tenant share the quota promises.
        """
        # NOTE: the wait deadline is wall-clock (condition waits are real
        # time) even when a fake clock drives backoff math in tests.
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._active and not self._closed:
                self.flush_backoff_completed_locked()
                if self._active:
                    break  # the flush's own notify predates our wait
                # sleep until the next backoff expiry (event-driven: adds
                # and earlier backoff pushes notify) — no fixed-rate poll
                wait = None
                if self._backoff:
                    wait = max(self._backoff[0][0] - self._clock(), 0.0)
                    if self._clock is not time.monotonic:
                        # fake clocks advance out-of-band; stay responsive
                        wait = min(wait, 0.05)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)
            if not self._active:
                return None
            qpi = self._active.popleft()
            qpi.attempts += 1
            self._scheduling_cycle += 1
            qpi.scheduling_cycle = self._scheduling_cycle
            ns = self._untrack_locked(qpi.pod, promote=_released is None)
            if ns is not None and _released is not None:
                _released.append(ns)
            trace.span_pod(
                "pop", qpi.pod,
                attempts=qpi.attempts, cycle=qpi.scheduling_cycle,
            )
            return qpi

    #: pop_batch holds the wave boundary while an event storm that just
    #: re-activated parked pods is still arriving (no same-GVK event for
    #: this long = settled), bounded by the max gather
    STORM_DEBOUNCE_S = 0.2
    STORM_MAX_GATHER_S = 1.0

    def pop_batch(
        self,
        max_pods: int,
        timeout: Optional[float] = None,
        gather_backoff_s: float = 0.35,
    ) -> List[QueuedPodInfo]:
        """Drain up to ``max_pods`` in FIFO order — the wave the TPU batch
        evaluator schedules in one fused kernel call.

        Two bounded waits keep a requeue burst on ONE wave instead of
        trickling through several (each its own full evaluation):

        ``gather_backoff_s``: after draining the activeQ, if the batch has
        room and more pods' backoff expires within this window, wait for
        them and take them too.  Backoff expiry times are unchanged (pods
        never leave early); only the wave boundary waits for them.

        Storm debounce: when a cluster-event burst (say 2k node-label
        updates) re-activates parked pods, the FIRST event moves them all
        — a wave starting right then evaluates against the half-updated
        cluster, fails half the burst, and pays a doubled per-pod backoff
        (queue.go:218-235 semantics) before a second wave.  While same-GVK
        events are still arriving (see move_all_to_active_or_backoff), the
        wave boundary holds until STORM_DEBOUNCE_S passes without one,
        capped at STORM_MAX_GATHER_S.

        Quota promotions are DEFERRED to the end of the batch: every pop
        here frees a quota slot, and an inline promotion would append the
        held pod to the activeQ this very loop is draining — the whole
        hold FIFO would cascade into one wave.  Collecting the freed
        namespaces and promoting once the batch is sealed keeps a
        tenant's share of any single wave at its cap (gang bypass
        aside); the promoted pods lead the NEXT wave."""
        released: List[str] = []
        with self._cond:
            # open the gather: promotions from ANY thread (a delete_many
            # on the dispatch thread included) defer to the seal below —
            # a promotion landing mid-gather would ride this very wave
            self._deferred_promos = []
        try:
            batch = self._pop_batch_gather(
                max_pods, timeout, gather_backoff_s, released
            )
        finally:
            with self._cond:
                pending = self._deferred_promos or []
                self._deferred_promos = None
                for ns in dict.fromkeys(pending + released):
                    self._promote_held_locked(ns)
        if batch:
            _sort_gangs_adjacent(batch)
        return batch

    def _pop_batch_gather(
        self,
        max_pods: int,
        timeout: Optional[float],
        gather_backoff_s: float,
        released: List[str],
    ) -> List[QueuedPodInfo]:
        first = self.pop(timeout, _released=released)
        if first is None:
            return []
        batch = [first]
        t_start = time.monotonic()
        with self._cond:
            while True:
                while self._active and len(batch) < max_pods:
                    qpi = self._active.popleft()
                    qpi.attempts += 1
                    self._scheduling_cycle += 1
                    qpi.scheduling_cycle = self._scheduling_cycle
                    ns = self._untrack_locked(qpi.pod, promote=False)
                    if ns is not None:
                        released.append(ns)
                    trace.span_pod(
                        "pop", qpi.pod,
                        attempts=qpi.attempts, cycle=qpi.scheduling_cycle,
                    )
                    batch.append(qpi)
                if len(batch) >= max_pods:
                    break
                now_w = time.monotonic()
                storm_wait = None
                if self._storm_gvk is not None:
                    since = now_w - self._last_move_walltime
                    opened = max(self._storm_open_walltime, t_start)
                    if (
                        since < self.STORM_DEBOUNCE_S
                        and now_w - opened < self.STORM_MAX_GATHER_S
                    ):
                        storm_wait = self.STORM_DEBOUNCE_S - since
                    else:
                        self._storm_gvk = None  # settled (or cap hit)
                backoff_wait = None
                if self._backoff:
                    w = self._backoff[0][0] - self._clock()
                    if w <= gather_backoff_s:
                        backoff_wait = max(w, 0.0)
                if storm_wait is None and backoff_wait is None:
                    break
                wait = min(
                    w for w in (storm_wait, backoff_wait) if w is not None
                )
                # releases the lock; producers/events can land meanwhile
                self._cond.wait(wait + 0.001)
                self.flush_backoff_completed_locked()
            self._complete_gangs_locked(batch, released)
        # promotions happen at the caller's seal (pop_batch's finally):
        # the admitted pods then lead the NEXT wave
        return batch

    def _complete_gangs_locked(
        self, batch: List[QueuedPodInfo], released: List[str]
    ) -> None:
        """Pull every still-queued member of a gang already in ``batch``
        out of the activeQ and into the batch — even past ``max_pods``:
        one wave must see the WHOLE gang, or its tail waits a full wave
        behind its head with the gang TTL burning (and two interleaved
        gangs would hold partial capacity against each other).  Bounded
        by gang sizes, which are slice-host counts, not wave counts."""
        keys = {gang_key(q.pod) for q in batch}
        keys.discard(None)
        if not keys or not self._active:
            return
        kept: Deque[QueuedPodInfo] = deque()
        for qpi in self._active:
            if gang_key(qpi.pod) in keys:
                qpi.attempts += 1
                self._scheduling_cycle += 1
                qpi.scheduling_cycle = self._scheduling_cycle
                # promotion deferred to pop_batch's seal (and because it
                # would append to the activeQ this loop is iterating)
                ns = self._untrack_locked(qpi.pod, promote=False)
                if ns is not None:
                    released.append(ns)
                batch.append(qpi)
            else:
                kept.append(qpi)
        self._active = kept

    def flush_backoff_completed_locked(self) -> None:
        # caller holds self._cond
        now = self._clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, qpi = heapq.heappop(self._backoff)
            self._push_active(qpi)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- introspection (tests / observability) -----------------------------
    def stats(self) -> Dict[str, int]:
        with self._cond:
            out = {
                "active": len(self._active),
                "backoff": len(self._backoff),
                "unschedulable": len(self._unschedulable),
            }
            if self._quota_limits is not None:
                out["quota_held"] = sum(
                    len(d) for d in self._quota_held.values()
                )
            return out

    def quota_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-namespace {admitted, held, limit} under one lock hold —
        the churn bench samples this to audit that no tenant ever
        exceeds its cap (gang bypass aside, which has its own counter)."""
        with self._cond:
            if self._quota_limits is None:
                return {}
            spaces = (
                set(self._ns_admitted)
                | set(self._quota_held)
                | {k for k in self._quota_limits if k != "*"}
            )
            return {
                ns: {
                    "admitted": self._ns_admitted.get(ns, 0),
                    "held": len(self._quota_held.get(ns, ())),
                    "limit": self._quota_limit(ns),
                }
                for ns in spaces
            }

    def pending_unschedulable(self) -> List[QueuedPodInfo]:
        with self._cond:
            return list(self._unschedulable.values())


def _sort_gangs_adjacent(batch: List[QueuedPodInfo]) -> None:
    """Stable in-place reorder: members of one gang become adjacent at
    the gang's FIRST occurrence; singletons and distinct gangs keep
    their relative pop order.  The wave engine then evaluates a gang as
    one contiguous run — its members arbitrate capacity together and
    reach Permit in the same commit pass."""
    first: Dict[str, int] = {}
    keyed = []
    for i, qpi in enumerate(batch):
        k = gang_key(qpi.pod)
        slot = i if k is None else first.setdefault(k, i)
        keyed.append((slot, i, qpi))
    keyed.sort(key=lambda e: (e[0], e[1]))
    batch[:] = [qpi for _, _, qpi in keyed]


def _spec_changed(old_pod, new_pod) -> bool:
    if old_pod is None:
        return True
    return old_pod.spec != new_pod.spec or old_pod.metadata.labels != new_pod.metadata.labels
