"""Device-mesh sharding for the batch evaluator (SURVEY.md §7 stage 9).

The scaling axes of this domain are the pod and node dimensions of the
(pods × nodes) scheduling matrices — the analog of data/model parallelism
(SURVEY.md §5.7/§5.8).  Design, per the standard JAX recipe: pick a Mesh,
annotate the tables' shardings, and let XLA's GSPMD partitioner insert the
collectives (the masked-argmax reduction over sharded node columns rides
ICI as tree-reduce; nothing NCCL-like is hand-written).

Mesh axes:
* ``"pods"``  — data-parallel axis: pod waves split across devices; each
  device schedules its pod shard independently (decisions are per-pod).
* ``"nodes"`` — model-parallel axis: the node table splits across devices;
  per-pod reductions (max score, min tie-break hash) become cross-device
  collectives inserted by XLA.

The reference has no equivalent — its "fabric" is client-go informers +
REST over loopback (k8sapiserver.go:45-62); multi-host scale-out there
means nothing.  Here one chip holds ~10k nodes easily; the node axis is
sharded when the cluster (or the pod wave) outgrows one chip's HBM.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from minisched_tpu.models.tables import NodeTable, PodTable

POD_AXIS = "pods"
NODE_AXIS = "nodes"


def mesh_shape_key(mesh: Optional[Mesh]) -> Tuple:
    """Hashable (axis, size) signature of a mesh — folded into every
    compile-cache key the mesh path touches (ISSUE 7 satellite: an
    executable compiled for one mesh factoring must never be served to
    another, even where the table shapes coincide)."""
    if mesh is None:
        return ()
    return tuple((name, int(size)) for name, size in mesh.shape.items())


def mesh_axis_sizes(mesh: Optional[Mesh]) -> Tuple[int, int]:
    """(pod-axis size, node-axis size); (1, 1) off-mesh."""
    if mesh is None:
        return 1, 1
    return int(mesh.shape[POD_AXIS]), int(mesh.shape[NODE_AXIS])


def cap_multiple(base: int, axis: int) -> int:
    """Table-capacity quantum under a mesh axis: capacities must stay
    lane-padded (multiples of ``base``) AND divide evenly across the
    axis's shards — lcm covers non-power-of-two factorings (a 6-device
    2×3 mesh) where 128 alone would leave a 3-shard axis with ragged
    tiles."""
    return base * axis // math.gcd(base, axis)


def resolve_mesh(env: Optional[Dict[str, str]] = None) -> Optional[Mesh]:
    """The live engine's startup mesh policy (ISSUE 7 tentpole):

    * ``MINISCHED_MESH=0`` — never shard (the single-device packed path,
      byte-for-byte the pre-mesh engine);
    * ``MINISCHED_MESH=1`` — always build a mesh over every visible
      device, even a degenerate 1-device one (same placements, exercises
      the sharded program);
    * unset — auto: a mesh exactly when ``jax.device_count() > 1``
      (multi-chip hosts shard by default, laptops/CI keep the exact
      single-device behavior).

    ``MINISCHED_MESH_POD_SHARDS`` pins the pod-axis factoring (default:
    hosts on the pod axis, chips on the node axis — see make_mesh)."""
    env = env if env is not None else os.environ
    flag = env.get("MINISCHED_MESH", "")
    if flag == "0":
        return None
    if flag not in ("", "0", "1"):
        raise ValueError(f"MINISCHED_MESH must be '', '0' or '1', got {flag!r}")
    if flag != "1" and jax.device_count() <= 1:
        return None
    pod_shards = env.get("MINISCHED_MESH_POD_SHARDS", "")
    return make_mesh(pod_shards=int(pod_shards) if pod_shards else None)


def default_pod_shards(n_devices: int, n_processes: int = 1) -> int:
    """The pod-axis size of the 2D mesh factoring.

    Multi-host: the pod axis is DATA-parallel — per-pod decisions need no
    cross-pod-shard collectives — while the node axis carries the
    argmax/argmin reductions.  So hosts belong on the POD axis (the
    inter-host DCN link only moves the final per-pod results) and each
    host's chips on the NODE axis (the per-wave collectives ride ICI) —
    the standard "DCN on the data axis, ICI on the model axis" recipe.
    Single host: largest power-of-two divisor ≤ √n keeps the per-device
    (P, N) tiles near-square (HBM-friendly).
    """
    if n_processes > 1 and n_devices % n_processes == 0:
        return n_processes
    shards = 1
    while shards * 2 <= math.isqrt(n_devices) and n_devices % (shards * 2) == 0:
        shards *= 2
    return shards


def make_mesh(
    n_devices: Optional[int] = None,
    pod_shards: Optional[int] = None,
    devices=None,
) -> Mesh:
    """A 2D (pods × nodes) Mesh over the first ``n_devices`` devices.

    Factoring: ``default_pod_shards`` — hosts land on the pod axis (DCN
    carries no per-wave collectives there; the node-axis reductions stay
    on ICI), per-host chips on the node axis; ``pod_shards`` pins it.
    ``jax.devices()`` orders devices host-major, so reshaping to
    (processes, chips-per-process) puts each row's node shards on one
    host's ICI domain.
    """
    if n_devices is not None and n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    full_roster = devices is None
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, only {len(devices)} available")
    covers_all = full_roster and n == len(devices)
    devices = devices[:n]
    if pod_shards is None:
        # the hosts-on-pod-axis factoring relies on the (processes,
        # chips-per-process) reshape aligning mesh rows with hosts — only
        # true for the full host-major jax.devices() roster; a truncated
        # or caller-supplied list falls back to the square-ish factoring
        pod_shards = default_pod_shards(
            n, jax.process_count() if covers_all else 1
        )
    if n % pod_shards:
        raise ValueError(f"{n} devices not divisible by pod_shards={pod_shards}")
    grid = np.array(devices).reshape(pod_shards, n // pod_shards)
    return Mesh(grid, (POD_AXIS, NODE_AXIS))


def _table_sharding(
    mesh: Mesh, table: Any, axis: str, replicated: tuple = ()
) -> Any:
    """NamedSharding pytree: leading dim on ``axis``, trailing dims
    replicated; fields named in ``replicated`` replicate fully (their
    leading dim is NOT the table's primary axis — e.g. the NodeTable's
    tiny per-profile label/taint planes)."""
    from dataclasses import fields as dc_fields

    specs = {}
    for f in dc_fields(type(table)):
        leaf = getattr(table, f.name)
        if f.name in replicated:
            specs[f.name] = NamedSharding(mesh, P())
        else:
            extra = (None,) * (leaf.ndim - 1)
            specs[f.name] = NamedSharding(mesh, P(axis, *extra))
    return type(table)(**specs)


def pod_sharding(mesh: Mesh, table: PodTable):
    return _table_sharding(mesh, table, POD_AXIS)


def node_sharding(mesh: Mesh, table: NodeTable):
    from minisched_tpu.models.tables import NODE_PROFILE_COLS

    return _table_sharding(mesh, table, NODE_AXIS, replicated=NODE_PROFILE_COLS)


#: ConstraintTables field → mesh placement, derived from the single
#: authoritative layout map (models/constraints.CONSTRAINT_AXES): leading
#: pod dims split on "pods", trailing node dims on "nodes", small
#: per-combo/key metadata replicates.
from minisched_tpu.models.constraints import CONSTRAINT_AXES as _LAYOUT

_AXIS_NAME = {"pods": POD_AXIS, "nodes": NODE_AXIS, None: None}
_CONSTRAINT_AXES = {
    name: (kind, _AXIS_NAME[role]) for name, (kind, role) in _LAYOUT.items()
}


def constraint_sharding(mesh: Mesh, extra: Any) -> Any:
    """NamedSharding pytree for a ConstraintTables bundle: node-axis planes
    split with the node table, per-pod constraint arrays with the pod table,
    small combo metadata replicated."""
    from dataclasses import fields as dc_fields

    specs = {}
    for f in dc_fields(type(extra)):
        leaf = getattr(extra, f.name)
        kind, axis = _CONSTRAINT_AXES.get(f.name, ("first", POD_AXIS))
        if kind == "rep":
            spec = P()
        elif kind == "last":
            spec = P(*((None,) * (leaf.ndim - 1)), axis)
        else:
            spec = P(axis, *((None,) * (leaf.ndim - 1)))
        specs[f.name] = NamedSharding(mesh, spec)
    return type(extra)(**specs)


def static_col_shardings(mesh: Mesh, cols: Dict[str, Any]) -> Dict[str, Any]:
    """NamedSharding per device-resident static node column: leading
    node dim split on the node axis, the tiny per-profile label/taint
    planes replicated (they must be whole on every shard — every node
    row gathers through profile_id)."""
    from minisched_tpu.models.tables import NODE_PROFILE_COLS

    out = {}
    for name, leaf in cols.items():
        if name in NODE_PROFILE_COLS:
            out[name] = NamedSharding(mesh, P())
        else:
            out[name] = NamedSharding(
                mesh, P(NODE_AXIS, *((None,) * (leaf.ndim - 1)))
            )
    return out


def scan_constraint_sharding(mesh: Mesh, extra: Any) -> Any:
    """ConstraintTables shardings for the sequential-scan layout: the
    node-axis planes split with the node table, everything pod-indexed
    replicates (the scan walks pods one dynamic row slice at a time — a
    pod-sharded layout would turn every step into a cross-shard
    gather)."""
    from dataclasses import fields as dc_fields

    specs = {}
    for f in dc_fields(type(extra)):
        leaf = getattr(extra, f.name)
        kind, _axis = _CONSTRAINT_AXES.get(f.name, ("first", POD_AXIS))
        if kind == "last":
            spec = P(*((None,) * (leaf.ndim - 1)), NODE_AXIS)
        else:
            spec = P()
        specs[f.name] = NamedSharding(mesh, spec)
    return type(extra)(**specs)


def shard_tables(
    mesh: Mesh, pods: PodTable, nodes: NodeTable
) -> Tuple[PodTable, NodeTable]:
    """Place tables on the mesh: pods split on the pod axis, nodes on the
    node axis.  Capacities must divide the respective mesh axis sizes
    (tables.pad_to(128) guarantees this for meshes up to 128-wide)."""
    pods = jax.device_put(pods, pod_sharding(mesh, pods))
    nodes = jax.device_put(nodes, node_sharding(mesh, nodes))
    return pods, nodes


class _CompiledShardedStep:
    """One jitted executable per call signature (with/without the
    constraint tables) — waves may alternate between the two.  ``fn`` is
    ``fn(nodes, pods, extra=None)``.

    The node table is deliberately NOT donated: table builds route
    all-zero columns through a shared splitter executable whose outputs
    can ALIAS (one broadcasted-zero buffer serving several columns), and
    a donation-compiled program then rejects the call with "supplied N
    buffers but compiled program expected M" — an order-dependent live
    failure (a whole wave parked unschedulable) first seen when another
    engine's builds warmed the splitter caches.  Donation only saved an
    on-device copy on the virtual-mesh path; the single-chip hot path
    never goes through here."""

    #: process-wide count of poisoned-dispatch self-heals (see __call__)
    #: — repeated poisoning is a real bug and must be visible, not masked
    #: by silent recompiles
    heal_count = 0

    def __init__(self, mesh: Mesh, fn):
        self._mesh = mesh
        self._fn = fn
        self._jitted = {}

    def __call__(self, nodes, pods, extra=None):
        try:
            out = self._call(nodes, pods, extra)
            # execution is async — the poisoned-dispatch fault below only
            # surfaces when results are awaited, which would be outside
            # this handler.  Blocking here costs pipelining only on the
            # virtual-mesh path.
            jax.block_until_ready(out)
            return out
        # the fault has surfaced as ValueError on this jaxlib, but PJRT
        # execution errors are XlaRuntimeError (a RuntimeError) in other
        # paths — catch both, gate on the message
        except (ValueError, RuntimeError) as err:
            # jit-cache poisoning self-heal: with other engines' builds
            # in this process's jit caches, dispatch can land on an
            # executable traced for a DIFFERENT argument set and fail
            # with "Execution supplied N buffers but compiled program
            # expected M" (constant delta, every wave — the whole wave
            # would park unschedulable).  Dropping the entry recompiles
            # against THIS call's actual structure; a second failure is
            # a real bug and surfaces.
            if "buffers but compiled program expected" not in str(err):
                raise
            import os as _os
            import sys as _sys
            # heals are ALWAYS visible (advisor r4): a genuine argument-
            # mismatch bug in a new caller would otherwise be silently
            # masked by its first recompile and only surface if it
            # repeats.  The counter lets harnesses assert no-heal runs.
            _CompiledShardedStep.heal_count += 1
            print(
                f"[sharded-step] poisoned dispatch #"
                f"{_CompiledShardedStep.heal_count}; recompiling "
                f"({str(err)[-120:]})",
                file=_sys.stderr,
                flush=True,
            )
            # evict only the poisoned signature — other entries' compiled
            # executables (warm shapes, the other extra variant) are fine
            self._jitted.pop(self._sig_key(nodes, pods, extra), None)
            try:
                out = self._call(nodes, pods, extra)
                jax.block_until_ready(out)
            except Exception as err2:
                if _os.environ.get("MINISCHED_DEBUG_HEAL"):
                    print("[sharded-step] heal retry FAILED:",
                          type(err2).__name__, str(err2)[-200:], flush=True)
                raise
            if _os.environ.get("MINISCHED_DEBUG_HEAL"):
                print("[sharded-step] heal retry ok", flush=True)
            return out

    def _sig_key(self, nodes, pods, extra):
        # the mesh factoring is part of the key: a multi-engine process
        # can host differently-shaped meshes, and an executable compiled
        # for one must never serve another even at equal table shapes
        return (
            mesh_shape_key(self._mesh),
            extra is not None,
            tuple(
                (l.shape, str(l.dtype))
                for l in jax.tree_util.tree_leaves((nodes, pods, extra))
            ),
        )

    def _call(self, nodes, pods, extra=None):
        # one jax.jit OBJECT per full input signature — not just per
        # with/without-extra: sharing one jit across signatures let the
        # dispatch fast path land on the executable of ANOTHER signature
        # (the prewarm's warm tables vs live waves) once enough other
        # programs populated this process's jit caches — the
        # buffers-count fault handled in __call__.  jax would retrace per
        # signature anyway; distinct jit objects only pin the dispatch.
        key = self._sig_key(nodes, pods, extra)
        if key not in self._jitted:
            mesh, fn = self._mesh, self._fn
            shardings = [node_sharding(mesh, nodes), pod_sharding(mesh, pods)]
            if extra is not None:
                shardings.append(constraint_sharding(mesh, extra))

                def wrapped(nodes, pods, extra):
                    return fn(nodes, pods, extra=extra)

            else:

                def wrapped(nodes, pods):
                    return fn(nodes, pods)

            # keep_unused: argument PRUNING is the second half of the
            # order-dependent failure this class documents above — the
            # compiled program and the dispatch fast path can disagree on
            # the pruned argument set ("supplied 102 buffers but compiled
            # program expected 109") once other engines' builds populated
            # the jit caches.  Keeping every argument makes both sides
            # count the same buffers; the cost is shipping a few unused
            # columns to a virtual mesh.
            self._jitted[key] = jax.jit(
                wrapped,
                in_shardings=tuple(shardings),
                keep_unused=True,
            )
        # trace-time Pallas guard (see MeshPackedCaller): the first call
        # traces the sharded program; fast routes incompatible with GSPMD
        # must take their XLA tails
        from minisched_tpu.ops import fused as _fused

        with _fused.mesh_trace_guard():
            if extra is not None:
                return self._jitted[key](nodes, pods, extra)
            return self._jitted[key](nodes, pods)


def sharded_repair_step(
    mesh: Mesh,
    filter_plugins,
    pre_score_plugins,
    score_plugins,
    ctx,
    max_rounds: int = 16,
    with_diagnostics: bool = False,
    split_static: bool = True,
):
    """The conflict-repair wave loop (ops/repair.repair_wave_step) jitted
    with explicit shardings over ``mesh`` — same placement contract as
    ``sharded_wave_step`` but never double-books a node.  The accept rule's
    sort/segment scans run replicated per pod shard; the evaluate inside
    each round keeps the (pods × nodes) tiles sharded on both axes.
    ``with_diagnostics``/``split_static`` pass through to repair_wave_step
    (the live engine runs with diagnostics for per-pod failing-plugin
    requeue gating)."""
    from functools import partial

    from minisched_tpu.ops.repair import repair_wave_step

    step = partial(
        repair_wave_step,
        filter_plugins=tuple(filter_plugins),
        pre_score_plugins=tuple(pre_score_plugins),
        score_plugins=tuple(score_plugins),
        ctx=ctx,
        max_rounds=max_rounds,
        with_diagnostics=with_diagnostics,
        split_static=split_static,
    )
    return _CompiledShardedStep(mesh, step)


def sharded_scan_step(
    mesh: Mesh,
    filter_plugins,
    pre_score_plugins,
    score_plugins,
    ctx,
):
    """The bind-exact sequential scan (ops/sequential.scan_schedule) jitted
    over ``mesh``.  The scan is sequential over PODS by construction, so
    only the NODE axis parallelizes: the node table (and every node-axis
    constraint plane) shards across devices and each step's evaluation
    reduces over node shards via XLA collectives; pod-axis inputs stay
    replicated — a pod-sharded layout would turn every step's dynamic
    row slice into a cross-shard gather for no compute win."""
    from functools import partial

    from minisched_tpu.ops.sequential import scan_schedule

    step = partial(
        scan_schedule,
        filter_plugins=tuple(filter_plugins),
        pre_score_plugins=tuple(pre_score_plugins),
        score_plugins=tuple(score_plugins),
        ctx=ctx,
    )

    class _ScanStep(_CompiledShardedStep):
        def __call__(self, nodes, pods, extra=None):
            key = extra is not None
            if key not in self._jitted:
                node_sh = node_sharding(self._mesh, nodes)
                pod_rep = jax.tree_util.tree_map(
                    lambda _a: NamedSharding(self._mesh, P()), pods
                )
                shardings = [node_sh, pod_rep]
                if extra is not None:
                    # node-axis planes shard with the node table; pod-axis
                    # rows replicate (see docstring)
                    shardings.append(
                        scan_constraint_sharding(self._mesh, extra)
                    )

                    def wrapped(nodes, pods, extra):
                        return self._fn(nodes, pods, extra=extra)

                else:
                    def wrapped(nodes, pods):
                        return self._fn(nodes, pods)

                self._jitted[key] = jax.jit(
                    wrapped, in_shardings=tuple(shardings)
                )
            from minisched_tpu.ops import fused as _fused

            with _fused.mesh_trace_guard():
                if extra is not None:
                    # inputs re-placed per call (tables arrive host- or
                    # single-device-resident)
                    return self._jitted[key](nodes, pods, extra)
                return self._jitted[key](nodes, pods)

    return _ScanStep(mesh, step)


def sharded_wave_step(
    mesh: Mesh,
    filter_plugins,
    pre_score_plugins,
    score_plugins,
    ctx,
):
    """The full device step (evaluate + commit) jitted with explicit
    input/output shardings over ``mesh``.

    Input: (NodeTable sharded on nodes, PodTable sharded on pods).
    Output: (NodeTable same sharding, choice/best replicated per pod shard).
    XLA inserts the cross-node-shard argmax/argmin reductions and the
    scatter-add's collectives; the node table stays resident and sharded
    across waves (donated so updates are in-place).
    """
    from minisched_tpu.ops.state import wave_step

    chains = (
        tuple(filter_plugins),
        tuple(pre_score_plugins),
        tuple(score_plugins),
    )

    def step(nodes, pods, extra=None):
        return wave_step(nodes, pods, *chains, ctx, extra=extra)

    return _CompiledShardedStep(mesh, step)


class MeshPackedCaller:
    """The mesh-sharded twin of ``models.tables.PackedCaller`` — the live
    engine's ISSUE 7 tentpole path.

    Same single-program contract: the per-wave tables arrive as PACKED
    host buffers plus the device-resident static node columns, and the
    one jitted program unpacks them — but here the unpacked tables get
    explicit sharding constraints so GSPMD partitions the whole wave over
    the (pods × nodes) mesh: the flat buffers replicate (they are the
    wire format, a few MB), the static columns arrive already node-
    sharded, and XLA inserts the cross-shard argmax / tie-break-min /
    scatter collectives exactly as the dryrun steps above prove.

    ``scan_layout=True`` switches to the sequential-scan placement (pods
    replicated, only the node axis parallel — see sharded_scan_step).

    Inherits PackedCaller's dispatch-heal machinery; the jit-cache key
    additionally carries the mesh factoring (and the layout flag), so an
    executable compiled for one mesh never serves another."""

    def __init__(self, consumer, mesh: Mesh, scan_layout: bool = False):
        from minisched_tpu.models.tables import PackedCaller

        self._mesh = mesh
        self._scan_layout = scan_layout
        # composition via a single-inheritance subclass built here keeps
        # models/tables.py free of any jax.sharding import (host-build
        # code must stay importable without a mesh in sight)
        outer = self

        class _Caller(PackedCaller):
            def _key(self, pod_packed, node_static, node_agg_packed,
                     ex_schema):
                return (
                    mesh_shape_key(outer._mesh),
                    outer._scan_layout,
                ) + super()._key(
                    pod_packed, node_static, node_agg_packed, ex_schema
                )

            def _build_fn(self, key, pod_packed, node_static,
                          node_agg_packed, extra_packed):
                return outer._build_sharded_fn(
                    pod_packed, node_static, node_agg_packed, extra_packed
                )

        self._inner = _Caller(consumer)

    def __call__(self, pod_packed, node_static, node_agg_packed,
                 extra_packed=None):
        return self._inner(
            pod_packed, node_static, node_agg_packed, extra_packed
        )

    def _build_sharded_fn(self, pod_packed, node_static, node_agg_packed,
                          extra_packed):
        from minisched_tpu.models.constraints import ConstraintTables
        from minisched_tpu.models.tables import unpack_columns

        mesh = self._mesh
        scan_layout = self._scan_layout
        ex_schema = extra_packed.schema if extra_packed is not None else None
        pod_metas, pod_zeros = pod_packed.schema
        agg_metas, agg_zeros = node_agg_packed.schema
        consumer = self._inner._consumer
        replicated = NamedSharding(mesh, P())
        static_sh = static_col_shardings(mesh, node_static)
        # trace-time guard: kernels with mesh-incompatible fast routes
        # (the Pallas select_hosts tail cannot ride GSPMD partitioning
        # without a shard_map) consult this while the sharded program
        # traces — see ops.fused.tracing_under_mesh
        from minisched_tpu.ops import fused as _fused

        def run(pod_flat, agg_flat, ex_flat, static_cols):
            from minisched_tpu.models.tables import NodeTable, PodTable

            pods = PodTable(**unpack_columns(pod_flat, pod_metas, pod_zeros))
            nodes = NodeTable(
                **static_cols,
                **unpack_columns(agg_flat, agg_metas, agg_zeros),
            )
            extra = (
                ConstraintTables(**unpack_columns(ex_flat, *ex_schema))
                if ex_schema is not None
                else None
            )
            # the constraints are what make GSPMD split the compute: the
            # node table on the node axis (profile planes whole), pods on
            # the pod axis (or replicated for the scan layout), the
            # constraint planes per the authoritative layout map
            nodes = jax.lax.with_sharding_constraint(
                nodes, node_sharding(mesh, nodes)
            )
            if scan_layout:
                pods = jax.lax.with_sharding_constraint(
                    pods,
                    jax.tree_util.tree_map(lambda _a: replicated, pods),
                )
                if extra is not None:
                    extra = jax.lax.with_sharding_constraint(
                        extra, scan_constraint_sharding(mesh, extra)
                    )
            else:
                pods = jax.lax.with_sharding_constraint(
                    pods, pod_sharding(mesh, pods)
                )
                if extra is not None:
                    extra = jax.lax.with_sharding_constraint(
                        extra, constraint_sharding(mesh, extra)
                    )
            return consumer(pods, nodes, extra)

        jitted = jax.jit(
            run,
            # flat wire buffers replicate; statics arrive pre-sharded.
            # keep_unused: the compiled program and the dispatch fast
            # path must count the same buffers (see _CompiledShardedStep)
            in_shardings=(replicated, replicated, replicated, static_sh),
            keep_unused=True,
        )

        def traced(pod_flat, agg_flat, ex_flat, static_cols):
            with _fused.mesh_trace_guard():
                return jitted(pod_flat, agg_flat, ex_flat, static_cols)

        # expose clear_cache for the heal path
        traced.clear_cache = getattr(jitted, "clear_cache", lambda: None)
        return traced
