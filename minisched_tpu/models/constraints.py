"""Cross-pod constraint tables: the pod↔pod×node coupling arrays.

InterPodAffinity and PodTopologySpread couple pending pods to *assigned*
pods through label selectors and topology domains — the scheduling analog
of attention's token↔token coupling (SURVEY.md §5.7, §7 stage 8).  The
TPU-native factoring separates the two halves:

* **Host side** (this module): every distinct (namespaces, label-selector,
  topology-key) triple appearing in the wave's constraints becomes a
  **combo**; assigned pods are matched against each combo ONCE, and the
  per-node domain sums land in a dense ``combo_dsum[C, N]`` matrix.  The
  reverse direction (assigned pods' required anti-affinity) becomes a
  ``pod_matches_ex[P, T] × ex_domain[T, N]`` pair.

* **Device side** (plugins/interpodaffinity.py, podtopologyspread.py):
  kernels only gather combo rows and reduce — no string or object work.
  The reverse anti-affinity check is one bool matmul (MXU-shaped).

Semantics follow upstream v1.22 ``interpodaffinity`` / ``podtopologyspread``
(the reference's default roster enables both — scheduler_test.go:307-332),
including the affinity bootstrap special case (a pod matching its own
affinity term may land anywhere with the topology key when no pod matches
cluster-wide), spread's eligible-node gating, and SYMMETRIC preferred-term
scoring: assigned pods' preferred (and hard-weighted required) affinity
terms score toward incoming pods that match them, via the ``rev_weight``
plane (one ``pod_matches_combo @ rev_weight`` matmul on device).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from minisched_tpu.api.objects import LabelSelector, PodAffinityTerm
from minisched_tpu.models.tables import _register_table, pad_to

MAX_VOLUMES = 4  # PVC references per pod
MAX_TSC = 4  # topology spread constraints per pod
MAX_PA = 4  # required pod-affinity terms per pod
MAX_PAN = 4  # required pod-anti-affinity terms per pod
MAX_PPA = 8  # preferred (anti-)affinity terms per pod, both signs pooled

#: topology keys used by DoNotSchedule spread constraints must either have
#: at most this many distinct values (zone-like) or be unique-per-node
#: (hostname-like) — the two real-world shapes.  The one-hot domain
#: encoding the eligibility-aware filter kernel needs is O(D × N) per key.
MAX_DOMAINS = 64

TS_DO_NOT_SCHEDULE = 0
TS_SCHEDULE_ANYWAY = 1

#: packed-schema elision groups for the scan lane (pack_table
#: ``elide_groups``): columns whose zero-ness is a property of the
#: chunk's WORKLOAD, not of cluster state — each group elides as a unit
#: only when every member is all-zero, so e.g. a spread-only burst ships
#: no affinity/volume columns and XLA folds those whole per-step lanes
#: out of the blocked-scan program.  Gating counts (``*_n``) are members,
#: so zero-materialized values always read as "no constraints"
#: (TS_DO_NOT_SCHEDULE == 0 is safe: ``ts_n`` == 0 masks every slot).
SCAN_ELIDE_GROUPS = (
    (
        "pan_combo", "pan_n", "ppa_combo", "ppa_w", "ppa_n",
        "pa_combo", "pa_self", "pa_n",
    ),
    (
        "pod_claims", "pod_claim_valid", "pod_n_vols", "pod_vols_fam",
        "pod_missing", "claim_mask", "claim_zone_ok", "claim_cnt",
        "claim_family", "claim_ro",
    ),
    ("ts_combo", "ts_skew", "ts_mode", "ts_n"),
)

#: capacity quantum for the combo/ex-term/claim/volume axes — every
#: distinct padded size is a separate compiled executable (see the combo
#: matrices comment in build_constraint_tables)
CAP_QUANTUM = 32


@_register_table
@dataclass
class ConstraintTables:
    """Device-side cross-pod coupling state for one wave."""

    # per-combo (selector-group × topology-key), shape (C, N) / (C,)
    combo_dsum: Any  # i32[C, N] matching assigned pods in n's topo domain
    combo_haskey: Any  # bool[C, N] node carries the combo's topology key
    combo_global: Any  # i32[C] matching assigned pods cluster-wide
    combo_here: Any  # i32[C, N] matching assigned pods ON node n
    combo_key: Any  # i32[C] index into the topology-key axis below
    # per-topology-key domain encoding (spread's eligibility-aware filter:
    # upstream counts domains only over nodes passing the pod's
    # nodeSelector/required affinity, so domain sums are per-pod on device)
    topo_domain: Any  # i32[K, N] dense domain id; == D sentinel when keyless
    topo_onehot: Any  # bool[K, D, N] node ∈ domain d of key k (zone-like keys)
    topo_unique: Any  # bool[K] key is unique-per-node (hostname-like)
    # incoming pods' topology spread constraints
    ts_combo: Any  # i32[P, MAX_TSC]
    ts_skew: Any  # i32[P, MAX_TSC] max skew
    ts_mode: Any  # i32[P, MAX_TSC] 0=DoNotSchedule 1=ScheduleAnyway
    ts_n: Any  # i32[P]
    # incoming pods' required pod affinity
    pa_combo: Any  # i32[P, MAX_PA]
    pa_self: Any  # bool[P, MAX_PA] pod matches its own term selector
    pa_n: Any  # i32[P]
    # incoming pods' required pod anti-affinity
    pan_combo: Any  # i32[P, MAX_PAN]
    pan_n: Any  # i32[P]
    # incoming pods' preferred terms (weight < 0 encodes anti-affinity)
    ppa_combo: Any  # i32[P, MAX_PPA]
    ppa_w: Any  # i32[P, MAX_PPA]
    ppa_n: Any  # i32[P]
    # reverse direction: assigned pods' required anti-affinity terms
    ex_domain: Any  # bool[T, N] nodes in the owning pod's topo domain
    pod_matches_ex: Any  # bool[P, T] pending pod matches term selector
    # symmetric preferred scoring (upstream v1.22 interpodaffinity
    # PreScore): assigned pods' preferred affinity (+w) / anti-affinity
    # (−w) terms and required affinity terms (×HARD_POD_AFFINITY_WEIGHT),
    # accumulated as signed weight over the owner's topology domain per
    # combo.  Scored as pod_matches_combo @ rev_weight (one int matmul).
    rev_weight: Any  # i32[C, N] Σ signed term weights whose domain holds n
    # sequential-scan support (ops/sequential.py): which pending pods match
    # each combo's selector — commits update the combo aggregates with it —
    # and the exclusion plane accumulated from committed pods' required
    # anti-affinity terms (all-False outside the scan)
    pod_matches_combo: Any  # bool[P, C]
    combo_excl: Any  # bool[C, N] matching pods banned (committed pod's
    #                  anti-affinity domain)
    # volume coupling (VolumeBinding / NodeVolumeLimits)
    claim_mask: Any  # bool[C2, N] nodes OK for referenced claim c (bound
    #                  PV's node labels, or ∃ bindable free PV)
    pod_claims: Any  # i32[P, MAX_VOLUMES] indices into claim_mask
    vol_ok: Any  # bool[P] every referenced PVC exists
    pod_n_vols: Any  # i32[P] volumes this pod mounts
    # volume roster planes (VolumeZone / VolumeRestrictions / limit family)
    claim_zone_ok: Any  # bool[C2, N] bound PV's zone labels match node
    pod_vols_fam: Any  # i32[P, F] pod's DISTINCT volumes per driver family
    #                    (+ unresolvable mounts, counted generic per-mount)
    node_vols_fam: Any  # i32[F, N] distinct assigned volumes per family
    # per-volume mount state, one row per counting key — a bound claim's
    # PersistentVolume, or an unbound claim itself (claims bound to one PV
    # share a row; upstream's attach limits count unique volumes, not
    # mounts).  The repair loop carries vol_any/vol_rw across rounds so
    # intra-wave conflicts are enforced, not just assigned-pod ones.
    # Row Vd-1 is a dummy scatter target.
    claim_vol: Any  # i32[C2] volume row of claim c; -1 when unbound
    #                 (VolumeRestrictions: conflicts need a PV identity)
    claim_cnt: Any  # i32[C2] counting row of claim c (always >= 0)
    claim_family: Any  # i32[C2] driver family of claim c
    claim_ro: Any  # bool[C2] the claim mounts its volume read-only
    pod_claim_valid: Any  # bool[P, MAX_VOLUMES] slot holds a real claim
    pod_missing: Any  # i32[P] mounts whose PVC doesn't exist (generic)
    vol_any: Any  # bool[Vd, N] some assigned pod on n mounts volume v
    vol_rw: Any  # bool[Vd, N] ... with a writable mount


#: field → (kind, axis-role) — the ONE authority on how each plane is laid
#: out: "first"/pods = leading pod dim, "last"/nodes = trailing node dim,
#: "rep" = small metadata.  parallel/sharding.py turns this into mesh
#: shardings; ops/sequential.py uses the pod-axis set to slice per-pod rows.
CONSTRAINT_AXES = {
    "combo_dsum": ("last", "nodes"),
    "combo_haskey": ("last", "nodes"),
    "combo_here": ("last", "nodes"),
    "combo_global": ("rep", None),
    "combo_key": ("rep", None),
    "topo_domain": ("last", "nodes"),
    "topo_onehot": ("last", "nodes"),
    "topo_unique": ("rep", None),
    "ex_domain": ("last", "nodes"),
    "pod_matches_ex": ("first", "pods"),
    "rev_weight": ("last", "nodes"),
    "pod_matches_combo": ("first", "pods"),
    "combo_excl": ("last", "nodes"),
    "claim_mask": ("last", "nodes"),
    "claim_zone_ok": ("last", "nodes"),
    "node_vols_fam": ("last", "nodes"),
    "pod_vols_fam": ("first", "pods"),
    "claim_vol": ("rep", None),
    "claim_cnt": ("rep", None),
    "claim_family": ("rep", None),
    "claim_ro": ("rep", None),
    "pod_claim_valid": ("first", "pods"),
    "pod_missing": ("first", "pods"),
    "vol_any": ("last", "nodes"),
    "vol_rw": ("last", "nodes"),
    # per-pod constraint rows (default shape: leading pod dim)
    "ts_combo": ("first", "pods"),
    "ts_skew": ("first", "pods"),
    "ts_mode": ("first", "pods"),
    "ts_n": ("first", "pods"),
    "pa_combo": ("first", "pods"),
    "pa_self": ("first", "pods"),
    "pa_n": ("first", "pods"),
    "pan_combo": ("first", "pods"),
    "pan_n": ("first", "pods"),
    "ppa_combo": ("first", "pods"),
    "ppa_w": ("first", "pods"),
    "ppa_n": ("first", "pods"),
    "pod_claims": ("first", "pods"),
    "vol_ok": ("first", "pods"),
    "pod_n_vols": ("first", "pods"),
}

#: fields with a leading pod dimension (sliced per step by the scan)
POD_AXIS_FIELDS = tuple(
    name for name, (kind, _) in CONSTRAINT_AXES.items() if kind == "first"
)

#: fields the sequential scan carries and updates as pods commit
SCAN_CARRIED_FIELDS = (
    "combo_dsum", "combo_here", "combo_global", "combo_excl", "rev_weight",
    "vol_any", "vol_rw", "node_vols_fam",
)

#: upstream HardPodAffinityWeight default (scheduler API defaulting): the
#: weight at which EXISTING pods' required affinity terms score toward an
#: incoming pod that matches them (symmetric hard-affinity scoring)
HARD_POD_AFFINITY_WEIGHT = 1


def rev_pref_terms_of(p: Any):
    """The (namespaces, selector, topology-key, signed weight) stream of an
    ASSIGNED pod's scoring-relevant terms toward future incoming pods —
    upstream v1.22 interpodaffinity's symmetric PreScore set: preferred
    affinity (+w), preferred anti-affinity (−w), required affinity
    (×HARD_POD_AFFINITY_WEIGHT).  ONE definition shared by the from-scratch
    walk, the incremental index, and the scalar plugin."""
    aff = p.spec.affinity
    if aff is None:
        return
    ns = p.metadata.namespace
    pa = aff.pod_affinity
    if pa is not None:
        for term in pa.required:
            yield (
                _term_namespaces(term, ns), term.label_selector,
                term.topology_key, HARD_POD_AFFINITY_WEIGHT,
            )
        for wt in pa.preferred:
            yield (
                _term_namespaces(wt.term, ns), wt.term.label_selector,
                wt.term.topology_key, wt.weight,
            )
    pan = aff.pod_anti_affinity
    if pan is not None:
        for wt in pan.preferred:
            yield (
                _term_namespaces(wt.term, ns), wt.term.label_selector,
                wt.term.topology_key, -wt.weight,
            )


def _selector_sig(sel: LabelSelector) -> Tuple:
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple(
            (r.key, r.operator, tuple(r.values)) for r in sel.match_expressions
        ),
    )


def _term_namespaces(term: PodAffinityTerm, pod_ns: str) -> Tuple[str, ...]:
    return tuple(sorted(term.namespaces)) if term.namespaces else (pod_ns,)


class _ComboRegistry:
    def __init__(self):
        self.ids: Dict[Tuple, int] = {}
        self.combos: List[Tuple[Tuple[str, ...], LabelSelector, str]] = []

    def get(self, namespaces: Tuple[str, ...], sel: LabelSelector, topo: str) -> int:
        key = (namespaces, _selector_sig(sel), topo)
        if key not in self.ids:
            self.ids[key] = len(self.combos)
            self.combos.append((namespaces, sel, topo))
        return self.ids[key]


def _topo_key_axis(combos, nodes) -> Tuple[
    Dict[str, int], Any, Any, Any, Any, List[Dict[str, int]]
]:
    """Dense domain encoding per distinct topology key.

    Returns (key→index, topo_domain i32[K, N], topo_onehot bool[K, D, N],
    topo_unique bool[K], val_id i32[K, N], value→id dicts per key).  Keys
    whose cardinality exceeds MAX_DOMAINS must be unique-per-node
    (hostname-like) — their one-hot plane is unused (the kernel
    short-circuits to per-node counts); anything in between raises.
    ``val_id[k, i]`` is node i's label-VALUE id under key k (−1 when the
    node lacks the key) — the host-side gather axis that lets the combo
    planes fill without a per-combo × per-node Python loop.
    """
    N = len(nodes)
    keys = sorted({topo for (_, _, topo) in combos})
    key_ids = {k: i for i, k in enumerate(keys)}
    # K is an executable shape too — quantize to 4 so adding a second
    # topology key doesn't recompile (the onehot plane costs K×D×N bools)
    K = pad_to(max(len(keys), 1), 4)
    values: List[Dict[str, int]] = [{} for _ in range(K)]
    vals_per_node: List[List[Optional[int]]] = [[None] * N for _ in range(K)]
    for k, key in enumerate(keys):
        for i, node in enumerate(nodes):
            v = node.metadata.labels.get(key)
            if v is None:
                continue
            if v not in values[k]:
                values[k][v] = len(values[k])
            vals_per_node[k][i] = values[k][v]
    unique = np.zeros(K, bool)
    for k, key in enumerate(keys):
        n_domains = len(values[k])
        n_keyed = sum(1 for v in vals_per_node[k] if v is not None)
        unique[k] = n_domains == n_keyed and n_domains > 0
        if n_domains > MAX_DOMAINS and not unique[k]:
            raise ValueError(
                f"topology key {key!r}: {n_domains} domains exceed "
                f"MAX_DOMAINS={MAX_DOMAINS} and the key is not unique-per-node"
            )
    D = MAX_DOMAINS
    Ncap = N  # caller re-pads below
    topo_domain = np.full((K, Ncap), D, np.int32)
    topo_onehot = np.zeros((K, D, Ncap), bool)
    val_id = np.full((K, Ncap), -1, np.int32)
    for k in range(len(keys)):
        for i, dom in enumerate(vals_per_node[k]):
            if dom is None:
                continue
            val_id[k, i] = dom
            if unique[k]:
                topo_domain[k, i] = 0  # unused by the unique path; != D marks haskey
            else:
                topo_domain[k, i] = dom
                topo_onehot[k, dom, i] = True
    return key_ids, topo_domain, topo_onehot, unique, val_id, values


def _matches(sel: LabelSelector, namespaces: Tuple[str, ...], pod: Any) -> bool:
    return pod.metadata.namespace in namespaces and sel.matches(pod.metadata.labels)


def _sig_groups(pods: Sequence[Any]):
    """Group pods by their (namespace, labels) signature.

    Selector matching is a pure function of that signature, and real
    populations are replica sets — thousands of pods collapse to a
    handful of signatures, so selector × pod matching can run selector ×
    GROUP (the per-combo fold over assumed/pending pods was ~0.5s per
    scan chunk at 32 combos × 16k pods).  Returns (representative pods,
    int32 group id per pod)."""
    group_of: Dict[Tuple, int] = {}
    reps: List[Any] = []
    ids = np.empty(len(pods), np.int32)
    for i, p in enumerate(pods):
        sig = (
            p.metadata.namespace,
            tuple(sorted(p.metadata.labels.items())),
        )
        g = group_of.get(sig)
        if g is None:
            g = group_of[sig] = len(reps)
            reps.append(p)
        ids[i] = g
    return reps, ids


def _claim_zone_row(pvc: Any, pv_by_name: Dict, nodes: Sequence[Any], zone_ok) -> List[bool]:
    """VolumeZone's per-node verdict for one claim: unbound claims pass
    everywhere (VolumeBinding owns them), a dangling volume_name passes
    nowhere, bound claims defer to the plugin's pv_zone_ok."""
    if not pvc.spec.volume_name:
        return [True] * len(nodes)
    pv = pv_by_name.get(pvc.spec.volume_name)
    if pv is None:
        return [False] * len(nodes)
    return [zone_ok(pv, n) for n in nodes]


def build_constraint_tables(
    pending_pods: Sequence[Any],
    nodes: Sequence[Any],
    assigned_pods: Sequence[Any],
    pod_capacity: Optional[int] = None,
    node_capacity: Optional[int] = None,
    pvcs: Sequence[Any] = (),
    pvs: Sequence[Any] = (),
    scan_planes: bool = True,
    index: Any = None,
    extra_assigned: Sequence[Any] = (),
    device: bool = True,
    elide_zeros: bool = True,
    elide_groups: Tuple[Tuple[str, ...], ...] = (),
):
    """Build the wave's coupling tables.

    ``nodes`` must be in the SAME order as the NodeTable build (name-sorted)
    so node indices line up.  ``assigned_pods`` are pods with
    ``spec.node_name`` set; others are ignored.  ``pvcs``/``pvs`` feed the
    volume coupling planes (VolumeBinding / NodeVolumeLimits).

    ``scan_planes``: build ``pod_matches_combo`` (the O(P × selector-groups)
    pending-pod matching the sequential scan's commit updates need).  On by
    default — all-False would silently break scan parity — wave-only
    callers (DeviceScheduler, bench wave paths) pass False to skip the
    host-side matching cost.

    ``index``: a ``constraint_index.ConstraintIndex`` — the assigned-pod
    planes then come from its event-maintained aggregates in
    O(nonzero + planes) instead of walking ``assigned_pods`` (pass ``()``).
    ``extra_assigned``: assigned pods the index hasn't seen yet (the
    engine's still-assumed binds), folded through the same per-pod logic
    the from-scratch walk uses.
    """
    P = pod_capacity or pad_to(len(pending_pods))
    N = node_capacity or pad_to(len(nodes))
    node_idx = {n.metadata.name: i for i, n in enumerate(nodes)}
    assigned = [p for p in assigned_pods if p.spec.node_name in node_idx]
    if index is not None:
        # the fold below re-applies the from-scratch per-pod logic to just
        # these; pods on nodes outside this wave's view are skipped the
        # same way the assigned filter above skips them
        extra_assigned = [
            p for p in extra_assigned if p.spec.node_name in node_idx
        ]

    reg = _ComboRegistry()
    # sparse rows: (pod index, row) only for pods that CARRY cross-pod
    # constraints — a plain 16k-pod wave walked three O(P) loops doing
    # nothing per pod (~150ms/wave of host time at config5 scale)
    pod_rows: List[Tuple[int, Dict[str, List]]] = []
    for pi, pod in enumerate(pending_pods):
        aff = pod.spec.affinity
        if not pod.spec.topology_spread_constraints and (
            aff is None
            or (aff.pod_affinity is None and aff.pod_anti_affinity is None)
        ):
            continue
        row: Dict[str, List] = {"ts": [], "pa": [], "pan": [], "ppa": []}
        ns = pod.metadata.namespace
        for c in pod.spec.topology_spread_constraints:
            cid = reg.get((ns,), c.label_selector, c.topology_key)
            mode = (
                TS_DO_NOT_SCHEDULE
                if c.when_unsatisfiable == "DoNotSchedule"
                else TS_SCHEDULE_ANYWAY
            )
            row["ts"].append((cid, c.max_skew, mode))
        aff = pod.spec.affinity
        if aff is not None and aff.pod_affinity is not None:
            for term in aff.pod_affinity.required:
                nss = _term_namespaces(term, ns)
                cid = reg.get(nss, term.label_selector, term.topology_key)
                row["pa"].append((cid, _matches(term.label_selector, nss, pod)))
            for wt in aff.pod_affinity.preferred:
                nss = _term_namespaces(wt.term, ns)
                cid = reg.get(nss, wt.term.label_selector, wt.term.topology_key)
                row["ppa"].append((cid, wt.weight))
        if aff is not None and aff.pod_anti_affinity is not None:
            for term in aff.pod_anti_affinity.required:
                nss = _term_namespaces(term, ns)
                cid = reg.get(nss, term.label_selector, term.topology_key)
                row["pan"].append(cid)
            for wt in aff.pod_anti_affinity.preferred:
                nss = _term_namespaces(wt.term, ns)
                cid = reg.get(nss, wt.term.label_selector, wt.term.topology_key)
                row["ppa"].append((cid, -wt.weight))
        for kind, cap in (("ts", MAX_TSC), ("pa", MAX_PA), ("pan", MAX_PAN),
                          ("ppa", MAX_PPA)):
            if len(row[kind]) > cap:
                raise ValueError(
                    f"pod {pod.metadata.name}: >{cap} {kind} constraints"
                )
        pod_rows.append((pi, row))

    # --- symmetric preferred contributions (assigned pods' terms) ----------
    # cid → topology value → Σ signed weight; combos register here too, so
    # C covers them before the matrices are allocated
    rev_vals: Dict[int, Dict[str, int]] = {}

    def _collect_rev(p: Any) -> None:
        labels = nodes[node_idx[p.spec.node_name]].metadata.labels
        for nss, sel, topo, w in rev_pref_terms_of(p):
            val = labels.get(topo)
            if val is None:
                continue  # owner's node lacks the key: no domain to score
            cid = reg.get(nss, sel, topo)
            vals = rev_vals.setdefault(cid, {})
            vals[val] = vals.get(val, 0) + w

    if index is not None:
        for key, sel_obj, vals in index.rev_pref_list():
            nss_k, _sig, topo_k = key
            cid = reg.get(nss_k, sel_obj, topo_k)
            dst = rev_vals.setdefault(cid, {})
            for val, w in vals.items():
                dst[val] = dst.get(val, 0) + w
        for p in extra_assigned:
            _collect_rev(p)
    else:
        for p in assigned:
            _collect_rev(p)

    # --- combo matrices ----------------------------------------------------
    # capacity quantum 32 (not 8): C/T/C2/Vd are EXECUTABLE shapes — a
    # wave whose combo count steps over a small quantum recompiles the
    # whole evaluator mid-run (~30s on the tunnel).  32 keeps one shape
    # for realistic rosters at the cost of a few spare 1-MB planes.
    C = pad_to(max(len(reg.combos), 1), CAP_QUANTUM)
    combo_dsum = np.zeros((C, N), np.int32)
    combo_haskey = np.zeros((C, N), bool)
    combo_global = np.zeros(C, np.int32)
    combo_here = np.zeros((C, N), np.int32)
    combo_key = np.zeros(C, np.int32)
    key_ids, topo_domain_, topo_onehot_, topo_unique, val_id_, key_vals = (
        _topo_key_axis(reg.combos, nodes)
    )
    # pad the node axis of the key-domain planes to capacity N
    K, D = topo_onehot_.shape[0], topo_onehot_.shape[1]
    topo_domain = np.full((K, N), D, np.int32)
    topo_domain[:, : topo_domain_.shape[1]] = topo_domain_
    topo_onehot = np.zeros((K, D, N), bool)
    topo_onehot[:, :, : topo_onehot_.shape[2]] = topo_onehot_
    pod_matches_combo = np.zeros((P, C), bool)
    combo_excl = np.zeros((C, N), bool)
    rev_weight = np.zeros((C, N), np.int32)
    # scan mode matches every combo (commits update aggregates with it);
    # wave mode matches only the rev-active combos — the symmetric score
    # needs "does this pending pod match the assigned pod's term", and a
    # wave over a cluster with no such terms pays nothing
    match_combos = (
        range(len(reg.combos)) if scan_planes else sorted(rev_vals)
    )
    if match_combos:
        # combos sharing (namespaces, selector) across topology keys match
        # identically — compute each distinct group once, against pod
        # SIGNATURES rather than pods (replicas share label sets)
        p_reps, p_gid = _sig_groups(pending_pods)
        match_cache: Dict[Tuple, Any] = {}
        for cid in match_combos:
            nss, sel, _topo = reg.combos[cid]
            mkey = (nss, _selector_sig(sel))
            row = match_cache.get(mkey)
            if row is None:
                grp = np.fromiter(
                    (_matches(sel, nss, r) for r in p_reps),
                    dtype=bool,
                    count=len(p_reps),
                )
                row = match_cache[mkey] = grp[p_gid]
            pod_matches_combo[: len(pending_pods), cid] = row
    n_real = len(nodes)
    # assumed/assigned-pod fold by signature group: sig → {node: count} —
    # each combo then matches the handful of signatures, not every pod.
    # With an index the planes already hold the indexed population, so
    # only the assume-cache extras fold here; without one, all assigned.
    _fold_src = extra_assigned if index is not None else assigned
    a_reps, a_nodes = [], []
    if _fold_src:
        a_reps, a_gid = _sig_groups(_fold_src)
        a_nodes = [dict() for _ in a_reps]
        for g, p in zip(a_gid, _fold_src):
            d = a_nodes[g]
            node = p.spec.node_name
            d[node] = d.get(node, 0) + 1
    for cid, (nss, sel, topo) in enumerate(reg.combos):
        k = key_ids[topo]
        combo_key[cid] = k
        domain_count: Dict[str, int] = {}
        if index is not None:
            # O(nonzero): per-node counts from the index, assumed pods
            # folded through the same matcher; domain sums derive from the
            # CURRENT node labels so label churn self-heals
            here = index.combo_aggregate(nss, sel, topo)
            for g, rep in enumerate(a_reps):
                if _matches(sel, nss, rep):
                    for node, cnt in a_nodes[g].items():
                        here[node] = here.get(node, 0) + cnt
            total = 0
            for node_name, cnt in here.items():
                i = node_idx.get(node_name)
                if i is None:
                    continue  # node outside this wave's view
                total += cnt
                combo_here[cid, i] = cnt
                val = nodes[i].metadata.labels.get(topo)
                if val is not None:
                    domain_count[val] = domain_count.get(val, 0) + cnt
            combo_global[cid] = total
        else:
            total = 0
            for g, rep in enumerate(a_reps):
                if not _matches(sel, nss, rep):
                    continue
                for node, cnt in a_nodes[g].items():
                    i = node_idx[node]
                    total += cnt
                    combo_here[cid, i] += cnt
                    val = nodes[i].metadata.labels.get(topo)
                    if val is not None:
                        domain_count[val] = domain_count.get(val, 0) + cnt
            combo_global[cid] = total
        # haskey/dsum/rev rows as gathers through the node→value-id axis
        # (a per-combo × per-node Python loop here cost ~1s per scan chunk
        # at 32 combos × 10k nodes)
        rv = rev_vals.get(cid)
        vid = val_id_[k, :n_real]  # (n_real,) value id, -1 absent
        has = vid >= 0
        combo_haskey[cid, :n_real] = has
        vals_k = key_vals[k]
        safe_vid = np.where(has, vid, 0)
        if domain_count:
            cnt_by_vid = np.zeros(max(len(vals_k), 1), np.int32)
            for val, c in domain_count.items():
                vi = vals_k.get(val)
                if vi is not None:
                    cnt_by_vid[vi] = c
            combo_dsum[cid, :n_real] = np.where(has, cnt_by_vid[safe_vid], 0)
        if rv:
            rw_by_vid = np.zeros(max(len(vals_k), 1), np.int32)
            for val, w in rv.items():
                vi = vals_k.get(val)
                if vi is not None:
                    rw_by_vid[vi] = w
            rev_weight[cid, :n_real] = np.where(has, rw_by_vid[safe_vid], 0)

    # --- reverse anti-affinity terms (deduped: replicas sharing one term
    # and one topology domain collapse to a single row) --------------------
    ex_ids: Dict[Tuple, int] = {}
    ex_terms: List[Tuple[Tuple[str, ...], LabelSelector, str, str]] = []

    def _add_ex_terms_of(p: Any) -> None:
        aff = p.spec.affinity
        if aff is None or aff.pod_anti_affinity is None:
            return
        for term in aff.pod_anti_affinity.required:
            owner_val = nodes[node_idx[p.spec.node_name]].metadata.labels.get(
                term.topology_key
            )
            if owner_val is None:
                continue  # owner's node lacks the key: term can't be violated
            nss = _term_namespaces(term, p.metadata.namespace)
            key = (nss, _selector_sig(term.label_selector), term.topology_key,
                   owner_val)
            if key not in ex_ids:
                ex_ids[key] = len(ex_terms)
                ex_terms.append(
                    (nss, term.label_selector, term.topology_key, owner_val)
                )

    if index is not None:
        for key, sel_obj, owner_nodes in index.ex_term_list():
            if key in ex_ids or not any(n in node_idx for n in owner_nodes):
                continue
            nss_k, _sig, topo_k, owner_val = key
            ex_ids[key] = len(ex_terms)
            ex_terms.append((nss_k, sel_obj, topo_k, owner_val))
        for p in extra_assigned:
            _add_ex_terms_of(p)
    else:
        for p in assigned:
            _add_ex_terms_of(p)
    T = pad_to(max(len(ex_terms), 1), CAP_QUANTUM)
    ex_domain = np.zeros((T, N), bool)
    pod_matches_ex = np.zeros((P, T), bool)
    for t, (nss, sel, topo, owner_val) in enumerate(ex_terms):
        for i, node in enumerate(nodes):
            if node.metadata.labels.get(topo) == owner_val:
                ex_domain[t, i] = True
        for i, pod in enumerate(pending_pods):
            pod_matches_ex[i, t] = _matches(sel, nss, pod)

    # --- volume coupling ---------------------------------------------------
    # feasibility semantics come from ONE place each — the VolumeBinding /
    # VolumeZone / VolumeRestrictions / volume-limit plugins — so the
    # host-side tables can never drift from the scalar filters
    from minisched_tpu.plugins.volumebinding import claim_node_mask
    from minisched_tpu.plugins.volumelimits import FAMILIES, volume_family
    from minisched_tpu.plugins.volumezone import pv_zone_ok

    pvc_by_key = {pvc.metadata.key: pvc for pvc in pvcs}
    pv_by_name = {pv.metadata.name: pv for pv in pvs}
    # claims mounted by assigned pods, grouped per node (restriction and
    # family counting both walk these) — skipped on the index path, which
    # supplies the equivalent per-node aggregates below
    node_claims: List[List[Any]] = [[] for _ in range(len(nodes))]
    if index is None:
        for p in assigned:
            for vol in p.spec.volumes:
                opvc = pvc_by_key.get(f"{p.metadata.namespace}/{vol}")
                node_claims[node_idx[p.spec.node_name]].append(opvc)

    # counting key of a claim: its bound PV, else the claim itself —
    # upstream's attach limits count unique VOLUMES, so claims sharing a
    # PV share a row (tuple-keyed to keep the two namespaces apart)
    def count_key(pvc: Any) -> Tuple[str, str]:
        if pvc.spec.volume_name:
            return ("pv", pvc.spec.volume_name)
        return ("pvc", pvc.metadata.key)

    vol_ids: Dict[Tuple[str, str], int] = {}  # counting key → vol-plane row

    def vol_id(key: Tuple[str, str]) -> int:
        if key not in vol_ids:
            vol_ids[key] = len(vol_ids)
        return vol_ids[key]

    claim_ids: Dict[str, int] = {}
    claim_rows: List[List[bool]] = []
    zone_rows: List[List[bool]] = []
    claim_vol_l: List[int] = []
    claim_cnt_l: List[int] = []
    claim_fam_l: List[int] = []
    claim_ro_l: List[bool] = []
    vol_ok = np.zeros(P, bool)
    pod_claims = np.zeros((P, MAX_VOLUMES), np.int32)
    pod_claim_valid = np.zeros((P, MAX_VOLUMES), bool)
    pod_missing = np.zeros(P, np.int32)
    pod_n_vols = np.zeros(P, np.int32)
    F = len(FAMILIES)
    pod_vols_fam = np.zeros((P, F), np.int32)
    # a pod with no volumes trivially passes (ok=True, zero counts) — only
    # volume-carrying pods pay the per-claim walk
    vol_ok[: len(pending_pods)] = True
    for i, pod in enumerate(pending_pods):
        vols = pod.spec.volumes
        if not vols:
            continue
        if len(vols) > MAX_VOLUMES:
            raise ValueError(f"pod {pod.metadata.name}: >{MAX_VOLUMES} volumes")
        pod_n_vols[i] = len(vols)
        ok = True
        seen_keys: set = set()
        for j, vol in enumerate(vols):
            key = f"{pod.metadata.namespace}/{vol}"
            if key not in pvc_by_key:
                ok = False
                pod_missing[i] += 1
                pod_vols_fam[i, volume_family(None, pv_by_name)] += 1
                continue
            pvc = pvc_by_key[key]
            ck = count_key(pvc)
            if ck not in seen_keys:  # distinct volumes, not mounts
                seen_keys.add(ck)
                pod_vols_fam[i, volume_family(pvc, pv_by_name)] += 1
            if key not in claim_ids:
                claim_ids[key] = len(claim_rows)
                claim_rows.append(claim_node_mask(pvc, pvs, nodes))
                zone_rows.append(_claim_zone_row(pvc, pv_by_name, nodes, pv_zone_ok))
                row = vol_id(ck)
                claim_cnt_l.append(row)
                claim_vol_l.append(row if pvc.spec.volume_name else -1)
                claim_fam_l.append(volume_family(pvc, pv_by_name))
                claim_ro_l.append(pvc.spec.read_only)
            pod_claims[i, j] = claim_ids[key]
            pod_claim_valid[i, j] = True
        vol_ok[i] = ok
    C2 = pad_to(max(len(claim_rows), 1), CAP_QUANTUM)
    claim_mask = np.zeros((C2, N), bool)
    claim_zone_ok = np.zeros((C2, N), bool)
    claim_vol = np.full(C2, -1, np.int32)
    claim_cnt = np.zeros(C2, np.int32)
    claim_family = np.zeros(C2, np.int32)
    claim_ro = np.zeros(C2, bool)
    for cid, row in enumerate(claim_rows):
        claim_mask[cid, : len(row)] = row
        claim_zone_ok[cid, : len(row)] = zone_rows[cid]
        claim_vol[cid] = claim_vol_l[cid]
        claim_cnt[cid] = claim_cnt_l[cid]
        claim_family[cid] = claim_fam_l[cid]
        claim_ro[cid] = claim_ro_l[cid]
    # per-volume mount state from assigned pods: one pre-pass over node
    # claims (O(assigned mounts)), rows only for volumes the wave's claims
    # reference; last row stays a dummy scatter target
    Vd = pad_to(len(vol_ids) + 1, CAP_QUANTUM)
    vol_any = np.zeros((Vd, N), bool)
    vol_rw = np.zeros((Vd, N), bool)
    node_vols_fam = np.zeros((F, N), np.int32)
    if index is not None:
        # O(nonzero): the index's per-node volume state, assumed pods
        # folded through the wave's own PVC/PV view
        nvs = index.node_vol_state()
        for p in extra_assigned:
            nv = nvs.setdefault(p.spec.node_name, {})
            for j, vol in enumerate(p.spec.volumes):
                opvc = pvc_by_key.get(f"{p.metadata.namespace}/{vol}")
                if opvc is None:
                    ent = nv.setdefault(
                        ("miss", p.metadata.uid, j),
                        [0, 0, volume_family(None, pv_by_name)],
                    )
                    ent[0] += 1
                    continue
                ck = count_key(opvc)
                fam = volume_family(opvc, pv_by_name)
                ent = nv.setdefault(ck, [0, 0, fam])
                ent[0] += 1
                ent[2] = fam
                if opvc.spec.volume_name and not opvc.spec.read_only:
                    ent[1] += 1
        for node_name, entries in nvs.items():
            n = node_idx.get(node_name)
            if n is None:
                continue
            for vk, (mounts, rw_mounts, fam) in entries.items():
                if mounts <= 0:
                    continue
                node_vols_fam[fam, n] += 1  # distinct volumes per node
                v = vol_ids.get(vk)
                if v is not None:
                    vol_any[v, n] = True
                    if rw_mounts > 0:
                        vol_rw[v, n] = True
    else:
        for n, claims in enumerate(node_claims):
            seen_node: set = set()
            for opvc in claims:
                if opvc is None:
                    # no identity: each unresolvable mount counts by itself
                    node_vols_fam[0, n] += 1
                    continue
                ck = count_key(opvc)
                if ck not in seen_node:  # distinct volumes per node
                    seen_node.add(ck)
                    node_vols_fam[volume_family(opvc, pv_by_name), n] += 1
                v = vol_ids.get(ck)
                if v is not None:
                    vol_any[v, n] = True
                    if opvc.spec.volume_name and not opvc.spec.read_only:
                        vol_rw[v, n] = True

    # --- per-pod constraint arrays ----------------------------------------
    ts_combo = np.zeros((P, MAX_TSC), np.int32)
    ts_skew = np.zeros((P, MAX_TSC), np.int32)
    ts_mode = np.zeros((P, MAX_TSC), np.int32)
    ts_n = np.zeros(P, np.int32)
    pa_combo = np.zeros((P, MAX_PA), np.int32)
    pa_self = np.zeros((P, MAX_PA), bool)
    pa_n = np.zeros(P, np.int32)
    pan_combo = np.zeros((P, MAX_PAN), np.int32)
    pan_n = np.zeros(P, np.int32)
    ppa_combo = np.zeros((P, MAX_PPA), np.int32)
    ppa_w = np.zeros((P, MAX_PPA), np.int32)
    ppa_n = np.zeros(P, np.int32)
    for i, row in pod_rows:
        for j, (cid, skew, mode) in enumerate(row["ts"]):
            ts_combo[i, j], ts_skew[i, j], ts_mode[i, j] = cid, skew, mode
        ts_n[i] = len(row["ts"])
        for j, (cid, self_match) in enumerate(row["pa"]):
            pa_combo[i, j], pa_self[i, j] = cid, self_match
        pa_n[i] = len(row["pa"])
        for j, cid in enumerate(row["pan"]):
            pan_combo[i, j] = cid
        pan_n[i] = len(row["pan"])
        for j, (cid, w) in enumerate(row["ppa"]):
            ppa_combo[i, j], ppa_w[i, j] = cid, w
        ppa_n[i] = len(row["ppa"])

    # one batched transfer (per-array device_put pays a dispatch RTT each);
    # device=False instead returns the still-on-host PackedTable for
    # consumers that unpack inside their own program (ops/repair packed
    # mode — a separate splitter program alternating with the evaluator
    # stalled ~1.4s per wave on the tunneled runtime)
    from minisched_tpu.models.tables import batched_device_put, pack_table

    host_cols = dict(
            combo_dsum=combo_dsum, combo_haskey=combo_haskey,
            combo_global=combo_global, combo_here=combo_here,
            combo_key=combo_key, topo_domain=topo_domain,
            topo_onehot=topo_onehot, topo_unique=topo_unique,
            ts_combo=ts_combo, ts_skew=ts_skew, ts_mode=ts_mode, ts_n=ts_n,
            pa_combo=pa_combo, pa_self=pa_self, pa_n=pa_n,
            pan_combo=pan_combo, pan_n=pan_n,
            ppa_combo=ppa_combo, ppa_w=ppa_w, ppa_n=ppa_n,
            ex_domain=ex_domain, pod_matches_ex=pod_matches_ex,
            pod_matches_combo=pod_matches_combo, combo_excl=combo_excl,
            rev_weight=rev_weight,
            claim_mask=claim_mask, pod_claims=pod_claims, vol_ok=vol_ok,
            pod_n_vols=pod_n_vols,
            claim_zone_ok=claim_zone_ok,
            pod_vols_fam=pod_vols_fam, node_vols_fam=node_vols_fam,
            claim_vol=claim_vol, claim_cnt=claim_cnt,
            claim_family=claim_family, claim_ro=claim_ro,
            pod_claim_valid=pod_claim_valid, pod_missing=pod_missing,
            vol_any=vol_any, vol_rw=vol_rw,
        )
    if not device:
        # elide_zeros=False callers (the scan lane) trade wire bytes for
        # ONE packed schema per capacity: with elision, every distinct
        # zero-set is a fresh consumer executable, and the scan's planes
        # flip zero/nonzero mid-run (combo counts appear after the first
        # commits) — each flip cost a ~5-50s compile/cache-load on the
        # tunnel.  Waves keep elision: plain waves elide everything and
        # their schema is stable.  elide_groups (SCAN_ELIDE_GROUPS) is
        # the scan lane's bounded middle ground: per-WORKLOAD zero
        # groups (affinity terms, pod volumes) elide as units, folding
        # their whole per-step compute lanes for e.g. spread-only bursts.
        return pack_table(
            host_cols, (), P,
            elide_zeros=elide_zeros, elide_groups=elide_groups,
        )
    return ConstraintTables(**batched_device_put(host_cols))
