"""Incremental assigned-pod aggregates for the cross-pod constraint planes.

``build_constraint_tables`` derives every assigned-pod plane (combo
``here``/``global``/domain sums, the reverse anti-affinity terms, the
volume mount/family state) by walking the FULL assigned-pod population —
O(cluster) host Python per wave.  That is the reference's own per-cycle
re-list pattern one layer up (``minisched/minisched.go:40`` — SURVEY.md
§7's "#1 pattern not to copy"), and at 10k×100k it charged every wave
~200ms regardless of what changed.

``ConstraintIndex`` maintains the same aggregates from informer events —
O(changes), exactly like the NodeInfo cache (engine/cache.py) — and
``build_constraint_tables(..., index=...)`` assembles the dense planes
from it in O(nonzero + planes).  The engine folds still-assumed pods
(binds whose events haven't landed) in at assemble time, under one hold
of the index lock so no event can land between the membership check and
the aggregate reads; the fold re-applies the from-scratch per-pod logic
and the randomized equivalence suite (tests/test_constraint_index.py)
is the drift tripwire between the two paths.

Growth bound: the combo registry keeps every distinct (namespaces,
selector, topology-key) group ever seen by a wave, and each assigned-pod
event matches against every GROUP (selector-deduped).  Real rosters
reuse a handful of selectors, so groups plateau; per-claim volume maps
are pruned when their last pod leaves.

Consistency model (same as the NodeInfo cache): the index is updated on
the informer dispatch thread; reads see event-stream state plus the
fold-in of assumed pods.  Self-healing derivations keep label churn
correct without rescans:

* combo domain sums are derived at assemble time from the ``here`` dicts
  plus the CURRENT node labels (a node changing its zone moves its counts
  automatically);
* reverse anti-affinity owner domains are re-resolved when the owner
  node's labels change (the node-update handler re-adds affected pods);
* PVC bind / PV create events re-resolve the volume records of the pods
  referencing them (a claim's counting identity switches from the claim
  to its bound PV — upstream counts unique volumes).

Registry ids are index-private; ``build_constraint_tables`` keeps its
wave-local combo ids and queries by structural key.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from minisched_tpu.api.objects import LabelSelector

# the ONE definition of combo/term identity — shared with the from-scratch
# walk so the two paths cannot drift on key shape
from minisched_tpu.models.constraints import (
    _matches,
    _selector_sig,
    _term_namespaces,
)

#: combo key: (namespaces, selector signature, topology key)
ComboKey = Tuple[Tuple[str, ...], Tuple, str]
#: reverse anti-affinity term key: combo key + the owner's topo value
ExKey = Tuple[Tuple[str, ...], Tuple, str, str]
#: volume counting key: ("pv", volume_name) | ("pvc", claim_key) |
#: ("miss", pod_uid, slot)
VolKey = Tuple


class _SigMeta:
    __slots__ = ("namespace", "labels")

    def __init__(self, namespace: str, labels: Dict[str, str]):
        self.namespace = namespace
        self.labels = labels


class _SigRep:
    """namespace/labels shim standing in for every pod sharing a label
    signature in selector matching — ``_matches`` reads only
    ``pod.metadata.namespace`` and ``.labels``, and retaining a real pod
    object here would pin its whole spec/status past removal."""

    __slots__ = ("metadata",)

    def __init__(self, namespace: str, labels: Dict[str, str]):
        self.metadata = _SigMeta(namespace, labels)


class _PodRecord:
    """What one assigned pod contributed — enough to subtract it again
    without re-matching (labels may have changed since)."""

    __slots__ = (
        "node", "sig", "ex_keys", "vols", "claims", "has_anti", "rev",
    )

    def __init__(self, node: str):
        self.node = node
        #: the pod's label-signature id — combo membership lives at the
        #: SIGNATURE level (``_sig_combos``), not per record: replica
        #: populations collapse to a handful of signatures, so selector
        #: matching (per add and per new-combo backfill) runs against
        #: signatures instead of pods
        self.sig: int = -1
        self.ex_keys: List[ExKey] = []
        #: (VolKey, family, rw) per mount — one entry per spec.volumes slot
        self.vols: List[Tuple[VolKey, int, bool]] = []
        #: referenced claim keys (for PVC/PV re-resolution)
        self.claims: List[str] = []
        #: pod carries node-label-SENSITIVE terms (required anti-affinity
        #: owner domains, symmetric preferred/hard-affinity contributions)
        #: — node label changes (or the node's ADD arriving after the
        #: pod's, informers being separate dispatch threads) change them
        self.has_anti = False
        #: symmetric preferred contributions: (ComboKey, owner topo value,
        #: signed weight) per scoring-relevant term of this assigned pod
        self.rev: List[Tuple[ComboKey, str, int]] = []


class ConstraintIndex:
    def __init__(self) -> None:
        # REENTRANT: the engine holds it across a whole table assembly
        # (lock() below) while the read methods re-acquire it — a plain
        # lock would deadlock, and not holding it across the assembly
        # lets a bind land between the assumed-fold membership check and
        # the aggregate reads, counting the pod twice for that wave
        self._mu = threading.RLock()
        # persistent combo registry: key → id; per id the match group and
        # the per-node assigned-match counts
        self._combo_ids: Dict[ComboKey, int] = {}
        self._combo_sel: List[Tuple[Tuple[str, ...], LabelSelector]] = []
        self._combo_here: List[Dict[str, int]] = []
        # distinct (namespaces, selector-sig) match groups shared across
        # topology keys: group key → combo ids in the group (one match
        # test per GROUP per SIGNATURE, as the from-scratch builder does)
        self._group_ids: Dict[Tuple, List[int]] = {}
        # label-signature tables: selector matching is a pure function of
        # (namespace, labels), and real populations are replica sets —
        # deferring combo registration to a late wave used to backfill
        # each new combo over EVERY assigned pod (~1M matcher calls at
        # 100k pods × 32 combos); against signatures it's 32 × #sigs.
        # Signatures are REFCOUNTED and their ids recycled: populations
        # with per-pod-unique labels (StatefulSets' pod-name label) would
        # otherwise grow these tables one entry per pod ever assigned —
        # and the rep is a namespace/labels shim, never the pod object
        self._sig_ids: Dict[Tuple, int] = {}  # (ns, labels items) → sig id
        self._sig_rep: List[Optional[Any]] = []  # sig id → _SigRep | None
        self._sig_combos: List[List[int]] = []  # sig id → matching combo ids
        self._sig_nodes: List[Dict[str, int]] = []  # sig id → node → count
        self._sig_count: List[int] = []  # sig id → live records
        self._sig_key: List[Optional[Tuple]] = []  # sig id → _sig_ids key
        self._sig_free: List[int] = []  # recycled sig ids
        # reverse anti-affinity: key → per-owner-node count
        self._ex_terms: Dict[ExKey, Dict[str, int]] = {}
        self._ex_sel: Dict[ExKey, LabelSelector] = {}
        # symmetric preferred scoring: combo key → owner topo value →
        # Σ signed weight of assigned pods' terms owning that domain
        self._rev_pref: Dict[ComboKey, Dict[str, int]] = {}
        self._rev_sel: Dict[ComboKey, LabelSelector] = {}
        # volume state: node → VolKey → [mounts, rw_mounts, family]
        self._node_vols: Dict[str, Dict[VolKey, List[int]]] = {}
        # claim key → uids of assigned pods mounting it (PVC/PV re-resolve)
        self._claim_pods: Dict[str, Set[str]] = {}
        # bound volume name → claim keys referencing it (PV events)
        self._vol_claims: Dict[str, Set[str]] = {}
        self._pods: Dict[str, Any] = {}  # uid → pod object
        self._records: Dict[str, _PodRecord] = {}
        # node → uids of pods with required anti-affinity ON that node —
        # the re-resolution set for node add/label events (O(affected),
        # never O(all records))
        self._node_anti: Dict[str, Set[str]] = {}
        # claim resolution source — the live PVC/PV listers, injected by
        # wire(); event handlers resolve through the informer cache so the
        # index sees the same objects the wave build does
        self._pvc_lister = None
        self._pv_lister = None

    # -- wiring ------------------------------------------------------------
    def wire(self, informer_factory: Any) -> None:
        """Register handlers.  MUST run BEFORE the NodeInfo cache's
        (engine/cache.py) so the index is never behind it: the engine
        prunes its assume-cache against the NodeInfo cache's view, and a
        pruned pod missing from the index would drop out of the planes
        for a wave.  Index-ahead is safe (the assumed fold checks index
        membership first)."""
        from minisched_tpu.controlplane.informer import ResourceEventHandlers

        def assigned(pod: Any) -> bool:
            return bool(pod.spec.node_name)

        pvc_inf = informer_factory.informer_for("PersistentVolumeClaim")
        pv_inf = informer_factory.informer_for("PersistentVolume")
        node_inf = informer_factory.informer_for("Node")
        # informer cache keys are "namespace/name"; cluster-scoped kinds
        # (Node, PV) key as "/<name>"
        self._pvc_lister = pvc_inf.get
        self._pv_lister = lambda name: pv_inf.get(f"/{name}")
        self._node_get = lambda name: node_inf.get(f"/{name}")
        informer_factory.informer_for("Pod").add_event_handlers(
            ResourceEventHandlers(on_batch=self._pod_batch)
        )
        informer_factory.informer_for("Node").add_event_handlers(
            ResourceEventHandlers(
                # ADD matters too: informers are separate dispatch threads,
                # so a pod's event can beat its node's — the owner labels
                # read empty and its ex-terms would be silently dropped
                on_add=lambda node: self.update_node(None, node),
                on_update=self.update_node,
            )
        )
        pvc_inf.add_event_handlers(
            ResourceEventHandlers(
                on_add=lambda pvc: self.claim_changed(pvc.metadata.key),
                on_update=lambda old, new: self.claim_changed(new.metadata.key),
                on_delete=lambda pvc: self.claim_changed(pvc.metadata.key),
            )
        )
        pv_inf.add_event_handlers(
            ResourceEventHandlers(
                on_add=lambda pv: self.volume_changed(pv.metadata.name),
                on_update=lambda old, new: self.volume_changed(new.metadata.name),
                on_delete=lambda pv: self.volume_changed(pv.metadata.name),
            )
        )

    # -- event handlers ----------------------------------------------------
    def _pod_batch(self, events: List[Any]) -> None:
        """Informer batch fast path: one lock hold for a whole wave's bind
        events.  Gates on assignment itself (batch handlers receive the
        raw batch; pending pods never touch the planes); errors are
        contained per event so one malformed object cannot drop the rest
        of the batch from the index."""
        from minisched_tpu.controlplane.store import EventType

        with self._mu:
            for ev in events:
                try:
                    if not ev.obj.spec.node_name:
                        continue
                    if ev.type == EventType.DELETED:
                        self._remove(ev.obj.metadata.uid)
                    elif ev.type == EventType.ADDED:
                        self._add(ev.obj)
                    else:
                        self._remove(ev.obj.metadata.uid)
                        self._add(ev.obj)
                except Exception:
                    import traceback

                    traceback.print_exc()

    def add_pod(self, pod: Any) -> None:
        with self._mu:
            self._add(pod)

    def update_pod(self, old: Any, new: Any) -> None:
        with self._mu:
            self._remove(new.metadata.uid)
            self._add(new)

    def delete_pod(self, pod: Any) -> None:
        with self._mu:
            self._remove(pod.metadata.uid)

    def update_node(self, old: Any, new: Any) -> None:
        """A node's labels feed the reverse anti-affinity owner domains —
        re-resolve the anti-affinity pods on it.  (Combo domain sums
        self-heal: they are derived from CURRENT labels at assemble
        time.)"""
        if old is not None and old.metadata.labels == new.metadata.labels:
            return
        with self._mu:
            for uid in list(self._node_anti.get(new.metadata.name, ())):
                pod = self._pods.get(uid)
                if pod is not None:
                    self._remove(uid)
                    self._add(pod)

    def claim_changed(self, claim_key: str) -> None:
        """A PVC appeared / bound / changed — the counting identity and
        family of every mount referencing it may have moved."""
        with self._mu:
            self._reresolve_claims({claim_key})

    def volume_changed(self, pv_name: str) -> None:
        with self._mu:
            refs = self._vol_claims.get(pv_name)
            if refs is None:
                return
            # opportunistic sweep of claims no pod mounts anymore
            dead = {ck for ck in refs if not self._claim_pods.get(ck)}
            refs -= dead
            if not refs:
                del self._vol_claims[pv_name]
                return
            self._reresolve_claims(set(refs))

    def _reresolve_claims(self, claim_keys: Set[str]) -> None:
        uids: Set[str] = set()
        for ck in claim_keys:
            uids |= self._claim_pods.get(ck, set())
        for uid in uids:
            pod = self._pods.get(uid)
            if pod is not None:
                self._remove(uid)
                self._add(pod)

    # -- contribution maintenance (shared by events and the assumed fold) --
    def _lookup_pvc(self, key: str) -> Any:
        return self._pvc_lister(key) if self._pvc_lister is not None else None

    def _lookup_pv(self, name: str) -> Any:
        return self._pv_lister(name) if self._pv_lister is not None else None

    def _contribution(self, pod: Any) -> _PodRecord:
        """Compute the pod's record against the CURRENT registry and the
        live PVC/PV caches — the one place contribution math lives."""
        from minisched_tpu.plugins.volumelimits import volume_family

        rec = _PodRecord(pod.spec.node_name)
        aff = pod.spec.affinity
        if (
            aff is not None
            and aff.pod_anti_affinity is not None
            and aff.pod_anti_affinity.required
        ):
            rec.has_anti = True
            # the owner's CURRENT node labels give the term's domain value
            owner_labels = self._node_labels(pod.spec.node_name)
            for term in aff.pod_anti_affinity.required:
                owner_val = owner_labels.get(term.topology_key)
                if owner_val is None:
                    continue  # owner's node lacks the key: can't be violated
                nss = _term_namespaces(term, pod.metadata.namespace)
                key = (nss, _selector_sig(term.label_selector),
                       term.topology_key, owner_val)
                self._ex_sel.setdefault(key, term.label_selector)
                rec.ex_keys.append(key)
        # symmetric preferred/hard-affinity contributions (the terms this
        # ASSIGNED pod scores toward future incoming pods) — ONE term
        # stream shared with the from-scratch walk
        from minisched_tpu.models.constraints import rev_pref_terms_of

        owner_labels = None
        for nss, sel, topo, w in rev_pref_terms_of(pod):
            # node-label-sensitive either way: a label change can grant or
            # revoke the owner's topology key — re-resolve on node events
            rec.has_anti = True
            if owner_labels is None:
                owner_labels = self._node_labels(pod.spec.node_name)
            owner_val = owner_labels.get(topo)
            if owner_val is None:
                continue  # owner's node lacks the key: no domain to score
            ck: ComboKey = (nss, _selector_sig(sel), topo)
            self._rev_sel.setdefault(ck, sel)
            rec.rev.append((ck, owner_val, w))
        uid = pod.metadata.uid
        for j, vol in enumerate(pod.spec.volumes):
            claim_key = f"{pod.metadata.namespace}/{vol}"
            rec.claims.append(claim_key)
            pvc = self._lookup_pvc(claim_key)
            if pvc is None:
                # no identity: each unresolvable mount counts by itself
                rec.vols.append((("miss", uid, j), 0, False))
                continue
            pv_by_name = _LazyPVMap(self._lookup_pv)
            fam = volume_family(pvc, pv_by_name)
            if pvc.spec.volume_name:
                vk: VolKey = ("pv", pvc.spec.volume_name)
                rw = not pvc.spec.read_only
            else:
                vk = ("pvc", claim_key)
                rw = False  # unbound: no PV identity to conflict on
            rec.vols.append((vk, fam, rw))
        # signature LAST (advisor r4): _sig_of creates a refcount-0
        # registry entry on first sight, and apply_events swallows
        # per-event exceptions — a raise in any step above would strand
        # the entry in _sig_ids/_sig_rep forever (only _remove releases).
        # Nothing above reads rec.sig, so creating it after every
        # fallible step means a failed _contribution mutates no
        # signature state.
        rec.sig = self._sig_of(pod)
        return rec

    def _sig_of(self, pod: Any) -> int:
        """The pod's label-signature id, creating (and combo-matching)
        the signature on first sight — every later pod with the same
        (namespace, labels) costs one dict lookup instead of a matcher
        call per selector group.  The caller (_add) owns the refcount."""
        key = (
            pod.metadata.namespace,
            tuple(sorted(pod.metadata.labels.items())),
        )
        sid = self._sig_ids.get(key)
        if sid is None:
            rep = _SigRep(pod.metadata.namespace, dict(pod.metadata.labels))
            cids: List[int] = []
            for gkey, ids in self._group_ids.items():
                nss, _sig = gkey
                sel = self._combo_sel[ids[0]][1]
                if _matches(sel, nss, rep):
                    cids.extend(ids)
            if self._sig_free:
                sid = self._sig_free.pop()
                self._sig_rep[sid] = rep
                self._sig_combos[sid] = cids
                self._sig_nodes[sid] = {}
                self._sig_count[sid] = 0
                self._sig_key[sid] = key
            else:
                sid = len(self._sig_rep)
                self._sig_rep.append(rep)
                self._sig_combos.append(cids)
                self._sig_nodes.append({})
                self._sig_count.append(0)
                self._sig_key.append(key)
            self._sig_ids[key] = sid
        return sid

    def _sig_release(self, sid: int) -> None:
        """Drop one reference; free and recycle the id at zero."""
        self._sig_count[sid] -= 1
        if self._sig_count[sid] <= 0:
            key = self._sig_key[sid]
            if key is not None:
                self._sig_ids.pop(key, None)
            self._sig_rep[sid] = None
            self._sig_combos[sid] = []
            self._sig_nodes[sid] = {}
            self._sig_key[sid] = None
            self._sig_free.append(sid)

    def _node_labels(self, node_name: str) -> Dict[str, str]:
        # set by wire(): the Node informer's get; absent in unit tests
        # that drive the index directly — they pass nodes via _node_get
        node = self._node_get(node_name) if self._node_get else None
        return node.metadata.labels if node is not None else {}

    _node_get = None  # injected by wire() below

    def _add(self, pod: Any) -> None:
        uid = pod.metadata.uid
        if uid in self._records:
            return  # duplicate event
        rec = self._contribution(pod)
        self._pods[uid] = pod
        self._records[uid] = rec
        node = rec.node
        for cid in self._sig_combos[rec.sig]:
            here = self._combo_here[cid]
            here[node] = here.get(node, 0) + 1
        sn = self._sig_nodes[rec.sig]
        sn[node] = sn.get(node, 0) + 1
        self._sig_count[rec.sig] += 1
        for key in rec.ex_keys:
            owners = self._ex_terms.setdefault(key, {})
            owners[node] = owners.get(node, 0) + 1
        for ck, owner_val, w in rec.rev:
            vals = self._rev_pref.setdefault(ck, {})
            vals[owner_val] = vals.get(owner_val, 0) + w
        if rec.vols:
            nv = self._node_vols.setdefault(node, {})
            for vk, fam, rw in rec.vols:
                ent = nv.get(vk)
                if ent is None:
                    ent = nv[vk] = [0, 0, fam]
                ent[0] += 1
                ent[1] += 1 if rw else 0
                ent[2] = fam
        for ck in rec.claims:
            self._claim_pods.setdefault(ck, set()).add(uid)
            pvc = self._lookup_pvc(ck)
            if pvc is not None and pvc.spec.volume_name:
                self._vol_claims.setdefault(pvc.spec.volume_name, set()).add(ck)
        if rec.has_anti:
            self._node_anti.setdefault(node, set()).add(uid)

    def _remove(self, uid: str) -> None:
        rec = self._records.pop(uid, None)
        if rec is None:
            return
        self._pods.pop(uid, None)
        node = rec.node
        for cid in self._sig_combos[rec.sig]:
            here = self._combo_here[cid]
            n = here.get(node, 0) - 1
            if n <= 0:
                here.pop(node, None)
            else:
                here[node] = n
        sn = self._sig_nodes[rec.sig]
        left = sn.get(node, 0) - 1
        if left <= 0:
            sn.pop(node, None)
        else:
            sn[node] = left
        self._sig_release(rec.sig)
        for key in rec.ex_keys:
            owners = self._ex_terms.get(key)
            if owners is not None:
                n = owners.get(node, 0) - 1
                if n <= 0:
                    owners.pop(node, None)
                else:
                    owners[node] = n
        for ck, owner_val, w in rec.rev:
            vals = self._rev_pref.get(ck)
            if vals is not None:
                left = vals.get(owner_val, 0) - w
                if left == 0:
                    vals.pop(owner_val, None)
                    if not vals:
                        self._rev_pref.pop(ck, None)
                else:
                    vals[owner_val] = left
        nv = self._node_vols.get(node)
        if nv is not None:
            for vk, _fam, rw in rec.vols:
                ent = nv.get(vk)
                if ent is None:
                    continue
                ent[0] -= 1
                ent[1] -= 1 if rw else 0
                if ent[0] <= 0:
                    del nv[vk]
        for ck in rec.claims:
            pods = self._claim_pods.get(ck)
            if pods is not None:
                pods.discard(uid)
                if not pods:
                    # prune the claim's reverse maps when its last pod
                    # leaves (a long-running service would otherwise
                    # accrete one entry per claim ever mounted).  Stale
                    # old-volname entries (claim re-bound between adds)
                    # are swept by volume_changed below.
                    del self._claim_pods[ck]
                    pvc = self._lookup_pvc(ck)
                    if pvc is not None and pvc.spec.volume_name:
                        refs = self._vol_claims.get(pvc.spec.volume_name)
                        if refs is not None:
                            refs.discard(ck)
                            if not refs:
                                del self._vol_claims[pvc.spec.volume_name]
        if rec.has_anti:
            anti = self._node_anti.get(node)
            if anti is not None:
                anti.discard(uid)

    # -- reads (wave assembly) ---------------------------------------------
    def combo_aggregate(
        self, nss: Tuple[str, ...], sel: LabelSelector, topo: str
    ) -> Dict[str, int]:
        """Per-node assigned-match counts for one combo, registering (and
        backfilling over the current population) if unseen.  Caller holds
        nothing; returns a COPY."""
        key = (nss, _selector_sig(sel), topo)
        with self._mu:
            cid = self._combo_ids.get(key)
            if cid is None:
                cid = self._register_combo(key, nss, sel)
            return dict(self._combo_here[cid])

    def _register_combo(
        self, key: ComboKey, nss: Tuple[str, ...], sel: LabelSelector
    ) -> int:
        cid = len(self._combo_sel)
        self._combo_ids[key] = cid
        self._combo_sel.append((nss, sel))
        here: Dict[str, int] = {}
        gkey = (nss, key[1])
        group = self._group_ids.get(gkey)
        if group:
            # same (namespaces, selector) under another topology key:
            # matches are identical — share the backfill and the
            # signature membership
            here.update(self._combo_here[group[0]])
            for cids in self._sig_combos:
                if group[0] in cids:
                    cids.append(cid)
            group.append(cid)
        else:
            # one-time backfill against SIGNATURES (a handful), not the
            # assigned population — a combo registered late (the deferred
            # scan lane queries at drain end, 100k pods assigned) used to
            # pay one matcher call per pod here
            for sid, rep in enumerate(self._sig_rep):
                if rep is not None and _matches(sel, nss, rep):
                    self._sig_combos[sid].append(cid)
                    for node, cnt in self._sig_nodes[sid].items():
                        here[node] = here.get(node, 0) + cnt
            self._group_ids[gkey] = [cid]
        self._combo_here.append(here)
        return cid

    def lock(self):
        """The index's RLock as a context manager.  The engine wraps the
        assumed-pod membership check AND the whole constraint-table
        assembly in one hold, so no event can slip a pod into the
        aggregates after it was selected for the assumed fold (the
        TOCTOU double-count).  Events block for the duration (~tens of
        ms per wave) — the same trade the store's ``locked()`` makes for
        checkpoint snapshots."""
        return self._mu

    def assigned_uids(self) -> Set[str]:
        with self._mu:
            return set(self._records)

    def ex_term_list(self) -> List[Tuple[ExKey, LabelSelector, Set[str]]]:
        """Live reverse anti-affinity terms: (key, selector, owner nodes)."""
        with self._mu:
            return [
                (key, self._ex_sel[key], set(owners))
                for key, owners in self._ex_terms.items()
                if owners
            ]

    def rev_pref_list(self) -> List[Tuple[ComboKey, LabelSelector, Dict[str, int]]]:
        """Live symmetric preferred contributions: (combo key, selector,
        owner-topo-value → Σ signed weight)."""
        with self._mu:
            return [
                (ck, self._rev_sel[ck], dict(vals))
                for ck, vals in self._rev_pref.items()
                if vals
            ]

    def node_vol_state(self) -> Dict[str, Dict[VolKey, List[int]]]:
        """node → VolKey → [mounts, rw_mounts, family] (copied)."""
        with self._mu:
            return {
                node: {vk: list(ent) for vk, ent in nv.items()}
                for node, nv in self._node_vols.items()
                if nv
            }


class _LazyPVMap:
    """dict-shaped adapter over the PV informer get — volume_family only
    calls .get(name)."""

    def __init__(self, lookup):
        self._lookup = lookup

    def get(self, name: str, default: Any = None) -> Any:
        out = self._lookup(name)
        return out if out is not None else default
