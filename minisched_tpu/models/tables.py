"""Struct-of-arrays cluster state: NodeTable / PodTable.

The TPU-native replacement for per-object ``NodeInfo`` graphs (SURVEY.md §7
design stance): cluster state lives as flat, statically-shaped arrays in HBM
so every registered plugin can evaluate as a vectorized ``(pods × nodes)``
computation inside one jit.  The reference instead re-lists all nodes and
re-wraps them per pod every cycle (minisched/minisched.go:40,126-127) — the
#1 pattern not to copy.

Conventions:

* CPU in milli-cores (int32), memory in MiB (int32) — integer units keep
  parity with the scalar oracle bit-exact (no float resource math).
* Tables are padded to TPU-friendly sizes (multiples of 128 lanes) with a
  ``valid`` mask; kernels must mask, never rely on dynamic shapes
  (recompilation is the enemy — SURVEY.md §7 hard part 4).
* String data (label keys/values, taints) is carried as stable 32-bit
  FNV-1a hashes computed host-side; kernels compare ints.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MIB = 1024 * 1024

# Fixed per-object capacities for variable-length k8s fields; overflow raises
# host-side at table-build time (static shapes are non-negotiable under jit).
MAX_TAINTS = 8
MAX_TOLERATIONS = 8
MAX_LABELS = 16

EFFECT_NONE = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3
_EFFECT_CODES = {
    "": EFFECT_NONE,
    "NoSchedule": EFFECT_NO_SCHEDULE,
    "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
    "NoExecute": EFFECT_NO_EXECUTE,
}

TOLERATION_OP_EQUAL_CODE = 0
TOLERATION_OP_EXISTS_CODE = 1


def fnv1a32(s: str) -> int:
    """Stable 32-bit FNV-1a; returned as signed int32 range for jnp."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    # map to signed int32
    return h - (1 << 32) if h >= (1 << 31) else h


#: hash of the empty string — used as the "absent" sentinel nowhere; absent
#: slots use 0 with a count field instead.
def pad_to(n: int, multiple: int = 128) -> int:
    if n == 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def _register_table(cls):
    """Register a dataclass of jnp arrays as a pytree."""
    names = [f.name for f in fields(cls)]
    jax.tree_util.register_pytree_node(
        cls,
        lambda t: ([getattr(t, n) for n in names], None),
        lambda _, leaves: cls(**dict(zip(names, leaves))),
    )
    return cls


@_register_table
@dataclass
class NodeTable:
    """All scheduler-relevant node state, shape (N,) or (N, K)."""

    # resources
    alloc_cpu: Any  # i32[N] allocatable milli-cpu
    alloc_mem: Any  # i32[N] allocatable MiB
    alloc_pods: Any  # i32[N] allocatable pod count
    req_cpu: Any  # i32[N] requested (sum of assigned pods)
    req_mem: Any  # i32[N]
    req_pods: Any  # i32[N]
    # flags
    unschedulable: Any  # bool[N] (spec.unschedulable)
    # nodenumber plugin
    suffix: Any  # i32[N] trailing-digit of name, -1 if none
    # taints
    taint_key: Any  # i32[N, MAX_TAINTS] fnv hash
    taint_value: Any  # i32[N, MAX_TAINTS]
    taint_effect: Any  # i32[N, MAX_TAINTS] effect code
    num_taints: Any  # i32[N]
    # labels
    label_key: Any  # i32[N, MAX_LABELS]
    label_value: Any  # i32[N, MAX_LABELS]
    num_labels: Any  # i32[N]
    # padding mask
    valid: Any  # bool[N]

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])


@_register_table
@dataclass
class PodTable:
    """All scheduler-relevant pending-pod state, shape (P,) or (P, K)."""

    req_cpu: Any  # i32[P] requested milli-cpu (sum of containers)
    req_mem: Any  # i32[P] MiB
    req_pods: Any  # i32[P] (1)
    suffix: Any  # i32[P] trailing digit of name, -1 if none
    # tolerations
    tol_key: Any  # i32[P, MAX_TOLERATIONS]
    tol_value: Any  # i32[P, MAX_TOLERATIONS]
    tol_effect: Any  # i32[P, MAX_TOLERATIONS]
    tol_op: Any  # i32[P, MAX_TOLERATIONS] 0=Equal 1=Exists
    tol_empty_key: Any  # bool[P, MAX_TOLERATIONS] key=="" (Exists-all)
    num_tols: Any  # i32[P]
    # node selector (match_labels only; expressions handled host-side for now)
    sel_key: Any  # i32[P, MAX_LABELS]
    sel_value: Any  # i32[P, MAX_LABELS]
    num_sel: Any  # i32[P]
    # deterministic tie-break seed per pod
    seed: Any  # u32[P]
    valid: Any  # bool[P]

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])


# ---------------------------------------------------------------------------
# Builders (host side, numpy)
# ---------------------------------------------------------------------------


def _name_suffix(name: str) -> int:
    """Trailing single digit of an object name, -1 if absent — the
    nodenumber plugin's key (nodenumber.go:21,50-64 parses the last rune)."""
    if name and name[-1].isdigit():
        return int(name[-1])
    return -1


def pod_seed(uid: str) -> int:
    """Deterministic per-pod tie-break seed (unsigned 32-bit)."""
    return fnv1a32(uid) & 0xFFFFFFFF


def build_node_table(nodes: Sequence[Any], pods_by_node: Dict[str, List[Any]] = None,
                     capacity: int = None) -> Tuple[NodeTable, List[str]]:
    """Build a NodeTable from Node objects (+ already-assigned pods).

    Returns (table, node_names) where node_names[i] is row i's name; the
    order is the given order (callers sort for determinism).
    """
    pods_by_node = pods_by_node or {}
    n = len(nodes)
    cap = capacity or pad_to(n)
    if n > cap:
        raise ValueError(f"{n} nodes exceed table capacity {cap}")

    def zeros(shape, dtype=np.int32):
        return np.zeros(shape, dtype)

    t = dict(
        alloc_cpu=zeros(cap), alloc_mem=zeros(cap), alloc_pods=zeros(cap),
        req_cpu=zeros(cap), req_mem=zeros(cap), req_pods=zeros(cap),
        unschedulable=np.zeros(cap, bool), suffix=np.full(cap, -1, np.int32),
        taint_key=zeros((cap, MAX_TAINTS)), taint_value=zeros((cap, MAX_TAINTS)),
        taint_effect=zeros((cap, MAX_TAINTS)), num_taints=zeros(cap),
        label_key=zeros((cap, MAX_LABELS)), label_value=zeros((cap, MAX_LABELS)),
        num_labels=zeros(cap), valid=np.zeros(cap, bool),
    )
    names: List[str] = []
    for i, node in enumerate(nodes):
        names.append(node.metadata.name)
        alloc = node.status.allocatable
        t["alloc_cpu"][i] = alloc.milli_cpu
        t["alloc_mem"][i] = alloc.memory // MIB
        t["alloc_pods"][i] = alloc.pods
        t["unschedulable"][i] = node.spec.unschedulable
        t["suffix"][i] = _name_suffix(node.metadata.name)
        taints = node.spec.taints
        if len(taints) > MAX_TAINTS:
            raise ValueError(f"node {node.metadata.name}: >{MAX_TAINTS} taints")
        for j, taint in enumerate(taints):
            t["taint_key"][i, j] = fnv1a32(taint.key)
            t["taint_value"][i, j] = fnv1a32(taint.value)
            t["taint_effect"][i, j] = _EFFECT_CODES[taint.effect]
        t["num_taints"][i] = len(taints)
        labels = node.metadata.labels
        if len(labels) > MAX_LABELS:
            raise ValueError(f"node {node.metadata.name}: >{MAX_LABELS} labels")
        for j, (k, v) in enumerate(sorted(labels.items())):
            t["label_key"][i, j] = fnv1a32(k)
            t["label_value"][i, j] = fnv1a32(v)
        t["num_labels"][i] = len(labels)
        t["valid"][i] = True
        for p in pods_by_node.get(node.metadata.name, ()):  # assigned pods
            req = p.resource_requests()
            t["req_cpu"][i] += req.milli_cpu
            t["req_mem"][i] += req.memory // MIB
            t["req_pods"][i] += 1
    return NodeTable(**{k: jnp.asarray(v) for k, v in t.items()}), names


def build_pod_table(pods: Sequence[Any], capacity: int = None) -> Tuple[PodTable, List[str]]:
    p = len(pods)
    cap = capacity or pad_to(p)
    if p > cap:
        raise ValueError(f"{p} pods exceed table capacity {cap}")

    def zeros(shape, dtype=np.int32):
        return np.zeros(shape, dtype)

    t = dict(
        req_cpu=zeros(cap), req_mem=zeros(cap), req_pods=zeros(cap),
        suffix=np.full(cap, -1, np.int32),
        tol_key=zeros((cap, MAX_TOLERATIONS)), tol_value=zeros((cap, MAX_TOLERATIONS)),
        tol_effect=zeros((cap, MAX_TOLERATIONS)), tol_op=zeros((cap, MAX_TOLERATIONS)),
        tol_empty_key=np.zeros((cap, MAX_TOLERATIONS), bool), num_tols=zeros(cap),
        sel_key=zeros((cap, MAX_LABELS)), sel_value=zeros((cap, MAX_LABELS)),
        num_sel=zeros(cap),
        seed=np.zeros(cap, np.uint32), valid=np.zeros(cap, bool),
    )
    names: List[str] = []
    for i, pod in enumerate(pods):
        names.append(pod.metadata.name)
        req = pod.resource_requests()
        t["req_cpu"][i] = req.milli_cpu
        t["req_mem"][i] = req.memory // MIB
        t["req_pods"][i] = 1
        t["suffix"][i] = _name_suffix(pod.metadata.name)
        tols = pod.spec.tolerations
        if len(tols) > MAX_TOLERATIONS:
            raise ValueError(f"pod {pod.metadata.name}: >{MAX_TOLERATIONS} tolerations")
        for j, tol in enumerate(tols):
            t["tol_key"][i, j] = fnv1a32(tol.key)
            t["tol_value"][i, j] = fnv1a32(tol.value)
            t["tol_effect"][i, j] = _EFFECT_CODES[tol.effect]
            t["tol_op"][i, j] = (
                TOLERATION_OP_EXISTS_CODE if tol.operator == "Exists"
                else TOLERATION_OP_EQUAL_CODE
            )
            t["tol_empty_key"][i, j] = tol.key == ""
        t["num_tols"][i] = len(tols)
        sel = pod.spec.node_selector
        if len(sel) > MAX_LABELS:
            raise ValueError(f"pod {pod.metadata.name}: >{MAX_LABELS} selector terms")
        for j, (k, v) in enumerate(sorted(sel.items())):
            t["sel_key"][i, j] = fnv1a32(k)
            t["sel_value"][i, j] = fnv1a32(v)
        t["num_sel"][i] = len(sel)
        t["seed"][i] = pod_seed(pod.metadata.uid or pod.metadata.name)
        t["valid"][i] = True
    return PodTable(**{k: jnp.asarray(v) for k, v in t.items()}), names
