"""Struct-of-arrays cluster state: NodeTable / PodTable.

The TPU-native replacement for per-object ``NodeInfo`` graphs (SURVEY.md §7
design stance): cluster state lives as flat, statically-shaped arrays in HBM
so every registered plugin can evaluate as a vectorized ``(pods × nodes)``
computation inside one jit.  The reference instead re-lists all nodes and
re-wraps them per pod every cycle (minisched/minisched.go:40,126-127) — the
#1 pattern not to copy.

Conventions:

* CPU in milli-cores (int32), memory in MiB (int32) — integer units keep
  parity with the scalar oracle bit-exact (no float resource math).
* Tables are padded to TPU-friendly sizes (multiples of 128 lanes) with a
  ``valid`` mask; kernels must mask, never rely on dynamic shapes
  (recompilation is the enemy — SURVEY.md §7 hard part 4).
* String data (label keys/values, taints) is carried as stable 32-bit
  FNV-1a hashes computed host-side; kernels compare ints.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any, Dict, List, Optional, Sequence, Tuple

import os

import jax
import jax.numpy as jnp
import numpy as np

from minisched_tpu.api.objects import (
    DEFAULT_POD_CPU_REQUEST,
    DEFAULT_POD_MEMORY_REQUEST,
    MIB,
    gang_key as _gang_key,
)

# upstream GetNonzeroRequests defaults in device units, applied by the
# resource *scorers* (never the Fit filter) to pods with no explicit
# request — derived from the canonical api.objects constants so the scalar
# oracle and the tables can never quantize differently
DEFAULT_NONZERO_CPU = DEFAULT_POD_CPU_REQUEST  # milli-CPU
DEFAULT_NONZERO_MEM_MIB = DEFAULT_POD_MEMORY_REQUEST // MIB

# Fixed per-object capacities for variable-length k8s fields; overflow raises
# host-side at table-build time (static shapes are non-negotiable under jit).
MAX_TAINTS = 8
MAX_TOLERATIONS = 8
MAX_LABELS = 16
MAX_IMAGES = 8  # images cached per node (ImageLocality)
MAX_CONTAINERS = 4  # containers per pod
MAX_PORTS = 8  # host ports per pod / in-use ports tracked per node
MAX_AFF_TERMS = 4  # required node-affinity NodeSelectorTerms per pod
MAX_PREF_TERMS = 4  # preferred node-affinity terms per pod
MAX_AFF_REQS = 4  # match expressions per term
MAX_AFF_VALS = 4  # operand values per In/NotIn expression

EFFECT_NONE = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3
_EFFECT_CODES = {
    "": EFFECT_NONE,
    "NoSchedule": EFFECT_NO_SCHEDULE,
    "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
    "NoExecute": EFFECT_NO_EXECUTE,
}

TOLERATION_OP_EQUAL_CODE = 0
TOLERATION_OP_EXISTS_CODE = 1

# node-affinity / label-selector expression operator codes
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
#: encodes an expression that can never match (e.g. Gt/Lt with a
#: non-integer or missing operand — the scalar path treats those as
#: no-match, never as an error; api/objects.py:_match_expression)
OP_INVALID = 6
_OP_CODES = {
    "In": OP_IN,
    "NotIn": OP_NOT_IN,
    "Exists": OP_EXISTS,
    "DoesNotExist": OP_DOES_NOT_EXIST,
    "Gt": OP_GT,
    "Lt": OP_LT,
}


def fnv1a32(s: str) -> int:
    """Stable 32-bit FNV-1a; returned as signed int32 range for jnp."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    # map to signed int32
    return h - (1 << 32) if h >= (1 << 31) else h


#: hash of the empty string — used as the "absent" sentinel nowhere; absent
#: slots use 0 with a count field instead.
def pad_to(n: int, multiple: int = 128) -> int:
    if n == 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


import functools


#: schema → times seen (batched_device_put packs only on reuse)
_SCHEMA_SEEN: Dict[Tuple, int] = {}


_ZERO_DT = {"bool": jnp.bool_, "uint32": jnp.uint32, "int32": jnp.int32}


def unpack_columns(
    flat,
    metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...],
    zero_metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...] = (),
) -> Dict[str, Any]:
    """TRACEABLE inverse of ``pack_columns``: slice the flat int32 buffer
    back into named, dtyped columns (+ all-zero columns materialized in
    place).  Usable inside a larger jit — the wave evaluator unpacks its
    tables inside its OWN program so a wave costs one executable, not an
    alternation of splitter programs with the evaluator (each switch
    stalled ~1.4s on the tunneled runtime)."""
    out = {}
    off = 0
    for name, kind, shape in metas:
        size = 1
        for d in shape:
            size *= d
        seg = flat[off : off + size].reshape(shape)
        off += size
        if kind == "bool":
            out[name] = seg != 0
        elif kind == "uint32":
            out[name] = jax.lax.bitcast_convert_type(seg, jnp.uint32)
        else:
            out[name] = seg
    for name, kind, shape in zero_metas:
        out[name] = jnp.zeros(shape, _ZERO_DT[kind])
    return out


def pack_columns(
    host: Dict[str, Any],
) -> Tuple[Tuple[Tuple[str, str, Tuple[int, ...]], ...], Any]:
    """(metas, flat int32 buffer): the host half of ``batched_device_put``
    without the device call — callers hand the flat buffer to a jitted
    function that runs ``unpack_columns`` with these metas inside."""
    arrays = {k: np.asarray(v) for k, v in host.items()}
    metas = _col_metas(arrays)
    parts = []
    for (k, kind, _shape), v in zip(metas, arrays.values()):
        if kind == "bool":
            parts.append(v.ravel().astype(np.int32))
        elif kind == "uint32":
            parts.append(v.ravel().view(np.int32))
        else:
            parts.append(np.ascontiguousarray(v.ravel(), dtype=np.int32))
    flat = np.concatenate(parts) if parts else np.zeros(0, np.int32)
    return metas, flat


@functools.lru_cache(maxsize=None)
def _flat_splitter(
    metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...],
    zero_metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...] = (),
):
    """Jitted device-side splitter for one packed-table schema."""

    def split(flat):
        return unpack_columns(flat, metas, zero_metas)

    return jax.jit(split)


@dataclass
class PackedTable:
    """A table still on the host, packed for single-buffer transfer: the
    consumer jit takes ``flat`` as an argument and rebuilds the columns
    with ``unpack_columns(flat, metas, zero_metas)`` INSIDE its own
    program.  ``metas``/``zero_metas`` are static (part of the consumer's
    jit cache key); equal schemas hit the same executable."""

    metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...]
    zero_metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...]
    flat: Any  # np.int32[total]
    capacity: int = 0

    @property
    def schema(self) -> Tuple:
        return (self.metas, self.zero_metas)

    def unpack(self, flat=None) -> Dict[str, Any]:
        return unpack_columns(
            self.flat if flat is None else flat, self.metas, self.zero_metas
        )


def pack_table(
    host: Dict[str, Any],
    zero_metas: Tuple = (),
    capacity: int = 0,
    elide_zeros: bool = False,
    elide_groups: Tuple[Tuple[str, ...], ...] = (),
) -> PackedTable:
    """``elide_zeros``: move columns that are entirely zero into
    ``zero_metas`` (materialized on device by the consumer's unpack, zero
    wire bytes).  Host→device transfer degrades ~50× once a large program
    is resident on the tunneled runtime, so bytes not shipped are the
    cheapest bytes: a plain config5 wave's 10MB constraint table is
    almost entirely zero planes.  NOTE: the zero-set is part of the
    schema — a column flipping nonzero compiles a new consumer
    executable, so flips must be rare/one-way (combo planes go nonzero
    once cross-pod pods land and stay there).

    ``elide_groups``: the selective middle ground — each GROUP of column
    names elides as a unit, and only when every member is all-zero.
    Consumers whose zero-sets must stay schema-stable against state
    churn (the scan lane) use this for the columns whose zero-ness is a
    property of the WORKLOAD (a spread-only burst carries no affinity
    terms, no volumes): XLA then constant-folds those columns' whole
    compute lanes out of the per-step program, while the schema space
    stays bounded at one executable per group subset actually seen."""
    if elide_zeros:
        live: Dict[str, Any] = {}
        zeros = list(zero_metas)
        for k, v in host.items():
            arr = np.asarray(v)
            if not arr.any():
                zeros.append((k, _wire_kind(arr.dtype), tuple(arr.shape)))
            else:
                live[k] = arr
        host, zero_metas = live, tuple(zeros)
    elif elide_groups:
        zeros = list(zero_metas)
        live = dict(host)
        for group in elide_groups:
            members = [k for k in group if k in live]
            if members and all(
                not np.asarray(live[k]).any() for k in members
            ):
                for k in members:
                    arr = np.asarray(live.pop(k))
                    zeros.append(
                        (k, _wire_kind(arr.dtype), tuple(arr.shape))
                    )
        host, zero_metas = live, tuple(zeros)
    metas, flat = pack_columns(host)
    return PackedTable(metas, tuple(zero_metas), flat, capacity)


class PackedCaller:
    """Per-schema jit cache around a ``consumer(pods, nodes, extra)``
    function: arguments arrive as PackedTables (+ the device-resident
    static node columns) and are unpacked INSIDE the consumer's one jitted
    program.  Separate splitter programs alternating with the evaluator
    stalled ~1.4s per program switch on the tunneled runtime; this keeps a
    wave to one executable and three flat transfers.

    Schemas are static jit-cache keys, so capacities must follow the same
    quantization discipline as device-table consumers."""

    def __init__(self, consumer):
        self._consumer = consumer
        self._fns: Dict[Tuple, Any] = {}

    def _build_fn(self, key, pod_packed, node_static, node_agg_packed,
                  extra_packed):
        from minisched_tpu.models.constraints import ConstraintTables

        ex_schema = extra_packed.schema if extra_packed is not None else None
        pod_metas, pod_zeros = pod_packed.schema
        agg_metas, agg_zeros = node_agg_packed.schema
        consumer = self._consumer

        def run(pod_flat, agg_flat, ex_flat, static_cols):
            pods = PodTable(
                **unpack_columns(pod_flat, pod_metas, pod_zeros)
            )
            nodes = NodeTable(
                **static_cols,
                **unpack_columns(agg_flat, agg_metas, agg_zeros),
            )
            extra = (
                ConstraintTables(
                    **unpack_columns(ex_flat, *ex_schema)
                )
                if ex_schema is not None
                else None
            )
            return consumer(pods, nodes, extra)

        return jax.jit(run)

    def _key(self, pod_packed, node_static, node_agg_packed, ex_schema):
        """The jit-cache key for one call signature — subclasses extend
        it (the mesh variant folds the mesh factoring in)."""
        return (pod_packed.schema, node_agg_packed.schema, ex_schema,
                tuple(sorted(node_static)))

    def __call__(self, pod_packed, node_static, node_agg_packed,
                 extra_packed=None):
        ex_schema = extra_packed.schema if extra_packed is not None else None
        key = self._key(pod_packed, node_static, node_agg_packed, ex_schema)
        fn = self._fns.get(key)
        if fn is None:
            fn = self._build_fn(
                key, pod_packed, node_static, node_agg_packed, extra_packed
            )
            self._fns[key] = fn
        ex_flat = (
            extra_packed.flat
            if extra_packed is not None
            else np.zeros(0, np.int32)
        )
        try:
            return fn(
                pod_packed.flat, node_agg_packed.flat, ex_flat, node_static
            )
        except ValueError as err:
            # jax 0.9's C++ dispatch can return a WRONG-ARITY executable
            # for this call after unrelated large programs compiled in the
            # same process ("Execution supplied N buffers but compiled
            # program expected M buffers") — an upstream cache-dispatch
            # bug, not a shape problem on our side: the same signature
            # succeeded before.  Self-heal: drop the poisoned entry,
            # clear that jit's caches, recompile once.
            if "buffers but compiled program expected" not in str(err):
                raise
            self._fns.pop(key, None)
            try:
                fn.clear_cache()
            except Exception:
                pass
            fn = self._build_fn(
                key, pod_packed, node_static, node_agg_packed, extra_packed
            )
            self._fns[key] = fn
            return fn(
                pod_packed.flat, node_agg_packed.flat, ex_flat, node_static
            )


def _wire_kind(dtype) -> str:
    """Wire-format kind of a column dtype (the packed transfer's only
    three legal dtypes)."""
    if dtype == np.bool_:
        return "bool"
    return "uint32" if dtype == np.uint32 else "int32"


def _col_metas(arrays: Dict[str, Any]) -> Tuple[Tuple[str, str, Tuple[int, ...]], ...]:
    for k, v in arrays.items():
        if v.dtype not in (np.bool_, np.uint32, np.int32):
            raise TypeError(
                f"batched_device_put: column {k!r} has dtype {v.dtype}; only "
                "bool/uint32/int32 ride the packed wire format"
            )
    return tuple(
        (k, _wire_kind(v.dtype), tuple(v.shape)) for k, v in arrays.items()
    )


def batched_device_put(
    t: Dict[str, Any],
    zero_metas: Tuple[Tuple[str, str, Tuple[int, ...]], ...] = (),
    force_packed: bool = False,
    elide_zeros: bool = False,
) -> Dict[str, Any]:
    """Move a dict of host numpy columns to device in ONE transfer.

    Per-array device_put pays a full dispatch round-trip per LEAF (~33ms
    on the tunneled runtime — a 37-column table cost >1s in pure latency).
    Packing every column into one flat int32 buffer makes it one
    round-trip + bandwidth; a cached jitted splitter rebuilds the columns
    on device.  bools widen to int32 on the wire; uint32 rides as a
    bitcast.

    ``zero_metas``: extra (name, kind, shape) columns known to be all-zero
    — created inside the SAME compiled splitter (zero wire bytes, and no
    second executable to load; one tunnel program-load costs ~0.4s).

    ``elide_zeros``: auto-detect all-zero columns and move them into
    zero_metas.  The zero-set keys the splitter executable, so this is
    for ONE-SHOT big builds (a 100k-pod table whose wide affinity planes
    are all zero pays seconds of tunnel transfer for nothing) — wave-loop
    builds whose feature mix flips per wave must not use it.
    """
    arrays = {k: np.asarray(v) for k, v in t.items()}
    if elide_zeros:
        live: Dict[str, Any] = {}
        zeros = list(zero_metas)
        for k, v in arrays.items():
            if v.size >= 4096 and not v.any():
                zeros.append((k, _wire_kind(v.dtype), tuple(v.shape)))
            else:
                live[k] = v
        arrays, zero_metas = live, tuple(zeros)
    metas = _col_metas(arrays)
    total = sum(v.size for v in arrays.values())
    _SCHEMA_SEEN[metas] = _SCHEMA_SEEN.get(metas, 0) + 1
    if _SCHEMA_SEEN[metas] == 1 and os.environ.get("MINISCHED_LOG_SCHEMAS"):
        import sys as _sys
        import time as _time

        cols = ",".join(f"{k}{list(v.shape)}" for k, v in arrays.items())
        print(
            f"[schema t={_time.monotonic():.1f}] total={total} {cols[:400]}",
            file=_sys.stderr,
            flush=True,
        )
    if (not force_packed and not zero_metas and total < 50_000
            and _SCHEMA_SEEN[metas] < 2):
        # small one-shot tables (tests, tiny scenarios): per-leaf puts are
        # fine.  Anything big OR repeated takes the packed path — the
        # splitter's compile is served by the persistent compilation cache
        # (utils/compilecache.py) after the first-ever build, so even a
        # one-shot 39-column constraint table beats 39 tunnel round-trips.
        return {k: jnp.asarray(v) for k, v in arrays.items()}
    _, flat = pack_columns(arrays)
    return _flat_splitter(metas, zero_metas)(flat)


def _register_table(cls):
    """Register a dataclass of jnp arrays as a pytree."""
    names = [f.name for f in fields(cls)]
    jax.tree_util.register_pytree_node(
        cls,
        lambda t: ([getattr(t, n) for n in names], None),
        lambda _, leaves: cls(**dict(zip(names, leaves))),
    )
    return cls


@_register_table
@dataclass
class NodeTable:
    """All scheduler-relevant node state, shape (N,) or (N, K)."""

    # identity
    name_hash: Any  # i32[N] fnv of node name (NodeName filter)
    # resources
    alloc_cpu: Any  # i32[N] allocatable milli-cpu
    alloc_mem: Any  # i32[N] allocatable MiB
    alloc_eph: Any  # i32[N] allocatable ephemeral-storage MiB
    alloc_pods: Any  # i32[N] allocatable pod count
    req_cpu: Any  # i32[N] requested (sum of assigned pods)
    req_mem: Any  # i32[N]
    req_eph: Any  # i32[N]
    req_pods: Any  # i32[N]
    # NonZeroRequested aggregates (upstream applies 100m CPU / 200Mi memory
    # defaults to request-less pods for the scorers only)
    nzreq_cpu: Any  # i32[N]
    nzreq_mem: Any  # i32[N]
    # flags
    unschedulable: Any  # bool[N] (spec.unschedulable)
    # nodenumber plugin
    suffix: Any  # i32[N] trailing-digit of name, -1 if none
    # multi-host slice topology (gang/topology-aware placement):
    # fnv hash of spec.slice_id (0 = not part of a slice), torus
    # coordinates within the slice, host index, and the slice's torus
    # DIMENSIONS (0 = unknown → non-wrapping distance) — static node
    # columns read by the GangTopology locality scorer
    slice_hash: Any  # i32[N]
    torus_x: Any  # i32[N]
    torus_y: Any  # i32[N]
    torus_z: Any  # i32[N]
    host_index: Any  # i32[N] (-1 = none)
    slice_dx: Any  # i32[N] torus ring size per axis (0 = unknown)
    slice_dy: Any  # i32[N]
    slice_dz: Any  # i32[N]
    # label/taint PROFILES: real clusters are built from node pools, so
    # 10k nodes collapse to a handful of distinct (labels, taints)
    # signatures.  Label/taint-dependent kernels (NodeAffinity,
    # TaintToleration, spread's eligibility gate) evaluate per
    # (pod × profile) — the heavy unrolled expression machinery shrinks
    # by N/Dp (~300× at config5 scale) — and expand to (pod × node) with
    # ONE gather through profile_id.  Padded node rows point at profile
    # 0; the evaluators' valid mask excludes them regardless.
    profile_id: Any  # i32[N] node → profile row
    # per-profile taints
    prof_taint_key: Any  # i32[Dp, MAX_TAINTS] fnv hash
    prof_taint_value: Any  # i32[Dp, MAX_TAINTS]
    prof_taint_effect: Any  # i32[Dp, MAX_TAINTS] effect code
    prof_num_taints: Any  # i32[Dp]
    # per-profile labels
    prof_label_key: Any  # i32[Dp, MAX_LABELS]
    prof_label_value: Any  # i32[Dp, MAX_LABELS]
    prof_label_numval: Any  # i32[Dp, MAX_LABELS] value parsed as int (Gt/Lt)
    prof_label_num_ok: Any  # bool[Dp, MAX_LABELS] value was an integer
    prof_num_labels: Any  # i32[Dp]
    # cached images (ImageLocality)
    image_key: Any  # i32[N, MAX_IMAGES] fnv of image name
    image_size_mb: Any  # i32[N, MAX_IMAGES]
    num_images: Any  # i32[N]
    # host ports claimed by assigned pods (NodePorts)
    used_port: Any  # i32[N, MAX_PORTS]
    num_used_ports: Any  # i32[N]
    # padding mask
    valid: Any  # bool[N]

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])


@_register_table
@dataclass
class PodTable:
    """All scheduler-relevant pending-pod state, shape (P,) or (P, K)."""

    req_cpu: Any  # i32[P] requested milli-cpu (sum of containers)
    req_mem: Any  # i32[P] MiB
    req_eph: Any  # i32[P] MiB
    req_pods: Any  # i32[P] (1)
    suffix: Any  # i32[P] trailing digit of name, -1 if none
    spec_node_name: Any  # i32[P] fnv of spec.node_name, 0 = unset (NodeName)
    # tolerations
    tol_key: Any  # i32[P, MAX_TOLERATIONS]
    tol_value: Any  # i32[P, MAX_TOLERATIONS]
    tol_effect: Any  # i32[P, MAX_TOLERATIONS]
    tol_op: Any  # i32[P, MAX_TOLERATIONS] 0=Equal 1=Exists
    tol_empty_key: Any  # bool[P, MAX_TOLERATIONS] key=="" (Exists-all)
    num_tols: Any  # i32[P]
    # node selector (spec.nodeSelector match_labels)
    sel_key: Any  # i32[P, MAX_LABELS]
    sel_value: Any  # i32[P, MAX_LABELS]
    num_sel: Any  # i32[P]
    # required node affinity: OR over terms, AND over requirements
    aff_required: Any  # bool[P] required affinity present (even if 0 terms)
    aff_key: Any  # i32[P, MAX_AFF_TERMS, MAX_AFF_REQS]
    aff_op: Any  # i32[P, T, R] operator code (OP_*)
    aff_vals: Any  # i32[P, T, R, MAX_AFF_VALS] value hashes (In/NotIn)
    aff_nvals: Any  # i32[P, T, R]
    aff_numval: Any  # i32[P, T, R] integer operand (Gt/Lt)
    aff_nreqs: Any  # i32[P, T]
    aff_nterms: Any  # i32[P] 0 = no required affinity
    # preferred node affinity: weighted terms (NodeAffinity score)
    pref_weight: Any  # i32[P, MAX_PREF_TERMS]
    pref_key: Any  # i32[P, MAX_PREF_TERMS, MAX_AFF_REQS]
    pref_op: Any  # i32[P, T, R]
    pref_vals: Any  # i32[P, T, R, MAX_AFF_VALS]
    pref_nvals: Any  # i32[P, T, R]
    pref_numval: Any  # i32[P, T, R]
    pref_nreqs: Any  # i32[P, T]
    pref_nterms: Any  # i32[P]
    # container images + host ports
    image_key: Any  # i32[P, MAX_CONTAINERS]
    num_containers: Any  # i32[P]
    port: Any  # i32[P, MAX_PORTS]
    num_ports: Any  # i32[P]
    # gang/topology placement (GangTopology scorer): gang identity hash
    # (0 = singleton) plus the gang's ALREADY-PLACED aggregate, computed
    # host-side at table build (engine/gang.py): majority slice hash,
    # torus coordinate SUMS (centroid × count — integer math, no
    # division until the kernel) and placed-member count
    gang_id: Any  # i32[P] fnv of 'namespace/gangname', 0 = none
    gang_slice: Any  # i32[P] majority slice of placed members, 0 = none
    gang_sx: Any  # i32[P] sum of placed members' torus_x
    gang_sy: Any  # i32[P]
    gang_sz: Any  # i32[P]
    gang_n: Any  # i32[P] placed-member count
    # deterministic tie-break seed per pod
    seed: Any  # u32[P]
    valid: Any  # bool[P]

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])


# ---------------------------------------------------------------------------
# Builders (host side, numpy)
# ---------------------------------------------------------------------------


def _name_suffix(name: str) -> int:
    """Trailing single ASCII digit of an object name, -1 if absent — the
    nodenumber plugin's key (nodenumber.go:21,50-64 parses the last rune
    with strconv.Atoi, which accepts ASCII digits only; str.isdigit would
    also accept Unicode digits and diverge from both Go and the native
    batch kernel)."""
    if name and "0" <= name[-1] <= "9":
        return int(name[-1])
    return -1


def pod_seed(uid: str) -> int:
    """Deterministic per-pod tie-break seed (unsigned 32-bit)."""
    return fnv1a32(uid) & 0xFFFFFFFF


#: NodeTable columns with a leading PROFILE axis (replicated on a mesh —
#: they are tiny and the node sharding must not split them)
NODE_PROFILE_COLS = (
    "prof_taint_key", "prof_taint_value", "prof_taint_effect",
    "prof_num_taints", "prof_label_key", "prof_label_value",
    "prof_label_numval", "prof_label_num_ok", "prof_num_labels",
)


def _node_table_skeleton(cap: int, prof_cap: int) -> Dict[str, Any]:
    def zeros(shape, dtype=np.int32):
        return np.zeros(shape, dtype)

    return dict(
        name_hash=zeros(cap),
        alloc_cpu=zeros(cap), alloc_mem=zeros(cap), alloc_eph=zeros(cap),
        alloc_pods=zeros(cap),
        req_cpu=zeros(cap), req_mem=zeros(cap), req_eph=zeros(cap),
        req_pods=zeros(cap), nzreq_cpu=zeros(cap), nzreq_mem=zeros(cap),
        unschedulable=np.zeros(cap, bool), suffix=np.full(cap, -1, np.int32),
        slice_hash=zeros(cap), torus_x=zeros(cap), torus_y=zeros(cap),
        torus_z=zeros(cap), host_index=np.full(cap, -1, np.int32),
        slice_dx=zeros(cap), slice_dy=zeros(cap), slice_dz=zeros(cap),
        profile_id=zeros(cap),
        prof_taint_key=zeros((prof_cap, MAX_TAINTS)),
        prof_taint_value=zeros((prof_cap, MAX_TAINTS)),
        prof_taint_effect=zeros((prof_cap, MAX_TAINTS)),
        prof_num_taints=zeros(prof_cap),
        prof_label_key=zeros((prof_cap, MAX_LABELS)),
        prof_label_value=zeros((prof_cap, MAX_LABELS)),
        prof_label_numval=zeros((prof_cap, MAX_LABELS)),
        prof_label_num_ok=np.zeros((prof_cap, MAX_LABELS), bool),
        prof_num_labels=zeros(prof_cap),
        image_key=zeros((cap, MAX_IMAGES)), image_size_mb=zeros((cap, MAX_IMAGES)),
        num_images=zeros(cap),
        used_port=zeros((cap, MAX_PORTS)), num_used_ports=zeros(cap),
        valid=np.zeros(cap, bool),
    )


class _ProfileRegistry:
    """Dedupes nodes into (labels, taints) profiles.  Pass 1 assigns ids
    (``pid_for``); the skeleton is then sized ``capacity`` (a multiple of
    64 — see there) and pass 2 encodes one row per profile
    (``encode_rows``)."""

    def __init__(self) -> None:
        self.ids: Dict[Tuple, int] = {}
        self.nodes: List[Any] = []  # representative node per profile

    def pid_for(self, node: Any) -> int:
        labels = node.metadata.labels
        if len(labels) > MAX_LABELS:
            raise ValueError(f"node {node.metadata.name}: >{MAX_LABELS} labels")
        taints = node.spec.taints
        if len(taints) > MAX_TAINTS:
            raise ValueError(f"node {node.metadata.name}: >{MAX_TAINTS} taints")
        sig = (
            tuple(sorted(labels.items())),
            # sorted: taint matching is order-independent, so [A,B] and
            # [B,A] must share a profile (spurious profiles waste Dp rows
            # and can cross the 64 boundary → recompile)
            tuple(sorted((t.key, t.value, t.effect) for t in taints)),
        )
        pid = self.ids.get(sig)
        if pid is None:
            pid = self.ids[sig] = len(self.nodes)
            self.nodes.append(node)
        return pid

    @property
    def capacity(self) -> int:
        # quantized HARD (multiples of 64): Dp is an executable shape, so
        # every distinct value is a fresh compile — a cluster gaining its
        # 17th label signature mid-run must not recompile the wave
        # evaluator (measured: a 75s compile inside a wave).  64 covers
        # any sane pool layout; past each multiple of 64 the next step
        # (and one recompile) is unavoidable.
        return pad_to(max(len(self.nodes), 1), 64)

    def encode_rows(self, t: Dict[str, Any]) -> None:
        for pid, node in enumerate(self.nodes):
            for j, taint in enumerate(node.spec.taints):
                t["prof_taint_key"][pid, j] = fnv1a32(taint.key)
                t["prof_taint_value"][pid, j] = fnv1a32(taint.value)
                t["prof_taint_effect"][pid, j] = _EFFECT_CODES[taint.effect]
            t["prof_num_taints"][pid] = len(node.spec.taints)
            labels = node.metadata.labels
            for j, (k, v) in enumerate(sorted(labels.items())):
                t["prof_label_key"][pid, j] = fnv1a32(k)
                t["prof_label_value"][pid, j] = fnv1a32(v)
                try:
                    t["prof_label_numval"][pid, j] = int(v)
                    t["prof_label_num_ok"][pid, j] = True
                except ValueError:
                    pass
            t["prof_num_labels"][pid] = len(labels)


def _prof_cap(reg: "_ProfileRegistry", requested: int = None) -> int:
    """Requested profile capacity, validated against the registry —
    warm builds pass the LIVE cluster's Dp so shapes match."""
    if requested is None:
        return reg.capacity
    if len(reg.nodes) > requested:
        raise ValueError(
            f"{len(reg.nodes)} profiles exceed requested capacity {requested}"
        )
    return requested


def node_profile_capacity(nodes: Sequence[Any]) -> int:
    """The profile-axis capacity (Dp) a table over ``nodes`` will get —
    for warm builds that must match the live executable's shapes."""
    reg = _ProfileRegistry()
    for node in nodes:
        reg.pid_for(node)
    return reg.capacity


def _encode_node_static(t: Dict[str, Any], i: int, node: Any, pid: int) -> None:
    """Everything about row ``i`` that comes from the Node object itself
    (identity, allocatable, images, profile membership) — the assigned-pod
    aggregates are filled by the caller, the label/taint planes live on
    the profile rows."""
    t["name_hash"][i] = fnv1a32(node.metadata.name)
    alloc = node.status.allocatable
    t["alloc_cpu"][i] = alloc.milli_cpu
    t["alloc_mem"][i] = alloc.memory // MIB
    t["alloc_eph"][i] = alloc.ephemeral_storage // MIB
    t["alloc_pods"][i] = alloc.pods
    t["unschedulable"][i] = node.spec.unschedulable
    t["suffix"][i] = _name_suffix(node.metadata.name)
    # written unconditionally: _patch_rows re-encodes updated rows in
    # place, and a node LEAVING a slice must clear its old coordinates
    has_slice = bool(node.spec.slice_id)
    t["slice_hash"][i] = fnv1a32(node.spec.slice_id) if has_slice else 0
    t["torus_x"][i] = node.spec.torus_x if has_slice else 0
    t["torus_y"][i] = node.spec.torus_y if has_slice else 0
    t["torus_z"][i] = node.spec.torus_z if has_slice else 0
    t["host_index"][i] = node.spec.host_index
    t["slice_dx"][i] = node.spec.slice_dx if has_slice else 0
    t["slice_dy"][i] = node.spec.slice_dy if has_slice else 0
    t["slice_dz"][i] = node.spec.slice_dz if has_slice else 0
    t["profile_id"][i] = pid
    images = node.status.images
    if len(images) > MAX_IMAGES:
        raise ValueError(f"node {node.metadata.name}: >{MAX_IMAGES} images")
    for j, (img, size) in enumerate(sorted(images.items())):
        t["image_key"][i, j] = fnv1a32(img)
        t["image_size_mb"][i, j] = size // MIB
    t["num_images"][i] = len(images)
    t["valid"][i] = True


def _encode_node_ports(t: Dict[str, Any], i: int, node_name: str, pods) -> None:
    used_ports: List[int] = []
    for p in pods:
        for c in p.spec.containers:
            if c.ports:
                used_ports.extend(c.ports)
    if len(used_ports) > MAX_PORTS:
        raise ValueError(f"node {node_name}: >{MAX_PORTS} used ports")
    for j, port in enumerate(used_ports):
        t["used_port"][i, j] = port
    t["num_used_ports"][i] = len(used_ports)


def build_node_table(nodes: Sequence[Any], pods_by_node: Dict[str, List[Any]] = None,
                     capacity: int = None,
                     prof_capacity: int = None) -> Tuple[NodeTable, List[str]]:
    """Build a NodeTable from Node objects (+ already-assigned pods).

    Returns (table, node_names) where node_names[i] is row i's name; the
    order is the given order (callers sort for determinism).
    """
    pods_by_node = pods_by_node or {}
    n = len(nodes)
    cap = capacity or pad_to(n)
    if n > cap:
        raise ValueError(f"{n} nodes exceed table capacity {cap}")
    reg = _ProfileRegistry()
    pids = [reg.pid_for(node) for node in nodes]
    t = _node_table_skeleton(cap, _prof_cap(reg, prof_capacity))
    reg.encode_rows(t)
    names: List[str] = []
    for i, node in enumerate(nodes):
        names.append(node.metadata.name)
        _encode_node_static(t, i, node, pids[i])
        assigned = pods_by_node.get(node.metadata.name, ())
        for p in assigned:
            req = p.resource_requests()
            t["req_cpu"][i] += req.milli_cpu
            t["req_mem"][i] += req.memory // MIB
            t["req_eph"][i] += req.ephemeral_storage // MIB
            t["req_pods"][i] += 1
            t["nzreq_cpu"][i] += req.milli_cpu or DEFAULT_NONZERO_CPU
            t["nzreq_mem"][i] += (req.memory // MIB) or DEFAULT_NONZERO_MEM_MIB
        _encode_node_ports(t, i, node.metadata.name, assigned)
    return NodeTable(**batched_device_put(t)), names


def build_node_table_from_infos(
    node_infos: Sequence[Any], capacity: int = None
) -> Tuple[NodeTable, List[str]]:
    """NodeTable straight from NodeInfo snapshots: reuses the request
    aggregates the snapshot already computed instead of re-walking every
    assigned pod (NodeInfo accumulates with the same MiB-floored integer
    discipline — see framework/nodeinfo.py — so the two builders are
    bit-identical).  The wave engine rebuilds the table every wave; at
    100k assigned pods the re-walk was the dominant host cost."""
    n = len(node_infos)
    cap = capacity or pad_to(n)
    if n > cap:
        raise ValueError(f"{n} nodes exceed table capacity {cap}")
    reg = _ProfileRegistry()
    pids = [reg.pid_for(ni.node) for ni in node_infos]
    t = _node_table_skeleton(cap, reg.capacity)
    reg.encode_rows(t)
    names: List[str] = []
    for i, ni in enumerate(node_infos):
        names.append(ni.name)
        _encode_node_static(t, i, ni.node, pids[i])
        _fill_aggregate_row(t, i, ni)
    return NodeTable(**batched_device_put(t)), names


def _fill_aggregate_row(t: Dict[str, Any], i: int, ni: Any) -> None:
    """The assigned-pod aggregate columns of row ``i`` from a NodeInfo
    (NodeInfo maintains them incrementally, ports included)."""
    t["req_cpu"][i] = ni.requested.milli_cpu
    t["req_mem"][i] = ni.req_mem_mib
    t["req_eph"][i] = ni.req_eph_mib
    t["req_pods"][i] = len(ni.pods)
    t["nzreq_cpu"][i] = ni.non_zero_requested.milli_cpu
    t["nzreq_mem"][i] = ni.nzreq_mem_mib
    ports = ni.used_ports
    if len(ports) > MAX_PORTS:
        raise ValueError(f"node {ni.name}: >{MAX_PORTS} used ports")
    for j, port in enumerate(ports):
        t["used_port"][i, j] = port
    t["num_used_ports"][i] = len(ports)


#: NodeTable columns that come from the Node OBJECT (cacheable across
#: waves keyed on resource_version) vs. the assigned-pod aggregates
#: (cheap, re-filled per wave from NodeInfo's incremental sums)
_NODE_STATIC_COLS = (
    "name_hash", "alloc_cpu", "alloc_mem", "alloc_eph", "alloc_pods",
    "unschedulable", "suffix", "profile_id",
    "slice_hash", "torus_x", "torus_y", "torus_z", "host_index",
    "slice_dx", "slice_dy", "slice_dz",
    "image_key", "image_size_mb", "num_images", "valid",
) + NODE_PROFILE_COLS
_NODE_AGG_COLS = (
    "req_cpu", "req_mem", "req_eph", "req_pods", "nzreq_cpu", "nzreq_mem",
    "used_port", "num_used_ports",
)

#: sentinel for "caller does not participate in dirty tracking" — distinct
#: from None, which means "everything is dirty, rebuild the base fully"
DIRTY_UNTRACKED = object()


def _agg_delta_fp(agg_delta) -> Tuple:
    """Canonical fingerprint of a per-node assume-delta (see
    CachedNodeTableBuilder._apply_agg_delta's row shape) — the idle-wave
    gate compares THIS, not identity: two consecutive waves folding the
    same surviving assumptions produce byte-identical aggregate columns,
    so re-folding is pure waste.  O(len(delta)); () for no delta."""
    if not agg_delta:
        return ()
    return tuple(
        sorted(
            (name, tuple(d[:6]), tuple(d[6]))
            for name, d in agg_delta.items()
        )
    )


class CachedNodeTableBuilder:
    """Per-wave NodeTable builds with the static columns cached.

    The wave engine rebuilds its NodeTable every wave, but the node
    OBJECTS rarely change — only the assigned-pod aggregates do.  The
    static encode (hashing names/labels/taints for 10k nodes) is ~0.3s
    per wave; this builder re-runs it only when the name-sorted
    (name, resource_version) signature changes (node added/removed/
    updated) and otherwise just re-fills the aggregate columns from the
    NodeInfos' incrementally-maintained sums.
    """

    def __init__(self, device_static: bool = True, mesh: Any = None):
        import threading

        # scan lanes (loop thread) and the wave-pipeline build worker
        # share ONE builder — the static cache, aggregate base, and
        # double buffers below are all mutable state, so every build
        # serializes through this lock (contention only when a scan
        # flush coincides with a pipelined build)
        self._build_lock = threading.RLock()
        #: jax.sharding.Mesh — static columns then live device-resident
        #: SHARDED on the node axis (profile planes replicated), and node
        #: capacities quantize to lcm(128, node-axis size) so every shard
        #: gets equal whole tiles (parallel/sharding.cap_multiple)
        self._mesh = mesh
        self._cap_mult = 128
        if mesh is not None:
            from minisched_tpu.parallel.sharding import (
                cap_multiple,
                mesh_axis_sizes,
            )

            self._cap_mult = cap_multiple(128, mesh_axis_sizes(mesh)[1])
        #: lazily-built single-default-device copy of the static columns
        #: — the mesh engine's per-wave sharding-failure fallback runs
        #: the single-device evaluator against it (see
        #: DeviceScheduler._eval_packed_wave)
        self._static_dev_fallback: Optional[Dict[str, Any]] = None
        self._sig = None
        self._static: Dict[str, Any] = {}
        self._static_dev: Dict[str, Any] = {}
        # incremental AGGREGATE base: persistent host copies of the
        # assigned-pod sum columns, re-encoded only for the rows a
        # snapshot's dirty-set names (informer events mark nodes dirty;
        # SchedulerCache.snapshot_for_tables drains the set atomically
        # with the snapshot).  A full _fill_aggregates walk is O(all
        # nodes) of Python attribute access per wave (~0.7s of the
        # config5 wave loop); the incremental path is O(touched nodes).
        self._agg_base: Optional[Dict[str, Any]] = None
        self._agg_base_names: Tuple[str, ...] = ()
        #: dirty rows re-encoded by the last build (0 = full rebuild
        #: counted as len(nodes)); observability reads it per wave
        self.last_dirty_rows = 0
        #: True when the last tracked build took the idle-wave skip path
        #: (tables reused wholesale — no encode, no fold, no transfer);
        #: the pipeline copies it onto the PreparedWave per wave
        self.last_build_skipped = False
        # idle-wave reuse cache (ISSUE 8): the last TRACKED build's
        # output, reusable wholesale when a later snapshot proves nothing
        # changed — dirty-set empty, same capacities, same assume-delta
        # fingerprint, and the statics unchanged (cache epoch match, or
        # the (name, rv) signature compare when the caller has no epoch).
        # Invalidated whenever the statics re-encode or the aggregate
        # base is touched; packing paths copy out of the scratch buffers,
        # so the cached tables can never be mutated by later builds.
        self._reuse_packed: Optional[Tuple] = None
        self._reuse_table: Optional[Tuple] = None
        self._reuse_key: Optional[Tuple] = None
        self._reuse_epoch: Optional[int] = None
        # reusable per-wave aggregate scratch: the assume-delta folds
        # into a COPY of the base (never the base itself).  ONE buffer
        # suffices — what keeps an in-flight wave's tables safe from the
        # next build is not buffer rotation but the copy every packing
        # path makes under _build_lock (pack_columns' np.concatenate /
        # batched_device_put) before the lock releases.
        self._agg_scratch: Optional[Dict[str, Any]] = None
        # incremental-rebuild state: host copy of the static columns, the
        # persistent profile registry, and the encoded profile capacity —
        # a node UPDATE re-encodes just its row instead of all N (a 2k-
        # node label change used to re-encode 10k nodes, ~1.2s host work)
        self._host_static: Dict[str, Any] = {}
        self._reg: Any = None
        self._prof_cap_val: int = 0
        #: keep the static columns device-resident between builds.  Turn
        #: OFF when the consumer donates its node-table argument against
        #: a sharding that could alias these buffers (the mesh engine:
        #: sharded steps donate argnum 0 — a 1-device mesh's device_put
        #: may alias instead of copy, and a donated cached buffer poisons
        #: every later wave)
        self._device_static = device_static
        self._names: List[str] = []
        self._name_index: Dict[str, int] = {}

    def _static_sig(self, node_infos: Sequence[Any], cap: int,
                    prof_capacity: int) -> Tuple:
        return (
            cap,
            prof_capacity,
            tuple(
                (ni.node.metadata.name, ni.node.metadata.resource_version)
                for ni in node_infos
            ),
        )

    def _drop_reuse(self) -> None:
        """Invalidate the idle-wave reuse cache (statics about to
        re-encode, aggregate base about to change, or a build failed)."""
        self._reuse_packed = None
        self._reuse_table = None
        self._reuse_key = None
        self._reuse_epoch = None

    def _ensure_static(self, node_infos: Sequence[Any], cap: int,
                       prof_capacity: int) -> None:
        """Re-encode + (optionally) re-upload the static columns only when
        the name-sorted (name, resource_version) signature changes."""
        sig = self._static_sig(node_infos, cap, prof_capacity)
        if sig == self._sig:
            return
        self._drop_reuse()  # statics changing: cached tables are stale
        if self._patch_rows(node_infos, sig):
            return
        reg = _ProfileRegistry()
        pids = [reg.pid_for(ni.node) for ni in node_infos]
        t = _node_table_skeleton(cap, _prof_cap(reg, prof_capacity))
        reg.encode_rows(t)
        names: List[str] = []
        for i, ni in enumerate(node_infos):
            names.append(ni.name)
            _encode_node_static(t, i, ni.node, pids[i])
        self._host_static = {k: t[k] for k in _NODE_STATIC_COLS}
        self._reg = reg
        self._prof_cap_val = _prof_cap(reg, prof_capacity)
        # static columns live on DEVICE between builds: re-uploading the
        # label/taint/image planes for 10k+ nodes every wave cost tens of
        # MB of tunnel bandwidth per wave for bytes that only change when
        # a node object changes.  The host copy is retained for row
        # patching (~2MB at 10k nodes).
        self._static = {} if self._device_static else dict(self._host_static)
        if self._device_static:
            self._place_static_dev(self._host_static)
        self._names = names
        self._name_index = {name: i for i, name in enumerate(names)}
        self._sig = sig

    def _patch_rows(self, node_infos: Sequence[Any], sig: Tuple) -> bool:
        """Incremental static update: same node set/order/capacities, only
        some nodes' resource_versions changed — re-encode just those rows
        in the host copy and re-upload.  Returns False (caller does a full
        rebuild) on membership/order/capacity changes, a stepped profile
        capacity, or an encode error."""
        cap, prof_capacity, rows = sig
        if (
            self._sig is None
            or not self._host_static
            or self._sig[0] != cap
            or self._sig[1] != prof_capacity
            or len(self._sig[2]) != len(rows)
            or any(a[0] != b[0] for a, b in zip(self._sig[2], rows))
        ):
            return False
        changed = [
            i for i, (a, b) in enumerate(zip(self._sig[2], rows)) if a[1] != b[1]
        ]
        t = self._host_static
        try:
            for i in changed:
                node = node_infos[i].node
                pid = self._reg.pid_for(node)
                if _prof_cap(self._reg, prof_capacity) != self._prof_cap_val:
                    return False  # Dp stepped: schema change, rebuild fully
                # clear variable-length slots a shorter re-encode would
                # leave stale
                t["image_key"][i] = 0
                t["image_size_mb"][i] = 0
                _encode_node_static(t, i, node, pid)
        except ValueError:
            return False
        # profile planes: new profiles appended by pid_for get encoded;
        # existing rows are rewritten in place (idempotent)
        self._reg.encode_rows(t)
        if self._device_static:
            self._place_static_dev(t)
        else:
            self._static = dict(t)
        self._sig = sig
        return True

    def _place_static_dev(self, t: Dict[str, Any]) -> None:
        """Upload the static columns; under a mesh they land SHARDED
        (node axis split, profile planes replicated) so the packed wave
        program consumes them in place — no per-wave resharding."""
        cols = batched_device_put(t)
        if self._mesh is not None:
            from minisched_tpu.parallel.sharding import static_col_shardings

            cols = jax.device_put(
                cols, static_col_shardings(self._mesh, cols)
            )
        self._static_dev = cols
        self._static_dev_fallback = None  # stale: re-derive on demand

    def static_dev_default(self) -> Dict[str, Any]:
        """Single-default-device copy of the current static columns —
        what the mesh engine's per-wave fallback evaluator consumes when
        a sharded wave fails (the sharded statics would drag the
        single-device program back onto the mesh)."""
        with self._build_lock:
            if not self._host_static:
                raise RuntimeError("no static columns built yet")
            if self._static_dev_fallback is None:
                self._static_dev_fallback = batched_device_put(
                    dict(self._host_static)
                )
            return self._static_dev_fallback

    @staticmethod
    def _fill_aggregates(node_infos: Sequence[Any], cap: int) -> Dict[str, Any]:
        t: Dict[str, Any] = {}
        for k in _NODE_AGG_COLS:
            t[k] = (
                np.zeros((cap, MAX_PORTS), np.int32)
                if k == "used_port"
                else np.zeros(cap, np.int32)
            )
        for i, ni in enumerate(node_infos):
            _fill_aggregate_row(t, i, ni)
        return t

    def _apply_agg_delta(self, t: Dict[str, Any], agg_delta) -> None:
        """Fold the wave engine's assume-cache deltas into the aggregate
        columns numerically — the alternative (NodeInfo.add_pod per assumed
        pod into cloned infos) cost ~250ms per 16k-pod wave and duplicated
        work the cache's own event path does once the binds land.  A delta
        row is ``[milli_cpu, mem_mib, eph_mib, pods, nz_milli_cpu,
        nz_mem_mib, ports]`` with the exact NodeInfo.add_pod quantization
        (sum-of-floors MiB — parity depends on it)."""
        idx = self._name_index
        for name, d in agg_delta.items():
            i = idx.get(name)
            if i is None:
                continue  # node left the roster; the assumption prunes next
            t["req_cpu"][i] += d[0]
            t["req_mem"][i] += d[1]
            t["req_eph"][i] += d[2]
            t["req_pods"][i] += d[3]
            t["nzreq_cpu"][i] += d[4]
            t["nzreq_mem"][i] += d[5]
            ports = d[6]
            if ports:
                n = int(t["num_used_ports"][i])
                if n + len(ports) > MAX_PORTS:
                    raise ValueError(f"node {name}: >{MAX_PORTS} used ports")
                for j, port in enumerate(ports, start=n):
                    t["used_port"][i, j] = port
                t["num_used_ports"][i] = n + len(ports)

    def node_capacity(self, n: int) -> int:
        """The capacity a table over ``n`` nodes will get — pad_to with
        this builder's mesh-aligned multiple (prewarm must match it or
        the warm executable is wasted)."""
        return pad_to(max(n, 1), self._cap_mult)

    def _cap_for(self, node_infos: Sequence[Any], capacity) -> int:
        n = len(node_infos)
        cap = capacity or pad_to(n, self._cap_mult)
        if n > cap:
            raise ValueError(f"{n} nodes exceed table capacity {cap}")
        if cap % self._cap_mult:
            raise ValueError(
                f"node capacity {cap} not a multiple of {self._cap_mult} "
                "(mesh node-axis shards need equal whole tiles)"
            )
        return cap

    def _update_agg_base(
        self, node_infos: Sequence[Any], cap: int, dirty
    ) -> Dict[str, Any]:
        """Bring the persistent aggregate base up to this snapshot.
        ``dirty`` names the nodes whose aggregates changed since the last
        drained snapshot (None = rebuild everything).  Any failure
        invalidates the base — a partial application must never survive
        into the next wave's increments."""
        names = tuple(ni.name for ni in node_infos)
        base = self._agg_base
        self._drop_reuse()  # base about to change; caller re-caches
        try:
            if (
                base is None
                or dirty is None
                or self._agg_base_names != names
                or base["req_cpu"].shape[0] != cap
            ):
                base = self._fill_aggregates(node_infos, cap)
                self._agg_base = base
                self._agg_base_names = names
                self.last_dirty_rows = len(node_infos)
                return base
            idx = self._name_index
            n = 0
            for name in dirty:
                i = idx.get(name)
                if i is None:
                    continue  # left the roster: membership change would
                    # have arrived as dirty=None; a stray name is stale
                # clear variable-length slots a shorter re-encode would
                # leave stale, then re-encode the row from ITS NodeInfo
                base["used_port"][i] = 0
                _fill_aggregate_row(base, i, node_infos[i])
                n += 1
            self.last_dirty_rows = n
            return base
        except Exception:
            self._agg_base = None  # never trust a half-applied base
            raise

    def _wave_agg_copy(self, base: Dict[str, Any], cap: int) -> Dict[str, Any]:
        """Copy the base into the reusable scratch buffer — the per-wave
        assume-delta folds into the copy, never the base.  Reuse is safe
        because every consumer path copies out of the scratch (see
        _agg_scratch) before _build_lock releases."""
        buf = self._agg_scratch
        if buf is None or buf["req_cpu"].shape[0] != cap:
            buf = self._agg_scratch = {
                k: np.empty_like(v) for k, v in base.items()
            }
        for k, v in base.items():
            np.copyto(buf[k], v)
        return buf

    def _try_reuse(
        self, cached, node_infos: Sequence[Any], cap: int, prof_capacity,
        dirty, agg_delta, epoch,
    ):
        """The idle-wave gate (ISSUE 8): return the previous build's
        output wholesale — no static encode, no aggregate re-fold, no
        packing, no device transfer — when this snapshot provably changes
        nothing: the drained dirty-set is EMPTY (tracked), capacities
        match, the assume-delta fingerprint matches, and the node objects
        are unchanged (cache-epoch handshake; callers without an epoch
        pay an O(nodes) signature compare, still zero build work).
        Returns None when any condition fails — the caller builds."""
        if dirty is DIRTY_UNTRACKED:
            # untracked (scan-lane / prewarm) builds leave the wave
            # stats ALONE: the pipeline's build worker reads
            # last_build_skipped / last_dirty_rows after its tracked
            # build returns, and a concurrent loop-thread scan flush
            # through this same builder must not clobber them
            return None
        self.last_build_skipped = False
        if (
            dirty is None
            or dirty
            or cached is None
            or self._agg_base is None
            or self._reuse_key is None
            or self._reuse_key[0] != cap
            or self._reuse_key[1] != prof_capacity
            or self._reuse_key[2] != _agg_delta_fp(agg_delta)
        ):
            return None
        if epoch is not None and self._reuse_epoch is not None:
            if epoch != self._reuse_epoch:
                return None  # node objects (or aggregates) changed
        elif self._static_sig(node_infos, cap, prof_capacity) != self._sig:
            return None
        from minisched_tpu.observability import counters

        counters.inc("wave_build.skipped")
        self.last_dirty_rows = 0
        self.last_build_skipped = True
        return cached

    def _cache_reuse(
        self, out, packed: bool, cap: int, prof_capacity, agg_delta, epoch
    ):
        """Record a TRACKED build's output for the idle-wave gate and
        return it (possibly upgraded).  One key serves both modes; the
        other mode's cached output is dropped so a mode switch can never
        serve tables keyed for the other.

        Packed single-device outputs get their aggregate flat buffer
        committed to device HERE: the consumer jit then uses the
        committed array directly — the wave that built it still pays its
        one transfer (device_put instead of jit's implicit one), and
        every SKIPPED wave after it ships zero bytes.  Under a mesh the
        flat stays host-side (MeshPackedCaller owns placement there, and
        the per-wave single-device fallback consumes the same buffer)."""
        self._reuse_key = (cap, prof_capacity, _agg_delta_fp(agg_delta))
        self._reuse_epoch = epoch
        if packed:
            if self._mesh is None:
                static_dev, agg, names = out
                agg = PackedTable(
                    agg.metas, agg.zero_metas,
                    jax.device_put(agg.flat), agg.capacity,
                )
                out = (static_dev, agg, names)
            self._reuse_packed, self._reuse_table = out, None
        else:
            self._reuse_table, self._reuse_packed = out, None
        return out

    def _aggregates_for(
        self, node_infos: Sequence[Any], cap: int, dirty, agg_delta
    ) -> Dict[str, Any]:
        if dirty is DIRTY_UNTRACKED:
            # caller outside the dirty protocol (scan lanes, prewarm,
            # one-shot builds): fresh fill, persistent base untouched —
            # its undrained changes stay pending for the wave path, and
            # the wave stats (last_dirty_rows/last_build_skipped) stay
            # the TRACKED builds' (see _try_reuse: the pipeline reads
            # them cross-thread after its build)
            t = self._fill_aggregates(node_infos, cap)
        else:
            base = self._update_agg_base(node_infos, cap, dirty)
            t = self._wave_agg_copy(base, cap)
        if agg_delta:
            self._apply_agg_delta(t, agg_delta)
        return t

    def build(self, node_infos: Sequence[Any], capacity: int = None,
              prof_capacity: int = None, agg_delta=None,
              dirty=DIRTY_UNTRACKED, epoch=None):
        with self._build_lock:
            try:
                cap = self._cap_for(node_infos, capacity)
                reused = self._try_reuse(
                    self._reuse_table, node_infos, cap, prof_capacity,
                    dirty, agg_delta, epoch,
                )
                if reused is not None:
                    table, names = reused
                    return table, list(names)
                self._ensure_static(node_infos, cap, prof_capacity)
                t = self._aggregates_for(node_infos, cap, dirty, agg_delta)
                if self._device_static:
                    cols = dict(self._static_dev)
                    cols.update(batched_device_put(t))
                else:
                    cols = dict(self._static)
                    cols.update(t)
                    cols = batched_device_put(cols)
                out = NodeTable(**cols), list(self._names)
                if dirty is not DIRTY_UNTRACKED:
                    out = self._cache_reuse(
                        out, False, cap, prof_capacity, agg_delta, epoch
                    )
                return out
            except Exception:
                # a TRACKED build consumed its snapshot's drained dirty
                # set the moment the snapshot was taken — failing at ANY
                # point (static encode, device put) before the base
                # reflects those rows would strand them stale forever;
                # invalidate so the next tracked build refills fully
                if dirty is not DIRTY_UNTRACKED:
                    self._agg_base = None
                self._drop_reuse()
                raise

    def build_packed(self, node_infos: Sequence[Any], capacity: int = None,
                     prof_capacity: int = None, agg_delta=None,
                     dirty=DIRTY_UNTRACKED, epoch=None):
        """Single-program variant: (static device cols, PackedTable of the
        per-wave aggregate columns, names).  The consumer jit unpacks the
        aggregates and merges the device-resident statics inside its own
        program — no splitter executable per wave.  Requires
        ``device_static=True`` (the statics must already live on device).

        ``dirty``: the snapshot's drained dirty-set (see
        SchedulerCache.snapshot_for_tables) — the aggregate columns then
        re-encode only those rows into the persistent base instead of
        walking every NodeInfo.  Callers outside the dirty protocol leave
        the default (full fresh fill, base untouched).

        ``epoch``: the cache epoch the snapshot carried — with an EMPTY
        drained dirty-set and an unchanged assume-delta it arms the
        idle-wave gate (_try_reuse): the previous build's tables come
        back wholesale and ``wave_build.skipped`` increments."""
        with self._build_lock:
            try:
                assert self._device_static, (
                    "build_packed needs device-resident statics"
                )
                cap = self._cap_for(node_infos, capacity)
                reused = self._try_reuse(
                    self._reuse_packed, node_infos, cap, prof_capacity,
                    dirty, agg_delta, epoch,
                )
                if reused is not None:
                    static_dev, packed, names = reused
                    return static_dev, packed, list(names)
                self._ensure_static(node_infos, cap, prof_capacity)
                t = self._aggregates_for(node_infos, cap, dirty, agg_delta)
                out = (
                    self._static_dev,
                    pack_table(t, (), cap),
                    list(self._names),
                )
                if dirty is not DIRTY_UNTRACKED:
                    out = self._cache_reuse(
                        out, True, cap, prof_capacity, agg_delta, epoch
                    )
                return out
            except Exception:
                # see build(): a failed TRACKED build must not strand the
                # drained dirty rows — invalidate, full refill next time
                if dirty is not DIRTY_UNTRACKED:
                    self._agg_base = None
                self._drop_reuse()
                raise


def _encode_terms(t: Dict[str, Any], prefix: str, i: int, terms, max_terms: int,
                  what: str) -> None:
    """Encode NodeSelectorTerms (or preferred-term preferences) into the
    ``{prefix}_*`` expression arrays of row ``i``."""
    if len(terms) > max_terms:
        raise ValueError(f"{what}: >{max_terms} node-affinity terms")
    for j, term in enumerate(terms):
        reqs = term.match_expressions
        if len(reqs) > MAX_AFF_REQS:
            raise ValueError(f"{what}: >{MAX_AFF_REQS} requirements per term")
        for r, req in enumerate(reqs):
            t[f"{prefix}_key"][i, j, r] = fnv1a32(req.key)
            t[f"{prefix}_op"][i, j, r] = _OP_CODES[req.operator]
            if req.operator in ("In", "NotIn"):
                if len(req.values) > MAX_AFF_VALS:
                    raise ValueError(f"{what}: >{MAX_AFF_VALS} values per expression")
                for v, val in enumerate(req.values):
                    t[f"{prefix}_vals"][i, j, r, v] = fnv1a32(val)
                t[f"{prefix}_nvals"][i, j, r] = len(req.values)
            elif req.operator in ("Gt", "Lt"):
                try:
                    t[f"{prefix}_numval"][i, j, r] = int(req.values[0])
                except (ValueError, IndexError, OverflowError):
                    t[f"{prefix}_op"][i, j, r] = OP_INVALID
        t[f"{prefix}_nreqs"][i, j] = len(reqs)
    t[f"{prefix}_nterms"][i] = len(terms)


def _pod_is_simple(pod: Any) -> bool:
    """A pod the vectorized fast path can encode: default-shaped spec with
    at most resource requests — no tolerations / selector / affinity /
    spread constraints / host ports / pinned node, single container."""
    spec = pod.spec
    return (
        not spec.tolerations
        and not spec.node_selector
        and spec.affinity is None
        and not spec.topology_spread_constraints
        and not spec.node_name
        and spec.gang is None
        and len(spec.containers) <= 1
        and not (spec.containers and spec.containers[0].ports)
    )


#: shared all-zero request vector for container-less simple pods (read-only)
_ZERO_REQS = None  # set lazily below to avoid import cycles


def _get_zero_reqs():
    global _ZERO_REQS
    if _ZERO_REQS is None:
        from minisched_tpu.api.objects import ResourceList

        _ZERO_REQS = ResourceList()
    return _ZERO_REQS


def _build_pod_table_fast(pods: Sequence[Any], cap: int,
                          device: bool = True,
                          invalid_rows: Sequence[Any] = ()):
    """Columnar fast path for simple pods: per-field list comprehensions +
    native batch string kernels (minisched_tpu.native) instead of the
    per-pod row-write loop — ~10× on the host build that feeds the device
    waves (the reference instead re-lists and re-wraps objects per cycle,
    minisched.go:40)."""
    from minisched_tpu import native

    p = len(pods)
    names = [pod.metadata.name for pod in pods]
    # simple pods have ≤1 container, so the request sum IS the container's
    # already-parsed ResourceList — reading it directly skips the
    # per-pod ResourceList allocation + memo write of resource_requests()
    # (~60% of the cold fast build; the memo exists for the paths that DO
    # aggregate per pod: assume-cache, NodeInfo).  req_pods is pinned to 1
    # below, matching resource_requests' max(pods, 1) floor.
    _zero = _get_zero_reqs()
    reqs = [
        pod.spec.containers[0].requests if pod.spec.containers else _zero
        for pod in pods
    ]

    def col(values, dtype=np.int32, fill=0):
        arr = np.full(cap, fill, dtype)
        arr[:p] = values
        return arr

    host = dict(
        req_cpu=col([r.milli_cpu for r in reqs]),
        req_mem=col([r.memory // MIB for r in reqs]),
        req_eph=col([r.ephemeral_storage // MIB for r in reqs]),
        req_pods=col(1),
        # padding rows match the slow path's -1 initializer exactly
        suffix=col(native.name_suffix_batch(names), fill=-1),
        num_containers=col([len(pod.spec.containers) for pod in pods]),
        seed=col(
            native.pod_seed_batch(
                [pod.metadata.uid or pod.metadata.name for pod in pods]
            ),
            np.uint32,
        ),
        valid=col(True, bool),
    )
    img = np.zeros((cap, MAX_CONTAINERS), np.int32)
    img[:p, 0] = [
        fnv1a32(pod.spec.containers[0].image)
        if pod.spec.containers and pod.spec.containers[0].image
        else 0
        for pod in pods
    ]
    host["image_key"] = img
    # every constraint column is all-zero for simple pods: materialized ON
    # DEVICE inside the same compiled splitter as the packed transfer (no
    # wire bytes, no second executable) — the table is ~50× wider than its
    # live fast-path columns and PCIe/tunnel bandwidth on the host build
    # was the wave pipeline's bottleneck.
    if invalid_rows:
        host["valid"][list(invalid_rows)] = False
    if not device:
        return pack_table(host, _zero_pod_metas(cap), cap), names
    cols = batched_device_put(host, zero_metas=_zero_pod_metas(cap))
    return PodTable(**cols), names


@functools.lru_cache(maxsize=None)
def _zero_pod_metas(cap: int) -> Tuple[Tuple[str, str, Tuple[int, ...]], ...]:
    """(name, kind, shape) of every PodTable column that is all-zero for
    simple pods, for ``batched_device_put``'s on-device zero fill."""
    TR = (cap, MAX_AFF_TERMS, MAX_AFF_REQS)
    PR = (cap, MAX_PREF_TERMS, MAX_AFF_REQS)
    i32, b = "int32", "bool"
    return (
        ("spec_node_name", i32, (cap,)),
        ("tol_key", i32, (cap, MAX_TOLERATIONS)),
        ("tol_value", i32, (cap, MAX_TOLERATIONS)),
        ("tol_effect", i32, (cap, MAX_TOLERATIONS)),
        ("tol_op", i32, (cap, MAX_TOLERATIONS)),
        ("tol_empty_key", b, (cap, MAX_TOLERATIONS)),
        ("num_tols", i32, (cap,)),
        ("sel_key", i32, (cap, MAX_LABELS)),
        ("sel_value", i32, (cap, MAX_LABELS)),
        ("num_sel", i32, (cap,)),
        ("aff_required", b, (cap,)),
        ("aff_key", i32, TR),
        ("aff_op", i32, TR),
        ("aff_vals", i32, TR + (MAX_AFF_VALS,)),
        ("aff_nvals", i32, TR),
        ("aff_numval", i32, TR),
        ("aff_nreqs", i32, TR[:2]),
        ("aff_nterms", i32, (cap,)),
        ("pref_weight", i32, (cap, MAX_PREF_TERMS)),
        ("pref_key", i32, PR),
        ("pref_op", i32, PR),
        ("pref_vals", i32, PR + (MAX_AFF_VALS,)),
        ("pref_nvals", i32, PR),
        ("pref_numval", i32, PR),
        ("pref_nreqs", i32, PR[:2]),
        ("pref_nterms", i32, (cap,)),
        ("port", i32, (cap, MAX_PORTS)),
        ("num_ports", i32, (cap,)),
        ("gang_id", i32, (cap,)),
        ("gang_slice", i32, (cap,)),
        ("gang_sx", i32, (cap,)),
        ("gang_sy", i32, (cap,)),
        ("gang_sz", i32, (cap,)),
        ("gang_n", i32, (cap,)),
    )


def build_pod_table(pods: Sequence[Any], capacity: int = None,
                    force_packed: bool = False, device: bool = True,
                    invalid_rows: Sequence[int] = (),
                    elide_zeros: bool = False,
                    gang_view: Optional[Dict[str, Tuple]] = None):
    """``device=False`` returns (PackedTable, names) instead of a
    device-resident PodTable — for consumers that unpack the flat
    buffer inside their own jitted program (ops/repair packed mode).
    ``invalid_rows``: row indices marked valid=False — INTERIOR padding
    for the blocked scan lane, whose block structure needs placeholder
    rows between real pods (tail padding is automatic).
    ``elide_zeros`` (device=True slow path only): materialize all-zero
    columns on device instead of shipping them — for one-shot big
    builds (see batched_device_put); wave-loop builds must not set it.
    ``gang_view``: gang key → (slice_hash, sx, sy, sz, n) aggregate of
    the gang's ALREADY-PLACED members (engine/gang.py) — encoded into
    each member row's gang_* columns so the GangTopology scorer pulls
    new members toward them; None leaves the aggregates zero (cold
    start / gang-less callers)."""
    p = len(pods)
    cap = capacity or pad_to(p)
    if p > cap:
        raise ValueError(f"{p} pods exceed table capacity {cap}")

    if all(_pod_is_simple(pod) for pod in pods):
        return _build_pod_table_fast(
            pods, cap, device=device, invalid_rows=invalid_rows
        )

    def zeros(shape, dtype=np.int32):
        return np.zeros(shape, dtype)

    TR = (cap, MAX_AFF_TERMS, MAX_AFF_REQS)
    PR = (cap, MAX_PREF_TERMS, MAX_AFF_REQS)
    t = dict(
        req_cpu=zeros(cap), req_mem=zeros(cap), req_eph=zeros(cap),
        req_pods=zeros(cap),
        suffix=np.full(cap, -1, np.int32), spec_node_name=zeros(cap),
        tol_key=zeros((cap, MAX_TOLERATIONS)), tol_value=zeros((cap, MAX_TOLERATIONS)),
        tol_effect=zeros((cap, MAX_TOLERATIONS)), tol_op=zeros((cap, MAX_TOLERATIONS)),
        tol_empty_key=np.zeros((cap, MAX_TOLERATIONS), bool), num_tols=zeros(cap),
        sel_key=zeros((cap, MAX_LABELS)), sel_value=zeros((cap, MAX_LABELS)),
        num_sel=zeros(cap),
        aff_required=np.zeros(cap, bool),
        aff_key=zeros(TR), aff_op=zeros(TR), aff_vals=zeros(TR + (MAX_AFF_VALS,)),
        aff_nvals=zeros(TR), aff_numval=zeros(TR),
        aff_nreqs=zeros(TR[:2]), aff_nterms=zeros(cap),
        pref_weight=zeros((cap, MAX_PREF_TERMS)),
        pref_key=zeros(PR), pref_op=zeros(PR), pref_vals=zeros(PR + (MAX_AFF_VALS,)),
        pref_nvals=zeros(PR), pref_numval=zeros(PR),
        pref_nreqs=zeros(PR[:2]), pref_nterms=zeros(cap),
        image_key=zeros((cap, MAX_CONTAINERS)), num_containers=zeros(cap),
        port=zeros((cap, MAX_PORTS)), num_ports=zeros(cap),
        gang_id=zeros(cap), gang_slice=zeros(cap),
        gang_sx=zeros(cap), gang_sy=zeros(cap), gang_sz=zeros(cap),
        gang_n=zeros(cap),
        seed=np.zeros(cap, np.uint32), valid=np.zeros(cap, bool),
    )
    # common columns go columnar (listcomps + native batch kernels — same
    # encoding as the fast path); the per-pod loop below only touches the
    # complex optional fields a pod actually carries
    from minisched_tpu import native

    names = [pod.metadata.name for pod in pods]
    reqs = [pod.resource_requests() for pod in pods]
    t["req_cpu"][:p] = [r.milli_cpu for r in reqs]
    t["req_mem"][:p] = [r.memory // MIB for r in reqs]
    t["req_eph"][:p] = [r.ephemeral_storage // MIB for r in reqs]
    t["req_pods"][:p] = 1
    t["suffix"][:p] = native.name_suffix_batch(names)
    t["num_containers"][:p] = [len(pod.spec.containers) for pod in pods]
    t["seed"][:p] = native.pod_seed_batch(
        [pod.metadata.uid or pod.metadata.name for pod in pods]
    )
    t["valid"][:p] = True
    t["image_key"][:p, 0] = [
        fnv1a32(pod.spec.containers[0].image)
        if pod.spec.containers and pod.spec.containers[0].image
        else 0
        for pod in pods
    ]

    # pods sharing one affinity structure (every replica of a deployment)
    # encode once: the cache maps the structural signature to the encoded
    # row values, skipping re-hashing per pod
    aff_cache: Dict[Any, Dict[str, Any]] = {}
    _AFF_FIELDS = (
        "aff_required", "aff_key", "aff_op", "aff_vals", "aff_nvals",
        "aff_numval", "aff_nreqs", "aff_nterms", "pref_weight", "pref_key",
        "pref_op", "pref_vals", "pref_nvals", "pref_numval", "pref_nreqs",
        "pref_nterms",
    )

    def _terms_sig(terms):
        return tuple(
            tuple((r.key, r.operator, tuple(r.values)) for r in term.match_expressions)
            for term in terms
        )

    for i, pod in enumerate(pods):
        if pod.spec.node_name:
            t["spec_node_name"][i] = fnv1a32(pod.spec.node_name)
        tols = pod.spec.tolerations
        if tols:
            if len(tols) > MAX_TOLERATIONS:
                raise ValueError(
                    f"pod {pod.metadata.name}: >{MAX_TOLERATIONS} tolerations"
                )
            for j, tol in enumerate(tols):
                t["tol_key"][i, j] = fnv1a32(tol.key)
                t["tol_value"][i, j] = fnv1a32(tol.value)
                t["tol_effect"][i, j] = _EFFECT_CODES[tol.effect]
                t["tol_op"][i, j] = (
                    TOLERATION_OP_EXISTS_CODE if tol.operator == "Exists"
                    else TOLERATION_OP_EQUAL_CODE
                )
                t["tol_empty_key"][i, j] = tol.key == ""
            t["num_tols"][i] = len(tols)
        sel = pod.spec.node_selector
        if sel:
            if len(sel) > MAX_LABELS:
                raise ValueError(
                    f"pod {pod.metadata.name}: >{MAX_LABELS} selector terms"
                )
            for j, (k, v) in enumerate(sorted(sel.items())):
                t["sel_key"][i, j] = fnv1a32(k)
                t["sel_value"][i, j] = fnv1a32(v)
            t["num_sel"][i] = len(sel)
        aff = pod.spec.affinity
        na = aff.node_affinity if aff is not None else None
        if na is not None:
            sig = (
                None
                if na.required_terms is None
                else _terms_sig(na.required_terms),
                tuple(
                    (p.weight, *_terms_sig([p.preference])) for p in na.preferred
                ),
            )
            cached = aff_cache.get(sig)
            if cached is None:
                if na.required_terms is not None:
                    t["aff_required"][i] = True
                    _encode_terms(t, "aff", i, na.required_terms, MAX_AFF_TERMS,
                                  f"pod {pod.metadata.name}")
                _encode_terms(t, "pref", i,
                              [p.preference for p in na.preferred],
                              MAX_PREF_TERMS, f"pod {pod.metadata.name}")
                for j, pref in enumerate(na.preferred):
                    t["pref_weight"][i, j] = pref.weight
                aff_cache[sig] = {f: t[f][i].copy() for f in _AFF_FIELDS}
            else:
                for f, val in cached.items():
                    t[f][i] = val
        containers = pod.spec.containers
        if len(containers) > MAX_CONTAINERS:
            raise ValueError(
                f"pod {pod.metadata.name}: >{MAX_CONTAINERS} containers"
            )
        if len(containers) > 1 or (containers and containers[0].ports):
            ports: List[int] = []
            for j, c in enumerate(containers):
                t["image_key"][i, j] = fnv1a32(c.image) if c.image else 0
                ports.extend(c.ports)
            if len(ports) > MAX_PORTS:
                raise ValueError(f"pod {pod.metadata.name}: >{MAX_PORTS} ports")
            for j, port in enumerate(ports):
                t["port"][i, j] = port
            t["num_ports"][i] = len(ports)
        key = _gang_key(pod)
        if key is not None:
            t["gang_id"][i] = fnv1a32(key)
            agg = (gang_view or {}).get(key)
            if agg is not None:
                t["gang_slice"][i] = agg[0]
                t["gang_sx"][i] = agg[1]
                t["gang_sy"][i] = agg[2]
                t["gang_sz"][i] = agg[3]
                t["gang_n"][i] = agg[4]
    if invalid_rows:
        t["valid"][list(invalid_rows)] = False
    if not device:
        # NO zero-elision here (unlike the constraint tables): the slow
        # pod schema's zero-set varies with each wave's feature mix, and
        # every distinct set is a fresh consumer executable — measured as
        # ~50s of mid-run compiles at config5 scale.  The fast path's
        # FIXED _zero_pod_metas already covers the common all-simple wave.
        return pack_table(t, (), cap), names
    return PodTable(**batched_device_put(
        t, force_packed=force_packed, elide_zeros=elide_zeros
    )), names
