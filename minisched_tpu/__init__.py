"""tpu-minisched: a TPU-native pluggable scheduling framework.

A ground-up rebuild of the capabilities of Shunpoco/mini-kube-scheduler
(an educational Kubernetes scheduler) designed for JAX/XLA: host-side
event-driven control plane + scheduling queue, and a device-side batch
evaluator where registered filter/score plugins compile into one fused
(pods × nodes) kernel with seeded masked-argmax host selection.

See SURVEY.md for the reference analysis and BASELINE.md for targets.
"""

__version__ = "0.1.0"
