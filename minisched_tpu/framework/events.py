"""Cluster events: what happened in the cluster, and which pods it may help.

Re-creates framework.ClusterEvent / GVK / ActionType and the wildcard
matching semantics the reference's queue relies on
(minisched/queue/queue.go:167-202, minisched/eventhandler.go:37-58,
minisched/initialize.go:140-179).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Iterable, List, Set


class ActionType(enum.IntFlag):
    """Bit-flag action types (framework.ActionType)."""

    ADD = 1 << 0
    DELETE = 1 << 1
    UPDATE_NODE_ALLOCATABLE = 1 << 2
    UPDATE_NODE_LABEL = 1 << 3
    UPDATE_NODE_TAINT = 1 << 4
    UPDATE_NODE_CONDITION = 1 << 5
    UPDATE = (
        UPDATE_NODE_ALLOCATABLE
        | UPDATE_NODE_LABEL
        | UPDATE_NODE_TAINT
        | UPDATE_NODE_CONDITION
    )
    ALL = ADD | DELETE | UPDATE


class GVK(str, enum.Enum):
    """Group-version-kind names used for event registration (framework.GVK)."""

    POD = "Pod"
    NODE = "Node"
    PERSISTENT_VOLUME = "PersistentVolume"
    PERSISTENT_VOLUME_CLAIM = "PersistentVolumeClaim"
    STORAGE_CLASS = "storage.k8s.io/StorageClass"
    CSI_NODE = "storage.k8s.io/CSINode"
    SERVICE = "Service"
    WILDCARD = "*"


@dataclass(frozen=True)
class ClusterEvent:
    """An event a plugin can subscribe to (framework.ClusterEvent).

    ``is_wildcard`` mirrors upstream: Resource "*" with ActionType All
    matches everything (semantics used at minisched/queue/queue.go:171-176).
    """

    resource: GVK
    action_type: ActionType
    label: str = ""

    def is_wildcard(self) -> bool:
        return self.resource == GVK.WILDCARD and self.action_type == ActionType.ALL

    def match(self, incoming: "ClusterEvent") -> bool:
        """Does this *registered* event cover the *incoming* event?

        Mirrors queue.go:181-190 (resource equality-or-wildcard AND
        action-type bit intersection, queue.go:192-202).
        """
        if self.is_wildcard():
            return True
        if self.resource != incoming.resource and self.resource != GVK.WILDCARD:
            return False
        return bool(self.action_type & incoming.action_type)


# Canonical events (upstream defines these as package vars).
WILDCARD_EVENT = ClusterEvent(GVK.WILDCARD, ActionType.ALL, "WildCardChange")
NODE_ADD = ClusterEvent(GVK.NODE, ActionType.ADD, "NodeAdd")
POD_ADD = ClusterEvent(GVK.POD, ActionType.ADD, "PodAdd")
POD_DELETE = ClusterEvent(GVK.POD, ActionType.DELETE, "PodDelete")


# ClusterEventMap: registered event -> set of plugin names that care.
ClusterEventMap = Dict[ClusterEvent, Set[str]]


def merge_event_registrations(
    registrations: Iterable[tuple[str, List[ClusterEvent]]],
    event_map: ClusterEventMap,
) -> None:
    """Fold each plugin's EventsToRegister into the shared map.

    Equivalent of minisched/initialize.go:159-167 — with the reference's
    known bug fixed: events are registered under the *emitting plugin's own
    name* (the reference registers nodenumber's events under
    nodeunschedulable's name, initialize.go:154; SURVEY.md §7 "do not copy").
    """
    for plugin_name, events in registrations:
        for ev in events:
            event_map.setdefault(ev, set()).add(plugin_name)


def unioned_gvks(event_map: ClusterEventMap) -> Dict[GVK, ActionType]:
    """Union action types per GVK (minisched/initialize.go:169-179); used to
    decide which informer handlers to wire (eventhandler.go:37-58)."""
    out: Dict[GVK, ActionType] = {}
    for ev in event_map:
        out[ev.resource] = out.get(ev.resource, ActionType(0)) | ev.action_type
    return out


def event_helps_pod(
    incoming: ClusterEvent,
    failed_plugins: Set[str],
    event_map: ClusterEventMap,
) -> bool:
    """Can ``incoming`` possibly make a previously-unschedulable pod
    schedulable?  (podMatchesEvent, minisched/queue/queue.go:167-190.)

    True iff some registered event matching ``incoming`` belongs to at least
    one plugin that rejected the pod.  A pod with *no* recorded failed
    plugins is conservatively retried on any event (upstream behavior).
    """
    if not failed_plugins:
        return True
    for registered, plugin_names in event_map.items():
        if registered.match(incoming) and (plugin_names & failed_plugins):
            return True
    return False
