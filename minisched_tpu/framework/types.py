"""Core scheduling-framework types.

TPU-native re-creation of the types the reference imports from
``k8s.io/kubernetes/pkg/scheduler/framework`` (see SURVEY.md §2 tail):
``Status`` + codes (reference usage: minisched/minisched.go:90,215,
minisched/waitingpod/waitingpod.go:96,112), ``CycleState``
(minisched/minisched.go:37, nodenumber.go:46-61), ``NodeScore`` /
``NodeScoreList`` / ``PluginToNodeScores`` (minisched/minisched.go:164-199),
``FitError`` / ``Diagnosis`` (minisched/minisched.go:143-148,287-290), and
``QueuedPodInfo`` (minisched/queue/queue.go:156-164).

Design stance (SURVEY.md §7): these are *host-side* control-plane types in
plain Python — device-side state lives in struct-of-arrays tables
(``minisched_tpu.models.tables``), not in per-object graphs.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1


class Code(enum.IntEnum):
    """Status codes, mirroring the upstream scheduler framework's enum.

    The reference relies on Success / Error / Unschedulable /
    UnschedulableAndUnresolvable / Wait / Skip semantics (e.g. filter
    short-circuit at minisched/minisched.go:130-137 and the permit Wait
    protocol at minisched/minisched.go:201-237).
    """

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


class Status:
    """Result of running a plugin or an extension point.

    A ``None`` status is treated as Success, matching upstream convention
    (helpers accept ``Optional[Status]``).
    """

    __slots__ = ("code", "reasons", "err", "plugin")

    def __init__(
        self,
        code: Code = Code.SUCCESS,
        reasons: Optional[List[str]] = None,
        err: Optional[BaseException] = None,
        plugin: str = "",
    ):
        self.code = code
        self.reasons = list(reasons) if reasons else []
        self.err = err
        self.plugin = plugin

    # -- constructors ------------------------------------------------------
    @staticmethod
    def success() -> "Status":
        return Status(Code.SUCCESS)

    @staticmethod
    def error(msg: str) -> "Status":
        s = Status(Code.ERROR, [msg])
        s.err = RuntimeError(msg)
        return s

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(Code.UNSCHEDULABLE, list(reasons))

    @staticmethod
    def unresolvable(*reasons: str) -> "Status":
        return Status(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, list(reasons))

    @staticmethod
    def wait() -> "Status":
        return Status(Code.WAIT)

    @staticmethod
    def skip() -> "Status":
        return Status(Code.SKIP)

    @staticmethod
    def from_error(err: BaseException) -> "Status":
        s = Status(Code.ERROR, [str(err)])
        s.err = err
        return s

    # -- predicates --------------------------------------------------------
    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_wait(self) -> bool:
        return self.code == Code.WAIT

    def is_skip(self) -> bool:
        return self.code == Code.SKIP

    def is_unschedulable(self) -> bool:
        return self.code in (
            Code.UNSCHEDULABLE,
            Code.UNSCHEDULABLE_AND_UNRESOLVABLE,
        )

    def with_plugin(self, name: str) -> "Status":
        self.plugin = name
        return self

    def message(self) -> str:
        return ", ".join(self.reasons)

    def as_error(self) -> Optional[BaseException]:
        """Error view of a non-success status.

        The reference has a known bug passing stale/nil errors to ErrorFunc
        (minisched/minisched.go:64,73,92) — we always derive the error from
        the status itself (SURVEY.md §7 "known bugs — do not copy").
        """
        if self.is_success():
            return None
        if self.err is not None:
            return self.err
        return RuntimeError(self.message() or self.code.name)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Status({self.code.name}, {self.reasons!r}, plugin={self.plugin!r})"


def status_code(status: Optional[Status]) -> Code:
    return Code.SUCCESS if status is None else status.code


def is_success(status: Optional[Status]) -> bool:
    return status is None or status.is_success()


class CycleState:
    """Per-scheduling-cycle scratch state shared between extension points.

    Mirrors framework.CycleState (used at minisched/minisched.go:37 and
    written/read by the nodenumber plugin, nodenumber.go:46-61): a
    thread-safe keyed store plus the ``skip_filter_plugins`` /
    ``skip_score_plugins`` sets newer upstream versions carry.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._storage: Dict[str, Any] = {}
        self.skip_filter_plugins: Set[str] = set()
        self.skip_score_plugins: Set[str] = set()

    def read(self, key: str) -> Any:
        with self._lock:
            if key not in self._storage:
                raise KeyError(key)
            return self._storage[key]

    def write(self, key: str, value: Any) -> None:
        with self._lock:
            self._storage[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._storage.pop(key, None)

    def clone(self) -> "CycleState":
        import copy as _copy

        c = CycleState()
        with self._lock:
            # deep-copy values: upstream clones each StateData so mutable
            # plugin state never aliases across cycle copies
            c._storage = {k: _copy.deepcopy(v) for k, v in self._storage.items()}
            c.skip_filter_plugins = set(self.skip_filter_plugins)
            c.skip_score_plugins = set(self.skip_score_plugins)
        return c


@dataclass
class NodeScore:
    """Score of one node from one plugin (framework.NodeScore)."""

    name: str
    score: int


NodeScoreList = List[NodeScore]
PluginToNodeScores = Dict[str, NodeScoreList]


@dataclass
class Diagnosis:
    """Why a pod failed to schedule (framework.Diagnosis).

    ``node_to_status`` maps node name → failing Status;
    ``unschedulable_plugins`` feeds the event-gated requeue predicate
    (minisched/queue/queue.go:71-73,167-190).
    """

    node_to_status: Dict[str, Status] = field(default_factory=dict)
    unschedulable_plugins: Set[str] = field(default_factory=set)


class FitError(Exception):
    """No node fits the pod (framework.FitError, minisched.go:143-148)."""

    def __init__(self, pod: Any, num_all_nodes: int, diagnosis: Diagnosis):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.diagnosis = diagnosis
        super().__init__(self._message())

    def _message(self) -> str:
        reasons: Dict[str, int] = {}
        for status in self.diagnosis.node_to_status.values():
            for reason in status.reasons:
                reasons[reason] = reasons.get(reason, 0) + 1
        parts = [f"{count} {reason}" for reason, count in sorted(reasons.items())]
        detail = ", ".join(parts) or "no reasons given"
        return (
            f"0/{self.num_all_nodes} nodes are available: {detail}."
        )


@dataclass
class PodInfo:
    """Wrapper of a pod carried through the queue (framework.PodInfo)."""

    pod: Any

    @property
    def uid(self) -> str:
        return self.pod.metadata.uid


@dataclass
class QueuedPodInfo:
    """Queue bookkeeping around a pod (framework.QueuedPodInfo; reference
    constructs these at minisched/queue/queue.go:156-164 and in ErrorFunc,
    minisched/minisched.go:283-298)."""

    pod_info: PodInfo
    timestamp: float = field(default_factory=time.monotonic)
    attempts: int = 0
    initial_attempt_timestamp: float = field(default_factory=time.monotonic)
    unschedulable_plugins: Set[str] = field(default_factory=set)
    #: queue scheduling-cycle number stamped at pop time (upstream
    #: podSchedulingCycle): lets the queue detect a cluster move-request
    #: that fired DURING this pod's attempt and route the failure to the
    #: backoffQ instead of stranding it in the unschedulableQ
    scheduling_cycle: int = 0

    @property
    def pod(self) -> Any:
        return self.pod_info.pod

    @property
    def uid(self) -> str:
        return self.pod_info.uid
