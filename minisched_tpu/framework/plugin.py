"""The plugin contract: extension points, both scalar and batched.

The reference wires four plugin chains — filter / pre-score / score / permit
(minisched/initialize.go:25-28,44-66) — against the upstream interfaces
``framework.{Filter,PreScore,Score,Permit}Plugin`` + ``ScoreExtensions`` +
``EnqueueExtensions``.  This module re-creates that contract twice:

* **Scalar protocol** — per-(pod, node) methods exactly mirroring the
  upstream signatures.  This is what the parity oracle
  (``minisched_tpu.engine``) runs, one pod at a time, matching the Go loop
  in minisched/minisched.go:115-237 step for step.

* **Batch protocol** (``BatchEvaluable``) — the TPU-native design (SURVEY.md
  §7): a plugin is additionally a *vectorized predicate/score function over
  struct-of-arrays tables*, returning a ``(pods × nodes)`` mask or score
  matrix.  All batch methods must be pure and jax-traceable so the fused
  evaluator (``minisched_tpu.ops.fused``) can compose every registered
  plugin into ONE jitted kernel: filter → pre-score → score → normalize →
  weighted-sum → masked-argmax.

A plugin that implements both protocols is parity-checked by
tests/test_parity.py: identical placements, bit-exact.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Protocol, Tuple, runtime_checkable

from minisched_tpu.framework.events import ClusterEvent
from minisched_tpu.framework.nodeinfo import NodeInfo
from minisched_tpu.framework.types import CycleState, NodeScoreList, Status


class Plugin:
    """Base: every plugin has a stable name (framework.Plugin)."""

    #: does the plugin's batch kernel read node state that intra-wave
    #: commits update (``ops/state.apply_placements``'s req_*/nzreq_*/
    #: used_port scatters, or the repair loop's carried volume planes)?
    #: The conflict-repair loop re-evaluates ONLY these plugins per round;
    #: everything else (node identity, labels, taints, cross-pod combo
    #: planes — which are static within a wave by design) is computed once.
    #: Resource/port/volume plugins override this to True.
    reads_committed_state = False

    def name(self) -> str:
        return type(self).__name__


# ---------------------------------------------------------------------------
# Scalar extension points (upstream-shaped)
# ---------------------------------------------------------------------------


@runtime_checkable
class PreFilterPlugin(Protocol):
    """Once-per-pod prep before the per-node filter loop (the upstream
    framework.PreFilterPlugin — needed by cross-pod plugins that aggregate
    cluster-wide state, e.g. PodTopologySpread's per-domain match counts)."""

    def name(self) -> str: ...

    def pre_filter(
        self, state: CycleState, pod: Any, node_infos: List[NodeInfo]
    ) -> Status: ...


@runtime_checkable
class FilterPlugin(Protocol):
    def name(self) -> str: ...

    def filter(self, state: CycleState, pod: Any, node_info: NodeInfo) -> Status:
        """Reject or accept one (pod, node) pair
        (framework.FilterPlugin.Filter; called minisched/minisched.go:130)."""
        ...


@runtime_checkable
class PostFilterPlugin(Protocol):
    """Runs when filtering leaves no feasible node (the upstream
    framework.PostFilterPlugin — DefaultPreemption is the in-tree member;
    the reference's config machinery carries its args through conversion,
    scheduler/scheduler_test.go:164,205, plugin/plugins.go:77-141)."""

    def name(self) -> str: ...

    def post_filter(
        self, state: CycleState, pod: Any, node_infos: List[NodeInfo],
        diagnosis: Any,
    ) -> Tuple[Optional[str], Status]:
        """Attempt to make the pod schedulable (e.g. by evicting victims).
        Returns (nominated node name or None, status); a Success status
        means the pod should become schedulable there once the cluster
        reacts (victims terminate).

        Contract for evicting plugins: record every pod you deleted in a
        ``last_victims`` list attribute, reset at the start of each call.
        The wave engine reads it to keep its shared preemption snapshot
        consistent across a wave's losers without re-listing the store
        (DefaultPreemption is the reference implementation)."""
        ...


@runtime_checkable
class PreScorePlugin(Protocol):
    def name(self) -> str: ...

    def pre_score(
        self, state: CycleState, pod: Any, nodes: List[Any]
    ) -> Status:
        """Once-per-pod prep before scoring (minisched/minisched.go:153-162)."""
        ...


class ScoreExtensions(Protocol):
    def normalize_score(
        self, state: CycleState, pod: Any, scores: NodeScoreList
    ) -> Status:
        """Rescale a plugin's raw node scores to [0, 100]
        (minisched/minisched.go:178-183)."""
        ...


@runtime_checkable
class ScorePlugin(Protocol):
    def name(self) -> str: ...

    def score(self, state: CycleState, pod: Any, node_name: str) -> Tuple[int, Status]:
        """Score one (pod, node) pair (minisched/minisched.go:171-176)."""
        ...

    def score_extensions(self) -> Optional[ScoreExtensions]: ...


@runtime_checkable
class ReservePlugin(Protocol):
    """Reserve/Unreserve (upstream framework.ReservePlugin): claim
    plugin-held resources for a chosen (pod, node) before permit/bind;
    Unreserve rolls the claim back when a later phase fails."""

    def name(self) -> str: ...

    def reserve(self, state: CycleState, pod: Any, node_name: str) -> Status: ...

    def unreserve(self, state: CycleState, pod: Any, node_name: str) -> None: ...


@runtime_checkable
class PermitPlugin(Protocol):
    def name(self) -> str: ...

    def permit(
        self, state: CycleState, pod: Any, node_name: str
    ) -> Tuple[Status, float]:
        """Approve / reject / delay binding; returns (status, timeout_s)
        (minisched/minisched.go:208-236)."""
        ...


@runtime_checkable
class EnqueueExtensions(Protocol):
    def events_to_register(self) -> List[ClusterEvent]:
        """Which cluster events might make a pod this plugin rejected
        schedulable again (minisched/initialize.go:140-157)."""
        ...


# ---------------------------------------------------------------------------
# Batch (TPU) protocol
# ---------------------------------------------------------------------------


class BatchEvaluable:
    """Mixin declaring the vectorized form of a plugin.

    Methods take a ``BatchContext`` (static per-compilation config), a
    ``PodTable`` and ``NodeTable`` (minisched_tpu.models.tables) whose leaves
    are jnp arrays, and return arrays.  They are traced inside ONE jit — no
    python control flow on array values, no host callbacks.

    Conventions:
      * mask arrays are bool ``(P, N)``; True = feasible.
      * score arrays are int32 ``(P, N)`` in [MIN_NODE_SCORE, MAX_NODE_SCORE]
        after normalize; raw scores may exceed that before normalize.
      * ``batch_pre_score`` returns an aux dict of arrays, passed to
        ``batch_score`` — the array analog of writing CycleState
        (nodenumber.go:58-61).
    """

    #: set False for plugins that have no scalar counterpart (none today)
    has_batch = True
    #: plugins whose kernels read cross-pod constraint tables (an ``extra``
    #: pytree built per wave, models/constraints.py) set this True; their
    #: batch_filter/batch_score take a trailing ``extra`` argument
    needs_extra = False

    def batch_filter(self, ctx: Any, pods: Any, nodes: Any):
        raise NotImplementedError

    def batch_pre_score(self, ctx: Any, pods: Any, nodes: Any) -> Dict[str, Any]:
        return {}

    def batch_score(self, ctx: Any, pods: Any, nodes: Any, aux: Dict[str, Any]):
        raise NotImplementedError

    def batch_normalize(self, ctx: Any, scores, mask):
        """Default: identity (plugins without ScoreExtensions)."""
        return scores


# ---------------------------------------------------------------------------
# Capability probing helpers
# ---------------------------------------------------------------------------


def implements_pre_filter(p: Any) -> bool:
    return callable(getattr(p, "pre_filter", None))


def implements_filter(p: Any) -> bool:
    return callable(getattr(p, "filter", None))


def implements_post_filter(p: Any) -> bool:
    return callable(getattr(p, "post_filter", None))


def implements_pre_score(p: Any) -> bool:
    return callable(getattr(p, "pre_score", None))


def implements_score(p: Any) -> bool:
    return callable(getattr(p, "score", None))


def implements_permit(p: Any) -> bool:
    return callable(getattr(p, "permit", None))


def implements_reserve(p: Any) -> bool:
    # both halves: a reserve without its rollback would crash the
    # unguarded unreserve path on the first permit/bind failure
    return callable(getattr(p, "reserve", None)) and callable(
        getattr(p, "unreserve", None)
    )


def implements_enqueue(p: Any) -> bool:
    return callable(getattr(p, "events_to_register", None))


def implements_batch(p: Any) -> bool:
    # duck-typed, not isinstance: delegating wrappers (the simulator
    # recorders, plugins/simulator.py) forward ``has_batch`` and the batch
    # kernels through __getattr__ without subclassing BatchEvaluable — an
    # isinstance check would wrongly reject a wrapped batch plugin and
    # break device_mode + record_results
    return bool(getattr(p, "has_batch", False))
