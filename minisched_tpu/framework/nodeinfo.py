"""NodeInfo: a node plus scheduler-relevant aggregates.

Re-creates framework.NodeInfo (wrapped per listed node at
minisched/minisched.go:126-127).  Tracks the pods assigned to the node and
their aggregate resource requests so filter/score plugins can read
``requested`` vs ``allocatable`` without rescanning pods.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from minisched_tpu.api.objects import (
    DEFAULT_POD_CPU_REQUEST,
    DEFAULT_POD_MEMORY_REQUEST,
    MIB,
    Node,
    Pod,
    ResourceList,
)


def non_zero_requests(pod: Pod) -> ResourceList:
    """Upstream GetNonzeroRequests: pods with no explicit cpu/memory request
    count as 100m / 200Mi for the resource scorers (never the Fit filter)."""
    req = pod.resource_requests()
    nz = req.clone()
    if nz.milli_cpu == 0:
        nz.milli_cpu = DEFAULT_POD_CPU_REQUEST
    if nz.memory == 0:
        nz.memory = DEFAULT_POD_MEMORY_REQUEST
    return nz


class NodeInfo:
    """Aggregates use the device unit discipline (models/tables.py): memory
    is accumulated as per-pod MiB-floored int (sum-of-floors), exactly the
    way the NodeTable builder accumulates — bit-exact oracle/kernel parity
    depends on the two paths quantizing identically."""

    __slots__ = (
        "node",
        "pods",
        "requested",
        "non_zero_requested",
        "req_mem_mib",
        "req_eph_mib",
        "nzreq_mem_mib",
        "used_ports",
        "_cow",
    )

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = node
        self.pods: List[Pod] = []
        self.requested: ResourceList = ResourceList()
        self.non_zero_requested: ResourceList = ResourceList()
        self.req_mem_mib: int = 0
        self.req_eph_mib: int = 0
        self.nzreq_mem_mib: int = 0
        #: host ports claimed by assigned pods, in pod-then-container order
        #: (the NodeTable used_port encoding reads this directly instead of
        #: re-walking every pod's containers per wave)
        self.used_ports: List[int] = []
        #: copy-on-write: clone() shares the mutable state and flags BOTH
        #: sides; the first mutation on either materializes private copies
        self._cow = False

    @property
    def name(self) -> str:
        return self.node.metadata.name if self.node else ""

    def _materialize(self) -> None:
        if self._cow:
            self.pods = list(self.pods)
            self.used_ports = list(self.used_ports)
            self.requested = self.requested.clone()
            self.non_zero_requested = self.non_zero_requested.clone()
            self._cow = False

    def add_pod(self, pod: Pod) -> None:
        self._materialize()
        self.pods.append(pod)
        req = pod.resource_requests()
        self.requested.add(req)
        # non_zero_requests(pod), inlined against the one walk above — the
        # second resource_requests walk per event was a quarter of the
        # cache's cost at wave scale (quantization identical: only cpu and
        # memory get the non-zero defaults)
        nz = self.non_zero_requested
        nz.milli_cpu += req.milli_cpu or DEFAULT_POD_CPU_REQUEST
        nz.memory += req.memory or DEFAULT_POD_MEMORY_REQUEST
        nz.pods += req.pods
        nz.ephemeral_storage += req.ephemeral_storage
        for k, v in req.scalar.items():
            nz.scalar[k] = nz.scalar.get(k, 0) + v
        self.req_mem_mib += req.memory // MIB
        self.req_eph_mib += req.ephemeral_storage // MIB
        self.nzreq_mem_mib += (req.memory // MIB) or (
            DEFAULT_POD_MEMORY_REQUEST // MIB
        )
        for c in pod.spec.containers:
            if c.ports:
                self.used_ports.extend(c.ports)

    def remove_pod(self, pod: Pod) -> None:
        self._materialize()
        for i, p in enumerate(self.pods):
            if p.metadata.uid == pod.metadata.uid:
                del self.pods[i]
                # subtract what the STORED object contributed (the caller's
                # copy may differ, e.g. an update refreshing the object)
                req = p.resource_requests()
                self.requested.sub(req)
                nz = self.non_zero_requested
                nz.milli_cpu -= req.milli_cpu or DEFAULT_POD_CPU_REQUEST
                nz.memory -= req.memory or DEFAULT_POD_MEMORY_REQUEST
                nz.pods -= req.pods
                nz.ephemeral_storage -= req.ephemeral_storage
                for k, v in req.scalar.items():
                    nz.scalar[k] = nz.scalar.get(k, 0) - v
                self.req_mem_mib -= req.memory // MIB
                self.req_eph_mib -= req.ephemeral_storage // MIB
                self.nzreq_mem_mib -= (req.memory // MIB) or (
                    DEFAULT_POD_MEMORY_REQUEST // MIB
                )
                for c in p.spec.containers:
                    for port in c.ports:
                        self.used_ports.remove(port)
                return

    def clone(self) -> "NodeInfo":
        """O(1) copy-on-write clone.  Both sides keep reading the shared
        pods/ports/request state; whichever mutates first (via
        add_pod/remove_pod) materializes its own copies.  A 10k-node
        snapshot clone was ~200ms per wave of list/ResourceList copying
        for nodes that mostly don't change; now only touched nodes pay."""
        self._cow = True
        ni = NodeInfo(self.node)
        ni.pods = self.pods
        ni.requested = self.requested
        ni.non_zero_requested = self.non_zero_requested
        ni.req_mem_mib = self.req_mem_mib
        ni.req_eph_mib = self.req_eph_mib
        ni.nzreq_mem_mib = self.nzreq_mem_mib
        ni.used_ports = self.used_ports
        ni._cow = True
        return ni


def build_node_infos(nodes: List[Node], pods: List[Pod]) -> List[NodeInfo]:
    """Snapshot helper: wrap nodes and attach assigned pods."""
    by_name: Dict[str, NodeInfo] = {}
    infos: List[NodeInfo] = []
    for n in nodes:
        ni = NodeInfo(n)
        by_name[n.metadata.name] = ni
        infos.append(ni)
    for p in pods:
        if p.spec.node_name and p.spec.node_name in by_name:
            by_name[p.spec.node_name].add_pod(p)
    return infos
