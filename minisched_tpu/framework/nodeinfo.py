"""NodeInfo: a node plus scheduler-relevant aggregates.

Re-creates framework.NodeInfo (wrapped per listed node at
minisched/minisched.go:126-127).  Tracks the pods assigned to the node and
their aggregate resource requests so filter/score plugins can read
``requested`` vs ``allocatable`` without rescanning pods.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from minisched_tpu.api.objects import Node, Pod, ResourceList


class NodeInfo:
    __slots__ = ("node", "pods", "requested")

    def __init__(self, node: Optional[Node] = None):
        self.node: Optional[Node] = node
        self.pods: List[Pod] = []
        self.requested: ResourceList = ResourceList()

    @property
    def name(self) -> str:
        return self.node.metadata.name if self.node else ""

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)
        self.requested.add(pod.resource_requests())

    def remove_pod(self, pod: Pod) -> None:
        for i, p in enumerate(self.pods):
            if p.metadata.uid == pod.metadata.uid:
                del self.pods[i]
                self.requested.sub(pod.resource_requests())
                return

    def clone(self) -> "NodeInfo":
        ni = NodeInfo(self.node)
        ni.pods = list(self.pods)
        ni.requested = self.requested.clone()
        return ni


def build_node_infos(nodes: List[Node], pods: List[Pod]) -> List[NodeInfo]:
    """Snapshot helper: wrap nodes and attach assigned pods."""
    by_name: Dict[str, NodeInfo] = {}
    infos: List[NodeInfo] = []
    for n in nodes:
        ni = NodeInfo(n)
        by_name[n.metadata.name] = ni
        infos.append(ni)
    for p in pods:
        if p.spec.node_name and p.spec.node_name in by_name:
            by_name[p.spec.node_name].add_pod(p)
    return infos
