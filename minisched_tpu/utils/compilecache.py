"""Persistent XLA compilation cache setup.

On the tunneled TPU runtime a single jit compile costs seconds of
round-trip latency (a trivial matmul measured 13.5s cold vs 0.63s from
the disk cache), and the wave pipeline's executables are keyed on a small
set of static table capacities — exactly the shape the JAX persistent
cache is built for.  The reference has no analog (Go compiles ahead of
time); for a jit-traced framework the cache IS the AOT story.

Call :func:`enable_persistent_cache` before the first compilation — the
bench, the driver entry points, and the test conftest all do.  Disable
with ``MINISCHED_CACHE=0``; relocate with ``MINISCHED_CACHE_DIR``.
"""

from __future__ import annotations

import hashlib
import os
import platform

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), ".jax_cache")


def _machine_key() -> str:
    """Fingerprint of the host CPU the cache entries were compiled for.

    XLA:CPU serves AOT executables out of the persistent cache keyed on
    the computation only — an artifact compiled on a host with (say)
    AVX-512 subfeatures loads on a host without them and warns of
    potential SIGILL.  Namespacing the cache directory by (arch, CPU
    flags) makes cross-machine loads impossible while same-type hosts
    still share everything.

    Even with matching real features, XLA:CPU loads still log a
    mismatch for the pseudo-features ``+prefer-no-gather`` /
    ``+prefer-no-scatter`` — compile-side options the load-side CPUID
    detection never reports.  Those lines are benign (the executable
    loads and runs; the whole test suite passes off cached entries);
    only *real* ISA flags can SIGILL, and those are covered by this
    digest.
    """
    flags = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass  # non-Linux: arch alone still separates the big classes
    digest = hashlib.sha1(
        f"{platform.machine()}|{flags}".encode()
    ).hexdigest()[:12]
    return f"{platform.machine()}-{digest}"


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a repo-local directory,
    namespaced per host machine type (see ``_machine_key``).

    Idempotent (jax.config.update is repeat-safe); returns the directory in
    effect (None when disabled via ``MINISCHED_CACHE=0``).  Safe to call
    after jax is imported — the config flags take effect for every
    compilation that follows.
    """
    if os.environ.get("MINISCHED_CACHE", "1") == "0":
        return None
    cache_dir = cache_dir or os.environ.get("MINISCHED_CACHE_DIR", _DEFAULT_DIR)
    cache_dir = os.path.join(cache_dir, _machine_key())
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything: the tunnel RTT dominates even trivial compiles
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # keep the jax-level executable cache but NOT XLA's own AOT kernel
    # caches: XLA:CPU AOT loads hard-check machine features — including
    # XLA pseudo-features host detection never reports — so every load
    # warns about a mismatch and is documented as able to SIGILL.  The
    # executables this build actually needs cached (the tunnel-compiled
    # wave/scan programs) live in the jax layer.
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except Exception:
        pass  # older jax without the option: nothing to disable
    return cache_dir
