"""Retry with exponential backoff.

Re-creates ``util/retry.go:18-26`` (RetryWithExponentialBackOff wrapping
wait.ExponentialBackoff): 100ms initial delay, factor 3, 6 steps — the
policy the resultstore uses to flush annotations (store.go:120-128).

``jitter`` (upstream wait.Backoff.Jitter, 0.1 in retry.go:13) is exposed
behind a parameter defaulting to 0 so the existing call sites stay
byte-exact; the remote control-plane client turns it on — synchronized
retry storms against a recovering apiserver are exactly what jitter
exists to break up.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional

INITIAL_DURATION_S = 0.1  # util/retry.go:11
FACTOR = 3.0  # util/retry.go:12
JITTER = 0.0  # util/retry.go:13 (jitter 0.1 upstream; 0 keeps tests exact)
STEPS = 6  # util/retry.go:14


class RetryTimeoutError(Exception):
    """All backoff steps exhausted without the fn reporting success."""


def backoff_delays(
    initial_duration_s: float = INITIAL_DURATION_S,
    factor: float = FACTOR,
    steps: int = STEPS,
    jitter: float = JITTER,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """The sleep schedule between ``steps`` attempts (``steps - 1``
    delays): initial * factor^i, each stretched by up to ``jitter``
    fraction (wait.Jitter semantics: delay * (1 + jitter*rand)).  A
    seeded ``rng`` makes the jittered schedule reproducible."""
    if jitter and rng is None:
        rng = random.Random()
    delay = initial_duration_s
    for _ in range(max(steps - 1, 0)):
        d = delay
        if jitter:
            d *= 1.0 + jitter * rng.random()
        yield d
        delay *= factor


def retry_with_exponential_backoff(
    fn: Callable[[], bool],
    initial_duration_s: float = INITIAL_DURATION_S,
    factor: float = FACTOR,
    steps: int = STEPS,
    sleep: Callable[[float], None] = time.sleep,
    jitter: float = JITTER,
    rng: Optional[random.Random] = None,
) -> None:
    """Call ``fn`` until it returns True; sleep initial*factor^i (jittered
    when ``jitter`` > 0) between attempts; raise RetryTimeoutError after
    ``steps`` attempts.  ``fn`` raising propagates immediately (matches
    wait.ExponentialBackoff's error passthrough)."""
    delays = backoff_delays(initial_duration_s, factor, steps, jitter, rng)
    for step in range(steps):
        if fn():
            return
        if step < steps - 1:
            sleep(next(delays))
    raise RetryTimeoutError(f"retry exhausted after {steps} steps")
