"""Retry with exponential backoff.

Re-creates ``util/retry.go:18-26`` (RetryWithExponentialBackOff wrapping
wait.ExponentialBackoff): 100ms initial delay, factor 3, 6 steps — the
policy the resultstore uses to flush annotations (store.go:120-128).
"""

from __future__ import annotations

import time
from typing import Callable

INITIAL_DURATION_S = 0.1  # util/retry.go:11
FACTOR = 3.0  # util/retry.go:12
JITTER = 0.0  # util/retry.go:13 (jitter 0.1 upstream; 0 keeps tests exact)
STEPS = 6  # util/retry.go:14


class RetryTimeoutError(Exception):
    """All backoff steps exhausted without the fn reporting success."""


def retry_with_exponential_backoff(
    fn: Callable[[], bool],
    initial_duration_s: float = INITIAL_DURATION_S,
    factor: float = FACTOR,
    steps: int = STEPS,
    sleep: Callable[[float], None] = time.sleep,
) -> None:
    """Call ``fn`` until it returns True; sleep initial*factor^i between
    attempts; raise RetryTimeoutError after ``steps`` attempts.  ``fn``
    raising propagates immediately (matches wait.ExponentialBackoff's
    error passthrough)."""
    delay = initial_duration_s
    for step in range(steps):
        if fn():
            return
        if step < steps - 1:
            sleep(delay)
            delay *= factor
    raise RetryTimeoutError(f"retry exhausted after {steps} steps")
