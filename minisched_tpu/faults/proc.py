"""Process-level chaos: kill the control plane, not just its responses.

Everything in :mod:`minisched_tpu.faults` so far injects failures into a
control plane that keeps existing — calls fail, streams drop, but the
process survives.  Real clusters lose the apiserver itself: OOM-kill,
node reboot, rolling upgrade.  This module makes THAT failure happen on
demand: a :class:`ServerSupervisor` runs the HTTP façade in a child
process over a ``file://`` WAL store, SIGKILLs it (no shutdown handler
runs — torn WAL tails and half-written responses included), and restarts
it on the same port.  Recovery is the durable store's checkpoint ⊕ WAL
tail replay; the port stays fixed so clients need no re-discovery, only
the retry/reconnect machinery they already have.

The child is a fresh ``python -c`` subprocess importing only the
control-plane modules, so the parent's JAX runtime and thread pool never
leak into it — exactly the process isolation a real apiserver has.  (Not
multiprocessing spawn: that re-imports the parent's __main__, which under
pytest or a REPL is somewhere between heavy and impossible.)

The kill schedule can ride the same deterministic fabric as every other
injection point (``proc.kill``): whether tick *n* kills is the blake2s
schedule, so a failing soak reproduces byte-for-byte from its seed.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Any, Optional


def _free_port() -> int:
    """One ephemeral port, reused for every incarnation of the child —
    the client's base_url must survive restarts.  The race (another
    process grabbing it between close and child bind) is real but
    vanishing at test scale; HTTPServer sets allow_reuse_address, so our
    own TIME_WAIT ghosts never block the rebind."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_main(
    wal_path: str,
    port: int,
    compact_every_s: Optional[float] = None,
    archive: bool = False,
    fsync: bool = False,
    parent_pid: Optional[int] = None,
    salvage: str = "off",
    scrub_every_s: Optional[float] = None,
    fault_seed: Optional[int] = None,
    fault_rules: Optional[dict] = None,
) -> None:
    """The child's whole life: recover the store from disk, serve REST on
    the fixed port, optionally compact on a timer, park until SIGKILL.
    Runs in a fresh interpreter — import inside, keep it light.

    ``salvage`` is the store's mid-file-corruption policy at replay (the
    disk-chaos soaks reopen with ``"covered"`` so a checkpoint-covered
    bad frame never bricks a restart).  ``fault_rules`` arms a
    FaultFabric in THIS process — ``{point: {rate, after, max_fires}}``
    — which is how the disk points (``disk.enospc`` / ``wal.bitflip`` /
    ``wal.torn_mid`` / ``ckpt.corrupt``) fire inside the server that
    owns the WAL, not in the test harness.  ``scrub_every_s`` starts the
    store's background integrity scrub."""
    from minisched_tpu.controlplane.durable import DurableObjectStore
    from minisched_tpu.controlplane.httpserver import start_api_server

    store = DurableObjectStore(
        wal_path, fsync=fsync, archive_compacted=archive, salvage=salvage
    )
    if fault_rules:
        from minisched_tpu.faults import FaultFabric

        fabric = FaultFabric(fault_seed or 0)
        for point, rule in fault_rules.items():
            fabric.on(point, **rule)
        store.faults = fabric
    if scrub_every_s:
        store.start_scrub(scrub_every_s)
    start_api_server(store, port=port)
    if compact_every_s:
        def compactor() -> None:
            while True:
                time.sleep(compact_every_s)
                try:
                    store.compact()
                except Exception:
                    pass  # compaction is best-effort; the WAL still grows

        threading.Thread(target=compactor, daemon=True).start()
    if parent_pid:
        # orphan watchdog: an aborted soak (supervisor process gone
        # without stop()) must not strand a listener on the fixed port.
        # Polling beats PR_SET_PDEATHSIG-via-preexec_fn: preexec forces
        # subprocess onto fork (unsafe under the parent's JAX threads).
        def watchdog() -> None:
            while os.getppid() == parent_pid:
                time.sleep(0.5)
            os.kill(os.getpid(), signal.SIGKILL)

        threading.Thread(target=watchdog, daemon=True).start()
    threading.Event().wait()  # until SIGKILL — no orderly shutdown, ever


#: the -c stub each child incarnation boots through
_CHILD_CMD = (
    "import json, sys; "
    "from minisched_tpu.faults.proc import _child_main; "
    "_child_main(**json.loads(sys.argv[1]))"
)


class ServerSupervisor:
    """Run the REST control plane as a killable child process.

    ``compact_every_s`` arms periodic checkpoint compaction in the child
    (snapshot + WAL truncate), so restarts exercise the bounded-replay
    path AND watch resumes can hit 410.  ``archive_history=True`` keeps
    every truncated WAL segment in ``<wal>.history`` — the full-history
    double-bind audit stays possible across compactions.
    """

    def __init__(
        self,
        wal_path: str,
        port: int = 0,
        compact_every_s: Optional[float] = None,
        archive_history: bool = True,
        fsync: bool = False,
        boot_timeout_s: float = 30.0,
        salvage: str = "off",
        scrub_every_s: Optional[float] = None,
        fault_seed: Optional[int] = None,
        fault_rules: Optional[dict] = None,
    ):
        self._wal = wal_path
        self._port = port or _free_port()
        self._compact_every_s = compact_every_s
        self._archive = archive_history
        self._fsync = fsync
        self._boot_timeout_s = boot_timeout_s
        self._salvage = salvage
        self._scrub_every_s = scrub_every_s
        self._fault_seed = fault_seed
        self._fault_rules = fault_rules
        self._proc: Any = None
        self._chaos_thread: Optional[threading.Thread] = None
        self._chaos_stop = threading.Event()
        #: lifecycle evidence the soaks assert on
        self.kills = 0
        self.restarts = 0

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    @property
    def metrics_url(self) -> str:
        """Where to scrape THIS child's telemetry: the façade itself
        serves ``/metrics`` (Prometheus text) and ``/debug/trace``
        (JSONL spans), so the supervised process is scrapeable on the
        same fixed port clients already know."""
        return self.base_url + "/metrics"

    @property
    def wal_path(self) -> str:
        return self._wal

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> str:
        """Spawn the child and block until /healthz answers — the same
        readiness gate the reference's StartAPIServer polls."""
        if self.alive():
            raise RuntimeError("control-plane child already running")
        cfg = {
            "wal_path": self._wal,
            "port": self._port,
            "compact_every_s": self._compact_every_s,
            "archive": self._archive,
            "fsync": self._fsync,
            "parent_pid": os.getpid(),
            "salvage": self._salvage,
            "scrub_every_s": self._scrub_every_s,
            "fault_seed": self._fault_seed,
            "fault_rules": self._fault_rules,
        }
        env = dict(os.environ)
        # the child must import minisched_tpu from THIS checkout even when
        # the supervisor runs from a test process whose cwd is elsewhere
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        self._proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD_CMD, json.dumps(cfg)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + self._boot_timeout_s
        url = self.base_url + "/healthz"
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise RuntimeError(
                    f"control-plane child died at boot "
                    f"(exitcode {self._proc.returncode})"
                )
            try:
                with urllib.request.urlopen(url, timeout=1.0) as r:
                    if r.status == 200:
                        return self.base_url
            except OSError:
                pass
            time.sleep(0.05)
        raise RuntimeError(
            f"control-plane child failed /healthz within "
            f"{self._boot_timeout_s}s"
        )

    def kill(self) -> None:
        """SIGKILL — no atexit, no flush, no goodbye.  Whatever the WAL
        holds at this instant is the whole truth the next life recovers
        (a torn mid-append tail is truncated at replay)."""
        if self._proc is None:
            return
        if self._proc.poll() is None:
            self._proc.kill()
            self.kills += 1
        try:
            self._proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            pass
        self._proc = None

    def restart(self) -> str:
        base = self.start()
        self.restarts += 1
        return base

    def kill_and_restart(self) -> str:
        self.kill()
        return self.restart()

    def stop(self) -> None:
        """Supervisor teardown: stop the chaos thread, then the child."""
        self._chaos_stop.set()
        if self._chaos_thread is not None:
            self._chaos_thread.join(timeout=10.0)
            self._chaos_thread = None
        self.kill()

    # -- scheduled chaos ----------------------------------------------------
    def start_chaos(
        self,
        fabric: Any = None,
        interval_s: float = 1.0,
        max_kills: int = 3,
    ) -> None:
        """Background killer: every ``interval_s`` of child uptime, decide
        whether to SIGKILL + restart.  With a FaultFabric the decision is
        its deterministic ``proc.kill`` schedule (arm the point with a
        rate); without one, every tick kills.  Stops after ``max_kills``
        or ``stop()``."""
        if self._chaos_thread is not None:
            raise RuntimeError("chaos already running")
        self._chaos_stop.clear()

        def run() -> None:
            while not self._chaos_stop.is_set() and self.kills < max_kills:
                if self._chaos_stop.wait(interval_s):
                    return
                if fabric is not None and not fabric.should_fire(
                    "proc.kill", str(self._port)
                ):
                    continue
                try:
                    self.kill_and_restart()
                except Exception:
                    # a failed restart leaves the plane down; the next
                    # tick retries rather than killing the soak thread
                    import traceback

                    traceback.print_exc()

        self._chaos_thread = threading.Thread(
            target=run, name="proc-chaos", daemon=True
        )
        self._chaos_thread.start()

    def wait_chaos_done(self, timeout_s: float = 120.0) -> bool:
        """Block until the scheduled kills all happened (the soak then
        drives to convergence on a STABLE plane)."""
        t = self._chaos_thread
        if t is None:
            return True
        t.join(timeout=timeout_s)
        return not t.is_alive()
