"""Deterministic network-fault layer: partitions as data (DESIGN.md §28).

The process nemeses (SIGKILL, bit-flips, ENOSPC) kill *machines*; this
module kills *links*.  Every replication-plane client call — a
follower's stream/status/ack traffic, the coordinator's arbiter lease
CAS — consults :data:`GLOBAL_NET` before touching the socket, keyed on
the (src, dst) replica-id pair and a channel:

    ``arbiter``  — lease acquire/renew/read traffic (the failure
                   detector's input)
    ``data``     — /repl/* stream, status, ack, checkpoint fetch

A link rule is either IMPOSED (``cut()`` / the ``/net/partition`` HTTP
control surface — how the chaos soak partitions child processes from
the parent test) or SCHEDULED through an embedded
:class:`~minisched_tpu.faults.FaultFabric` at the ``net.drop`` point
(key ``"src>dst"``), so flaky-link chaos reproduces byte-for-byte from
a seed like every other fault in the fabric.  Modes:

    ``drop``       — fail immediately (connection refused: the fast,
                     honest partition)
    ``blackhole``  — hang for the caller's timeout, then fail (the slow
                     partition that exercises timeout paths, capped so
                     soaks converge)
    ``delay``      — sleep ``delay_s`` then let the call through (the
                     one-way latency asymmetry)

Rules are DIRECTIONAL: ``cut("r0", "r1")`` severs r0→r1 only; tests
wanting a symmetric partition install both directions (on both
processes — each process enforces only its own outbound edges, exactly
like a real firewall).  Failures surface as :class:`NetPartitioned`, an
``OSError`` subclass, so every existing retry/degrade path treats a
partitioned link exactly like a dead peer.

Counters (observability/counters.py registry): ``net.partition.dropped``
/ ``blackholed`` / ``delayed`` per enforced verdict, ``cuts`` / ``heals``
per rule change, gauge ``net.partition.links`` = live imposed rules.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from minisched_tpu.faults import FaultFabric
from minisched_tpu.observability import counters

#: longest a blackholed call may hang when the caller gave no timeout —
#: bounds the worst case so an un-timeouted code path cannot wedge a soak
BLACKHOLE_CAP_S = 5.0

_MODES = ("drop", "blackhole", "delay")
_CHANNELS = ("*", "arbiter", "data")


class NetPartitioned(OSError):
    """A call refused/failed by the network-fault layer (never raised by
    real networking).  Subclasses OSError on purpose: partition handling
    must ride the SAME retry/fence/degrade paths as real link death."""


class NetFabric:
    """One process's outbound network-fault table.

    ``identity`` is this process's replica id (the implicit ``src`` of
    every outbound check); replica children set it at boot, the test
    process sets it per in-process actor by passing ``src=`` explicitly.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.identity: str = ""
        # (src, dst) -> {"mode", "channel", "delay_s"}
        self._links: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._fabric: Optional[FaultFabric] = None
        self._enforced: Dict[str, int] = {}

    # -- configuration ----------------------------------------------------
    def configure(
        self,
        identity: str = "",
        seed: int = 0,
        rules: Optional[List[dict]] = None,
    ) -> "NetFabric":
        """Boot-time setup (replica children): set identity, arm the
        blake2s-scheduled ``net.drop`` point when a seed is given, and
        install any pre-imposed link rules."""
        with self._mu:
            if identity:
                self.identity = str(identity)
            if seed:
                self._fabric = FaultFabric(int(seed)).on("net.drop", rate=1.0)
        for rule in rules or []:
            self.cut(**rule)
        return self

    def flake(self, rate: float, seed: int, **kw: Any) -> "NetFabric":
        """Arm scheduled link drops: each outbound call fires per the
        deterministic (seed, "net.drop", "src>dst", n) schedule."""
        with self._mu:
            self._fabric = FaultFabric(int(seed)).on(
                "net.drop", rate=rate, **kw
            )
        return self

    def cut(
        self,
        src: str,
        dst: str,
        mode: str = "drop",
        channel: str = "*",
        delay_s: float = 0.0,
    ) -> None:
        """Impose a directional link rule (src may be "*": any local
        actor; dst may be "*": every peer)."""
        if mode not in _MODES:
            raise ValueError(f"unknown partition mode {mode!r}")
        if channel not in _CHANNELS:
            raise ValueError(f"unknown partition channel {channel!r}")
        with self._mu:
            self._links[(str(src), str(dst))] = {
                "mode": mode,
                "channel": channel,
                "delay_s": float(delay_s),
            }
            counters.inc("net.partition.cuts")
            counters.set_gauge("net.partition.links", len(self._links))

    def heal(self, src: str, dst: str) -> bool:
        with self._mu:
            gone = self._links.pop((str(src), str(dst)), None)
            if gone is not None:
                counters.inc("net.partition.heals")
            counters.set_gauge("net.partition.links", len(self._links))
            return gone is not None

    def heal_all(self) -> int:
        with self._mu:
            n = len(self._links)
            self._links.clear()
            if n:
                counters.inc("net.partition.heals", n)
            counters.set_gauge("net.partition.links", 0)
            return n

    # -- enforcement ------------------------------------------------------
    def _match(
        self, src: str, dst: str, channel: str
    ) -> Optional[Dict[str, Any]]:
        for key in ((src, dst), (src, "*"), ("*", dst), ("*", "*")):
            rule = self._links.get(key)
            if rule is not None and rule["channel"] in ("*", channel):
                return rule
        return None

    def check(
        self,
        dst: str,
        channel: str = "data",
        src: str = "",
        timeout_s: Optional[float] = None,
    ) -> None:
        """Gate one outbound call from ``src`` (default: our identity)
        to ``dst`` on ``channel``.  Raises :class:`NetPartitioned` when
        the link is cut; sleeps first for blackhole/delay modes."""
        src = src or self.identity
        with self._mu:
            rule = self._match(src, dst, channel)
            fabric = self._fabric
        if rule is None:
            if fabric is not None and fabric.should_fire(
                "net.drop", f"{src}>{dst}"
            ):
                self._count("dropped")
                raise NetPartitioned(
                    f"net.drop scheduled: {src} -> {dst} ({channel})"
                )
            return
        mode = rule["mode"]
        if mode == "drop":
            self._count("dropped")
            raise NetPartitioned(
                f"link cut: {src} -> {dst} ({channel})"
            )
        if mode == "blackhole":
            hang = min(
                timeout_s if timeout_s is not None else BLACKHOLE_CAP_S,
                BLACKHOLE_CAP_S,
            )
            time.sleep(max(0.0, hang))
            self._count("blackholed")
            raise NetPartitioned(
                f"link blackholed {hang:.1f}s: {src} -> {dst} ({channel})"
            )
        # delay: impose the latency, then let the call proceed
        time.sleep(max(0.0, float(rule["delay_s"])))
        self._count("delayed")

    def _count(self, verdict: str) -> None:
        counters.inc(f"net.partition.{verdict}")
        with self._mu:
            self._enforced[verdict] = self._enforced.get(verdict, 0) + 1

    # -- control surface (httpserver /net/partition) ----------------------
    def describe(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "identity": self.identity,
                "links": [
                    {"src": s, "dst": d, **rule}
                    for (s, d), rule in sorted(self._links.items())
                ],
                "enforced": dict(self._enforced),
            }

    def control(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Apply one control op: {"op": "cut"|"heal"|"heal_all", ...} —
        the wire form the chaos soak POSTs at replica children."""
        op = body.get("op")
        if op == "cut":
            self.cut(
                body["src"],
                body["dst"],
                mode=body.get("mode", "drop"),
                channel=body.get("channel", "*"),
                delay_s=float(body.get("delay_s", 0.0)),
            )
        elif op == "heal":
            self.heal(body["src"], body["dst"])
        elif op == "heal_all":
            self.heal_all()
        else:
            raise ValueError(f"unknown net control op {op!r}")
        return self.describe()


#: the process-wide instance every outbound replication-plane call
#: consults; replica children configure identity at boot, tests drive it
#: directly (in-process) or over POST /net/partition (child processes)
GLOBAL_NET = NetFabric()
