"""Seedable, deterministic fault-injection fabric.

The reference stack assumes an in-process, never-failing control plane
(sched.go boots apiserver+etcd in the same process and no caller checks an
error twice).  The production north star is the opposite: every layer of
this scheduler talks to a control plane that can time out, reset
connections, serve 5xx, lose a watch stream, or fail a WAL append — and
the engine must converge anyway without leaking assumed capacity.

This module is the one switchboard for making those failures HAPPEN on
demand.  Components take an optional ``FaultFabric`` and consult it at
*named injection points*; unconfigured points cost one attribute read.

Named points wired through the tree (grep for the literal string):

    store.get / store.list / store.create / store.update / store.delete
        — ObjectStore API calls raise InjectedFault (a flaky apiserver /
          etcd; ``store.update`` covers the bind subresource, which is a
          mutate under the hood)
    watch.drop
        — a store watch stream dies instead of delivering an event (the
          informer must reconnect + replay-diff); key = kind
    wal.append
        — DurableObjectStore refuses the mutation before touching memory
          (disk full / IO error surfaced as a failed API call)
    disk.enospc
        — the WAL append itself fails with OSError(ENOSPC): the store
          latches DEGRADED read-only (typed store.StorageDegraded, HTTP
          507 on the wire) until its recovery probe re-arms writes
    wal.bitflip
        — the append SUCCEEDS but one payload bit flips after the CRC
          was computed (the lying disk); replay and fsck must DETECT the
          frame, never silently apply it
    wal.torn_mid
        — only a prefix of the frame reaches the file and later appends
          bury it: mid-file torn write, located (offset/rv window) by
          replay instead of a bare JSONDecodeError
    ckpt.corrupt
        — one byte of a freshly-written checkpoint flips post-rename
          (bit rot); the sha256 sidecar convicts it and restore takes
          the fallback chain (prev generation → full WAL+archive replay)
    http.500 / http.reset
        — the REST façade answers 503, or closes the connection without
          any response bytes (the client sees a transport error and must
          retry); key = request path
    remote.request
        — the RemoteStore client fails an attempt before it leaves the
          process (connection reset on connect); key = request path
    engine.bind
        — the device engine's batch-bind transaction raises before the
          store call (exercises the wave's failed-commit requeue path)
    repl.ship
        — the leader's replication stream server drops a follower's
          connection mid-ship with no goodbye (a flaky replica link);
          the follower reconnects and resumes from its own WAL offset;
          key = replica id
    repl.ack
        — the leader's /repl/ack handler answers 503 and DISCARDS the
          follower's durability ack (the follower's write is real but
          unproven); the follower's next group or heartbeat re-ack
          heals it — quorum waits stretch, correctness holds; key =
          replica id
    net.drop
        — an outbound replication-plane call (arbiter lease CAS,
          follower stream/status/ack) is refused before it touches the
          socket: the scheduled flaky-link half of faults/net.py, keyed
          "src>dst"; imposed partitions (cut/blackhole/delay) live in
          :class:`minisched_tpu.faults.net.NetFabric` beside it

Determinism: whether call *n* at (point, key) fires is a pure function of
``(seed, point, key, n)`` — a blake2s hash, not a shared RNG — so the
fault schedule reproduces byte-for-byte for a fixed seed regardless of
thread interleaving, and two points never steal entropy from each other.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from hashlib import blake2s
from typing import Dict, FrozenSet, Optional, Tuple


class InjectedFault(RuntimeError):
    """An error manufactured by the fabric (never raised by real code)."""


@dataclass
class FaultRule:
    """Per-point firing policy.

    ``rate``: probability each eligible call fires.  ``after``: skip the
    first N calls at the point (let a scenario boot cleanly).
    ``max_fires``: stop injecting after this many fires (bounds the worst
    case so a soak always converges).  ``keys``: restrict to these call
    keys (e.g. only the Pod/Node watch streams).
    """

    rate: float
    after: int = 0
    max_fires: Optional[int] = None
    keys: Optional[FrozenSet[str]] = None


class FaultFabric:
    def __init__(self, seed: int):
        self._seed = int(seed)
        self._rules: Dict[str, FaultRule] = {}
        self._mu = threading.Lock()
        self._calls: Dict[Tuple[str, str], int] = {}
        self._fires: Dict[str, int] = {}

    @property
    def seed(self) -> int:
        return self._seed

    def on(
        self,
        point: str,
        rate: float,
        after: int = 0,
        max_fires: Optional[int] = None,
        keys=None,
    ) -> "FaultFabric":
        """Arm a point (chainable)."""
        self._rules[point] = FaultRule(
            rate=float(rate),
            after=after,
            max_fires=max_fires,
            keys=frozenset(keys) if keys is not None else None,
        )
        return self

    def _decision(self, point: str, key: str, n: int) -> float:
        h = blake2s(
            f"{self._seed}:{point}:{key}:{n}".encode(), digest_size=4
        ).digest()
        return int.from_bytes(h, "big") / 2**32

    def should_fire(self, point: str, key: str = "") -> bool:
        """True when this call at (point, key) is scheduled to fail.
        Counts the call either way — the decision depends on the per-key
        call ordinal, which is what makes the schedule deterministic."""
        rule = self._rules.get(point)
        if rule is None:
            return False
        with self._mu:
            n = self._calls.get((point, key), 0)
            self._calls[(point, key)] = n + 1
            if rule.keys is not None and key not in rule.keys:
                return False
            if n < rule.after:
                return False
            if (
                rule.max_fires is not None
                and self._fires.get(point, 0) >= rule.max_fires
            ):
                return False
            fire = self._decision(point, key, n) < rule.rate
            if fire:
                self._fires[point] = self._fires.get(point, 0) + 1
            return fire

    def check(self, point: str, key: str = "") -> None:
        """Raise InjectedFault when the schedule says this call fails."""
        if self.should_fire(point, key):
            raise InjectedFault(f"injected fault at {point} ({key})")

    def fires(self, point: str) -> int:
        with self._mu:
            return self._fires.get(point, 0)

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{'fires': per-point fire counts, 'calls': per-point call counts}
        — the chaos soak's injection evidence."""
        with self._mu:
            calls: Dict[str, int] = {}
            for (point, _key), n in self._calls.items():
                calls[point] = calls.get(point, 0) + n
            return {"fires": dict(self._fires), "calls": calls}

    def as_store_injector(self):
        """Adapter for ``ObjectStore.fault_injector`` (op, kind, key):
        routes mutations to the ``store.{op}`` points."""

        def injector(op: str, kind: str, key: str) -> None:
            self.check(f"store.{op}", f"{kind}/{key}")

        return injector


def wal_double_binds(wal_path: str):
    """Audit a DurableObjectStore WAL's FULL history for double binds:
    returns [(uid, first_node, other_node), ...] for every pod that ever
    appeared bound to two different nodes — the capacity bug the assume/
    requeue machinery must make impossible.  Shared by the chaos soak and
    the bench chaos role (one audit, one definition of 'double bind').

    When the store compacts with ``archive_compacted=True`` the truncated
    segments live in ``<path>.history``; the audit reads them first (in
    append order, i.e. mutation order) so compaction never shrinks the
    evidence.

    Records ride the walio frame reader in LENIENT mode: both legacy
    JSONL and v2 CRC-framed WALs audit identically, torn tails from a
    SIGKILL mid-append drop silently, and a corrupt region (an injected
    bit-flip that chaos later archived) is skipped by magic resync — an
    audit wants every record it can still prove intact, while REPLAY of
    the same bytes hard-fails (fsck reports the divergence)."""
    import os

    from minisched_tpu.controlplane.walio import iter_wal_records_lenient

    bound_to: dict = {}
    violations = []
    paths = [
        p
        for p in (
            wal_path + ".history",
            wal_path + ".pending-archive",  # claimed by a compaction a
            wal_path,                       # crash interrupted mid-copy
        )
        if os.path.exists(p)
    ]
    for path in paths:
        for rec in iter_wal_records_lenient(path):
            if rec.get("op") != "put" or rec.get("kind") != "Pod":
                continue
            obj = rec["obj"]
            node = (obj.get("spec") or {}).get("node_name")
            uid = (obj.get("metadata") or {}).get("uid")
            if not node:
                continue
            prev = bound_to.setdefault(uid, node)
            if prev != node:
                violations.append((uid, prev, node))
    return violations
