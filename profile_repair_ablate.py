"""Ablate the repair step's phases on the real device at config5 wave
shapes: evaluate / accept / apply / select, timed separately — the
round-4 op profile was FLAT (largest fusion 21%), so the lever must be
found empirically, not assumed (VERDICT r4 item 8).

PN/PW env: node/pod counts (default 10_000 × 16_384).
"""

import os
import sys
import time

from minisched_tpu.utils.compilecache import enable_persistent_cache

enable_persistent_cache()

import random
from functools import partial

import jax
import jax.numpy as jnp

from minisched_tpu.api.objects import make_node, make_pod
from minisched_tpu.models.constraints import build_constraint_tables
from minisched_tpu.models.tables import build_node_table, build_pod_table, pad_to
from minisched_tpu.ops.fused import BatchContext, evaluate, precompute_static, select_hosts
from minisched_tpu.ops.repair import accept_placements, repair_wave_step
from minisched_tpu.ops.state import apply_placements
from minisched_tpu.plugins.registry import build_plugins
from minisched_tpu.service.config import default_full_roster_config

print("backend:", jax.default_backend(), file=sys.stderr)

N_NODES = int(os.environ.get("PN", 10_000))
WAVE = int(os.environ.get("PW", 16_384))

rng = random.Random(55)
nodes = sorted(
    (
        make_node(
            f"node{i:05d}",
            unschedulable=rng.random() < 0.2,
            capacity={"cpu": "8", "memory": "16Gi", "pods": 110},
            labels={"zone": f"z{i % 16}"},
        )
        for i in range(N_NODES)
    ),
    key=lambda n: n.metadata.name,
)
pods = [
    make_pod(f"pod{i:06d}", requests={"cpu": "500m", "memory": "256Mi"})
    for i in range(WAVE)
]

cfg = default_full_roster_config()
chains = build_plugins(cfg)
ctx = BatchContext(weights=tuple(sorted(cfg.score_weights().items())))

node_table, names = build_node_table(nodes)
pod_table, _ = build_pod_table(pods, capacity=pad_to(WAVE))
extra = build_constraint_tables(
    pods, nodes, [],
    pod_capacity=pod_table.capacity, node_capacity=node_table.capacity,
    scan_planes=False,
)


def timed(label, fn, *args, reps=4, **kw):
    out = None
    best = float("inf")
    for rep in range(reps):
        t0 = time.monotonic()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        dt = time.monotonic() - t0
        if rep:  # rep 0 is compile
            best = min(best, dt)
    print(f"{label:<28s} {best*1e3:8.1f} ms", file=sys.stderr)
    return out


# 1. the full repair step (diagnostics OFF — the live packed path has
# them ON; compare both)
for diag in (False, True):
    step = jax.jit(partial(
        repair_wave_step,
        filter_plugins=tuple(chains.filter),
        pre_score_plugins=tuple(chains.pre_score),
        score_plugins=tuple(chains.score),
        ctx=ctx, max_rounds=16, with_diagnostics=diag,
    ))
    timed(f"full repair (diag={diag})", step, node_table, pod_table,
          extra=extra)

# 2. static precompute (once per wave) — StaticWavePlanes is not a
# pytree (it only ever lives inside one jitted program), so the probe
# returns its leaves
def _static_only(pods, nodes, extra):
    s = precompute_static(
        pods, nodes, tuple(chains.filter), tuple(chains.pre_score),
        tuple(chains.score), ctx, extra=extra,
    )
    return (s.static_mask, s.aux, s.raw_scores)

timed("precompute_static", jax.jit(_static_only), pod_table, node_table,
      extra)


# 3. static + one round's evaluate (loop-body shape); evaluate alone ≈
# this minus the static probe above
def _static_plus_round(pods, nodes, extra):
    s = precompute_static(
        pods, nodes, tuple(chains.filter), tuple(chains.pre_score),
        tuple(chains.score), ctx, extra=extra,
    )
    return evaluate(
        pods, nodes, tuple(chains.filter), tuple(chains.pre_score),
        tuple(chains.score), ctx, extra=extra, static=s,
    )

result = timed("static + 1 evaluate round", jax.jit(_static_plus_round),
               pod_table, node_table, extra)

# 4. accept_placements on the round's choice
fam_limits = tuple(
    (pl.volume_family_index, pl.max_volumes)
    for pl in chains.filter
    if getattr(pl, "volume_family_index", None) is not None
)
acc_fn = jax.jit(partial(accept_placements, check_resources=True,
                         check_ports=True))
accept = timed("accept_placements", acc_fn, node_table, pod_table,
               result.choice, pod_table.valid)

# 5. apply_placements scatter
app_fn = jax.jit(apply_placements)
timed("apply_placements", app_fn, node_table, pod_table,
      jnp.where(accept, result.choice, -1))

# 6. select_hosts alone at this shape (inside evaluate already, but
# isolate its share)
P = pod_table.valid.shape[0]
N = node_table.valid.shape[0]
scores = jnp.zeros((P, N), jnp.int32)
mask = pod_table.valid[:, None] & node_table.valid[None, :]
sel = jax.jit(select_hosts)
timed("select_hosts (current)", sel, scores, mask, pod_table.seed)


# 7. per-plugin ablation of the static half — which kernel owns
# precompute_static's share?
def _one_filter(pl):
    if getattr(pl, "needs_extra", False):
        return jax.jit(lambda p, n, e: pl.batch_filter(ctx, p, n, e))
    return jax.jit(lambda p, n, e: pl.batch_filter(ctx, p, n))


def _one_score(pl):
    def fn(p, n, e):
        aux = {}
        for pre in chains.pre_score:
            if pre.name() == pl.name():
                aux = pre.batch_pre_score(ctx, p, n)
        if getattr(pl, "needs_extra", False):
            return pl.batch_score(ctx, p, n, aux, e)
        return pl.batch_score(ctx, p, n, aux)

    return jax.jit(fn)


print("-- static filters --", file=sys.stderr)
for pl in chains.filter:
    if getattr(pl, "reads_committed_state", False):
        continue
    timed(f"  filter {pl.name()}", _one_filter(pl), pod_table, node_table,
          extra)
print("-- static scores --", file=sys.stderr)
for pl in chains.score:
    if getattr(pl, "reads_committed_state", False):
        continue
    timed(f"  score {pl.name()}", _one_score(pl), pod_table, node_table,
          extra)
print("-- dynamic (per round) --", file=sys.stderr)
for pl in chains.filter:
    if getattr(pl, "reads_committed_state", False):
        timed(f"  filter {pl.name()}", _one_filter(pl), pod_table,
              node_table, extra)
for pl in chains.score:
    if getattr(pl, "reads_committed_state", False):
        timed(f"  score {pl.name()}", _one_score(pl), pod_table, node_table,
              extra)
