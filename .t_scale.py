import os, time
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
import random
from minisched_tpu.api.objects import (Affinity, LabelSelector, PodAffinity,
    PodAffinityTerm, TopologySpreadConstraint, WeightedPodAffinityTerm, make_node, make_pod)
from minisched_tpu.models.constraints import build_constraint_tables
from minisched_tpu.models.tables import build_node_table, build_pod_table
from minisched_tpu.ops.fused import BatchContext
from minisched_tpu.ops.repair import RepairingEvaluator
from minisched_tpu.parallel import sharding
from minisched_tpu.plugins.interpodaffinity import InterPodAffinity
from minisched_tpu.plugins.podtopologyspread import PodTopologySpread
from minisched_tpu.plugins.noderesources import NodeResourcesFit, NodeResourcesLeastAllocated
from minisched_tpu.plugins.nodeunschedulable import NodeUnschedulable

N_NODES, N_PODS = 2100, 4100  # deliberately NOT divisible by the mesh axes
rng = random.Random(9)
zones = [f"z{i}" for i in range(12)]
nodes = sorted((make_node(f"node{i:04d}", labels={"zone": zones[i % 12]},
                          unschedulable=rng.random() < 0.1,
                          capacity={"cpu": "8", "memory": "16Gi", "pods": 24})
                for i in range(N_NODES)), key=lambda n: n.metadata.name)
assigned = []
for i in range(200):
    p = make_pod(f"asg{i}", labels={"app": f"a{i%4}"})
    p.metadata.uid = f"asg{i}"
    p.spec.node_name = rng.choice(nodes).metadata.name
    assigned.append(p)
pods = []
for i in range(N_PODS):
    p = make_pod(f"pod{i:05d}", labels={"app": f"a{i%4}"},
                 requests={"cpu": f"{rng.choice([250, 500])}m", "memory": "256Mi"})
    if i % 16 == 0:
        p.spec.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=8, topology_key="zone", when_unsatisfiable="ScheduleAnyway",
            label_selector=LabelSelector(match_labels={"app": p.metadata.labels["app"]}))]
    elif i % 16 == 1:
        p.spec.affinity = Affinity(pod_affinity=PodAffinity(
            preferred=[WeightedPodAffinityTerm(weight=20, term=PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": p.metadata.labels["app"]}),
                topology_key="zone"))]))
    pods.append(p)
ipa = InterPodAffinity(); ts = PodTopologySpread()
filters = (NodeUnschedulable(), NodeResourcesFit(), ipa, ts)
pres = (ipa, ts)
scores = (NodeResourcesLeastAllocated(), ipa, ts)
ctx = BatchContext(weights=())
by_node = {}
for p in assigned: by_node.setdefault(p.spec.node_name, []).append(p)
t0 = time.monotonic()
node_table, names = build_node_table(nodes, by_node)
pod_table, _ = build_pod_table(pods)
extra = build_constraint_tables(pods, nodes, assigned,
    pod_capacity=pod_table.capacity, node_capacity=node_table.capacity)
print(f"build: {time.monotonic()-t0:.1f}s caps pod={pod_table.capacity} node={node_table.capacity}")
t0 = time.monotonic()
ev = RepairingEvaluator(filters, pres, scores)
_, want, wr = ev(pod_table, node_table, extra)
want = want.tolist(); print(f"single-device repair: {time.monotonic()-t0:.1f}s rounds={int(wr)}")
t0 = time.monotonic()
mesh = sharding.make_mesh(8)
step = sharding.sharded_repair_step(mesh, filters, pres, scores, ctx)
node_table, _ = build_node_table(nodes, by_node)
pod_table, _ = build_pod_table(pods)
extra = build_constraint_tables(pods, nodes, assigned,
    pod_capacity=pod_table.capacity, node_capacity=node_table.capacity)
pod_table, node_table = sharding.shard_tables(mesh, pod_table, node_table)
extra = jax.device_put(extra, sharding.constraint_sharding(mesh, extra))
_, got, gr = step(node_table, pod_table, extra)
got = got.tolist(); print(f"sharded repair: {time.monotonic()-t0:.1f}s rounds={int(gr)}")
assert want == got, "sharded != single-device"
placed = sum(1 for c in got[:N_PODS] if c >= 0)
print(f"bit-equal OK, {placed}/{N_PODS} placed")
