"""Profile the scan lane's host-side constraint build on c5x shapes.

10k nodes, one 4096-cap chunk of spread-constrained pods (32 apps x 16
zones), packed mode (device=False, elide_zeros=False) — the exact call
the blocked lane makes per chunk.  Scratch tool, not part of the bench.
"""
import cProfile
import os
import pstats
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from minisched_tpu.api.objects import (
    LabelSelector,
    TopologySpreadConstraint,
    make_node,
    make_pod,
)
from minisched_tpu.models.constraints import build_constraint_tables
from minisched_tpu.models.tables import pad_to

N_NODES = int(os.environ.get("P_NODES", 10_000))
CAP = int(os.environ.get("P_CAP", 4096))
N_PODS = int(os.environ.get("P_PODS", 4096))
N_APPS = 32
N_ZONES = 16

nodes = []
for i in range(N_NODES):
    nodes.append(
        make_node(
            f"node-{i:05d}",
            capacity={"cpu": "8", "memory": "32Gi", "pods": "110"},
            labels={
                "zone": f"z{i % N_ZONES}",
                "kubernetes.io/hostname": f"node-{i:05d}",
            },
        )
    )

pods = []
for i in range(N_PODS):
    app = f"app{i % N_APPS}"
    p = make_pod(
        f"spread-{i:05d}",
        requests={"cpu": "100m", "memory": "128Mi"},
        labels={"app": app},
    )
    p.spec.topology_spread_constraints = [
        TopologySpreadConstraint(
            max_skew=4,
            topology_key="zone",
            when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_labels={"app": app}),
        )
    ]
    pods.append(p)

NCAP = pad_to(len(nodes))

t0 = time.monotonic()
extra = build_constraint_tables(
    pods, nodes, [], pod_capacity=CAP, node_capacity=NCAP,
    scan_planes=True, device=False, elide_zeros=False,
)
print(f"cold build: {time.monotonic() - t0:.3f}s")

for _ in range(2):
    t0 = time.monotonic()
    extra = build_constraint_tables(
        pods, nodes, [], pod_capacity=CAP, node_capacity=NCAP,
        scan_planes=True, device=False, elide_zeros=False,
    )
    print(f"warm build: {time.monotonic() - t0:.3f}s")

prof = cProfile.Profile()
prof.enable()
extra = build_constraint_tables(
    pods, nodes, [], pod_capacity=CAP, node_capacity=NCAP,
    scan_planes=True, device=False, elide_zeros=False,
)
prof.disable()
stats = pstats.Stats(prof)
stats.sort_stats("cumulative").print_stats(25)
