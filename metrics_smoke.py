"""End-to-end smoke for the live telemetry plane (`make metrics-smoke`).

Boots the REST façade + a live scheduler in one process, drives 100 pods
to bind, then validates the OBSERVER's view only through the wire:

* ``GET /metrics`` parses as Prometheus text exposition (the repo's own
  minimal parser, hist.parse_prometheus) and carries a NON-EMPTY
  ``sched_time_to_bind_seconds`` histogram covering every bind;
* ``GET /debug/trace`` returns JSONL spans with a complete
  enqueue→pop→bind→ack chain for bound pods;
* the scrape-side p99 (parsed buckets) matches the live registry's.

Exit 0 on success, 1 with a reason on any failure — CI-shaped.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

N_PODS = 100
N_NODES = 4


def fail(msg: str) -> None:
    print(f"[metrics-smoke] FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


def main() -> int:
    from minisched_tpu.api.objects import make_node, make_pod
    from minisched_tpu.controlplane.client import Client
    from minisched_tpu.controlplane.httpserver import start_api_server
    from minisched_tpu.observability import hist
    from minisched_tpu.service.config import default_scheduler_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    raw = getattr(client.store, "_store", client.store)
    server, base, shutdown = start_api_server(raw, port=0)
    svc = SchedulerService(client)
    svc.start_scheduler(default_scheduler_config(time_scale=0.01))
    try:
        for i in range(N_NODES):
            client.nodes().create(
                make_node(f"node{i}", capacity={"cpu": "64", "memory": "256Gi",
                                                "pods": 110})
            )
        for i in range(N_PODS):
            client.pods().create(make_pod(f"smoke-{i:03d}"))
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            bound = [p for p in client.pods().list() if p.spec.node_name]
            if len(bound) == N_PODS:
                break
            time.sleep(0.1)
        else:
            fail(f"only {len(bound)}/{N_PODS} pods bound within 120s")
        print(f"[metrics-smoke] {N_PODS} pods bound on {N_NODES} nodes")

        # -- /metrics: valid exposition, non-empty time-to-bind ------------
        with urllib.request.urlopen(base + "/metrics", timeout=10.0) as r:
            if r.status != 200:
                fail(f"/metrics answered {r.status}")
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        if "version=0.0.4" not in ctype:
            fail(f"/metrics content-type {ctype!r} is not text exposition")
        types, samples = hist.parse_prometheus(text)
        if types.get("sched_time_to_bind_seconds") != "histogram":
            fail("sched_time_to_bind_seconds missing or not histogram-typed")
        ttb_count = sum(
            v for n, _l, v in samples if n == "sched_time_to_bind_seconds_count"
        )
        if ttb_count < N_PODS:
            fail(
                f"time-to-bind histogram has {int(ttb_count)} samples, "
                f"want >= {N_PODS}"
            )
        scraped_p99 = hist.parsed_histogram_quantile(
            samples, "sched_time_to_bind_seconds", 0.99
        )
        live_p99 = hist.quantile_bounds("sched.time_to_bind_s", 0.99)
        if scraped_p99 != live_p99:
            fail(
                f"scrape-side p99 {scraped_p99} != live registry {live_p99}"
            )
        print(
            f"[metrics-smoke] /metrics: {len(samples)} samples, "
            f"{len(types)} metrics; time_to_bind count {int(ttb_count)}, "
            f"p99 bucket ({live_p99[0]}, {live_p99[1]}]s"
        )

        # -- /debug/trace: complete span chains ----------------------------
        with urllib.request.urlopen(base + "/debug/trace", timeout=10.0) as r:
            if r.status != 200:
                fail(f"/debug/trace answered {r.status}")
            lines = r.read().decode().strip().splitlines()
        spans = [json.loads(ln) for ln in lines]
        if not spans:
            fail("/debug/trace is empty")
        by_pod: dict = {}
        for s in spans:
            if "pod" in s:
                by_pod.setdefault(s["pod"], []).append(s["stage"])
        complete = 0
        for pod, stages in by_pod.items():
            if {"enqueue", "pop", "bind", "bind_ack"} <= set(stages):
                complete += 1
        if complete == 0:
            fail("no pod has a complete enqueue→pop→bind→bind_ack chain")
        print(
            f"[metrics-smoke] /debug/trace: {len(spans)} spans, "
            f"{complete} pods with complete enqueue→bind chains"
        )
        print("[metrics-smoke] OK")
        return 0
    finally:
        svc.shutdown_scheduler()
        shutdown()


if __name__ == "__main__":
    sys.exit(main())
