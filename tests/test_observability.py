"""Resultstore + simulator plugin wrapper (observability pipeline).

Mirrors the reference's test strategy (SURVEY.md §4):
``resultstore/store_test.go`` (state transitions + annotation flushing via
a fake client and a real informer) and ``plugin/plugins_test.go`` (wrapper
behavior with hand-written fake plugins and a mock store)."""

from __future__ import annotations

import json
import time
from unittest import mock

from minisched_tpu.api.objects import make_node, make_pod
from minisched_tpu.controlplane.client import Client
from minisched_tpu.controlplane.informer import (
    ResourceEventHandlers,
    SharedInformerFactory,
)
from minisched_tpu.framework.nodeinfo import build_node_infos
from minisched_tpu.framework.types import CycleState, NodeScore, Status
from minisched_tpu.observability import annotation
from minisched_tpu.observability.resultstore import PASSED_FILTER_MESSAGE, Store
from minisched_tpu.plugins.simulator import (
    convert_for_simulator,
    make_simulator_plugin,
    plugin_name,
    register_simulator_plugins,
)
from minisched_tpu.service.config import (
    PluginEnabled,
    PluginSet,
    default_full_roster_config,
)
from minisched_tpu.utils.retry import (
    RetryTimeoutError,
    retry_with_exponential_backoff,
)


# ---------------------------------------------------------------------------
# fake plugins (plugins_test.go:981-1042)
# ---------------------------------------------------------------------------


class FakeFilterPlugin:
    def __init__(self, reject: bool = False):
        self.reject = reject

    def name(self):
        return "FakeFilter"

    def filter(self, state, pod, node_info):
        if self.reject:
            return Status.unschedulable("fake says no")
        return Status.success()


class FakeScorePlugin:
    def name(self):
        return "FakeScore"

    def score(self, state, pod, node_name):
        return len(node_name), Status.success()

    def score_extensions(self):
        return None


class FakeNormalizingScorePlugin:
    def name(self):
        return "FakeNorm"

    def score(self, state, pod, node_name):
        return 10, Status.success()

    def score_extensions(self):
        outer = self

        class Ext:
            def normalize_score(self, state, pod, scores):
                for ns in scores:
                    ns.score = ns.score * 2
                return Status.success()

        return Ext()


# ---------------------------------------------------------------------------
# retry util (util/retry.go)
# ---------------------------------------------------------------------------


def test_retry_succeeds_after_failures():
    sleeps = []
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        return calls["n"] >= 3

    retry_with_exponential_backoff(fn, sleep=sleeps.append)
    assert calls["n"] == 3
    assert sleeps == [0.1, 0.1 * 3]  # 100ms initial, factor 3


def test_retry_exhausts():
    import pytest

    with pytest.raises(RetryTimeoutError):
        retry_with_exponential_backoff(lambda: False, sleep=lambda _: None)


# ---------------------------------------------------------------------------
# store state transitions (store_test.go:17-406)
# ---------------------------------------------------------------------------


def test_store_records_and_deletes():
    s = Store()
    s.add_filter_result("default/p1", "n1", "PluginA", "reason")
    s.add_score_result("default/p1", "n1", "PluginA", 42)
    s.add_normalized_score_result("default/p1", "n1", "PluginA", 50, weight=2)
    f, sc, fin = s.get_data("default/p1")
    assert f == {"n1": {"PluginA": "reason"}}
    assert sc == {"n1": {"PluginA": 42}}
    assert fin == {"n1": {"PluginA": 100}}  # normalized × weight
    assert s.has_data("default/p1")
    s.delete_data("default/p1")
    assert not s.has_data("default/p1")


def test_store_flush_to_annotations_via_informer():
    """store.go:62-67,90-135: a pod Update event flushes results onto the
    pod's annotations and clears the entry."""
    client = Client()
    store = Store(client)
    factory = SharedInformerFactory(client.store)
    factory.informer_for("Pod").add_event_handlers(
        ResourceEventHandlers(on_update=store.add_scheduling_result_to_pod)
    )
    factory.start()
    pod = client.pods().create(make_pod("p1"))
    store.add_filter_result(pod.metadata.key, "n1", "PluginA", PASSED_FILTER_MESSAGE)
    store.add_normalized_score_result(pod.metadata.key, "n1", "PluginA", 77)
    client.pods().update(pod.clone())  # any update triggers the flush

    deadline = time.time() + 5
    while time.time() < deadline:
        got = client.pods().get("p1")
        if annotation.FILTER_RESULT in got.metadata.annotations:
            break
        time.sleep(0.05)
    got = client.pods().get("p1")
    assert json.loads(got.metadata.annotations[annotation.FILTER_RESULT]) == {
        "n1": {"PluginA": "passed"}
    }
    assert json.loads(got.metadata.annotations[annotation.FINAL_SCORE_RESULT]) == {
        "n1": {"PluginA": 77}
    }
    assert not store.has_data(pod.metadata.key)
    factory.shutdown()


# ---------------------------------------------------------------------------
# simulator wrapper (plugins_test.go:389-772)
# ---------------------------------------------------------------------------


def test_wrapper_records_filter_results():
    store = mock.Mock(spec=Store)
    node = make_node("n1")
    [ni] = build_node_infos([node], [])
    pod = make_pod("p")
    ok = make_simulator_plugin(FakeFilterPlugin(), store)
    assert ok.name() == "FakeFilterForSimulator"
    st = ok.filter(CycleState(), pod, ni)
    assert st.is_success()
    store.add_filter_result.assert_called_once_with(
        "default/p", "n1", "FakeFilter", PASSED_FILTER_MESSAGE
    )

    store2 = mock.Mock(spec=Store)
    bad = make_simulator_plugin(FakeFilterPlugin(reject=True), store2)
    st = bad.filter(CycleState(), pod, ni)
    assert not st.is_success()
    store2.add_filter_result.assert_called_once_with(
        "default/p", "n1", "FakeFilter", "fake says no"
    )


def test_wrapper_records_scores_without_extensions():
    """A plugin without NormalizeScore records raw × weight as final."""
    store = mock.Mock(spec=Store)
    pod = make_pod("p")
    w = make_simulator_plugin(FakeScorePlugin(), store, weight=3)
    score, st = w.score(CycleState(), pod, "node-a")
    assert score == len("node-a") and st.is_success()
    store.add_score_result.assert_called_once_with(
        "default/p", "node-a", "FakeScore", 6
    )
    store.add_normalized_score_result.assert_called_once_with(
        "default/p", "node-a", "FakeScore", 6, 3
    )


def test_wrapper_records_normalized_scores():
    store = mock.Mock(spec=Store)
    pod = make_pod("p")
    w = make_simulator_plugin(FakeNormalizingScorePlugin(), store, weight=2)
    w.score(CycleState(), pod, "n1")
    store.add_normalized_score_result.assert_not_called()  # waits for normalize
    scores = [NodeScore("n1", 10), NodeScore("n2", 5)]
    st = w.score_extensions().normalize_score(CycleState(), pod, scores)
    assert st.is_success()
    assert [ns.score for ns in scores] == [20, 10]
    store.add_normalized_score_result.assert_any_call(
        "default/p", "n1", "FakeNorm", 20, 2
    )
    store.add_normalized_score_result.assert_any_call(
        "default/p", "n2", "FakeNorm", 10, 2
    )


def test_wrapper_capability_truthful():
    from minisched_tpu.framework.plugin import implements_filter, implements_score

    store = Store()
    f = make_simulator_plugin(FakeFilterPlugin(), store)
    s = make_simulator_plugin(FakeScorePlugin(), store)
    assert implements_filter(f) and not implements_score(f)
    assert implements_score(s) and not implements_filter(s)


# ---------------------------------------------------------------------------
# config conversion (ConvertForSimulator, plugins.go:146-202)
# ---------------------------------------------------------------------------


def test_convert_for_simulator():
    ps = PluginSet(
        enabled=[PluginEnabled("NodeResourcesFit"), PluginEnabled("TaintToleration", 3)]
    )
    out = convert_for_simulator(ps)
    assert [e.name for e in out.enabled] == [
        "NodeResourcesFitForSimulator",
        "TaintTolerationForSimulator",
    ]
    assert out.enabled[1].weight == 3
    assert out.disabled == ["*"]


def test_registered_simulator_plugins_build():
    from minisched_tpu.plugins.registry import build_plugins
    from minisched_tpu.plugins.simulator import convert_configuration_for_simulator

    store = Store()
    cfg = default_full_roster_config()
    register_simulator_plugins(store, {e.name: e.weight for e in cfg.score.enabled})
    converted = convert_configuration_for_simulator(cfg)
    chains = build_plugins(converted)
    assert all(p.name().endswith("ForSimulator") for p in chains.filter)
    assert all(p.name().endswith("ForSimulator") for p in chains.score)
    assert {p.name() for p in chains.filter} == {
        plugin_name(e.name) for e in cfg.filter.enabled
    }


# ---------------------------------------------------------------------------
# end-to-end: live scheduler with result recording
# ---------------------------------------------------------------------------


def test_live_scheduler_records_results_onto_annotations():
    from minisched_tpu.service.config import default_scheduler_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    svc = SchedulerService(client)
    svc.start_scheduler(
        default_scheduler_config(time_scale=0.01), record_results=True
    )
    client.nodes().create(make_node("node1"))
    client.pods().create(make_pod("pod1"))
    deadline = time.time() + 10
    while time.time() < deadline:
        got = client.pods().get("pod1")
        if (
            got.spec.node_name
            and annotation.FILTER_RESULT in got.metadata.annotations
        ):
            break
        time.sleep(0.05)
    got = client.pods().get("pod1")
    svc.shutdown_scheduler()
    assert got.spec.node_name == "node1"
    filt = json.loads(got.metadata.annotations[annotation.FILTER_RESULT])
    assert filt["node1"]["NodeUnschedulable"] == PASSED_FILTER_MESSAGE
    final = json.loads(got.metadata.annotations[annotation.FINAL_SCORE_RESULT])
    assert final["node1"]["NodeNumber"] == 10  # pod1 suffix matches node1


def test_restart_keeps_result_recording():
    """restart_scheduler must re-wire the flush handler and avoid double
    conversion (regression: results accumulated forever after restart)."""
    from minisched_tpu.service.config import default_scheduler_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    svc = SchedulerService(client)
    svc.start_scheduler(
        default_scheduler_config(time_scale=0.01), record_results=True
    )
    svc.restart_scheduler()
    cfg = svc.get_scheduler_config()
    # stored config is the pre-conversion one: no ForSimulatorForSimulator
    assert all("ForSimulator" not in e.name for e in cfg.filter.enabled)
    client.nodes().create(make_node("node1"))
    client.pods().create(make_pod("pod1"))
    deadline = time.time() + 10
    while time.time() < deadline:
        got = client.pods().get("pod1")
        if got.spec.node_name and annotation.FILTER_RESULT in got.metadata.annotations:
            break
        time.sleep(0.05)
    got = client.pods().get("pod1")
    svc.shutdown_scheduler()
    assert got.spec.node_name == "node1"
    assert annotation.FILTER_RESULT in got.metadata.annotations
    assert not svc.result_store.has_data("default/pod1")


def test_flush_does_not_clobber_concurrent_bind():
    """The annotation flush must be an atomic mutate: a bind landing
    between read and write survives (regression: last-writer-wins race)."""
    from minisched_tpu.api.objects import Binding

    client = Client()
    store = Store(client)
    pod = client.pods().create(make_pod("p1"))
    store.add_filter_result(pod.metadata.key, "n1", "PluginA", "passed")

    real_mutate = client.store.mutate
    bound = {"done": False}

    def racing_mutate(kind, ns, name, fn):
        # simulate the binding goroutine landing first
        if not bound["done"]:
            bound["done"] = True
            client.pods().bind(Binding("p1", "default", "n1"))
        return real_mutate(kind, ns, name, fn)

    client.store.mutate = racing_mutate
    try:
        store.add_scheduling_result_to_pod(pod, pod)
    finally:
        client.store.mutate = real_mutate
    got = client.pods().get("p1")
    assert got.spec.node_name == "n1"  # bind survived
    assert annotation.FILTER_RESULT in got.metadata.annotations


# ---------------------------------------------------------------------------
# batch bridge: the fused kernel's diagnostics land in the same store
# ---------------------------------------------------------------------------


def test_record_batch_result_from_diagnostics():
    from minisched_tpu.models.tables import build_node_table, build_pod_table
    from minisched_tpu.ops import fused
    from minisched_tpu.plugins.nodenumber import NodeNumber
    from minisched_tpu.plugins.nodeunschedulable import NodeUnschedulable

    nodes = [make_node("n0", unschedulable=True), make_node("n1")]
    pods = [make_pod("p1")]
    node_table, node_names = build_node_table(nodes)
    pod_table, _ = build_pod_table(pods)
    nn = NodeNumber()
    ev = fused.FusedEvaluator(
        [NodeUnschedulable()], [nn], [nn], with_diagnostics=True
    )
    result = ev(pod_table, node_table)
    store = Store()
    store.record_batch_result(
        result,
        ["default/p1"],
        node_names,
        ["NodeUnschedulable"],
        ["NodeNumber"],
        reasons={"NodeUnschedulable": "node(s) were unschedulable"},
    )
    filt, score, final = store.get_data("default/p1")
    assert filt["n0"]["NodeUnschedulable"] == "node(s) were unschedulable"
    assert filt["n1"]["NodeUnschedulable"] == PASSED_FILTER_MESSAGE
    assert score["n1"]["NodeNumber"] == 10  # raw score (pre-normalize)
    assert final["n1"]["NodeNumber"] == 10


def test_device_mode_records_wave_results_onto_annotations():
    """record_results=True + device_mode=True: the wave engine ingests a
    diagnostics evaluation per wave (record_batch_result) and the flush
    hook lands the same scheduler-simulator/* annotations the scalar
    recorders produce (SURVEY §2 row 10 — the batch path emits the same
    artifact)."""
    import json
    import time

    from minisched_tpu.api.objects import make_node, make_pod
    from minisched_tpu.controlplane.client import Client
    from minisched_tpu.observability.annotation import (
        FILTER_RESULT,
        SCORE_RESULT,
    )
    from minisched_tpu.service.config import default_full_roster_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    for i in range(4):
        client.nodes().create(
            make_node(f"node{i}", capacity={"cpu": "2", "memory": "4Gi",
                                            "pods": 110})
        )
    for i in range(3):
        client.pods().create(make_pod(f"pod{i}", requests={"cpu": "250m"}))
    svc = SchedulerService(client)
    svc.start_scheduler(
        default_full_roster_config(), record_results=True, device_mode=True,
        max_wave=8,
    )
    try:
        deadline = time.time() + 60
        annotated = None
        while time.time() < deadline:
            pods = client.pods().list()
            bound = [p for p in pods if p.spec.node_name]
            withann = [
                p for p in bound
                if FILTER_RESULT in p.metadata.annotations
            ]
            if len(bound) == 3 and len(withann) == 3:
                annotated = withann
                break
            time.sleep(0.1)
        assert annotated, "pods never got wave result annotations"
        rec = json.loads(
            annotated[0].metadata.annotations[FILTER_RESULT]
        )
        # per-node filter verdicts for the in-tree roster, unwrapped names
        assert "node0" in rec
        assert rec["node0"]["NodeUnschedulable"] == "passed"
        assert "NodeResourcesFit" in rec["node0"]
        score = json.loads(
            annotated[0].metadata.annotations[SCORE_RESULT]
        )
        assert "TaintToleration" in score["node0"]
    finally:
        svc.shutdown_scheduler()
