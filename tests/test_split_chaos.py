"""Split-protocol chaos (DESIGN.md §31, `make chaos-split`): crash-safe
autonomous splits on a live 2-group × 3-replica sharded plane.

Two kill schedules, each against real child processes with real WALs:

* the split COORDINATOR is SIGKILLed mid-freeze — with nobody left to
  unfreeze, every replica's WAL-journaled freeze lease must auto-thaw at
  its TTL: zero stranded frozen namespaces, ownership unchanged, zero
  acked-write loss;
* the SOURCE shard's leader is SIGKILLed mid-handoff (inside the freeze
  window) — the split must complete against the freshly-elected leader
  (the lease renewal before the flip proves no replica thawed under it)
  with every object delivered exactly once and vector-cursor watches
  intact.

Standing audits both times: every acked write survives, and the
full-history double-bind audit (`faults.wal_double_binds`) is clean over
EVERY replica's WAL — all six of them.
"""

from __future__ import annotations

import time

import pytest

from minisched_tpu.api.objects import make_pod
from minisched_tpu.controlplane.remote import RemoteStore
from minisched_tpu.controlplane.replproc import SplitCoordinator
from minisched_tpu.controlplane.shards import ShardedPlane, _raw_req
from minisched_tpu.controlplane.store import ShardFrozen
from minisched_tpu.faults import wal_double_binds

TTL_S = 1.0  # replication lease (election speed), not the freeze lease
FREEZE_TTL_S = 2.5  # the coordinator's freeze-lease TTL under test


def _all_replicas(plane):
    for gid, group in plane.groups.items():
        for r in group.replicas:
            yield gid, r


def _audit_wals(plane):
    for gid, r in _all_replicas(plane):
        assert wal_double_binds(r.wal_path) == [], (gid, r.replica_id)


def _shard_statuses(plane):
    """Live replicas' ShardInfo.describe() docs (dead ones skipped —
    a SIGKILLed leader has nothing stranded to hold)."""
    out = {}
    for gid, r in _all_replicas(plane):
        try:
            status, doc = _raw_req(
                r.base_url, "GET", "/shards/status", timeout_s=2.0
            )
        except Exception:  # noqa: BLE001 — dead replica
            continue
        if status == 200:
            out[r.replica_id] = doc
    return out


def test_coordinator_sigkill_mid_freeze_auto_thaws(tmp_path):
    """Kill the split coordinator INSIDE the freeze window (after the
    freeze fanout, before the handoff).  Nobody will ever send the
    unfreeze — the TTL'd lease on each replica is the only thaw.  Every
    replica must thaw within the lease TTL, no namespace stays frozen,
    ownership and epoch are unchanged, and every previously-acked write
    survives."""
    plane = ShardedPlane(
        str(tmp_path), k=2, replicas_per_group=3, fsync=True, ttl_s=TTL_S
    )
    try:
        plane.start()
        ss = plane.client(timeout_s=10.0, retries=4)
        ns = next(
            n for n in (f"tenant-{i:02d}" for i in range(40))
            if plane.topology.owner(n) == "g0"
        )
        acked = [f"pre-{i:03d}" for i in range(8)]
        for name in acked:
            ss.create("Pod", make_pod(name, namespace=ns))
        epoch0 = plane.topology.epoch

        coord = SplitCoordinator(
            plane.topology.as_dict(), ns, "g1",
            ttl_s=FREEZE_TTL_S, hold_s=3600.0,
        ).start()
        try:
            lease_id = coord.wait_frozen(timeout_s=30.0)
            assert lease_id
            # the freeze is live: a direct write to the source leader is
            # refused with the typed transient error (bounded retry —
            # satellite b's deadline turns the spin into a typed timeout)
            leader_url = plane.wait_for_leader("g0")["url"]
            direct = RemoteStore(
                leader_url, retries=0,
                frozen_deadline_s=0.4, backoff_initial_s=0.05,
            )
            try:
                with pytest.raises(ShardFrozen):
                    direct.create(
                        "Pod", make_pod("frozen-probe", namespace=ns)
                    )
            finally:
                direct.close()

            t_kill = time.monotonic()
            coord.kill()
            assert not coord.alive()

            # auto-thaw: the SAME namespace accepts writes again without
            # any unfreeze ever being sent — bounded by the lease TTL
            # (plus scheduling slack), NOT by operator intervention
            thawed_at = None
            deadline = t_kill + FREEZE_TTL_S + 10.0
            while time.monotonic() < deadline:
                try:
                    ss.create("Pod", make_pod("post-thaw", namespace=ns))
                    thawed_at = time.monotonic()
                    break
                except Exception:  # noqa: BLE001 — still frozen
                    time.sleep(0.1)
            assert thawed_at is not None, "namespace never thawed"
        finally:
            coord.kill()

        # zero stranded frozen namespaces, anywhere
        statuses = _shard_statuses(plane)
        assert statuses, "no replica answered /shards/status"
        for rid, doc in statuses.items():
            assert doc["leases"] == {}, (rid, doc["leases"])
            assert doc["topology"]["frozen"] == [], rid
            # the aborted split never flipped ownership
            assert doc["epoch"] == epoch0, rid
            assert ns not in doc["topology"].get("overrides", {}), rid

        # zero acked-write loss
        names = {p.metadata.name for p in ss.list("Pod")}
        assert set(acked) <= names and "post-thaw" in names
        ss.close()
    finally:
        plane.stop()
    _audit_wals(plane)


def test_split_completes_across_source_leader_failover(tmp_path):
    """SIGKILL the SOURCE group's leader inside the freeze window
    (satellite c): the coordinator's probe finds the freshly-elected
    leader, the handoff ships from it, the pre-flip lease renewal proves
    no replica thawed mid-election, and the split COMPLETES — every
    object on the target exactly once, the source purged, vector-cursor
    watches intact, no stranded freeze."""
    plane = ShardedPlane(
        str(tmp_path), k=2, replicas_per_group=3, fsync=True, ttl_s=TTL_S
    )
    try:
        plane.start()
        ss = plane.client(timeout_s=10.0, retries=4)
        ns = next(
            n for n in (f"tenant-{i:02d}" for i in range(40))
            if plane.topology.owner(n) == "g0"
        )
        pods = [f"mv-{i:03d}" for i in range(10)]
        for name in pods:
            ss.create("Pod", make_pod(name, namespace=ns))

        # a vector-cursor watch opened BEFORE the split must survive it
        # with every component cursor intact: each delivered event
        # strictly advances exactly the component that produced it
        # (exactly-once PER SHARD), and a post-split resume from the
        # final cursor replays nothing already seen
        watch, snap = ss.watch("Pod", send_initial=True)
        seen: list = []
        deadline = time.monotonic() + 30.0
        while len(seen) < len(pods) and time.monotonic() < deadline:
            seen.extend(watch.next_batch(timeout=0.25))
        assert len(seen) == len(pods)

        from minisched_tpu.controlplane.shards import split_namespace

        def kill_source_leader(lease_id: str) -> None:
            old = plane.leader("g0")
            assert old is not None
            old_id = old.replica_id
            old.kill()
            plane.wait_for_leader(
                "g0", timeout_s=20 * TTL_S, exclude=old_id
            )

        # the freeze TTL must outlive the election, or the renewal
        # rightly refuses and the split aborts — that path is pinned
        # in-process in test_shards.py; here the split must COMPLETE
        result = split_namespace(
            plane.topology, ns, "g1", ttl_s=30.0,
            _after_freeze=kill_source_leader,
        )
        assert result["from"] == "g0" and result["to"] == "g1"
        assert result["objects"] == len(pods)
        assert plane.topology.owner(ns) == "g1"
        ss.refresh_topology()

        # exactly-once on the plane: the merged list holds each moved
        # pod ONCE (a duplicate surviving on the source would double it)
        listed = [
            p for p in ss.list("Pod") if p.metadata.namespace == ns
        ]
        assert sorted(p.metadata.name for p in listed) == pods

        # writes flow to the new owner (the stale router 421-chases);
        # the pre-split watch must deliver that event exactly once
        ss.create("Pod", make_pod("post-split", namespace=ns))
        post: list = []
        deadline = time.monotonic() + 15.0
        while (
            not any(e.obj.metadata.name == "post-split" for e in post)
            and time.monotonic() < deadline
        ):
            post.extend(watch.next_batch(timeout=0.25))
        post.extend(watch.next_batch(timeout=0.5))
        assert [
            e.obj.metadata.name for e in post
            if e.obj.metadata.name == "post-split"
        ] == ["post-split"]

        # vector cursors intact across the split: every delivered event
        # advanced its components monotonically, and every LIVE event
        # (the split's transition events, the post-split create) carries
        # a distinct cursor — an equal pair would mean a replay
        cursors = [dict(e.rv) for e in seen + post]
        for a, b in zip(cursors, cursors[1:]):
            assert all(b.get(g, 0) >= rv for g, rv in a.items()), (a, b)
        live = [dict(e.rv) for e in post]
        for a, b in zip(live, live[1:]):
            assert a != b, a

        # ... and a resume from the final cursor replays NOTHING
        final = post[-1].rv
        watch.stop()
        w2, _ = ss.watch("Pod", send_initial=False, resume_rv=dict(final))
        try:
            assert not w2.next_batch(timeout=0.75), "resume replayed"
            ss.create("Pod", make_pod("post-resume", namespace=ns))
            fresh: list = []
            deadline = time.monotonic() + 15.0
            while not fresh and time.monotonic() < deadline:
                fresh.extend(w2.next_batch(timeout=0.25))
            assert [e.obj.metadata.name for e in fresh] == ["post-resume"]
        finally:
            w2.stop()

        # no stranded freeze anywhere, epoch advanced everywhere alive
        for rid, doc in _shard_statuses(plane).items():
            assert doc["leases"] == {}, (rid, doc["leases"])
            assert doc["topology"]["frozen"] == [], rid
            assert doc["epoch"] == plane.topology.epoch, rid

        # the follower-serving read plane advertises its peers — the
        # router's endpoint discovery (satellite a) rides this list
        status, doc = _raw_req(
            plane.wait_for_leader("g1")["url"], "GET", "/repl/status"
        )
        assert status == 200
        assert len(doc.get("peers", [])) == 3
        ss.close()
    finally:
        plane.stop()
    _audit_wals(plane)


@pytest.mark.slow
def test_coordinator_kill_then_retry_completes(tmp_path):
    """Soak the full recovery arc: coordinator killed mid-freeze, lease
    auto-thaws, a SECOND coordinator retries the same split and
    completes it — the half-pushed state of the first attempt (a
    partially-seeded target at worst) must not wedge the retry."""
    plane = ShardedPlane(
        str(tmp_path), k=2, replicas_per_group=3, fsync=True, ttl_s=TTL_S
    )
    try:
        plane.start()
        ss = plane.client(timeout_s=10.0, retries=4)
        ns = next(
            n for n in (f"tenant-{i:02d}" for i in range(40))
            if plane.topology.owner(n) == "g0"
        )
        pods = [f"rt-{i:03d}" for i in range(6)]
        for name in pods:
            ss.create("Pod", make_pod(name, namespace=ns))

        first = SplitCoordinator(
            plane.topology.as_dict(), ns, "g1",
            ttl_s=FREEZE_TTL_S, hold_s=3600.0,
        ).start()
        first.wait_frozen(timeout_s=30.0)
        first.kill()
        # wait out the auto-thaw before the retry (a live foreign lease
        # rightly refuses a second coordinator's freeze): a probe write
        # landing proves every replica reaped the orphan
        deadline = time.monotonic() + FREEZE_TTL_S + 10.0
        while time.monotonic() < deadline:
            try:
                ss.create("Pod", make_pod("thaw-probe", namespace=ns))
                break
            except Exception:  # noqa: BLE001 — still frozen
                time.sleep(0.1)
        pods.append("thaw-probe")
        pods.sort()
        deadline = time.monotonic() + 30.0
        retry = None
        while time.monotonic() < deadline:
            c = SplitCoordinator(
                plane.topology.as_dict(), ns, "g1",
                ttl_s=5.0, hold_s=0.0,
            ).start()
            try:
                c.wait_frozen(timeout_s=10.0)
            except RuntimeError:
                c.kill()
                time.sleep(0.25)
                continue
            retry = c
            break
        assert retry is not None, "retry coordinator never got the lease"
        result = retry.wait_done(timeout_s=60.0)
        assert result["to"] == "g1" and result["objects"] == len(pods)
        plane.topology.epoch = result["epoch"]
        plane.topology.overrides[ns] = "g1"
        ss.refresh_topology()

        listed = [
            p for p in ss.list("Pod") if p.metadata.namespace == ns
        ]
        assert sorted(p.metadata.name for p in listed) == pods
        for rid, doc in _shard_statuses(plane).items():
            assert doc["leases"] == {}, (rid, doc["leases"])
        ss.close()
    finally:
        plane.stop()
    _audit_wals(plane)
