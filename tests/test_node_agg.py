"""Incremental per-node request aggregates in the store.

The capacity-validated bind transaction used to scan the whole pod
population once per batch (``client._node_budgets`` — the ROADMAP crumb
from the HA plane); the store now maintains per-node sums on every Pod
commit, so the budget check is O(target nodes).  These tests pin:

* exactness across every mutation path (create / batch create / bind /
  update / delete / durable replay) against a brute-force scan;
* the regression the crumb names: a bind batch must not touch the pod
  population at all, no matter how many unrelated bound pods exist.
"""

from __future__ import annotations

from minisched_tpu.api.objects import Binding, make_node, make_pod
from minisched_tpu.controlplane.client import Client


def _brute(store) -> dict:
    agg: dict = {}
    for pod in store._objects.get("Pod", {}).values():
        if pod.spec.node_name:
            a = agg.setdefault(pod.spec.node_name, [0, 0, 0])
            r = pod.resource_requests()
            a[0] += r.milli_cpu
            a[1] += r.memory
            a[2] += r.pods
    return {k: tuple(v) for k, v in agg.items()}


def _index(store) -> dict:
    return {k: tuple(v) for k, v in store._pod_node_agg.items()}


def test_index_tracks_every_mutation_path():
    client = Client()
    store = client.store
    for i in range(4):
        client.nodes().create(
            make_node(
                f"n{i}", capacity={"cpu": "64", "memory": "128Gi", "pods": 256}
            )
        )
    client.pods().create_many(
        [
            make_pod(f"p{i}", requests={"cpu": "500m", "memory": "64Mi"})
            for i in range(20)
        ]
    )
    assert _index(store) == _brute(store) == {}  # nothing bound yet

    # batch bind (mutate_many path)
    res = client.pods().bind_many(
        [Binding(f"p{i}", "default", f"n{i % 4}") for i in range(10)]
    )
    assert not any(isinstance(r, BaseException) for r in res)
    assert _index(store) == _brute(store)

    # delete bound pods
    client.pods().delete("p0")
    client.pods().delete("p1")
    assert _index(store) == _brute(store)

    # update of a bound pod (same node): net zero, still exact
    p2 = client.pods().get("p2")
    client.pods().update(p2)
    assert _index(store) == _brute(store)

    # a create that arrives ALREADY bound (restore-style seed)
    pre = make_pod("pre", requests={"cpu": "250m"})
    pre.spec.node_name = "n3"
    client.pods().create(pre)
    assert _index(store) == _brute(store)

    # budgets = allocatable - index, and absent nodes get no budget
    budgets = client.pods()._node_budgets(store, {"n0", "n3", "ghost"})
    brute = _brute(store)
    for name in ("n0", "n3"):
        node = client.nodes().get(name)
        alloc = node.status.allocatable
        used = brute.get(name, (0, 0, 0))
        assert budgets[name] == [
            alloc.milli_cpu - used[0],
            alloc.memory - used[1],
            alloc.pods - used[2],
        ]
    assert "ghost" not in budgets


def test_bind_batch_cost_independent_of_unrelated_bound_pods():
    """The named regression: the bind-batch budget check must read the
    per-node index, never iterate the pod population — enforced by a pod
    map whose iteration surface raises."""
    client = Client()
    store = client.store
    client.nodes().create(
        make_node("a", capacity={"cpu": "640", "memory": "128Gi", "pods": 1000})
    )
    client.nodes().create(
        make_node("b", capacity={"cpu": "64", "memory": "128Gi", "pods": 256})
    )
    client.pods().create_many(
        [make_pod(f"bg{i}", requests={"cpu": "100m"}) for i in range(300)]
    )
    res = client.pods().bind_many(
        [Binding(f"bg{i}", "default", "a") for i in range(300)]
    )
    assert not any(isinstance(r, BaseException) for r in res)
    client.pods().create(make_pod("t1", requests={"cpu": "100m"}))

    class NoScan(dict):
        """A pod map whose population iteration fails the test."""

        def values(self):
            raise AssertionError(
                "bind batch scanned the pod population (O(all pods) again)"
            )

        def items(self):
            raise AssertionError("bind batch scanned the pod population")

        def __iter__(self):
            raise AssertionError("bind batch scanned the pod population")

    store._objects["Pod"] = NoScan(store._objects["Pod"].items())
    [res] = client.pods().bind_many([Binding("t1", "default", "b")])
    assert not isinstance(res, BaseException)
    # restore a plain dict so teardown/list paths work normally
    plain = {}
    plain.update(dict.items(store._objects["Pod"]))
    store._objects["Pod"] = plain
    assert _index(store) == _brute(store)
    assert client.pods().get("t1").spec.node_name == "b"


def test_out_of_capacity_still_enforced_via_index():
    """The commit-time capacity gate (HA over-commit backstop) answers
    from the index with unchanged semantics: the batch that fits commits,
    the one that would over-commit is rejected per-item."""
    from minisched_tpu.controlplane.client import OutOfCapacity

    client = Client()
    client.nodes().create(
        make_node("tiny", capacity={"cpu": "1", "memory": "4Gi", "pods": 10})
    )
    client.pods().create_many(
        [make_pod(f"c{i}", requests={"cpu": "600m"}) for i in range(2)]
    )
    res = client.pods().bind_many(
        [Binding("c0", "default", "tiny"), Binding("c1", "default", "tiny")]
    )
    assert res[0] is None or not isinstance(res[0], BaseException)
    assert isinstance(res[1], OutOfCapacity)


def test_durable_reopen_rebuilds_index(tmp_path):
    from minisched_tpu.controlplane.durable import DurableObjectStore

    wal = str(tmp_path / "agg.wal")
    store = DurableObjectStore(wal)
    client = Client(store=store)
    client.nodes().create(
        make_node("n0", capacity={"cpu": "64", "memory": "128Gi", "pods": 256})
    )
    client.pods().create_many(
        [make_pod(f"d{i}", requests={"cpu": "200m"}) for i in range(6)]
    )
    res = client.pods().bind_many(
        [Binding(f"d{i}", "default", "n0") for i in range(4)]
    )
    assert not any(isinstance(r, BaseException) for r in res)
    client.pods().delete("d0")
    expected = _brute(store)
    assert _index(store) == expected
    store.close()

    reopened = DurableObjectStore(wal)
    try:
        assert _index(reopened) == _brute(reopened) == expected
    finally:
        reopened.close()


def test_store_create_many_batch_semantics():
    """store.create_many: one transaction, per-item conflicts, watchers
    see one batched fanout in creation order, return_objects=False skips
    the clones."""
    from minisched_tpu.controlplane.store import EventType, ObjectStore

    store = ObjectStore()
    w, _ = store.watch("Pod", send_initial=False)
    a, b = make_pod("a"), make_pod("b")
    for p in (a, b):
        p.metadata.namespace = "default"
    first = store.create_many("Pod", [a, b])
    assert [o.metadata.name for o in first] == ["a", "b"]
    # conflict on "a" comes back per-item; "c" still creates
    c = make_pod("c")
    c.metadata.namespace = "default"
    res = store.create_many("Pod", [a, c], return_objects=False)
    assert isinstance(res[0], KeyError)
    assert res[1] is None
    events = w.next_batch(timeout=2.0)
    assert [
        (e.type, e.obj.metadata.name) for e in events
    ] == [
        (EventType.ADDED, "a"),
        (EventType.ADDED, "b"),
        (EventType.ADDED, "c"),
    ]
