"""Volume plugins (VolumeBinding + NodeVolumeLimits): scalar behavior,
batch parity, and the live PVC-gated scheduling scenario."""

from __future__ import annotations

import time

from minisched_tpu.api.objects import (
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PVCSpec,
    PVSpec,
    make_node,
    make_pod,
)
from minisched_tpu.controlplane.client import KIND_PV, KIND_PVC, Client
from minisched_tpu.framework.nodeinfo import build_node_infos
from minisched_tpu.framework.types import CycleState
from minisched_tpu.models.constraints import build_constraint_tables
from minisched_tpu.models.tables import build_node_table, build_pod_table
from minisched_tpu.ops.fused import FusedEvaluator
from minisched_tpu.plugins.nodeunschedulable import NodeUnschedulable
from minisched_tpu.plugins.volumebinding import NodeVolumeLimits, VolumeBinding

GI = 1024**3


def _pv(name, capacity=GI, claim="", labels=None):
    return PersistentVolume(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=PVSpec(
            capacity=capacity, claim_ref=claim,
            required_node_labels=dict(labels or {}),
        ),
    )


def _pvc(name, request=GI, volume=""):
    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name),
        spec=PVCSpec(request=request, volume_name=volume),
    )


def _client_with(nodes=(), pvs=(), pvcs=()):
    client = Client()
    for n in nodes:
        client.nodes().create(n)
    for pv in pvs:
        client.store.create(KIND_PV, pv)
    for pvc in pvcs:
        client.store.create(KIND_PVC, pvc)
    return client


def _vb(client):
    vb = VolumeBinding()
    vb.store_client = client
    return vb


def test_missing_pvc_is_unresolvable():
    client = _client_with(nodes=[make_node("n1")])
    [ni] = build_node_infos([client.nodes().get("n1")], [])
    pod = make_pod("p", volumes=["ghost"])
    st = _vb(client).filter(CycleState(), pod, ni)
    assert st.code.name == "UNSCHEDULABLE_AND_UNRESOLVABLE"


def test_bound_claim_pins_to_pv_node_labels():
    zone_a = make_node("a", labels={"zone": "a"})
    zone_b = make_node("b", labels={"zone": "b"})
    client = _client_with(
        nodes=[zone_a, zone_b],
        pvs=[_pv("pv1", claim="default/data", labels={"zone": "a"})],
        pvcs=[_pvc("data", volume="pv1")],
    )
    infos = build_node_infos([zone_a, zone_b], [])
    pod = make_pod("p", volumes=["data"])
    vb = _vb(client)
    assert vb.filter(CycleState(), pod, infos[0]).is_success()
    assert not vb.filter(CycleState(), pod, infos[1]).is_success()


def test_unbound_claim_needs_bindable_free_pv():
    node = make_node("n1", labels={"zone": "a"})
    client = _client_with(
        nodes=[node],
        pvs=[_pv("small", capacity=GI // 2), _pv("taken", claim="x/y")],
        pvcs=[_pvc("want", request=GI)],
    )
    [ni] = build_node_infos([node], [])
    pod = make_pod("p", volumes=["want"])
    assert not _vb(client).filter(CycleState(), pod, ni).is_success()
    client.store.create(KIND_PV, _pv("big", capacity=2 * GI))
    assert _vb(client).filter(CycleState(), pod, ni).is_success()


def test_node_volume_limits():
    node = make_node("n1")
    holder = make_pod("holder", volumes=["v1", "v2"])
    holder.metadata.uid = "holder"
    holder.spec.node_name = "n1"
    [ni] = build_node_infos([node], [holder])
    nvl = NodeVolumeLimits(max_volumes=3)
    ok = make_pod("ok", volumes=["v3"])
    over = make_pod("over", volumes=["v3", "v4"])
    assert nvl.filter(CycleState(), ok, ni).is_success()
    assert not nvl.filter(CycleState(), over, ni).is_success()


def test_batch_parity_volume_chain():
    """Oracle vs fused kernel with the volume planes in ConstraintTables."""
    nodes = [
        make_node("a", labels={"zone": "a"}),
        make_node("b", labels={"zone": "b"}),
    ]
    pvs = [
        _pv("pv-a", claim="default/bound-a", labels={"zone": "a"}),
        _pv("free-b", capacity=2 * GI, labels={"zone": "b"}),
    ]
    pvcs = [_pvc("bound-a", volume="pv-a"), _pvc("loose", request=GI)]
    client = _client_with(nodes=nodes, pvs=pvs, pvcs=pvcs)
    pods = [
        make_pod("p-bound", volumes=["bound-a"]),   # → zone a only
        make_pod("p-loose", volumes=["loose"]),      # → zone b only (free PV)
        make_pod("p-ghost", volumes=["nope"]),       # → unschedulable
        make_pod("p-free"),                          # → anywhere
    ]
    vb = _vb(client)
    nvl = NodeVolumeLimits()
    infos = build_node_infos(nodes, [])
    # scalar oracle
    from minisched_tpu.engine.scheduler import schedule_pod_once
    from minisched_tpu.framework.types import FitError

    oracle = []
    for pod in pods:
        try:
            oracle.append(
                schedule_pod_once([NodeUnschedulable(), vb, nvl], [], [], {},
                                  pod, infos)
            )
        except FitError:
            oracle.append("")
    # batch
    node_table, node_names = build_node_table(nodes)
    pod_table, _ = build_pod_table(pods)
    extra = build_constraint_tables(
        pods, nodes, [], pod_capacity=pod_table.capacity,
        node_capacity=node_table.capacity, pvcs=pvcs, pvs=pvs,
    )
    ev = FusedEvaluator([NodeUnschedulable(), vb, nvl], [], [])
    res = ev(pod_table, node_table, extra)
    batch = [
        node_names[c] if c >= 0 else "" for c in res.choice.tolist()[: len(pods)]
    ]
    assert oracle == batch
    assert batch[0] == "a" and batch[1] == "b" and batch[2] == ""


def test_record_results_injects_client_through_wrapper():
    """With record_results=True the VolumeBinding filter is simulator-
    wrapped; the store client must reach the INNER plugin (regression:
    setattr landed on the wrapper and the filter errored)."""
    from minisched_tpu.service.config import default_full_roster_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    svc = SchedulerService(client)
    svc.start_scheduler(
        default_full_roster_config(time_scale=0.01), record_results=True
    )
    try:
        client.nodes().create(make_node("node1", labels={"zone": "a"}))
        client.store.create(KIND_PV, _pv("pv1", claim="default/data"))
        client.store.create(KIND_PVC, _pvc("data", volume="pv1"))
        client.pods().create(make_pod("pod1", volumes=["data"]))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if client.pods().get("pod1").spec.node_name:
                break
            time.sleep(0.02)
        assert client.pods().get("pod1").spec.node_name == "node1"
    finally:
        svc.shutdown_scheduler()


def test_claim_bound_to_missing_pv_unschedulable_in_both_paths():
    """A PVC pointing at a deleted PV: scalar says unresolvable, batch
    must agree the pod is unschedulable everywhere (regression: batch
    placed it anywhere)."""
    nodes = [make_node("n1")]
    pvcs = [_pvc("orphan", volume="gone")]
    client = _client_with(nodes=nodes, pvcs=pvcs)
    pod = make_pod("p", volumes=["orphan"])
    vb = _vb(client)
    [ni] = build_node_infos(nodes, [])
    assert not vb.filter(CycleState(), pod, ni).is_success()
    node_table, _ = build_node_table(nodes)
    pod_table, _ = build_pod_table([pod])
    extra = build_constraint_tables(
        [pod], nodes, [], pod_capacity=pod_table.capacity,
        node_capacity=node_table.capacity, pvcs=pvcs, pvs=[],
    )
    res = FusedEvaluator([vb], [], [])(pod_table, node_table, extra)
    assert int(res.choice[0]) == -1


def test_repair_rounds_respect_volume_limits():
    """One wave of volume-heavy pods must not exceed max_volumes on a node
    (regression: acceptance ignored volume counts across rounds)."""
    from minisched_tpu.ops.repair import RepairingEvaluator

    nodes = [make_node("n1")]
    pvcs = [_pvc(f"v{i}", volume=f"pv{i}") for i in range(10)]
    pvs = [_pv(f"pv{i}", claim=f"default/v{i}") for i in range(10)]
    client = _client_with(nodes=nodes, pvs=pvs, pvcs=pvcs)
    pods = [make_pod(f"p{i}", volumes=[f"v{2*i}", f"v{2*i+1}"]) for i in range(5)]
    vb = _vb(client)
    nvl = NodeVolumeLimits(max_volumes=4)  # only 2 two-volume pods fit
    node_table, _ = build_node_table(nodes)
    pod_table, _ = build_pod_table(pods)
    extra = build_constraint_tables(
        pods, nodes, [], pod_capacity=pod_table.capacity,
        node_capacity=node_table.capacity, pvcs=pvcs, pvs=pvs,
    )
    ev = RepairingEvaluator([NodeUnschedulable(), vb, nvl], [], [])
    _, choice, _ = ev(pod_table, node_table, extra)
    placed = sum(1 for c in choice.tolist()[: len(pods)] if c >= 0)
    assert placed == 2


def test_repair_moves_to_runner_up_when_volumes_fill():
    """When earlier rounds fill a node's volume limit, later rounds must
    re-route contenders to other feasible nodes (regression: the filter
    saw static counts, so the contender stuck to the full node forever)."""
    from minisched_tpu.ops.repair import RepairingEvaluator

    nodes = [make_node("n1"), make_node("n2")]
    pvcs = [_pvc(f"v{i}", volume=f"pv{i}") for i in range(3)]
    pvs = [_pv(f"pv{i}", claim=f"default/v{i}") for i in range(3)]
    client = _client_with(nodes=nodes, pvs=pvs, pvcs=pvcs)
    pods = [make_pod(f"p{i}", volumes=[f"v{i}"]) for i in range(3)]
    vb = _vb(client)
    nvl = NodeVolumeLimits(max_volumes=2)
    node_table, node_names = build_node_table(nodes)
    pod_table, _ = build_pod_table(pods)
    extra = build_constraint_tables(
        pods, nodes, [], pod_capacity=pod_table.capacity,
        node_capacity=node_table.capacity, pvcs=pvcs, pvs=pvs,
    )
    ev = RepairingEvaluator([NodeUnschedulable(), vb, nvl], [], [])
    _, choice, _ = ev(pod_table, node_table, extra)
    placements = [
        node_names[c] if c >= 0 else "" for c in choice.tolist()[: len(pods)]
    ]
    # ALL three pods place: two on one node, the third on the other
    assert "" not in placements
    assert len(set(placements)) == 2


def test_device_wave_survives_overcap_pod():
    """A pod with more volumes than the static table cap parks alone; the
    rest of its wave still schedules (regression: whole wave dropped)."""
    from minisched_tpu.service.config import default_full_roster_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    svc = SchedulerService(client)
    svc.start_scheduler(
        default_full_roster_config(time_scale=0.01),
        device_mode=True,
        max_wave=16,
    )
    try:
        client.nodes().create(make_node("node1"))
        monster = make_pod("monster", volumes=[f"v{i}" for i in range(9)])
        client.pods().create(monster)
        client.pods().create(make_pod("normal1"))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if client.pods().get("normal1").spec.node_name == "node1":
                break
            time.sleep(0.05)
        assert client.pods().get("normal1").spec.node_name == "node1"
        assert client.pods().get("monster").spec.node_name == ""
    finally:
        svc.shutdown_scheduler()


def test_live_pod_waits_for_pv_then_schedules():
    """Full loop: a pod with an unbound PVC parks; a feasible PV appears →
    the PV event requeues it, the PV controller binds the claim, the pod
    schedules (the reference's volume scenario shape)."""
    from minisched_tpu.controlplane.pvcontroller import start_pv_controller
    from minisched_tpu.service.config import default_full_roster_config
    from minisched_tpu.service.service import SchedulerService

    client = Client()
    ctrl = start_pv_controller(client)
    svc = SchedulerService(client)
    svc.start_scheduler(default_full_roster_config(time_scale=0.01))
    try:
        client.nodes().create(make_node("node1", labels={"zone": "a"}))
        client.store.create(KIND_PVC, _pvc("data", request=GI))
        client.pods().create(make_pod("pod1", volumes=["data"]))

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if svc.scheduler.queue.stats()["unschedulable"] == 1:
                break
            time.sleep(0.02)
        assert client.pods().get("pod1").spec.node_name == ""

        client.store.create(KIND_PV, _pv("late", capacity=2 * GI))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if client.pods().get("pod1").spec.node_name:
                break
            time.sleep(0.02)
        assert client.pods().get("pod1").spec.node_name == "node1"
    finally:
        svc.shutdown_scheduler()
        ctrl.stop()
