"""Multi-chip LIVE wave engine (ISSUE 7): the mesh-sharded packed path
wired into the pipelined DeviceScheduler.

The dryrun suite (tests/test_sharding.py) proves the sharded STEPS; this
suite pins the tentpole's live contract on the virtual 8-device CPU mesh
the conftest forces:

* placements are BIT-IDENTICAL between the single-device engine and a
  ``MINISCHED_MESH=1`` engine — gangs included, through the full permit/
  bind chain (the parity suite of the acceptance criteria);
* a degenerate 1-device mesh is current behavior exactly;
* uneven pad shards (live node count not divisible by the node axis —
  trailing shards mostly padding) change nothing;
* a forced sharding failure falls back PER WAVE to the single-device
  evaluator (faults point ``mesh.evaluate``) and later waves retry the
  mesh — the regression guard for the fallback ladder.
"""

from __future__ import annotations

import time

import jax
import pytest

from minisched_tpu.api.objects import (
    GangSpec,
    make_gang_pods,
    make_node,
    make_pod,
)
from minisched_tpu.controlplane.client import Client
from minisched_tpu.observability import counters
from minisched_tpu.parallel import sharding
from minisched_tpu.service.config import (
    default_scheduler_config,
    gang_roster_config,
)
from minisched_tpu.service.service import SchedulerService


def _wait_bound(client, n, timeout=180.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        bound = {
            p.metadata.name: p.spec.node_name
            for p in client.pods().list()
            if p.spec.node_name
        }
        if len(bound) >= n:
            return bound
        time.sleep(0.05)
    raise AssertionError(f"only {len(bound)}/{n} pods bound in {timeout}s")


def _run_live(nodes, pods, cfg, device_mesh, max_wave=1024, faults=None):
    """One engine lap: seed everything, start, drain, return placements.
    All pods exist before the engine starts and max_wave covers them, and
    every pod's uid (the tie-break seed) is PINNED to its name — the
    process-global uid counter would otherwise hand the second run
    different uids and the seeded tie-breaks would differ for reasons
    that have nothing to do with the evaluator under test."""
    client = Client()
    client.nodes().create_many([n.clone() for n in nodes], return_objects=False)
    seeded = []
    for p in pods:
        c = p.clone()
        c.metadata.uid = f"uid-{c.metadata.name}"
        seeded.append(c)
    client.pods().create_many(seeded, return_objects=False)
    svc = SchedulerService(client)
    sched = svc.start_scheduler(
        cfg, device_mode=True, max_wave=max_wave, device_mesh=device_mesh
    )
    if faults is not None:
        sched.faults = faults
    try:
        bound = _wait_bound(client, len(pods))
    finally:
        svc.close()
    return bound, sched


def _simple_cluster(n_nodes=100, n_pods=150):
    import random

    rng = random.Random(11)
    nodes = [
        make_node(
            f"node{i:03d}",
            unschedulable=rng.random() < 0.2,
            capacity={"cpu": "16", "memory": "32Gi", "pods": 64},
        )
        for i in range(n_nodes)
    ]
    pods = [
        make_pod(f"p{i:04d}", requests={"cpu": "100m", "memory": "64Mi"})
        for i in range(n_pods)
    ]
    return nodes, pods


def test_eight_devices_available():
    assert len(jax.devices()) >= 8


def test_live_mesh_parity_simple_and_degenerate(monkeypatch):
    """Single-device vs MINISCHED_MESH=1 (the env resolution the tentpole
    names) vs an explicit degenerate 1-device mesh: bit-identical
    placements.  100 live nodes over a 4-wide node axis leave the last
    shard mostly padding — the uneven-pad-shards case."""
    nodes, pods = _simple_cluster()
    base, sched0 = _run_live(
        nodes, pods, default_scheduler_config(), device_mesh=None
    )
    assert sched0.mesh is None  # conftest pins MINISCHED_MESH=0

    counters.reset()
    monkeypatch.setenv("MINISCHED_MESH", "1")
    meshed, sched1 = _run_live(
        nodes, pods, default_scheduler_config(), device_mesh=None
    )
    assert sched1.mesh is not None
    assert sorted(sched1.mesh.shape.values()) and (
        int(jax.device_count())
        == int(sched1.mesh.shape["pods"]) * int(sched1.mesh.shape["nodes"])
    )
    assert meshed == base
    assert counters.get("wave_mesh.waves") > 0
    assert counters.get("wave_mesh.fallbacks") == 0
    # pad-waste ledger recorded something (capacity 128 > 100 live nodes)
    assert counters.get("wave_mesh.pad_node_rows") > 0

    monkeypatch.setenv("MINISCHED_MESH", "0")
    degenerate, sched2 = _run_live(
        nodes, pods, default_scheduler_config(),
        device_mesh=sharding.make_mesh(1),
    )
    assert degenerate == base


def test_resolve_mesh_policy():
    assert sharding.resolve_mesh(env={"MINISCHED_MESH": "0"}) is None
    m = sharding.resolve_mesh(env={"MINISCHED_MESH": "1"})
    assert m is not None and m.size == jax.device_count()
    # auto: >1 visible device → mesh (this suite forces 8)
    m2 = sharding.resolve_mesh(env={})
    assert m2 is not None and m2.size == jax.device_count()
    with pytest.raises(ValueError):
        sharding.resolve_mesh(env={"MINISCHED_MESH": "banana"})


def test_live_mesh_parity_gangs_full_roster():
    """The gang roster (full default chain + Coscheduling permit +
    GangTopology) through the live mesh engine: gangs admit all-or-
    nothing and land bit-identically to the single-device run."""
    import random

    rng = random.Random(5)
    nodes = []
    for s in range(2):
        for h in range(8):
            nodes.append(
                make_node(
                    f"slice{s}-host{h}",
                    capacity={"cpu": "16", "memory": "32Gi", "pods": 64},
                    slice_id=f"slice{s}",
                    torus=(h % 4, h // 4, 0),
                    host_index=h,
                    slice_dims=(4, 2, 0),
                )
            )
    nodes += [
        make_node(
            f"plain{i:02d}",
            unschedulable=rng.random() < 0.2,
            capacity={"cpu": "16", "memory": "32Gi", "pods": 64},
        )
        for i in range(20)
    ]
    pods = (
        make_gang_pods("ga", 4, requests={"cpu": "500m"})
        + [make_pod(f"s{i:03d}", requests={"cpu": "250m"}) for i in range(40)]
        + make_gang_pods("gb", 3, requests={"cpu": "500m"})
    )
    cfg = gang_roster_config()
    base, _ = _run_live(nodes, pods, cfg, device_mesh=None, max_wave=128)
    meshed, sched = _run_live(
        nodes, pods, cfg, device_mesh=sharding.make_mesh(8), max_wave=128
    )
    assert sched.mesh is not None
    assert meshed == base
    # both gangs landed whole (all-or-nothing survived the mesh)
    for g, size in (("ga", 4), ("gb", 3)):
        members = [v for k, v in meshed.items() if k.startswith(f"{g}-")]
        assert len(members) == size and all(members)


def test_mesh_sharding_failure_falls_back_per_wave():
    """A sharded-evaluate failure (injected at the ``mesh.evaluate``
    fabric point) degrades THAT wave to the single-device evaluator —
    same placements still commit — and later waves retry the mesh."""
    from minisched_tpu.faults import FaultFabric

    nodes, _ = _simple_cluster(n_nodes=40, n_pods=0)
    pods_a = [
        make_pod(f"a{i:03d}", requests={"cpu": "100m"}) for i in range(30)
    ]
    pods_b = [
        make_pod(f"b{i:03d}", requests={"cpu": "100m"}) for i in range(30)
    ]
    fabric = FaultFabric(1234).on("mesh.evaluate", rate=1.0, max_fires=1)

    client = Client()
    client.nodes().create_many(nodes, return_objects=False)
    counters.reset()
    svc = SchedulerService(client)
    sched = svc.start_scheduler(
        default_scheduler_config(),
        device_mode=True,
        max_wave=64,
        device_mesh=sharding.make_mesh(8),
    )
    sched.faults = fabric
    try:
        client.pods().create_many(pods_a, return_objects=False)
        _wait_bound(client, len(pods_a))
        assert fabric.fires("mesh.evaluate") == 1
        assert counters.get("wave_mesh.fallbacks") >= 1
        # the NEXT wave retries the mesh (per-wave ladder, not a latch)
        client.pods().create_many(pods_b, return_objects=False)
        _wait_bound(client, len(pods_a) + len(pods_b))
        assert counters.get("wave_mesh.waves") >= 1
    finally:
        svc.close()
    # every pod placed despite the injected failure; capacity respected
    by_node = {}
    for p in client.pods().list():
        assert p.spec.node_name
        by_node.setdefault(p.spec.node_name, []).append(p)
    for node in client.nodes().list():
        assert len(by_node.get(node.metadata.name, [])) <= (
            node.status.allocatable.pods
        )


def test_scan_lane_packed_mesh_parity():
    """The sequential scan's packed mesh layout (nodes sharded, pods
    replicated — sharded_scan_step's rule) is bit-identical to the
    single-device packed scan."""
    from minisched_tpu.framework.nodeinfo import build_node_infos
    from minisched_tpu.models.constraints import build_constraint_tables
    from minisched_tpu.models.tables import (
        CachedNodeTableBuilder,
        build_pod_table,
    )
    from minisched_tpu.ops.sequential import SequentialScheduler
    from minisched_tpu.plugins.nodenumber import NodeNumber
    from minisched_tpu.plugins.nodeunschedulable import NodeUnschedulable

    import random

    rng = random.Random(3)
    nodes = [
        make_node(f"n{i:03d}", unschedulable=rng.random() < 0.3)
        for i in range(70)  # uneven across any >1 node axis
    ]
    pods = [make_pod(f"p{i}") for i in range(40)]
    infos = build_node_infos(nodes, [])
    pt, _ = build_pod_table(pods, capacity=128, device=False)
    extra = build_constraint_tables(
        pods, nodes, [], pod_capacity=128, node_capacity=128,
        scan_planes=True, device=False, elide_zeros=False,
    )

    def run(mesh):
        b = CachedNodeTableBuilder(mesh=mesh)
        node_static, node_agg, _names = b.build_packed(infos)
        nn = NodeNumber()
        scan = SequentialScheduler(
            (NodeUnschedulable(),), (nn,), (nn,),
            weights={"NodeNumber": 1}, mesh=mesh,
        )
        _, choice, _ = scan.call_packed(pt, node_static, node_agg, extra)
        return jax.device_get(choice).tolist()

    assert run(sharding.make_mesh(8)) == run(None)
